"""Tests for the execution layer: device catalog, gang scheduler, local backend.

Covers the capability surface of the reference's device config + Kueue
integration + PyTorchJob deployer + pod lifecycle (SURVEY.md §2 components
6/11/12/24) against the in-repo fake cluster — the hermetic cluster test seam
the reference never had (SURVEY.md §4).
"""

import asyncio
import json

import pytest

from finetune_controller_tpu.controller.backends.local import LocalProcessBackend
from finetune_controller_tpu.controller.backends.scheduler import GangScheduler
from finetune_controller_tpu.controller.devices import (
    DeviceCatalog,
    DeviceFlavor,
    FlavorQuota,
    default_catalog,
    default_mesh_for,
    load_catalog,
)
from finetune_controller_tpu.controller.objectstore import LocalObjectStore
from finetune_controller_tpu.controller.schemas import BackendJobState, JobInput


from conftest import one_chip_catalog as _small_catalog
from conftest import run_async as run
from conftest import tiny_job_spec as _job_spec


# ---------------------------------------------------------------------------
# Device catalog
# ---------------------------------------------------------------------------


def test_default_catalog_flavors_and_quota():
    cat = default_catalog()
    assert "v5e-16" in cat.names() and "cpu-test" in cat.names()
    v5e16 = cat.get("v5e-16")
    assert v5e16.total_chips == 16
    assert v5e16.k8s_resource_name() == "google.com/tpu"
    sel = v5e16.accelerator_selectors()
    assert sel["cloud.google.com/gke-tpu-topology"] == "4x4"
    assert cat.quota_for("v5e-16") == 32
    # fallback to default flavor for unknown names (reference device_config.py:59-67)
    assert cat.get_worker("nope").name == "cpu-test"


def test_catalog_json_with_comments(tmp_path):
    p = tmp_path / "devices.json"
    p.write_text(
        """
{
  // a comment, as the reference allows (device_config.py:81-85)
  "flavors": [
    {"name": "v6e-8", "generation": "v6e", "topology": "2x4",
     "hosts": 2, "chips_per_host": 4, "queue": "q6"}
  ],
  "quotas": [{"flavor": "v6e-8", "nominal_chips": 8}],
  "default_flavor": "v6e-8"
}
"""
    )
    cat = load_catalog(p)
    assert cat.get("v6e-8").total_chips == 8
    assert cat.quota_for("v6e-8") == 8
    enum_cls = cat.device_enum()
    assert enum_cls["v6e-8"].value == "v6e-8"


def test_missing_catalog_falls_back_to_default(tmp_path):
    cat = load_catalog(tmp_path / "absent.json")
    assert "cpu-test" in cat.names()


def test_default_mesh_covers_all_chips():
    cat = default_catalog()
    mesh = default_mesh_for(cat.get("v5e-16"), num_slices=2)
    assert mesh["dp"] == 2 and mesh["fsdp"] == 16
    assert all(mesh.get(a, 1) == 1 for a in ("ep", "pp", "sp", "tp"))


# ---------------------------------------------------------------------------
# Gang scheduler
# ---------------------------------------------------------------------------


def test_scheduler_fifo_admission_and_positions():
    sched = GangScheduler(_small_catalog(quota=2))
    sched.submit("a", "chip-1")
    sched.submit("b", "chip-1")
    sched.submit("c", "chip-1")
    admitted = [w.job_id for w in sched.try_admit()]
    assert admitted == ["a", "b"]  # quota = 2 chips, 1 chip each
    assert sched.pending() == ["c"]
    assert sched.position("c") == 1
    assert sched.position("a") is None
    sched.release("a")
    assert [w.job_id for w in sched.try_admit()] == ["c"]
    assert sched.pending() == []


def test_scheduler_gang_all_or_nothing():
    sched = GangScheduler(_small_catalog(quota=2))
    sched.submit("big", "chip-1", num_slices=3)  # needs 3 > quota 2: never admits
    assert sched.try_admit() == []
    assert sched.position("big") == 1
    # best-effort FIFO: a small job behind the blocked one still admits
    sched.submit("small", "chip-1")
    assert [w.job_id for w in sched.try_admit()] == ["small"]
    usage = sched.usage()["chip-1"]
    assert usage["used_chips"] == 1 and usage["pending"] == 1


def test_scheduler_duplicate_rejected():
    sched = GangScheduler(_small_catalog())
    sched.submit("a", "chip-1")
    with pytest.raises(ValueError):
        sched.submit("a", "chip-1")


# ---------------------------------------------------------------------------
# Local backend (full pod lifecycle with a real trainer subprocess)
# ---------------------------------------------------------------------------


def _backend(tmp_path, quota=2):
    store = LocalObjectStore(tmp_path / "objects")
    backend = LocalProcessBackend(
        tmp_path / "sandbox", store, _small_catalog(quota=quota),
        sync_interval_s=0.2,
    )
    return backend, store


async def _wait_state(backend, job_id, states, timeout=120.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        report = await backend.get_job(job_id)
        if report is not None and report.state in states:
            return report
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"timeout waiting for {states}; last={report}")
        await asyncio.sleep(0.2)


def test_local_backend_end_to_end(tmp_path):
    async def main():
        backend, store = _backend(tmp_path)
        job = JobInput(job_id="t-1", user_id="u", model_name="tiny-test-lora",
                       device="chip-1", arguments={})
        await backend.submit(
            job, _job_spec(), backend.catalog.get("chip-1"),
            dataset_uri=None, artifacts_uri="obj://artifacts/u/t-1",
        )
        report = await _wait_state(
            backend, "t-1", {BackendJobState.SUCCEEDED, BackendJobState.FAILED}
        )
        logs = []
        it = await backend.read_logs("t-1")
        async for line in it:
            logs.append(line)
        assert report.state is BackendJobState.SUCCEEDED, "\n".join(logs[-30:])
        # artifact sidecar shipped metrics + done.txt to the object store
        keys = {o["uri"] for o in await store.list_prefix("obj://artifacts/u/t-1")}
        assert any("metrics" in k and k.endswith(".csv") for k in keys), keys
        assert any(k.endswith("done.txt") for k in keys)
        assert any("finished" in l for l in logs), logs[-10:]
        events = await backend.job_events("t-1")
        reasons = [e["reason"] for e in events]
        assert "Queued" in reasons and "Admitted" in reasons and "Succeeded" in reasons
        await backend.close()

    run(main())


def test_local_backend_queueing_and_cancel(tmp_path):
    async def main():
        backend, _ = _backend(tmp_path, quota=1)
        spec = _job_spec()
        flavor = backend.catalog.get("chip-1")
        for jid in ("q-1", "q-2"):
            await backend.submit(
                JobInput(job_id=jid, user_id="u", model_name="tiny-test-lora",
                         device="chip-1", arguments={}),
                spec, flavor, dataset_uri=None,
                artifacts_uri=f"obj://artifacts/u/{jid}",
            )
        # q-2 waits in queue while q-1 holds the only chip
        assert await backend.queue_snapshot() == ["q-2"]
        r2 = await backend.get_job("q-2")
        assert r2.state is BackendJobState.SUSPENDED
        # cancel q-1 -> q-2 admits
        assert await backend.delete_job("q-1")
        assert await backend.get_job("q-1") is None
        await _wait_state(
            backend, "q-2",
            {BackendJobState.CREATED, BackendJobState.RUNNING,
             BackendJobState.SUCCEEDED},
        )
        assert await backend.queue_snapshot() == []
        await backend.close()

    run(main())


def test_local_backend_failure_backoff(tmp_path):
    async def main():
        backend, _ = _backend(tmp_path)
        backend.backoff_limit = 1
        spec = _job_spec()
        # poison the spec post-render by pointing at a preset that doesn't exist
        job = JobInput(job_id="f-1", user_id="u", model_name="tiny-test-lora",
                       device="chip-1", arguments={})
        await backend.submit(
            job, spec, backend.catalog.get("chip-1"),
            dataset_uri=None, artifacts_uri="obj://artifacts/u/f-1",
        )
        handle = backend._handles["f-1"]
        bad = json.loads(handle.spec_path.read_text())
        bad["model"]["preset"] = "no-such-preset"
        handle.spec_path.write_text(json.dumps(bad))
        report = await _wait_state(
            backend, "f-1", {BackendJobState.FAILED}, timeout=120.0
        )
        assert report.metadata["restarts"] == 2  # 1 restart + final attempt counted
        events = await backend.job_events("f-1")
        assert any(e["reason"] == "Restarting" for e in events)
        await backend.close()

    run(main())


def test_local_backend_stages_dataset(tmp_path):
    async def main():
        backend, store = _backend(tmp_path)
        rows = b'{"text": "hello world hello world"}\n' * 8
        await store.put_bytes("obj://datasets/u/d1/train.jsonl", rows)
        job = JobInput(job_id="d-1", user_id="u", model_name="tiny-test-lora",
                       device="chip-1", arguments={})
        await backend.submit(
            job, _job_spec(), backend.catalog.get("chip-1"),
            dataset_uri="obj://datasets/u/d1/train.jsonl",
            artifacts_uri="obj://artifacts/u/d-1",
        )
        spec = json.loads((backend.root / "d-1" / "job.json").read_text())
        assert spec["dataset"]["path"].endswith("train.jsonl")
        await _wait_state(
            backend, "d-1", {BackendJobState.SUCCEEDED, BackendJobState.FAILED}
        )
        report = await backend.get_job("d-1")
        assert report.state is BackendJobState.SUCCEEDED
        await backend.close()

    run(main())


def test_admitted_without_handle_becomes_failed_tombstone(tmp_path):
    """ISSUE 5 satellite: a workload admitted after its handle vanished (a
    submit-path crash window) used to be silently released, leaving the DB
    job QUEUED forever.  It must now surface as a FAILED report that the
    retry supervisor classifies as an infra failure and requeues."""
    from finetune_controller_tpu.controller.monitor import JobMonitor
    from finetune_controller_tpu.controller.schemas import (
        DatabaseStatus,
        JobRecord,
    )
    from finetune_controller_tpu.controller.statestore import StateStore
    from finetune_controller_tpu.resilience.policy import RetryPolicy, classify_failure, FailureClass
    from finetune_controller_tpu.resilience.supervisor import RetrySupervisor

    async def main():
        backend, store = _backend(tmp_path, quota=1)
        spec = _job_spec()
        flavor = backend.catalog.get("chip-1")
        for jid in ("h-1", "h-2"):
            await backend.submit(
                JobInput(job_id=jid, user_id="u", model_name="tiny-test-lora",
                         device="chip-1", arguments={}),
                spec, flavor, dataset_uri=None,
                artifacts_uri=f"obj://artifacts/u/{jid}",
            )
        # simulate the crash window: h-2's handle is gone, its workload isn't
        backend._handles.pop("h-2")
        assert await backend.delete_job("h-1")  # frees the chip -> h-2 admits
        report = await backend.get_job("h-2")
        assert report is not None and report.state is BackendJobState.FAILED
        assert "backend error" in report.message
        # the message classifies as an infra failure (retryable)
        assert classify_failure(None, report.message) is FailureClass.INFRA
        assert any(r.job_id == "h-2" for r in await backend.list_jobs())

        # the monitor hands the tombstone to the supervisor -> RETRYING
        state = StateStore(tmp_path / "state")
        await state.connect()
        await state.create_job(JobRecord(
            job_id="h-2", user_id="u", model_name="tiny-test-lora",
            status=DatabaseStatus.QUEUED, device="chip-1",
        ))
        supervisor = RetrySupervisor(
            state, backend, backend.catalog,
            policy=RetryPolicy(max_attempts=3, base_delay_s=30.0, seed=0),
        )
        monitor = JobMonitor(state, store, backend, interval_s=0.1,
                             supervisor=supervisor)
        await monitor.tick()
        rec = await state.get_job("h-2")
        assert rec.status is DatabaseStatus.RETRYING, rec.metadata
        assert rec.metadata["failure_class"] == "infra"
        # the supervisor's substrate cleanup consumed the tombstone
        assert await backend.get_job("h-2") is None
        await backend.close()
        await state.close()

    run(main())


def test_warm_worker_pool_runs_job(tmp_path):
    """A pre-warmed trainer process (JAX already imported) picks up the job:
    the Started event records the warm worker, the job trains to success, and
    the pool is replenished for the next job."""
    import asyncio

    from finetune_controller_tpu.controller.backends.local import (
        LocalProcessBackend,
    )
    from finetune_controller_tpu.controller.datasets import upload_dataset_bytes
    from finetune_controller_tpu.controller.objectstore import LocalObjectStore
    from finetune_controller_tpu.controller.schemas import (
        BackendJobState,
        JobInput,
    )
    from finetune_controller_tpu.controller.statestore import StateStore
    from finetune_controller_tpu.controller.task_builder import (
        DatasetInput,
        task_builder,
    )

    from conftest import one_chip_catalog, run_async, tiny_job_spec

    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        catalog = one_chip_catalog()
        backend = LocalProcessBackend(
            tmp_path / "sandboxes", store, catalog,
            sync_interval_s=0.2, warm_workers=1,
        )
        await state.connect()
        await backend.prewarm()
        assert sum(len(p) for p in backend._warm.values()) == 1

        ds = await upload_dataset_bytes(
            store, state, user_id="u", filename="t.jsonl",
            data=b'{"text": "warm start"}\n' * 8, bucket="datasets",
        )
        await task_builder(
            JobInput(job_id="warm-1", user_id="u", model_name="tiny-test-lora",
                     device="chip-1", arguments={"total_steps": 2}),
            tiny_job_spec(2), DatasetInput(dataset_id=ds.dataset_id),
            state=state, store=store, backend=backend, catalog=catalog,
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        deadline = asyncio.get_event_loop().time() + 180
        while True:
            report = await backend.get_job("warm-1")
            if report.state in (BackendJobState.SUCCEEDED, BackendJobState.FAILED):
                break
            assert asyncio.get_event_loop().time() < deadline, report
            await asyncio.sleep(0.2)
        assert report.state is BackendJobState.SUCCEEDED, report

        events = await backend.job_events("warm-1")
        started = [e for e in events if e["reason"] == "Started"]
        assert started and "warm worker" in started[0]["message"], started
        # the job's trace identity reached the warm-claimed trainer via the
        # request line (the pooled process predates the job, so the spawn
        # env could not carry it): rank 0 recorded spans under the trace
        from finetune_controller_tpu.obs import (
            parse_event_lines,
            parse_span_lines,
        )

        rec = await state.get_job("warm-1")
        trace_id = rec.metadata["trace_id"]
        spans = parse_span_lines(
            await store.get_bytes(f"{rec.artifacts_uri}/trace/trainer.jsonl")
        )
        assert spans and all(s["trace_id"] == trace_id for s in spans)
        t_events = parse_event_lines(
            await store.get_bytes(f"{rec.artifacts_uri}/events.jsonl")
        )
        assert t_events and all(
            e["trace_id"] == trace_id and e["attrs"]["attempt"] == 1
            for e in t_events
        )
        # the claimed worker is replaced for the next job; the replenish runs
        # in the job task's finally block, so poll rather than assert a race
        deadline = asyncio.get_event_loop().time() + 30
        while sum(len(p) for p in backend._warm.values()) < 1:
            assert asyncio.get_event_loop().time() < deadline, backend._warm
            await asyncio.sleep(0.1)
        await backend.close()
        await state.close()

    run_async(main())
