"""Unit + integration tests for the resilience subsystem
(``finetune_controller_tpu/resilience/`` — docs/resilience.md).

Layers covered here (the end-to-end chaos runs live in tests/test_chaos.py):

* policy: exit classification, the attempt budget, decorrelated-jitter
  backoff bounds and seeded determinism;
* supervisor: schedule-on-failure, terminal user errors, attempt
  exhaustion, due-time resubmission, crash-safe re-adoption;
* monitor integration: FAILED routing, lost-job hand-off, the lease kill,
  plus the previously-untested ``_sweep_lost_jobs`` grace window and
  CANCELLED-cleanup paths;
* heartbeat: writer throttle/atomicity, lease-expiry decision table;
* faults: seeded store-fault determinism, kill-at-step once-file
  semantics;
* checkpoint hygiene: the ``step_N.tmp`` sweep regression test.
"""

import asyncio
import json
import os
import time

import pytest

from conftest import one_chip_catalog as _catalog
from conftest import run_async as run
from conftest import tiny_job_spec as _spec
from test_lifecycle import ScriptedBackend

from finetune_controller_tpu.controller import registry
from finetune_controller_tpu.controller.monitor import JobMonitor
from finetune_controller_tpu.controller.objectstore import LocalObjectStore
from finetune_controller_tpu.controller.schemas import (
    BackendJobReport,
    BackendJobState,
    DatabaseStatus,
    JobInput,
    JobRecord,
)
from finetune_controller_tpu.controller.statestore import StateStore
from finetune_controller_tpu.controller.task_builder import DatasetInput, task_builder
from finetune_controller_tpu.resilience import (
    FailureClass,
    HeartbeatWriter,
    LeaseChecker,
    RetryPolicy,
    StepFault,
    StepFaultInjector,
    classify_failure,
)
from finetune_controller_tpu.resilience.faults import (
    FaultInjectionError,
    FaultyObjectStore,
)
from finetune_controller_tpu.resilience.heartbeat import (
    HEARTBEAT_FILENAME,
    parse_heartbeat,
)
from finetune_controller_tpu.resilience.supervisor import RetrySupervisor


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------


def test_classify_failure_table():
    assert classify_failure(143) is FailureClass.PREEMPTION
    assert classify_failure(-15) is FailureClass.PREEMPTION
    assert classify_failure(137) is FailureClass.INFRA
    assert classify_failure(-9) is FailureClass.INFRA
    assert classify_failure(1) is FailureClass.USER
    assert classify_failure(2, "traceback follows") is FailureClass.USER
    # >128 is some other fatal signal: infrastructure, not the user's code
    assert classify_failure(139) is FailureClass.INFRA
    # message hints when the backend has no exit code
    assert classify_failure(None, "liveness lease expired") is FailureClass.INFRA
    assert classify_failure(None, "job no longer tracked by the backend") \
        is FailureClass.INFRA
    assert classify_failure(None, "resubmit failed: quota") is FailureClass.INFRA
    assert classify_failure(None, "") is FailureClass.UNKNOWN


def test_retry_policy_budget_and_terminal_classes():
    p = RetryPolicy(max_attempts=3, seed=0)
    assert p.should_retry(FailureClass.PREEMPTION, 1)
    assert p.should_retry(FailureClass.INFRA, 2)
    assert not p.should_retry(FailureClass.INFRA, 3)  # 3rd attempt was the last
    assert not p.should_retry(FailureClass.USER, 1)   # deterministic: terminal


def test_backoff_decorrelated_jitter_bounds_and_determinism():
    p = RetryPolicy(base_delay_s=1.0, max_delay_s=10.0, seed=42)
    delays = []
    prev = None
    for _ in range(50):
        d = p.next_delay(prev)
        hi = max(1.0, min(10.0, 3.0 * (prev or 1.0)))
        assert 1.0 <= d <= hi
        delays.append(d)
        prev = d
    assert all(d <= 10.0 for d in delays)  # cap holds even after growth
    # same seed, same schedule — the chaos harness depends on this
    p2 = RetryPolicy(base_delay_s=1.0, max_delay_s=10.0, seed=42)
    replay = []
    prev = None
    for _ in range(50):
        prev = p2.next_delay(prev)
        replay.append(prev)
    assert replay == delays


# ---------------------------------------------------------------------------
# checkpoint startup hygiene (satellite: stale step_N.tmp sweep)
# ---------------------------------------------------------------------------


def test_checkpoint_manager_sweeps_stale_tmp_dirs(tmp_path):
    from finetune_controller_tpu.train.checkpoint import CheckpointManager

    d = tmp_path / "ckpts"
    mgr = CheckpointManager(str(d), keep=3)
    mgr.save(1, {"w": [1.0, 2.0]}, blocking=True)
    # simulate a crash between makedirs(tmp) and os.replace in _save_msgpack
    stale = d / "step_9.tmp"
    stale.mkdir()
    (stale / "state.msgpack").write_bytes(b"partial")
    # ...and a SIGKILL mid-Orbax-save (observed shape in the chaos tests)
    stale_orbax = d / "step_7.orbax-checkpoint-tmp-1234567"
    stale_orbax.mkdir()
    mgr2 = CheckpointManager(str(d), keep=3)
    assert not stale.exists(), "stale .tmp staging dir must be swept on init"
    assert not stale_orbax.exists(), "stale orbax staging dir must be swept"
    assert mgr2.latest_step() == 1  # committed steps untouched
    # a future save of the swept step is not shadowed
    mgr2.save(9, {"w": [3.0, 4.0]}, blocking=True)
    assert mgr2.latest_step() == 9


def test_metrics_writer_truncates_replayed_rows_on_resume(tmp_path):
    """A crash after a logged row but before its checkpoint committed makes
    the resumed run replay those steps — the writer must drop the orphaned
    rows instead of duplicating them."""
    from finetune_controller_tpu.train.metrics import MetricsWriter

    w = MetricsWriter(str(tmp_path))
    for s in (10, 20, 30):
        w.write({"step": s, "loss": 1.0 / s})
    w.close()
    # resumed from the step-10 checkpoint: rows 20/30 were never committed
    w2 = MetricsWriter(str(tmp_path), append=True, resume_step=10)
    w2.write({"step": 20, "loss": 0.05})
    w2.close()
    with open(tmp_path / "metrics.csv", newline="") as f:
        import csv as _csv

        rows = list(_csv.DictReader(f))
    assert [int(float(r["step"])) for r in rows] == [10, 20]
    assert float(rows[1]["loss"]) == 0.05  # the replayed value, once


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------


async def _plane(tmp_path, *, clock, max_attempts=3):
    registry.reset()
    registry.load_builtin_models()
    state = StateStore(tmp_path / "state")
    store = LocalObjectStore(tmp_path / "objects")
    backend = ScriptedBackend()
    catalog = _catalog()
    supervisor = RetrySupervisor(
        state, backend, catalog,
        policy=RetryPolicy(
            max_attempts=max_attempts, base_delay_s=5.0, max_delay_s=5.0, seed=0,
        ),
        _clock=clock,
    )
    await state.connect()
    return state, store, backend, catalog, supervisor


async def _submit(state, store, backend, catalog, job_id="r-1"):
    spec = _spec()
    job = JobInput(
        job_id=job_id, user_id="u", model_name="tiny-test-lora",
        device="chip-1", arguments=spec.training_arguments.model_dump(),
    )
    await task_builder(
        job, spec, DatasetInput(),
        state=state, store=store, backend=backend, catalog=catalog,
        datasets_bucket="datasets", artifacts_bucket="artifacts",
    )
    return job


def test_supervisor_schedules_retry_then_resubmits_when_due(tmp_path):
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(tmp_path, clock=clock)
        await _submit(state, store, backend, catalog)

        job = await state.get_job("r-1")
        retried = await sup.on_job_failed(job, exit_code=137, message="exit code 137")
        assert retried
        rec = await state.get_job("r-1")
        assert rec.status is DatabaseStatus.RETRYING
        history = rec.metadata["attempt_history"]
        assert len(history) == 1
        assert history[0]["failure_class"] == "infra"
        assert history[0]["exit_code"] == 137
        assert rec.metadata["retry_next_at"] == pytest.approx(
            clock.t + history[0]["delay_s"]
        )
        assert "r-1" in backend.deleted  # substrate half cleared immediately

        # before the backoff expires nothing happens
        assert await sup.tick() == 0
        assert (await state.get_job("r-1")).status is DatabaseStatus.RETRYING

        clock.advance(history[0]["delay_s"] + 0.1)
        assert await sup.tick() == 1
        rec = await state.get_job("r-1")
        assert rec.status is DatabaseStatus.QUEUED
        assert rec.metadata["retry_next_at"] is None
        assert rec.start_time is None and rec.end_time is None
        assert rec.submitted_at == clock.t  # grace window restarted
        assert "r-1" in backend.reports  # really resubmitted to the backend
        assert sup.resubmits == 1

    run(main())


def test_supervisor_user_error_is_terminal(tmp_path):
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(tmp_path, clock=clock)
        await _submit(state, store, backend, catalog)
        job = await state.get_job("r-1")
        retried = await sup.on_job_failed(
            job, exit_code=1, message="exit code 1 after 1 attempts"
        )
        assert not retried
        rec = await state.get_job("r-1")
        assert rec.status is DatabaseStatus.FAILED
        assert rec.metadata["failure_class"] == "user"
        assert rec.metadata["attempt_history"][0]["delay_s"] is None
        clock.advance(1e6)
        assert await sup.tick() == 0  # nothing to resubmit, ever
        assert sup.terminal_failures == 1

    run(main())


def test_supervisor_exhausts_attempt_budget(tmp_path):
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(
            tmp_path, clock=clock, max_attempts=2
        )
        await _submit(state, store, backend, catalog)
        job = await state.get_job("r-1")
        assert await sup.on_job_failed(job, exit_code=143, message="preempted")
        clock.advance(10)
        assert await sup.tick() == 1
        job = await state.get_job("r-1")
        assert job.status is DatabaseStatus.QUEUED
        # second (and per the budget: last) attempt dies too
        assert not await sup.on_job_failed(job, exit_code=143, message="preempted")
        rec = await state.get_job("r-1")
        assert rec.status is DatabaseStatus.FAILED
        assert len(rec.metadata["attempt_history"]) == 2
        assert rec.metadata["failure_class"] == "preemption"

    run(main())


def test_supervisor_failed_resubmit_burns_an_attempt(tmp_path):
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(
            tmp_path, clock=clock, max_attempts=2
        )
        await _submit(state, store, backend, catalog)

        async def exploding_submit(*a, **k):
            raise RuntimeError("no quota")

        job = await state.get_job("r-1")
        await sup.on_job_failed(job, exit_code=137, message="exit code 137")
        backend.submit = exploding_submit
        clock.advance(10)
        assert await sup.tick() == 0
        rec = await state.get_job("r-1")
        # attempt 2 of 2 spent on the failed resubmit -> terminal
        assert rec.status is DatabaseStatus.FAILED
        assert len(rec.metadata["attempt_history"]) == 2
        assert "resubmit failed" in rec.metadata["attempt_history"][1]["message"]

    run(main())


def test_resubmit_lost_race_to_cancel_rolls_back(tmp_path):
    """A user cancel landing inside the resubmit's await window must win:
    the CAS transition fails and the freshly-spawned backend half is rolled
    back instead of resurrecting a cancelled job."""

    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(tmp_path, clock=clock)
        await _submit(state, store, backend, catalog)
        job = await state.get_job("r-1")
        await sup.on_job_failed(job, exit_code=137, message="exit code 137")

        orig_submit = backend.submit

        async def submit_then_cancel(*a, **k):
            await orig_submit(*a, **k)
            # the interleaved cancel (server handler on the same loop)
            await state.update_job_status("r-1", DatabaseStatus.CANCELLED)

        backend.submit = submit_then_cancel
        clock.advance(100)
        assert await sup.tick() == 0
        rec = await state.get_job("r-1")
        assert rec.status is DatabaseStatus.CANCELLED  # the cancel stuck
        assert backend.deleted.count("r-1") == 2  # schedule-time + rollback
        assert sup.resubmits == 0

        # a cancel BEFORE the due time is caught by the pre-submit recheck
        await _submit(state, store, backend, catalog, job_id="r-2")
        job2 = await state.get_job("r-2")
        await sup.on_job_failed(job2, exit_code=137, message="exit code 137")
        await state.update_job_status("r-2", DatabaseStatus.CANCELLED)
        clock.advance(100)
        assert await sup.tick() == 0
        assert (await state.get_job("r-2")).status is DatabaseStatus.CANCELLED

    run(main())


def test_failure_intake_lost_race_to_cancel_leaves_job_alone(tmp_path):
    """on_job_failed CAS-es from the caller's status snapshot: a cancel that
    interleaved since the snapshot wins — no RETRYING overwrite, no attempt
    recorded, no later resubmission of a cancelled job."""

    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(tmp_path, clock=clock)
        await _submit(state, store, backend, catalog)
        stale = await state.get_job("r-1")  # snapshot: QUEUED
        await state.update_job_status("r-1", DatabaseStatus.CANCELLED)
        assert not await sup.on_job_failed(
            stale, exit_code=137, message="exit code 137"
        )
        rec = await state.get_job("r-1")
        assert rec.status is DatabaseStatus.CANCELLED
        assert rec.metadata.get("attempt_history") in (None, [])
        assert sup.retries_scheduled == 0
        clock.advance(1e6)
        assert await sup.tick() == 0

    run(main())


def test_retrying_job_with_missing_due_time_self_heals(tmp_path):
    """A crash between the RETRYING status write and the metadata merge
    leaves retry_next_at unset — tick must treat that as due NOW, not skip
    the job forever."""

    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(tmp_path, clock=clock)
        await _submit(state, store, backend, catalog)
        # simulate the torn write: status flipped, metadata merge lost
        await state.update_job_status("r-1", DatabaseStatus.RETRYING)
        assert await sup.tick() == 1
        assert (await state.get_job("r-1")).status is DatabaseStatus.QUEUED

    run(main())


def test_heartbeat_writer_swallows_write_failures(tmp_path):
    clock = FakeClock()
    hb = HeartbeatWriter(
        str(tmp_path / "missing" / "dir"), interval_s=1.0, _clock=clock
    )
    assert hb.beat(1, force=True) is False  # failed, but did NOT raise
    assert hb.write_failures == 1 and hb.beats == 0


def test_delete_job_escalates_to_sigkill_for_sigterm_ignorers(tmp_path):
    """A trainer hung hard enough to trip the lease may ignore SIGTERM; the
    substrate half must still be DEAD before delete_job returns, or the
    respawn shares the sandbox with the old writer."""
    import sys

    from finetune_controller_tpu.controller.backends.local import (
        LocalProcessBackend,
        _JobHandle,
    )

    async def main():
        store = LocalObjectStore(tmp_path / "objects")
        backend = LocalProcessBackend(tmp_path / "sandboxes", store, _catalog())
        backend.term_grace_s = 0.5
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-c",
            "import signal, time; signal.signal(signal.SIGTERM, signal.SIG_IGN);"
            "print('armed', flush=True); time.sleep(120)",
            stdout=asyncio.subprocess.PIPE,
        )
        await proc.stdout.readline()  # SIG_IGN installed
        handle = _JobHandle("stuck-1", tmp_path / "sandboxes" / "stuck-1",
                            "obj://artifacts/x", [])
        handle.proc = proc
        backend._handles["stuck-1"] = handle
        backend.scheduler.submit("stuck-1", "chip-1", 1)
        assert await backend.delete_job("stuck-1")
        assert proc.returncode == -9  # SIGKILL landed; process is gone

    run(main())


def test_statestore_transition_job_status_cas(tmp_path):
    async def main():
        state = StateStore(tmp_path / "state")
        await state.connect()
        await state.create_job(JobRecord(job_id="t-1", user_id="u", model_name="m"))
        # expect mismatch: no write
        ok = await state.transition_job_status(
            "t-1", DatabaseStatus.RETRYING, DatabaseStatus.QUEUED
        )
        assert not ok
        assert (await state.get_job("t-1")).status is DatabaseStatus.QUEUED
        # expect match: transition + metadata merge + fields
        ok = await state.transition_job_status(
            "t-1", DatabaseStatus.QUEUED, DatabaseStatus.RUNNING,
            metadata={"note": "cas"}, start_time=5.0,
        )
        assert ok
        rec = await state.get_job("t-1")
        assert rec.status is DatabaseStatus.RUNNING
        assert rec.metadata["note"] == "cas" and rec.start_time == 5.0

    run(main())


def test_supervisor_readopts_retrying_jobs_across_restart(tmp_path):
    """Crash-safety: the schedule lives in the job document, so a brand-new
    supervisor (fresh process) resubmits a due RETRYING job it never saw."""

    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(tmp_path, clock=clock)
        await _submit(state, store, backend, catalog)
        job = await state.get_job("r-1")
        await sup.on_job_failed(job, exit_code=137, message="exit code 137")
        clock.advance(100)
        # "restart": a different supervisor instance over the same store
        sup2 = RetrySupervisor(
            state, backend, catalog, policy=RetryPolicy(seed=1), _clock=clock
        )
        assert await sup2.tick() == 1
        assert (await state.get_job("r-1")).status is DatabaseStatus.QUEUED

    run(main())


# ---------------------------------------------------------------------------
# monitor integration
# ---------------------------------------------------------------------------


def test_monitor_routes_failed_report_to_supervisor(tmp_path):
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(tmp_path, clock=clock)
        monitor = JobMonitor(state, store, backend, interval_s=0.1, supervisor=sup)
        await _submit(state, store, backend, catalog)
        backend.reports["r-1"] = BackendJobReport(
            job_id="r-1", state=BackendJobState.FAILED,
            start_time=1.0, completion_time=2.0,
            message="exit code 137 after 1 attempts",
            metadata={"exit_code": 137, "restarts": 0},
        )
        await monitor.tick()
        rec = await state.get_job("r-1")
        assert rec.status is DatabaseStatus.RETRYING
        assert rec.metadata["exit_code"] == 137  # forensics persisted
        assert rec.metadata["failure_class"] == "infra"
        # report was cleared with the substrate; further ticks must not burn
        # more attempts while the job waits out its backoff
        await monitor.tick()
        rec = await state.get_job("r-1")
        assert len(rec.metadata["attempt_history"]) == 1

        clock.advance(100)
        await monitor.tick()  # monitor drives supervisor.tick -> resubmit
        assert (await state.get_job("r-1")).status is DatabaseStatus.QUEUED

    run(main())


def test_monitor_retrying_job_ignores_stale_backend_report(tmp_path):
    """A FAILED report that lingers after the supervisor scheduled a retry
    (delete raced) must not re-fail the RETRYING job or burn attempts."""

    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(tmp_path, clock=clock)
        monitor = JobMonitor(state, store, backend, interval_s=0.1, supervisor=sup)
        await _submit(state, store, backend, catalog)
        job = await state.get_job("r-1")
        await sup.on_job_failed(job, exit_code=137, message="exit code 137")
        # resurrect a stale report the delete should have removed
        backend.reports["r-1"] = BackendJobReport(
            job_id="r-1", state=BackendJobState.FAILED,
            message="exit code 137 after 1 attempts",
            metadata={"exit_code": 137},
        )
        await monitor.tick()
        rec = await state.get_job("r-1")
        assert rec.status is DatabaseStatus.RETRYING
        assert len(rec.metadata["attempt_history"]) == 1

    run(main())


def test_monitor_without_supervisor_persists_failure_class(tmp_path):
    """Satellite: even with retries disabled, FAILED jobs carry exit_code +
    failure_class in metadata so users can tell OOM from bad hyperparams."""

    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        backend = ScriptedBackend()
        monitor = JobMonitor(state, store, backend, interval_s=0.1)
        await state.connect()
        await _submit(state, store, backend, _catalog(), job_id="nf-1")
        backend.reports["nf-1"] = BackendJobReport(
            job_id="nf-1", state=BackendJobState.FAILED,
            message="exit code 137 after 3 attempts",
            metadata={"exit_code": 137, "restarts": 2},
        )
        await monitor.tick()
        rec = await state.get_job("nf-1")
        assert rec.status is DatabaseStatus.FAILED
        assert rec.metadata["exit_code"] == 137
        assert rec.metadata["failure_class"] == "infra"
        assert backend.deleted == []  # forensics behavior unchanged

    run(main())


def test_monitor_routes_lost_job_to_supervisor(tmp_path):
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(tmp_path, clock=clock)
        monitor = JobMonitor(state, store, backend, interval_s=0.1, supervisor=sup)
        monitor.lost_job_grace_s = 0.0
        await _submit(state, store, backend, catalog)
        backend.reports.clear()  # substrate restart: the backend forgot it
        await monitor.tick()
        rec = await state.get_job("r-1")
        assert rec.status is DatabaseStatus.RETRYING
        assert rec.metadata["failure_class"] == "infra"
        clock.advance(100)
        await monitor.tick()
        assert (await state.get_job("r-1")).status is DatabaseStatus.QUEUED

    run(main())


def test_sweep_grace_window_spares_fresh_jobs(tmp_path):
    """Satellite: a job inside the lost-job grace window (just submitted,
    maybe still in the submit path) must NOT be declared lost."""

    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        backend = ScriptedBackend()
        monitor = JobMonitor(state, store, backend, interval_s=0.1)
        assert monitor.lost_job_grace_s == 30.0  # the documented default
        await state.connect()
        await _submit(state, store, backend, _catalog(), job_id="g-1")
        backend.reports.clear()
        await monitor.tick()  # submitted_at is ~now -> inside the window
        assert (await state.get_job("g-1")).status is DatabaseStatus.QUEUED

        # age the record past the window -> swept
        await state.update_job_fields("g-1", submitted_at=time.time() - 60)
        await monitor.tick()
        assert (await state.get_job("g-1")).status is DatabaseStatus.UNKNOWN
        # already-UNKNOWN jobs are not re-swept (no duplicate updates)
        await monitor.tick()
        assert (await state.get_job("g-1")).status is DatabaseStatus.UNKNOWN

    run(main())


def test_sweep_exempts_retrying_jobs(tmp_path):
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(tmp_path, clock=clock)
        monitor = JobMonitor(state, store, backend, interval_s=0.1, supervisor=sup)
        monitor.lost_job_grace_s = 0.0
        await _submit(state, store, backend, catalog)
        job = await state.get_job("r-1")
        await sup.on_job_failed(job, exit_code=137, message="exit code 137")
        # RETRYING by design has no backend half; the sweep must leave it be
        await monitor.tick()
        rec = await state.get_job("r-1")
        assert rec.status is DatabaseStatus.RETRYING
        assert len(rec.metadata["attempt_history"]) == 1

    run(main())


def test_cancelled_job_cleanup_with_and_without_backend_half(tmp_path):
    """Satellite: the CANCELLED branch — backend half present (cleaned on
    every tick until gone) and absent (tick is a no-op, no crash)."""

    async def main():
        state = StateStore(tmp_path / "state")
        store = LocalObjectStore(tmp_path / "objects")
        backend = ScriptedBackend()
        monitor = JobMonitor(state, store, backend, interval_s=0.1)
        await state.connect()
        await _submit(state, store, backend, _catalog(), job_id="c-1")
        await state.update_job_status("c-1", DatabaseStatus.CANCELLED)
        await monitor.tick()
        assert backend.deleted == ["c-1"]
        assert "c-1" not in backend.reports
        # backend half is gone now: ticking again must neither crash nor
        # re-delete (CANCELLED is final, the sweep exempts final states)
        await monitor.tick()
        assert backend.deleted == ["c-1"]
        assert (await state.get_job("c-1")).status is DatabaseStatus.CANCELLED

    run(main())


def test_monitor_lease_kill_requeues_stuck_job(tmp_path):
    """A RUNNING job with a stale heartbeat is killed and requeued."""

    async def main():
        clock = FakeClock(t=10_000.0)
        state, store, backend, catalog, sup = await _plane(tmp_path, clock=clock)
        lease = LeaseChecker(store, lease_s=120.0, _clock=clock)
        monitor = JobMonitor(
            state, store, backend, interval_s=0.1, supervisor=sup, lease=lease
        )
        await _submit(state, store, backend, catalog)
        rec = await state.get_job("r-1")
        backend.reports["r-1"] = BackendJobReport(
            job_id="r-1", state=BackendJobState.RUNNING, start_time=clock.t - 500,
        )
        # fresh heartbeat: healthy
        await store.put_bytes(
            f"{rec.artifacts_uri}/{HEARTBEAT_FILENAME}",
            json.dumps({"step": 10, "ts": clock.t - 30}).encode(),
        )
        await monitor.tick()
        assert (await state.get_job("r-1")).status is DatabaseStatus.RUNNING
        assert monitor.lease_kills == 0

        # heartbeat goes stale past the lease: stuck -> killed -> RETRYING
        clock.advance(200)
        await monitor.tick()
        assert monitor.lease_kills == 1
        assert "r-1" in backend.deleted
        rec = await state.get_job("r-1")
        assert rec.status is DatabaseStatus.RETRYING
        assert rec.metadata["failure_class"] == "infra"
        assert "lease expired" in rec.metadata["attempt_history"][0]["message"]

    run(main())


# ---------------------------------------------------------------------------
# heartbeat writer + lease decision table
# ---------------------------------------------------------------------------


def test_heartbeat_writer_throttles_and_writes_atomically(tmp_path):
    clock = FakeClock(t=100.0)
    hb = HeartbeatWriter(str(tmp_path), interval_s=10.0, _clock=clock)
    assert hb.beat(1)  # first beat always writes
    assert not hb.beat(2)  # throttled
    clock.advance(5)
    assert not hb.beat(3)
    clock.advance(6)
    assert hb.beat(4)
    assert hb.beat(5, force=True)  # force bypasses the throttle
    doc = parse_heartbeat((tmp_path / HEARTBEAT_FILENAME).read_bytes())
    assert doc["step"] == 5 and doc["ts"] == clock.t
    assert hb.beats == 3
    assert not (tmp_path / f"{HEARTBEAT_FILENAME}.tmp").exists()


def test_parse_heartbeat_rejects_torn_or_alien_payloads():
    assert parse_heartbeat(b"{ torn") is None
    assert parse_heartbeat(b"[1, 2]") is None
    assert parse_heartbeat(b'{"step": 1}') is None  # no ts
    assert parse_heartbeat(b'{"ts": "soon"}') is None
    assert parse_heartbeat(b'{"ts": 5.0, "step": 1}')["ts"] == 5.0


def test_lease_checker_decision_table(tmp_path):
    async def main():
        clock = FakeClock(t=10_000.0)
        store = LocalObjectStore(tmp_path / "objects")
        lease = LeaseChecker(store, lease_s=100.0, _clock=clock)
        job = JobRecord(
            job_id="l-1", user_id="u", model_name="m",
            artifacts_uri="obj://artifacts/finetune_jobs/u/l-1/artifacts",
        )
        report = BackendJobReport(
            job_id="l-1", state=BackendJobState.RUNNING, start_time=9_000.0
        )
        uri = f"{job.artifacts_uri}/{HEARTBEAT_FILENAME}"

        # 1. no heartbeat ever -> the lease does not bind
        assert not await lease.expired(job, report)
        # 2. fresh heartbeat -> healthy
        await store.put_bytes(uri, json.dumps({"step": 5, "ts": 9_950.0}).encode())
        assert not await lease.expired(job, report)
        # 3. stale heartbeat -> expired
        await store.put_bytes(uri, json.dumps({"step": 5, "ts": 9_800.0}).encode())
        assert await lease.expired(job, report)
        # 4. heartbeat older than the CURRENT attempt's start -> previous
        #    attempt's dying breath; the new attempt gets grace
        report2 = BackendJobReport(
            job_id="l-1", state=BackendJobState.RUNNING, start_time=9_900.0
        )
        assert not await lease.expired(job, report2)
        # 5. torn file -> never kills
        await store.put_bytes(uri, b"{ torn")
        assert not await lease.expired(job, report)
        # 6. lease disabled
        off = LeaseChecker(store, lease_s=0.0, _clock=clock)
        assert not await off.expired(job, report)

    run(main())


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------


def test_step_fault_env_roundtrip_and_once_file(tmp_path):
    once = str(tmp_path / "fired")
    fault = StepFault(kill_at_step=7, signum=15, once_file=once)
    env = fault.to_env()
    assert StepFault.from_env(env) == fault
    assert StepFault.from_env({}) is None
    assert StepFault.from_env({"FTC_FAULT_KILL_AT_STEP": "nope"}) is None

    # signum 0 is the no-op "liveness probe" signal: safe to send to self
    inj = StepFaultInjector(StepFault(kill_at_step=3, signum=0, once_file=once))
    assert not inj.maybe_fire(1)
    assert not inj.maybe_fire(2)
    assert inj.maybe_fire(3)
    assert os.path.exists(once)
    assert not inj.maybe_fire(4)  # fired flag
    # a respawned attempt (fresh injector, same once file) stays clean
    inj2 = StepFaultInjector(StepFault(kill_at_step=3, signum=0, once_file=once))
    assert not inj2.maybe_fire(3)
    # past-the-step arming still fires (cadence may skip the exact step)
    inj3 = StepFaultInjector(StepFault(kill_at_step=3, signum=0))
    assert inj3.maybe_fire(5)


def test_faulty_object_store_is_seed_deterministic(tmp_path):
    async def main():
        async def failure_mask(seed):
            inner = LocalObjectStore(tmp_path / f"objects_{seed}")
            store = FaultyObjectStore(inner, write_error_rate=0.5, seed=seed)
            mask = []
            for i in range(20):
                try:
                    await store.put_bytes(f"obj://b/k{i}", b"x")
                    mask.append(False)
                except FaultInjectionError:
                    mask.append(True)
            return mask, store

        mask_a, store_a = await failure_mask(7)
        mask_b, _ = await failure_mask(7)
        mask_c, _ = await failure_mask(8)
        assert mask_a == mask_b  # same seed, same schedule
        assert mask_a != mask_c  # different seed, different schedule
        assert any(mask_a) and not all(mask_a)
        assert store_a.injected_errors == sum(mask_a)
        # reads pass through untouched (and succeed for committed writes)
        ok = [i for i, failed in enumerate(mask_a) if not failed]
        assert await store_a.get_bytes(f"obj://b/k{ok[0]}") == b"x"

    run(main())


def test_faulty_object_store_slow_io_delays_writes(tmp_path):
    async def main():
        inner = LocalObjectStore(tmp_path / "objects")
        store = FaultyObjectStore(inner, slow_io_s=0.05, seed=0)
        t0 = time.perf_counter()
        await store.put_bytes("obj://b/slow", b"x")
        assert time.perf_counter() - t0 >= 0.05
        assert await store.get_bytes("obj://b/slow") == b"x"

    run(main())


# ---------------------------------------------------------------------------
# resume staging (the backend half of the resubmit contract)
# ---------------------------------------------------------------------------


def test_local_backend_stages_checkpoints_into_fresh_sandbox(tmp_path):
    """Resubmit onto a LOST sandbox: committed checkpoints and the metrics
    history come back from the object store; stale heartbeat and done.txt
    deliberately do not."""
    from finetune_controller_tpu.controller.backends.local import (
        LocalProcessBackend,
        _JobHandle,
    )

    async def main():
        store = LocalObjectStore(tmp_path / "objects")
        backend = LocalProcessBackend(
            tmp_path / "sandboxes", store, _catalog(), sync_interval_s=60
        )
        uri = "obj://artifacts/finetune_jobs/u/s-1/artifacts"
        await store.put_bytes(f"{uri}/checkpoints/step_20/state.msgpack", b"ck20")
        await store.put_bytes(f"{uri}/checkpoints/step_10/state.msgpack", b"ck10")
        await store.put_bytes(f"{uri}/metrics.csv", b"step,loss\n10,2.0\n")
        await store.put_bytes(f"{uri}/{HEARTBEAT_FILENAME}", b'{"ts": 1.0}')
        await store.put_bytes(f"{uri}/resolved_config.json", b"{}")

        sandbox = tmp_path / "sandboxes" / "s-1"
        handle = _JobHandle("s-1", sandbox, uri, ["*.csv", "checkpoints/**/*"])
        handle.artifacts_dir.mkdir(parents=True)
        await backend._stage_resume_state(handle)

        art = handle.artifacts_dir
        assert (art / "checkpoints/step_20/state.msgpack").read_bytes() == b"ck20"
        assert (art / "checkpoints/step_10/state.msgpack").read_bytes() == b"ck10"
        assert (art / "metrics.csv").exists()
        assert not (art / HEARTBEAT_FILENAME).exists()
        assert not (art / "resolved_config.json").exists()
        assert handle.restored_checkpoints == 3
        # the sync sidecar must not re-upload what was just pulled down
        assert set(handle.synced) == {
            "checkpoints/step_20/state.msgpack",
            "checkpoints/step_10/state.msgpack",
            "metrics.csv",
        }

        # a sandbox that SURVIVED is left untouched (no redundant downloads)
        handle2 = _JobHandle("s-1", sandbox, uri, [])
        await backend._stage_resume_state(handle2)
        assert handle2.restored_checkpoints == 0

    run(main())


# ---------------------------------------------------------------------------
# surfacing: the admin route's data source
# ---------------------------------------------------------------------------


def test_supervisor_pending_retries_snapshot(tmp_path):
    async def main():
        clock = FakeClock()
        state, store, backend, catalog, sup = await _plane(tmp_path, clock=clock)
        await _submit(state, store, backend, catalog)
        job = await state.get_job("r-1")
        await sup.on_job_failed(job, exit_code=137, message="exit code 137")
        pending = await sup.pending_retries()
        assert len(pending) == 1
        assert pending[0]["job_id"] == "r-1"
        assert pending[0]["attempts"] == 1
        assert pending[0]["failure_class"] == "infra"
        assert pending[0]["retry_next_at"] > clock.t

    run(main())
