"""The /metrics Prometheus endpoint: exposition format, content type, label
escaping, and the serve-plane gauges (ISSUE 4 satellite — the endpoint
shipped untested).
"""

from __future__ import annotations

import re

import pytest

from conftest import run_async
from finetune_controller_tpu.controller.server import (
    PROMETHEUS_CONTENT_TYPE,
    prom_escape,
)

#: exposition-format sample line: name{labels} value
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.e+-]+$"
)


def test_prom_escape():
    assert prom_escape('plain') == "plain"
    assert prom_escape('a"b') == 'a\\"b'
    assert prom_escape("a\\b") == "a\\\\b"
    assert prom_escape("a\nb") == "a\\nb"
    # composed: every dangerous char in one value stays one logical line
    hostile = 'x"\\\n'
    escaped = prom_escape(hostile)
    assert "\n" not in escaped


def test_metrics_format_and_content_type(tmp_path):
    from test_api import _client, _runtime

    async def main():
        client = await _client(_runtime(tmp_path), with_monitor=False)
        r = await client.get("/metrics")
        assert r.status == 200
        # text/plain; version=0.0.4 is the Prometheus exposition contract;
        # a bare text/plain parses but is ambiguous to scrapers
        assert r.headers["Content-Type"] == PROMETHEUS_CONTENT_TYPE
        body = await r.text()
        assert body.endswith("\n")
        types_seen = set()
        for line in body.strip().split("\n"):
            if line.startswith("# TYPE "):
                _, _, name, kind = line.split(" ")
                assert kind in ("counter", "gauge", "histogram"), line
                types_seen.add(name)
            elif line.startswith("# HELP "):
                continue
            else:
                assert _SAMPLE_RE.match(line), f"malformed sample: {line!r}"
        assert "ftc_monitor_ticks_total" in types_seen
        assert "ftc_jobs_active" in types_seen
        # observability layer (docs/observability.md): histogram families
        # announce themselves even before any observation, and every process
        # exports its identity + uptime
        assert "ftc_step_phase_ms" in types_seen
        assert "ftc_queue_wait_seconds" in types_seen
        assert "ftc_serve_ttft_seconds" in types_seen
        assert "ftc_build_info" in types_seen
        assert 'ftc_build_info{process="server"' in body
        assert 'ftc_uptime_seconds{process="server"}' in body
        await client.close()

    run_async(main())


def test_metrics_jobs_active_counts(tmp_path):
    from test_api import _client, _runtime
    from finetune_controller_tpu.controller.schemas import JobRecord

    async def main():
        rt = _runtime(tmp_path)
        client = await _client(rt, with_monitor=False)
        await rt.state.create_job(JobRecord(
            job_id="m-1", user_id="dev-user", model_name="tiny-test-lora",
        ))
        body = await (await client.get("/metrics")).text()
        # a non-final job shows up under its active status label
        assert 'ftc_jobs_active{status="queued"} 1' in body
        await client.close()

    run_async(main())


def test_metrics_sched_gauges(tmp_path):
    """The fair-share scheduler exports per-queue depth/share/borrowed
    gauges and the cluster preemption counter (docs/scheduling.md)."""
    from test_api import _client, _runtime
    from finetune_controller_tpu.sched import FairShareScheduler

    async def main():
        rt = _runtime(tmp_path)
        client = await _client(rt, with_monitor=False)
        # populate a scheduler directly (no subprocesses): one admitted
        # high-priority job, one pending low-priority job, one preemption
        sched = FairShareScheduler(rt.catalog, {"prod": 4.0, "batch": 1.0})
        sched.submit("m-lo", "chip-1", 2, queue="batch", priority="low")
        sched.try_admit()
        sched.submit("m-hi", "chip-1", 2, queue="prod", priority="high")
        sched.try_admit()
        assert [d.pair for d in sched.take_preemptions()] == [("m-lo", "m-hi")]
        rt.backend.scheduler = sched

        body = await (await client.get("/metrics")).text()
        assert 'ftc_sched_queue_depth{queue="prod"} 1' in body
        assert 'ftc_sched_queue_running{queue="batch"} 1' in body
        assert 'ftc_sched_queue_preemptions_total{queue="batch"} 1' in body
        assert "ftc_sched_preemptions_total 1" in body
        assert 'ftc_sched_queue_dominant_share{queue="batch"}' in body
        assert 'ftc_sched_queue_borrowed_chips{queue="batch"}' in body
        # elasticity counters (docs/elasticity.md)
        assert 'ftc_sched_queue_resizes_total{queue="batch"} 0' in body
        assert "ftc_sched_resizes_total 0" in body
        assert "ftc_sched_shrunk_workloads 0" in body
        await client.close()

    run_async(main())


def test_metrics_dpo_gauges(tmp_path):
    """Active dpo/rlhf jobs export their newest metrics row as ftc_dpo_*
    gauges (reward margin + the rollout-loop health triple); SFT jobs and
    absent columns emit nothing (docs/preference.md)."""
    from test_api import _client, _runtime
    from finetune_controller_tpu.controller.schemas import (
        DatabaseStatus,
        JobRecord,
        MetricsDocument,
    )

    async def main():
        rt = _runtime(tmp_path)
        client = await _client(rt, with_monitor=False)
        await rt.state.create_job(JobRecord(
            job_id="dpo-1", user_id="dev-user", model_name="tiny-dpo-test",
            status=DatabaseStatus.RUNNING, metadata={"task": "dpo"},
        ))
        await rt.state.create_job(JobRecord(
            job_id="rlhf-1", user_id="dev-user", model_name="tiny-rlhf-test",
            status=DatabaseStatus.RUNNING, metadata={"task": "rlhf"},
        ))
        await rt.state.create_job(JobRecord(
            job_id="sft-1", user_id="dev-user", model_name="tiny-test-lora",
            status=DatabaseStatus.RUNNING, metadata={"task": "causal_lm"},
        ))
        await rt.state.upsert_metrics(MetricsDocument(
            job_id="dpo-1",
            records=[{"step": 10, "reward_margin": 0.42, "dpo_accuracy": 0.9}],
        ))
        await rt.state.upsert_metrics(MetricsDocument(
            job_id="rlhf-1",
            records=[{"step": 5, "reward_margin": 0.1, "dpo_accuracy": 0.6,
                      "rollout_buffer_depth": 12, "rollout_staleness": 5,
                      "actor_tokens_per_sec": 133.5}],
        ))
        body = await (await client.get("/metrics")).text()
        assert 'ftc_dpo_reward_margin{job_id="dpo-1"} 0.42' in body
        assert 'ftc_dpo_accuracy{job_id="dpo-1"} 0.9' in body
        assert 'ftc_dpo_reward_margin{job_id="rlhf-1"} 0.1' in body
        # the rollout triple only exists for the actor/learner job
        assert 'ftc_dpo_rollout_buffer_depth{job_id="rlhf-1"} 12' in body
        assert 'ftc_dpo_rollout_staleness{job_id="rlhf-1"} 5' in body
        assert 'ftc_dpo_actor_tokens_per_sec{job_id="rlhf-1"} 133.5' in body
        assert 'ftc_dpo_rollout_buffer_depth{job_id="dpo-1"}' not in body
        assert 'job_id="sft-1"' not in body
        await client.close()

    run_async(main())


@pytest.mark.slow  # runs on every ci_check gate via the serve-fast stage
def test_metrics_serve_gauges_after_generate(tmp_path):
    """The serve plane exports queue/slot/token gauges per loaded job
    (fabricated promoted job — no trainer subprocess, keeps tier-1 fast)."""
    from test_api import _client
    from test_serve import _fabricate_promoted_job, _serve_runtime

    async def main():
        rt = _serve_runtime(tmp_path)
        client = await _client(rt, with_monitor=False)
        job_id = await _fabricate_promoted_job(rt)
        for _ in range(2):  # the identical repeat is a prefix-cache hit
            r = await client.post(
                f"/api/v1/jobs/{job_id}/generate",
                json={"tokens": [5, 9, 2, 7], "max_new_tokens": 5},
            )
            assert r.status == 200, await r.text()

        body = await (await client.get("/metrics")).text()
        assert "ftc_serve_models_loaded 1" in body
        label = f'job_id="{job_id}"'
        assert f"ftc_serve_tokens_generated_total{{{label}}} 10" in body
        assert f"ftc_serve_requests_completed_total{{{label}}} 2" in body
        assert f"ftc_serve_slots_total{{{label}}} {rt.settings.serve_slots}" in body
        assert f"ftc_serve_queue_depth{{{label}}} 0" in body
        assert f"ftc_serve_slots_busy{{{label}}} 0" in body
        # prefix-reuse counters (ISSUE 6): one cold miss, one exact-key hit
        # that reused all but the final prompt token
        assert f"ftc_serve_prefix_misses_total{{{label}}} 1" in body
        assert f"ftc_serve_prefix_hits_total{{{label}}} 1" in body
        assert f"ftc_serve_prefill_tokens_saved_total{{{label}}} 3" in body
        m = re.search(
            rf"ftc_serve_prefix_cache_bytes\{{{re.escape(label)}\}} (\d+)",
            body,
        )
        assert m is not None and int(m.group(1)) > 0
        # compile count stayed within the bucket-bounded budget (fill and
        # fill_from per bucket + the decode step, since the cache is on)
        m = re.search(
            rf"ftc_serve_compilations\{{{re.escape(label)}\}} (\d+)", body
        )
        assert m is not None
        assert int(m.group(1)) <= 2 * len(rt.settings.serve_prompt_buckets) + 1
        await client.close()

    run_async(main())
