"""Tests for job specs, the model registry, and auth.

Covers SURVEY.md §2 components 2,3,4: subclass type enforcement
(reference ``finetuning.py:110-145``), schema-as-form, plugin discovery
(``model_loader.py:14-45``), JWT mint/verify + introspection + entitlements
(``security.py``). The reference's only real test is the 401/200 middleware
test (``tests/test_security.py:1-36``) — these go well beyond it.
"""

import asyncio

import pytest

from finetune_controller_tpu.controller import registry
from finetune_controller_tpu.controller.examples import (
    BUILTIN_JOB_SPECS,
    LoRASFTArguments,
    TinyTestLoRA,
)
from finetune_controller_tpu.controller.security import (
    AuthError,
    TokenValidator,
    decode_jwt,
    dev_generate_token,
    dev_mock_token_introspection,
    encode_jwt,
    user_from_claims,
)
from finetune_controller_tpu.controller.specs import (
    BaseFineTuneJob,
    TrainingArguments,
    TrainingTask,
)


from conftest import run_async as run


# ---------------------------------------------------------------------------
# Specs
# ---------------------------------------------------------------------------


def test_subclass_type_enforcement():
    with pytest.raises(TypeError, match="model_name"):

        class BadName(BaseFineTuneJob):
            model_name = 123  # type: ignore[assignment]

    with pytest.raises(TypeError, match="task"):

        class BadTask(BaseFineTuneJob):
            model_name = "x"
            task = "causal_lm"  # type: ignore[assignment]  # must be the enum

    class Good(BaseFineTuneJob):
        model_name = "good"
        task = TrainingTask.CAUSAL_LM
        training_arguments: TrainingArguments

    assert Good.model_name == "good"


def test_arguments_validation_and_schema():
    with pytest.raises(Exception):  # pydantic ValidationError: extra forbidden
        LoRASFTArguments(not_a_field=1)
    with pytest.raises(Exception):  # constraint violation
        LoRASFTArguments(learning_rate=-1.0)
    schema = TinyTestLoRA.arguments_schema()
    props = schema["properties"]
    assert props["learning_rate"]["description"] == "Peak AdamW learning rate"
    assert props["lora_rank"]["default"] == 16


def test_build_trainer_spec_and_run_cmd():
    job = TinyTestLoRA(
        training_arguments=LoRASFTArguments(total_steps=5, batch_size=4, seq_len=32)
    )
    spec = job.build_trainer_spec(
        "tiny-abc", "/tmp/art", dataset_path="/tmp/ds.jsonl", mesh={"fsdp": 2}
    )
    assert spec["model"] == {"preset": "tiny-test", "lora": {"rank": 16}}
    assert spec["training"]["total_steps"] == 5
    assert spec["training"]["mode"] == "lora"
    assert spec["dataset"] == {"path": "/tmp/ds.jsonl"}
    assert spec["mesh"] == {"fsdp": 2}
    cmd = job.run_cmd("/data/job.json")
    assert "finetune_controller_tpu.train.cli" in cmd
    assert cmd.endswith("done.txt")  # completion signal convention


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_builtin_registration():
    registry.reset()
    registry.load_builtin_models()
    assert set(registry.JOB_MANIFESTS) == {c.model_name for c in BUILTIN_JOB_SPECS}
    assert registry.get_spec("tiny-test-lora") is TinyTestLoRA
    registry.reset()


def test_plugin_discovery(tmp_path):
    (tmp_path / "my_model.py").write_text(
        "from finetune_controller_tpu.controller.specs import (\n"
        "    BaseFineTuneJob, TrainingArguments)\n"
        "from pydantic import Field\n"
        "class MyArgs(TrainingArguments):\n"
        "    epochs: int = Field(3, ge=1)\n"
        "class MyModel(BaseFineTuneJob):\n"
        "    model_name = 'my-custom-model'\n"
        "    model_preset = 'tiny-test'\n"
        "    training_arguments: MyArgs\n"
    )
    (tmp_path / "broken.py").write_text("raise RuntimeError('bad plugin')\n")
    (tmp_path / "_private.py").write_text("raise RuntimeError('must not load')\n")
    registry.reset()
    names = registry.load_models_from_directory(tmp_path)
    assert names == ["my-custom-model"]  # broken plugin skipped, not fatal
    assert registry.get_spec("my-custom-model") is not None
    registry.reset()


def test_missing_plugin_dir_ok(tmp_path):
    registry.reset()
    assert registry.load_models_from_directory(tmp_path / "nope") == []
    registry.reset()


# ---------------------------------------------------------------------------
# Auth
# ---------------------------------------------------------------------------


def test_jwt_roundtrip_and_tamper():
    tok = dev_generate_token("alice", "s3cret", scopes=["m1"], ttl_s=60)
    claims = decode_jwt(tok, "s3cret")
    assert claims["sub"] == "alice" and claims["scp"] == ["m1"]
    with pytest.raises(AuthError, match="signature"):
        decode_jwt(tok, "wrong-secret")
    with pytest.raises(AuthError, match="malformed"):
        decode_jwt("abc.def")
    expired = encode_jwt({"sub": "a", "exp": 1.0}, "s3cret")
    with pytest.raises(AuthError, match="expired"):
        decode_jwt(expired, "s3cret")


def test_validator_local_and_introspection():
    async def go():
        v = TokenValidator(jwt_secret="s")
        user = await v.validate(dev_generate_token("bob", "s"))
        assert user.user_id == "bob"
        with pytest.raises(AuthError):
            await v.validate(dev_generate_token("bob", "other"))

        vi = TokenValidator(jwt_secret="s", introspect_fn=dev_mock_token_introspection)
        user = await vi.validate("valid_token")
        assert user.user_id == "dev-user"
        # cached second call works even if backend would now say no
        assert (await vi.validate("valid_token")).user_id == "dev-user"
        with pytest.raises(AuthError, match="not active"):
            await vi.validate("expired_token")

    run(go())


def test_entitlements():
    user = user_from_claims({"sub": "u", "scp": ["m1", "m3"]})
    assert user.entitled_models(["m1", "m2", "m3"]) == ["m1", "m3"]
    admin = user_from_claims({"sub": "a", "admin": True, "scp": ["m1"]})
    assert admin.entitled_models(["m1", "m2"]) == ["m1", "m2"]
    open_user = user_from_claims({"sub": "u2"})
    assert open_user.entitled_models(["m1", "m2"]) == ["m1", "m2"]


# ---------------------------------------------------------------------------
# JWKS / RS256 (reference: security.py:66-189)
# ---------------------------------------------------------------------------


def _rsa_keypair():
    from cryptography.hazmat.primitives.asymmetric import rsa

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pub = key.public_key().public_numbers()

    def b64u(i: int, length: int) -> str:
        import base64

        return base64.urlsafe_b64encode(i.to_bytes(length, "big")).rstrip(b"=").decode()

    jwk = {
        "kty": "RSA",
        "kid": "test-key",
        "alg": "RS256",
        "n": b64u(pub.n, 256),
        "e": b64u(pub.e, 3),
    }
    return key, jwk


def _mint_rs256(key, claims: dict, kid: str = "test-key") -> str:
    import base64
    import json as _json

    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding

    def b64(b: bytes) -> str:
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    header = b64(_json.dumps({"alg": "RS256", "typ": "JWT", "kid": kid}).encode())
    payload = b64(_json.dumps(claims).encode())
    sig = key.sign(
        f"{header}.{payload}".encode(), padding.PKCS1v15(), hashes.SHA256()
    )
    return f"{header}.{payload}.{b64(sig)}"


def test_rs256_jwks_validation():
    import time as _time

    from finetune_controller_tpu.controller.security import JWKSClient

    key, jwk = _rsa_keypair()
    fetches = []

    async def fake_fetch(url):
        fetches.append(url)
        return {"keys": [jwk]}

    async def go():
        jwks = JWKSClient("https://idp/jwks", fetch_fn=fake_fetch)
        v = TokenValidator(jwt_secret="unused", jwks_client=jwks)

        tok = _mint_rs256(key, {"sub": "carol", "scp": ["m1"],
                                "exp": _time.time() + 60})
        user = await v.validate(tok)
        assert user.user_id == "carol" and user.scopes == ["m1"]

        # key cache: a second token does not refetch the JWKS
        n_fetches = len(fetches)
        tok2 = _mint_rs256(key, {"sub": "dave", "exp": _time.time() + 60})
        assert (await v.validate(tok2)).user_id == "dave"
        assert len(fetches) == n_fetches

        # tampered signature rejected
        other_key, _ = _rsa_keypair()
        forged = _mint_rs256(other_key, {"sub": "mallory",
                                         "exp": _time.time() + 60})
        with pytest.raises(AuthError, match="signature"):
            await v.validate(forged)

        # unknown kid rejected (after refetch attempt)
        bad_kid = _mint_rs256(key, {"sub": "x", "exp": _time.time() + 60},
                              kid="nope")
        with pytest.raises(AuthError, match="unknown signing key"):
            await v.validate(bad_kid)

        # expired rejected
        old = _mint_rs256(key, {"sub": "y", "exp": 1.0})
        with pytest.raises(AuthError, match="expired"):
            await v.validate(old)

        # HS256 tokens still validate via the secret (mixed deployments)
        v2 = TokenValidator(jwt_secret="s", jwks_client=jwks)
        assert (await v2.validate(dev_generate_token("bob", "s"))).user_id == "bob"

    run(go())
