"""Tests for job specs, the model registry, and auth.

Covers SURVEY.md §2 components 2,3,4: subclass type enforcement
(reference ``finetuning.py:110-145``), schema-as-form, plugin discovery
(``model_loader.py:14-45``), JWT mint/verify + introspection + entitlements
(``security.py``). The reference's only real test is the 401/200 middleware
test (``tests/test_security.py:1-36``) — these go well beyond it.
"""

import asyncio

import pytest

from finetune_controller_tpu.controller import registry
from finetune_controller_tpu.controller.examples import (
    BUILTIN_JOB_SPECS,
    LoRASFTArguments,
    TinyTestLoRA,
)
from finetune_controller_tpu.controller.security import (
    AuthError,
    TokenValidator,
    decode_jwt,
    dev_generate_token,
    dev_mock_token_introspection,
    encode_jwt,
    user_from_claims,
)
from finetune_controller_tpu.controller.specs import (
    BaseFineTuneJob,
    TrainingArguments,
    TrainingTask,
)


from conftest import run_async as run


# ---------------------------------------------------------------------------
# Specs
# ---------------------------------------------------------------------------


def test_subclass_type_enforcement():
    with pytest.raises(TypeError, match="model_name"):

        class BadName(BaseFineTuneJob):
            model_name = 123  # type: ignore[assignment]

    with pytest.raises(TypeError, match="task"):

        class BadTask(BaseFineTuneJob):
            model_name = "x"
            task = "causal_lm"  # type: ignore[assignment]  # must be the enum

    class Good(BaseFineTuneJob):
        model_name = "good"
        task = TrainingTask.CAUSAL_LM
        training_arguments: TrainingArguments

    assert Good.model_name == "good"


def test_arguments_validation_and_schema():
    with pytest.raises(Exception):  # pydantic ValidationError: extra forbidden
        LoRASFTArguments(not_a_field=1)
    with pytest.raises(Exception):  # constraint violation
        LoRASFTArguments(learning_rate=-1.0)
    schema = TinyTestLoRA.arguments_schema()
    props = schema["properties"]
    assert props["learning_rate"]["description"] == "Peak AdamW learning rate"
    assert props["lora_rank"]["default"] == 16


def test_build_trainer_spec_and_run_cmd():
    job = TinyTestLoRA(
        training_arguments=LoRASFTArguments(total_steps=5, batch_size=4, seq_len=32)
    )
    spec = job.build_trainer_spec(
        "tiny-abc", "/tmp/art", dataset_path="/tmp/ds.jsonl", mesh={"fsdp": 2}
    )
    assert spec["model"] == {"preset": "tiny-test", "lora": {"rank": 16}}
    assert spec["training"]["total_steps"] == 5
    assert spec["training"]["mode"] == "lora"
    assert spec["dataset"] == {"path": "/tmp/ds.jsonl"}
    assert spec["mesh"] == {"fsdp": 2}
    cmd = job.run_cmd("/data/job.json")
    assert "finetune_controller_tpu.train.cli" in cmd
    assert cmd.endswith("done.txt")  # completion signal convention


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


def test_builtin_registration():
    registry.reset()
    registry.load_builtin_models()
    assert set(registry.JOB_MANIFESTS) == {c.model_name for c in BUILTIN_JOB_SPECS}
    assert registry.get_spec("tiny-test-lora") is TinyTestLoRA
    registry.reset()


def test_plugin_discovery(tmp_path):
    (tmp_path / "my_model.py").write_text(
        "from finetune_controller_tpu.controller.specs import (\n"
        "    BaseFineTuneJob, TrainingArguments)\n"
        "from pydantic import Field\n"
        "class MyArgs(TrainingArguments):\n"
        "    epochs: int = Field(3, ge=1)\n"
        "class MyModel(BaseFineTuneJob):\n"
        "    model_name = 'my-custom-model'\n"
        "    model_preset = 'tiny-test'\n"
        "    training_arguments: MyArgs\n"
    )
    (tmp_path / "broken.py").write_text("raise RuntimeError('bad plugin')\n")
    (tmp_path / "_private.py").write_text("raise RuntimeError('must not load')\n")
    registry.reset()
    names = registry.load_models_from_directory(tmp_path)
    assert names == ["my-custom-model"]  # broken plugin skipped, not fatal
    assert registry.get_spec("my-custom-model") is not None
    registry.reset()


def test_missing_plugin_dir_ok(tmp_path):
    registry.reset()
    assert registry.load_models_from_directory(tmp_path / "nope") == []
    registry.reset()


# ---------------------------------------------------------------------------
# Auth
# ---------------------------------------------------------------------------


def test_jwt_roundtrip_and_tamper():
    tok = dev_generate_token("alice", "s3cret", scopes=["m1"], ttl_s=60)
    claims = decode_jwt(tok, "s3cret")
    assert claims["sub"] == "alice" and claims["scp"] == ["m1"]
    with pytest.raises(AuthError, match="signature"):
        decode_jwt(tok, "wrong-secret")
    with pytest.raises(AuthError, match="malformed"):
        decode_jwt("abc.def")
    expired = encode_jwt({"sub": "a", "exp": 1.0}, "s3cret")
    with pytest.raises(AuthError, match="expired"):
        decode_jwt(expired, "s3cret")


def test_validator_local_and_introspection():
    async def go():
        v = TokenValidator(jwt_secret="s")
        user = await v.validate(dev_generate_token("bob", "s"))
        assert user.user_id == "bob"
        with pytest.raises(AuthError):
            await v.validate(dev_generate_token("bob", "other"))

        vi = TokenValidator(jwt_secret="s", introspect_fn=dev_mock_token_introspection)
        user = await vi.validate("valid_token")
        assert user.user_id == "dev-user"
        # cached second call works even if backend would now say no
        assert (await vi.validate("valid_token")).user_id == "dev-user"
        with pytest.raises(AuthError, match="not active"):
            await vi.validate("expired_token")

    run(go())


def test_entitlements():
    user = user_from_claims({"sub": "u", "scp": ["m1", "m3"]})
    assert user.entitled_models(["m1", "m2", "m3"]) == ["m1", "m3"]
    admin = user_from_claims({"sub": "a", "admin": True, "scp": ["m1"]})
    assert admin.entitled_models(["m1", "m2"]) == ["m1", "m2"]
    open_user = user_from_claims({"sub": "u2"})
    assert open_user.entitled_models(["m1", "m2"]) == ["m1", "m2"]
