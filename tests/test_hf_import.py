"""Pretrained-weight import tests: HF Llama-family checkpoints → our tree.

Verified the strong way — numerically, against ``transformers``' own PyTorch
forward pass on the same (random) weights. The reference never loads weights
(user containers bring their own — SURVEY.md §2.2), so this surface is pure
greenfield and the conversion is exactly where silent corruption would hide.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from finetune_controller_tpu.models.hf_import import load_llama_params
from finetune_controller_tpu.models.llama import PRESETS, LlamaForCausalLM
from finetune_controller_tpu.models.lora import LoRAConfig
from finetune_controller_tpu.train.trainer import TrainConfig, Trainer

TINY = PRESETS["tiny-test"].replace(dtype=jnp.float32)


def _save_hf_llama(tmp_path, *, tie=False):
    import torch
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM as HFModel

    torch.manual_seed(0)
    hf_cfg = HFConfig(
        vocab_size=TINY.vocab_size, hidden_size=TINY.d_model,
        num_hidden_layers=TINY.n_layers, num_attention_heads=TINY.n_heads,
        num_key_value_heads=TINY.n_kv_heads,
        intermediate_size=TINY.d_ff, rms_norm_eps=TINY.rms_eps,
        rope_theta=TINY.rope_theta, max_position_embeddings=TINY.max_seq_len,
        tie_word_embeddings=tie, attention_bias=False, mlp_bias=False,
    )
    model = HFModel(hf_cfg).eval()
    ckpt = tmp_path / "hf"
    model.save_pretrained(str(ckpt), safe_serialization=True)
    return model, ckpt


def test_import_matches_transformers_forward(tmp_path):
    torch = pytest.importorskip("torch")
    hf_model, ckpt = _save_hf_llama(tmp_path)

    params = load_llama_params(ckpt, TINY, dtype=jnp.float32)
    ours = LlamaForCausalLM(TINY)

    tokens = np.random.default_rng(0).integers(0, TINY.vocab_size, (2, 16))
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.float().numpy()
    out = ours.apply({"params": params}, jnp.asarray(tokens, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=1e-3)


def test_import_shape_mismatch_fails_loudly(tmp_path):
    pytest.importorskip("torch")
    _, ckpt = _save_hf_llama(tmp_path)
    wrong = TINY.replace(d_ff=64)
    with pytest.raises(ValueError):
        # conversion itself reads fine; the trainer-side adaptation catches
        # the shape mismatch. load_llama_params catches layer-count drift.
        trainer = Trainer(
            wrong.replace(lora=LoRAConfig(rank=2)),
            TrainConfig(mode="lora", total_steps=1, batch_size=2, seq_len=16),
        )
        state = trainer.init_state()
        trainer.load_pretrained(state, str(ckpt))


def test_trainer_loads_pretrained_and_trains(tmp_path):
    torch = pytest.importorskip("torch")
    hf_model, ckpt = _save_hf_llama(tmp_path)
    cfg = TINY.replace(lora=LoRAConfig(rank=4))
    trainer = Trainer(
        cfg, TrainConfig(mode="lora", total_steps=2, batch_size=2, seq_len=16,
                         learning_rate=1e-3),
    )
    state = trainer.init_state()
    state = trainer.load_pretrained(state, str(ckpt))

    # the loaded frozen base reproduces the HF forward through the trainer's
    # assembled model (LoRA deltas start at zero)
    tokens = np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 16))
    variables = trainer._assemble(state.frozen, state.trainable)
    out = trainer.model.apply(variables, jnp.asarray(tokens, jnp.int32))
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=1e-3)

    # and it trains
    batch = {"tokens": tokens.astype(np.int32),
             "loss_mask": np.ones_like(tokens, np.float32)}
    state2, metrics = trainer.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_qlora_pretrained_quantizes_on_load(tmp_path):
    pytest.importorskip("torch")
    _, ckpt = _save_hf_llama(tmp_path)
    cfg = TINY.replace(lora=LoRAConfig(rank=4), quantize_base=True, quant_block=32)
    trainer = Trainer(
        cfg, TrainConfig(mode="lora", total_steps=1, batch_size=2, seq_len=16),
    )
    state = trainer.init_state()
    state = trainer.load_pretrained(state, str(ckpt))
    blocks = state.frozen["params"]["blocks"]["block"]
    q = blocks["attn"]["q_proj"]
    assert q["kernel_packed"].dtype == jnp.uint8
    assert q["kernel_scales"].dtype == jnp.bfloat16
    # int4 round-trip stays close to the f32 original
    from finetune_controller_tpu.models.quant import dequantize_int4

    deq = dequantize_int4(q["kernel_packed"][0], q["kernel_scales"][0],
                          dtype=jnp.float32)
    orig = load_llama_params(ckpt, TINY, dtype=jnp.float32)
    ref = orig["blocks"]["block"]["attn"]["q_proj"]["kernel"][0]
    err = np.max(np.abs(np.asarray(deq) - np.asarray(ref)))
    assert err < np.max(np.abs(np.asarray(ref))) * 0.1

    batch = {"tokens": np.zeros((2, 16), np.int32),
             "loss_mask": np.ones((2, 16), np.float32)}
    _, metrics = trainer.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_mixtral_moe_import_matches_transformers(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import MixtralConfig, MixtralForCausalLM

    moe = PRESETS["tiny-moe-test"].replace(
        dtype=jnp.float32, capacity_factor=100.0,  # no token dropping
    )
    torch.manual_seed(0)
    hf_cfg = MixtralConfig(
        vocab_size=moe.vocab_size, hidden_size=moe.d_model,
        num_hidden_layers=moe.n_layers, num_attention_heads=moe.n_heads,
        num_key_value_heads=moe.n_kv_heads, intermediate_size=moe.d_ff,
        num_local_experts=moe.n_experts, num_experts_per_tok=moe.moe_top_k,
        rms_norm_eps=moe.rms_eps, rope_theta=moe.rope_theta,
        max_position_embeddings=moe.max_seq_len, tie_word_embeddings=False,
        attention_bias=False,
    )
    hf_model = MixtralForCausalLM(hf_cfg).eval()
    ckpt = tmp_path / "hf-moe"
    hf_model.save_pretrained(str(ckpt), safe_serialization=True)

    params = load_llama_params(ckpt, moe, dtype=jnp.float32)
    ours = LlamaForCausalLM(moe)
    tokens = np.random.default_rng(0).integers(0, moe.vocab_size, (2, 16))
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.float().numpy()
    out, _ = ours.apply(
        {"params": params}, jnp.asarray(tokens, jnp.int32), mutable=("moe_aux",)
    )
    np.testing.assert_allclose(np.asarray(out), ref, atol=5e-4, rtol=1e-2)


def test_gemma_import_matches_transformers(tmp_path):
    """Gemma family: head_dim decoupled from d_model/n_heads, GeGLU MLP,
    (1+w) RMSNorm, sqrt(d) embed scaling, tied head — all verified
    numerically against transformers' GemmaForCausalLM on shared weights."""
    torch = pytest.importorskip("torch")
    from transformers import GemmaConfig, GemmaForCausalLM

    cfg = PRESETS["tiny-gemma-test"].replace(dtype=jnp.float32)
    torch.manual_seed(0)
    hf_cfg = GemmaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        num_hidden_layers=cfg.n_layers, num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads, intermediate_size=cfg.d_ff,
        head_dim=cfg.head_dim, rms_norm_eps=cfg.rms_eps,
        rope_theta=cfg.rope_theta, max_position_embeddings=cfg.max_seq_len,
        hidden_activation="gelu_pytorch_tanh", tie_word_embeddings=True,
        attention_bias=False,
    )
    hf_model = GemmaForCausalLM(hf_cfg).eval()
    ckpt = tmp_path / "hf-gemma"
    hf_model.save_pretrained(str(ckpt), safe_serialization=True)

    params = load_llama_params(ckpt, cfg, dtype=jnp.float32)
    ours = LlamaForCausalLM(cfg)
    tokens = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16))
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.float().numpy()
    out = ours.apply({"params": params}, jnp.asarray(tokens, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), ref, atol=5e-4, rtol=1e-3)


def test_qwen2_import_matches_transformers(tmp_path):
    """Qwen-2 family: Llama-shaped decoder with q/k/v projection biases —
    verified numerically against transformers' Qwen2ForCausalLM."""
    torch = pytest.importorskip("torch")
    from transformers import Qwen2Config, Qwen2ForCausalLM

    cfg = PRESETS["tiny-qwen-test"].replace(dtype=jnp.float32)
    torch.manual_seed(0)
    hf_cfg = Qwen2Config(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        num_hidden_layers=cfg.n_layers, num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads, intermediate_size=cfg.d_ff,
        rms_norm_eps=cfg.rms_eps, rope_theta=cfg.rope_theta,
        max_position_embeddings=cfg.max_seq_len, tie_word_embeddings=False,
    )
    hf_model = Qwen2ForCausalLM(hf_cfg).eval()
    ckpt = tmp_path / "hf-qwen"
    hf_model.save_pretrained(str(ckpt), safe_serialization=True)

    params = load_llama_params(ckpt, cfg, dtype=jnp.float32)
    # biases actually landed (all-zero biases would hide a dropped mapping)
    assert "bias" in params["blocks"]["block"]["attn"]["q_proj"]
    ours = LlamaForCausalLM(cfg)
    tokens = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16))
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.float().numpy()
    out = ours.apply({"params": params}, jnp.asarray(tokens, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), ref, atol=5e-4, rtol=1e-3)


def test_llama32_rope_scaling_matches_transformers(tmp_path):
    """llama3-style RoPE scaling (Llama-3.1/3.2): our rope_inv_freqs and the
    scaled forward must match transformers' _compute_llama3_parameters path
    numerically. original_max_len is set BELOW the test seq len so the
    scaled long-wavelength band is actually exercised."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM as HFModel

    cfg = TINY.replace(
        tie_embeddings=True,
        rope_scaling_factor=8.0,
        rope_scaling_original_max_len=16,
        max_seq_len=128,
    )
    torch.manual_seed(0)
    hf_cfg = HFConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.d_model,
        num_hidden_layers=cfg.n_layers, num_attention_heads=cfg.n_heads,
        num_key_value_heads=cfg.n_kv_heads,
        intermediate_size=cfg.d_ff, rms_norm_eps=cfg.rms_eps,
        rope_theta=cfg.rope_theta, max_position_embeddings=cfg.max_seq_len,
        tie_word_embeddings=True, attention_bias=False, mlp_bias=False,
        rope_scaling={
            "rope_type": "llama3", "factor": 8.0,
            "low_freq_factor": 1.0, "high_freq_factor": 4.0,
            "original_max_position_embeddings": 16,
        },
    )
    hf_model = HFModel(hf_cfg).eval()
    ckpt = tmp_path / "hf-32"
    hf_model.save_pretrained(str(ckpt), safe_serialization=True)

    # frequency-level parity first (isolates the formula from the rest)
    from finetune_controller_tpu.models.llama import rope_inv_freqs

    ours_freqs = np.asarray(rope_inv_freqs(cfg))
    theirs = hf_model.model.rotary_emb.inv_freq.numpy()
    np.testing.assert_allclose(ours_freqs, theirs, rtol=1e-6)

    params = load_llama_params(ckpt, cfg, dtype=jnp.float32)
    ours = LlamaForCausalLM(cfg)
    # positions past original_max_len, so scaling wrongness would show
    tokens = np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 48))
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.float().numpy()
    out = ours.apply({"params": params}, jnp.asarray(tokens, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=1e-3)


def test_qlora_moe_experts_quantize_on_load(tmp_path):
    """Quantized MoE: a Mixtral checkpoint loads into a quantize_base
    config — the stacked (L, E, in, out) expert kernels quantize on the way
    in (the generic *_packed path in _adapt_loaded_params), dense
    projections too, and the quantized forward stays close to the f32
    oracle."""
    torch = pytest.importorskip("torch")
    from transformers import MixtralConfig, MixtralForCausalLM

    moe = PRESETS["tiny-moe-test"].replace(
        dtype=jnp.float32, capacity_factor=100.0,
        quantize_base=True, quant_block=32, lora=LoRAConfig(rank=4),
    )
    torch.manual_seed(0)
    hf_cfg = MixtralConfig(
        vocab_size=moe.vocab_size, hidden_size=moe.d_model,
        num_hidden_layers=moe.n_layers, num_attention_heads=moe.n_heads,
        num_key_value_heads=moe.n_kv_heads, intermediate_size=moe.d_ff,
        num_local_experts=moe.n_experts, num_experts_per_tok=moe.moe_top_k,
        rms_norm_eps=moe.rms_eps, rope_theta=moe.rope_theta,
        max_position_embeddings=moe.max_seq_len, tie_word_embeddings=False,
        attention_bias=False,
    )
    hf_model = MixtralForCausalLM(hf_cfg).eval()
    ckpt = tmp_path / "hf-moe-q"
    hf_model.save_pretrained(str(ckpt), safe_serialization=True)

    trainer = Trainer(
        moe, TrainConfig(mode="lora", total_steps=1, batch_size=2, seq_len=16),
    )
    state = trainer.init_state()
    state = trainer.load_pretrained(state, str(ckpt))

    blocks = state.frozen["params"]["blocks"]["block"]
    gate = blocks["moe"]["experts_gate_packed"]
    assert gate.dtype == jnp.uint8
    # (L, E, in/2, out): expert axis preserved through the vmapped quantize
    assert gate.shape == (
        moe.n_layers, moe.n_experts, moe.d_model // 2, moe.d_ff,
    )

    # quantized forward ~= the f32 import (int4 on top of f32 weights);
    # compare through LoRA-free configs — the adapters start at identity and
    # the frozen params tree is what we're checking
    tokens = np.random.default_rng(0).integers(0, moe.vocab_size, (2, 16))
    nolora = moe.replace(lora=LoRAConfig())
    f32_params = load_llama_params(ckpt, nolora.replace(quantize_base=False),
                                   dtype=jnp.float32)
    oracle = LlamaForCausalLM(nolora.replace(quantize_base=False))
    ref, _ = oracle.apply(
        {"params": f32_params}, jnp.asarray(tokens, jnp.int32),
        mutable=("moe_aux",),
    )
    q_model = LlamaForCausalLM(nolora)
    out, _ = q_model.apply(
        {"params": state.frozen["params"]}, jnp.asarray(tokens, jnp.int32),
        mutable=("moe_aux",),
    )
    # the tight guarantee lives at the weight level: per-expert int4
    # round-trip within 10% of the per-block absmax bound
    from finetune_controller_tpu.models.quant import dequantize_int4

    deq = dequantize_int4(
        np.asarray(gate[0, 0]),
        np.asarray(blocks["moe"]["experts_gate_scales"][0, 0]),
        dtype=jnp.float32,
    )
    orig = f32_params["blocks"]["block"]["moe"]["experts_gate"][0, 0]
    werr = np.max(np.abs(np.asarray(deq) - np.asarray(orig)))
    assert werr < 0.1 * np.max(np.abs(np.asarray(orig))), werr
    # logits: int4 error compounds through layers — sanity bound only
    err = np.max(np.abs(np.asarray(out) - np.asarray(ref)))
    scale = np.max(np.abs(np.asarray(ref)))
    assert err < 0.25 * scale, (err, scale)

    batch = {"tokens": np.zeros((2, 16), np.int32),
             "loss_mask": np.ones((2, 16), np.float32)}
    _, metrics = trainer.step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_llava_import_matches_transformers(tmp_path):
    """Round-5 (VERDICT #3): a real LLaVA checkpoint — CLIP vision tower
    (class token, pre-norm, quick-gelu, penultimate-layer features),
    projector, and Llama language model — imports with exact logits parity
    against transformers' LlavaForConditionalGeneration."""
    torch = pytest.importorskip("torch")
    from transformers import (
        CLIPVisionConfig,
        LlamaConfig as HFLlamaConfig,
        LlavaConfig as HFLlavaConfig,
        LlavaForConditionalGeneration,
    )

    from finetune_controller_tpu.models.hf_import import load_llava_params
    from finetune_controller_tpu.models.llama import LlamaConfig
    from finetune_controller_tpu.models.multimodal import (
        LlavaConfig,
        LlavaForCausalLM,
        ViTConfig,
    )

    torch.manual_seed(0)
    vcfg = CLIPVisionConfig(
        hidden_size=32, intermediate_size=64, num_hidden_layers=3,
        num_attention_heads=2, image_size=16, patch_size=8,
        hidden_act="quick_gelu",
    )
    tcfg = HFLlamaConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        max_position_embeddings=128, tie_word_embeddings=False,
    )
    hf_cfg = HFLlavaConfig(
        vision_config=vcfg, text_config=tcfg, image_token_index=255,
        projector_hidden_act="gelu", vision_feature_layer=-2,
        vision_feature_select_strategy="default",
    )
    hf_model = LlavaForConditionalGeneration(hf_cfg).eval()
    ckpt = tmp_path / "llava-tiny"
    hf_model.save_pretrained(str(ckpt), safe_serialization=True)

    n_patches = (16 // 8) ** 2
    text = [5, 6, 7, 8, 9, 10]
    input_ids = torch.tensor([[255] * n_patches + text])
    pixels = torch.tensor(
        np.random.default_rng(0).normal(0, 1, (1, 3, 16, 16)).astype(np.float32)
    )
    with torch.no_grad():
        ref = hf_model(
            input_ids=input_ids, pixel_values=pixels,
            attention_mask=torch.ones_like(input_ids),
        ).logits[:, n_patches:].float().numpy()

    cfg = LlavaConfig(
        vision=ViTConfig(
            image_size=16, patch_size=8, d_model=32, n_layers=3, n_heads=2,
            d_ff=64, cls_token=True, pre_norm=True, patch_bias=False,
            act="quick_gelu", feature_layer=-2, dtype=jnp.float32,
        ),
        text=LlamaConfig(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=128, rms_eps=1e-6, dtype=jnp.float32,
        ),
        projector_hidden=64,
    )
    params = load_llava_params(ckpt, cfg)
    ours = LlavaForCausalLM(cfg)
    out = ours.apply(
        {"params": params},
        jnp.asarray([text], jnp.int32),
        jnp.asarray(np.transpose(pixels.numpy(), (0, 2, 3, 1))),  # NCHW→NHWC
    )
    np.testing.assert_allclose(np.asarray(out), ref, atol=3e-5, rtol=1e-4)
