"""The prefix-reuse KV cache (ISSUE 6, docs/serving.md).

Three layers of proof:

* **trie semantics** (no model, no device): longest-common-prefix
  resolution including mid-edge divergence, and the bucket-granular reuse
  arithmetic (``resolve_reuse_length``);
* **byte-budget LRU**: eviction under pressure, recency refresh on hit,
  oversized-snapshot refusal;
* **the correctness anchor**: engine outputs with the cache ON are
  bit-identical to cache OFF (greedy and sampled, hit and miss), the
  compile budget stays ``2*len(buckets) + 1``, and evicting a snapshot
  while a request decodes from its splice changes nothing (lanes hold
  device-side copies).
"""

from __future__ import annotations

import jax
import pytest

from test_serve import _baseline, tiny_model  # noqa: F401 — shared fixture

from finetune_controller_tpu.serve.engine import (
    BatchEngine,
    EngineConfig,
    GenRequest,
)
from finetune_controller_tpu.serve.prefix_cache import (
    PrefixCache,
    resolve_reuse_length,
)


# ---------------------------------------------------------------------------
# resolve_reuse_length: bucket-granular reuse arithmetic
# ---------------------------------------------------------------------------


def test_resolve_reuse_length_bucket_granularity():
    buckets, cache_len = (32, 128, 512), 640  # max_new = 128
    # plain case: the suffix pads to the smallest bucket and fits
    assert resolve_reuse_length(100, 110, buckets, cache_len) == 100
    # full-prompt match still leaves one real token for the logits forward
    assert resolve_reuse_length(110, 110, buckets, cache_len) == 109
    # a match longer than the prompt is clamped the same way
    assert resolve_reuse_length(500, 110, buckets, cache_len) == 109
    # no match, or a single-token prompt, cannot reuse anything
    assert resolve_reuse_length(0, 110, buckets, cache_len) == 0
    assert resolve_reuse_length(1, 1, buckets, cache_len) == 0


def test_resolve_reuse_length_shrinks_on_bucket_overshoot():
    buckets, cache_len = (32, 128, 512), 640
    # match 381 of 512: suffix 131 pads to bucket 512 and 381+512 > 640 —
    # reuse shrinks to cache_len - 512 = 128 so the padded suffix fits
    reuse = resolve_reuse_length(381, 512, buckets, cache_len)
    assert reuse == 128
    suffix_bucket = next(b for b in buckets if 512 - reuse <= b)
    assert reuse + suffix_bucket <= cache_len
    assert reuse <= 381  # never reuses more than actually matched
    # tight cache: exactly the bucket itself -> miss, never an OOB splice
    assert resolve_reuse_length(381, 512, (512,), 512) == 0
    # one slack slot past the bucket: a (barely useful) 1-token reuse
    assert resolve_reuse_length(381, 512, (512,), 513) == 1


# ---------------------------------------------------------------------------
# Radix trie: longest-common-prefix lookup (no device arrays needed)
# ---------------------------------------------------------------------------


def _cache_with(pc: PrefixCache, key, tag, nbytes=10):
    assert pc.insert(key, tag, nbytes=nbytes)
    return tag


def test_trie_longest_prefix_resolution():
    pc = PrefixCache(budget_bytes=1000)
    _cache_with(pc, (1, 2, 3, 4, 5), "A")
    _cache_with(pc, (1, 2, 9, 9), "B")
    _cache_with(pc, (7, 7), "C")

    # exact key
    assert pc.lookup((1, 2, 3, 4, 5)) == (5, "A")
    # query extends a stored key: match = whole key
    assert pc.lookup((7, 7, 1, 2)) == (2, "C")
    # query diverges MID-EDGE: [1,2,3,9] shares 3 tokens with A's path
    n, cache = pc.lookup((1, 2, 3, 9))
    assert (n, cache) == (3, "A")
    # divergence at the [1,2] branch point: either snapshot proves 2 tokens
    n, cache = pc.lookup((1, 2, 5))
    assert n == 2 and cache in ("A", "B")
    # query is a strict prefix of a stored key
    n, cache = pc.lookup((1, 2, 9))
    assert (n, cache) == (3, "B")
    # complete miss
    assert pc.lookup((4, 4, 4)) == (0, None)
    assert len(pc) == 3


def test_trie_lru_byte_budget_eviction():
    pc = PrefixCache(budget_bytes=25)  # fits two 10-byte snapshots
    _cache_with(pc, (1, 1, 1), "A")
    _cache_with(pc, (2, 2, 2), "B")
    assert pc.total_bytes == 20
    _cache_with(pc, (3, 3, 3), "C")  # evicts A (least recently used)
    assert pc.lookup((1, 1, 1)) == (0, None)
    assert pc.lookup((2, 2, 2))[1] == "B"
    assert pc.evictions_total == 1 and pc.total_bytes == 20

    # a HIT refreshes recency: touch B, insert D -> C (not B) evicts
    pc.lookup((2, 2, 2))
    _cache_with(pc, (4, 4, 4), "D")
    assert pc.lookup((3, 3, 3)) == (0, None)
    assert pc.lookup((2, 2, 2))[1] == "B"

    # a snapshot larger than the whole budget is refused outright
    assert not pc.insert((5, 5, 5), "huge", nbytes=100)
    assert pc.lookup((5, 5, 5)) == (0, None)
    # re-inserting an existing key refreshes instead of double-counting
    assert pc.insert((2, 2, 2), "B2", nbytes=10)
    assert pc.total_bytes == 20
    assert pc.lookup((2, 2, 2))[1] == "B"


def test_trie_eviction_prunes_dead_branches():
    pc = PrefixCache(budget_bytes=100)
    _cache_with(pc, (1, 2, 3), "A")
    _cache_with(pc, (1, 2, 3, 4, 5), "B")
    # evict B by pressure: fill with unrelated keys sized to push it out
    pc.lookup((1, 2, 3))  # A is now most recent
    _cache_with(pc, (9,), "C", nbytes=85)  # 10+10+85 > 100 -> B evicts
    assert pc.evictions_total == 1
    # the pruned branch no longer resolves past A's key
    assert pc.lookup((1, 2, 3, 4, 5)) == (3, "A")
    assert pc.lookup((1, 2, 3)) == (3, "A")


# ---------------------------------------------------------------------------
# Engine integration: bit-identity, budget, mid-flight eviction
# ---------------------------------------------------------------------------


def _engine(model, variables, **kw):
    """test_serve's engine shape, with the prefix cache ON by default."""
    defaults = dict(slots=4, prompt_buckets=(8, 16), max_new_tokens=24,
                    prefix_cache_bytes=1 << 20)
    defaults.update(kw)
    return BatchEngine(model, variables, EngineConfig(**defaults))


SHARED = [5, 9, 2, 7, 1, 3, 3, 8, 2, 2]  # 10-token "system prompt"
PROMPTS = [SHARED + [11, 4], SHARED + [7, 7, 7], SHARED + [2], [6, 1, 4]]


def test_greedy_bit_identity_cache_on_vs_off(tiny_model):
    """The acceptance anchor: greedy tokens with the prefix cache enabled —
    misses, shared-prefix hits, and exact-key hits alike — are bit-identical
    to the cache-off engine and to single-request cached_generate."""
    model, variables = tiny_model
    eng = _engine(model, variables)

    def reqs(tag):
        return [
            GenRequest(request_id=f"{tag}{i}", tokens=p, max_new_tokens=8)
            for i, p in enumerate(PROMPTS)
        ]

    first = eng.run(reqs("a"))   # pass 1: misses seed the cache (+ 3 hits)
    second = eng.run(reqs("b"))  # pass 2: every prompt resolves a prefix
    assert eng.prefix_hits_total >= len(PROMPTS)  # pass 2 is all hits
    assert eng.prefill_tokens_saved_total > 0
    for i, p in enumerate(PROMPTS):
        want = _baseline(model, variables, p, 8)
        assert first[f"a{i}"].generated == want, f"pass-1 r{i} diverged"
        assert second[f"b{i}"].generated == want, f"hit-path r{i} diverged"
    # the budget holds with the cache on: fill+fill_from per bucket + decode
    assert eng.guard.on_excess == "raise"
    assert eng.compilations <= 2 * len(eng.config.prompt_buckets) + 1


@pytest.mark.slow  # runs on every ci_check gate via the serve-fast stage
def test_sampled_bit_identity_cache_on_vs_off(tiny_model):
    """Sampled decode reproduces the per-request PRNGKey(seed) stream on
    both the miss path and the prefix-hit path."""
    model, variables = tiny_model
    eng = _engine(model, variables)
    prompts = PROMPTS[:3]

    def reqs(tag):
        return [
            GenRequest(request_id=f"{tag}{i}", tokens=p, max_new_tokens=8,
                       temperature=0.7, top_k=5, seed=100 + i)
            for i, p in enumerate(prompts)
        ]

    first = eng.run(reqs("a"))
    second = eng.run(reqs("b"))  # all prefix hits
    assert eng.prefix_hits_total >= len(prompts)
    for i, p in enumerate(prompts):
        want = _baseline(model, variables, p, 8, temperature=0.7, top_k=5,
                         rng=jax.random.PRNGKey(100 + i))
        assert first[f"a{i}"].generated == want
        assert second[f"b{i}"].generated == want


@pytest.mark.slow  # runs on every ci_check gate via the serve-fast stage
def test_snapshot_eviction_mid_flight_is_invisible(tiny_model):
    """Evicting a snapshot while a request decodes from its splice changes
    nothing: lanes receive device-side copies, eviction only drops refs."""
    model, variables = tiny_model
    # budget sized to ONE snapshot: every insert evicts the previous one
    probe = _engine(model, variables)
    probe.admit(GenRequest(request_id="p", tokens=PROMPTS[0],
                           max_new_tokens=2))
    one_snapshot = probe.prefix_cache_bytes
    assert one_snapshot > 0
    eng = _engine(model, variables, prefix_cache_bytes=one_snapshot)

    r1 = GenRequest(request_id="r1", tokens=PROMPTS[0], max_new_tokens=8)
    eng.admit(r1)          # miss; snapshot for PROMPTS[0] stored
    eng.step()
    eng.step()             # r1 is mid-flight, decoding from the splice
    evictions_before = eng._prefix_cache.evictions_total
    r2 = GenRequest(request_id="r2", tokens=PROMPTS[3], max_new_tokens=4)
    eng.admit(r2)          # insert evicts r1's snapshot under the budget
    assert eng._prefix_cache.evictions_total > evictions_before
    results = {}
    while eng.active_requests:
        for r in eng.step():
            results[r.request_id] = r
    assert results["r1"].generated == _baseline(model, variables, PROMPTS[0], 8)
    assert results["r2"].generated == _baseline(model, variables, PROMPTS[3], 4)


@pytest.mark.slow  # runs on every ci_check gate via the serve-fast stage
def test_prefix_stats_and_disabled_engine(tiny_model):
    """Counter bookkeeping: hits/misses/saved line up with the workload, and
    a cache-off engine reports inert zeros."""
    model, variables = tiny_model
    eng = _engine(model, variables)
    req = GenRequest(request_id="x", tokens=PROMPTS[0], max_new_tokens=2)
    eng.run([req])
    assert (eng.prefix_hits_total, eng.prefix_misses_total) == (0, 1)
    eng.run([GenRequest(request_id="y", tokens=PROMPTS[0], max_new_tokens=2)])
    # exact-key hit reuses all but the final (logits-producing) token
    assert (eng.prefix_hits_total, eng.prefix_misses_total) == (1, 1)
    assert eng.prefill_tokens_saved_total == len(PROMPTS[0]) - 1
    assert eng.prefix_cache_entries == 1

    off = _engine(model, variables, prefix_cache_bytes=0)
    off.run([GenRequest(request_id="z", tokens=PROMPTS[0], max_new_tokens=2)])
    assert off.prefix_hits_total == 0 and off.prefix_misses_total == 0
    assert off.prefix_cache_bytes == 0 and off.prefix_cache_entries == 0
