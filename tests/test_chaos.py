"""End-to-end chaos tests: deterministic fault injection against the REAL
local backend + monitor + retry supervisor (docs/resilience.md).

The acceptance loop the reference never had (SURVEY.md §5.4): a job killed
mid-training is automatically classified, requeued with backoff, and its
respawned attempt RESUMES from the latest committed checkpoint; a
deterministic user error is NOT retried and lands FAILED with its failure
class in metadata.

Fast tests here run in CI's chaos-fast stage (scripts/ci_check.sh) and in
tier-1; the full kill→resume loss-trajectory identity proof and the
SIGKILL (crash-without-save) variant are marked ``slow``:

    pytest tests/test_chaos.py -m slow
"""

import asyncio
import csv
import signal
import time

import pytest

from conftest import one_chip_catalog
from conftest import run_async as run

from finetune_controller_tpu.controller import registry
from finetune_controller_tpu.controller.backends.local import LocalProcessBackend
from finetune_controller_tpu.controller.examples import LoRASFTArguments, TinyTestLoRA
from finetune_controller_tpu.controller.monitor import JobMonitor
from finetune_controller_tpu.controller.objectstore import LocalObjectStore
from finetune_controller_tpu.controller.schemas import DatabaseStatus, JobInput
from finetune_controller_tpu.controller.statestore import StateStore
from finetune_controller_tpu.controller.task_builder import DatasetInput, task_builder
from finetune_controller_tpu.resilience import StepFault
from finetune_controller_tpu.resilience.policy import RetryPolicy
from finetune_controller_tpu.resilience.supervisor import RetrySupervisor


def _arguments(total_steps=60, cadence=10):
    return LoRASFTArguments(
        total_steps=total_steps, warmup_steps=1, batch_size=2, seq_len=16,
        lora_rank=2, log_every=cadence, checkpoint_every=cadence,
    )


def _plane(tmp_path, *, fault: StepFault | None = None, subdir="plane"):
    """Real control plane with the backend's own restart budget ZEROED so
    recovery must flow through the supervisor (the controller half under
    test), and a fast seeded backoff."""
    registry.reset()
    registry.load_builtin_models()  # the supervisor rebuilds specs from here
    root = tmp_path / subdir
    state = StateStore(root / "state")
    store = LocalObjectStore(root / "objects")
    catalog = one_chip_catalog()
    backend = LocalProcessBackend(
        root / "sandboxes", store, catalog,
        sync_interval_s=0.2, backoff_limit=0,
        extra_env=fault.to_env() if fault else None,
    )
    supervisor = RetrySupervisor(
        state, backend, catalog,
        policy=RetryPolicy(
            max_attempts=3, base_delay_s=0.2, max_delay_s=0.5, seed=0
        ),
    )
    monitor = JobMonitor(state, store, backend, interval_s=0.1,
                         supervisor=supervisor)
    return state, store, catalog, backend, supervisor, monitor


async def _submit(state, store, backend, catalog, arguments, job_id):
    spec = TinyTestLoRA(training_arguments=arguments)
    await task_builder(
        JobInput(job_id=job_id, user_id="u", model_name="tiny-test-lora",
                 device="chip-1", arguments=arguments.model_dump()),
        spec, DatasetInput(),
        state=state, store=store, backend=backend, catalog=catalog,
        datasets_bucket="datasets", artifacts_bucket="artifacts",
    )


async def _drive_to_final(state, monitor, job_id, timeout_s=300):
    deadline = time.monotonic() + timeout_s
    while True:
        await monitor.tick()
        rec = await state.get_job(job_id)
        if rec.status.is_final:
            return rec
        assert time.monotonic() < deadline, (rec.status, rec.metadata)
        await asyncio.sleep(0.1)


def _metric_steps(sandbox_artifacts, column="step"):
    with open(sandbox_artifacts / "metrics.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    return [int(float(r[column])) for r in rows], rows


def test_chaos_kill_mid_training_requeues_with_backoff_and_resumes(tmp_path):
    """The headline loop: SIGTERM at step 25 (spot-reclaim shape) → backend
    reports FAILED (restart budget 0) → supervisor classifies `preemption`,
    schedules a backoff, resubmits → respawned attempt RESUMES from the
    committed checkpoint and finishes SUCCEEDED with step-continuous
    metrics."""

    async def main():
        total, cadence = 60, 10
        fault = StepFault(
            kill_at_step=25, signum=signal.SIGTERM,
            once_file=str(tmp_path / "fault_fired"),
        )
        state, store, catalog, backend, sup, monitor = _plane(
            tmp_path, fault=fault
        )
        await state.connect()
        await _submit(state, store, backend, catalog,
                      _arguments(total, cadence), "chaos-1")
        handle = backend._handles["chaos-1"]
        rec = await _drive_to_final(state, monitor, "chaos-1")

        assert rec.status is DatabaseStatus.SUCCEEDED, rec.metadata
        # exactly one injected failure, classified as preemption (exit 143)
        history = rec.metadata["attempt_history"]
        assert len(history) == 1, history
        assert history[0]["failure_class"] == "preemption"
        assert history[0]["exit_code"] == 143
        assert history[0]["delay_s"] >= 0.2  # the backoff actually applied
        assert sup.retries_scheduled == 1 and sup.resubmits == 1
        assert (tmp_path / "fault_fired").exists()

        # resume proof: the respawned attempt continued, not restarted
        log_text = (handle.sandbox / "logs.txt").read_text()
        assert "resumed from checkpoint step" in log_text
        steps, _ = _metric_steps(handle.artifacts_dir)
        assert steps == sorted(set(steps)), "duplicate/out-of-order rows"
        assert steps[-1] == total
        assert steps == list(range(cadence, total + 1, cadence))

        # artifacts + liveness heartbeat shipped to the store
        assert await store.exists(rec.artifacts_uri + "/done.txt")
        assert await store.exists(rec.artifacts_uri + "/heartbeat.json")
        await backend.close()
        await state.close()

    run(main())


def test_chaos_user_error_is_terminal_with_failure_class(tmp_path):
    """A deterministic user error (batch_size not divisible by
    grad_accum_steps — the trainer constructor raises) must NOT be retried:
    one attempt, FAILED, ``failure_class: user`` in metadata."""

    async def main():
        args = LoRASFTArguments(
            total_steps=5, warmup_steps=1, batch_size=3, seq_len=16,
            lora_rank=2, grad_accum_steps=2,  # 3 % 2 != 0 -> ValueError
        )
        state, store, catalog, backend, sup, monitor = _plane(tmp_path)
        await state.connect()
        await _submit(state, store, backend, catalog, args, "chaos-user-1")
        rec = await _drive_to_final(state, monitor, "chaos-user-1",
                                    timeout_s=180)
        assert rec.status is DatabaseStatus.FAILED
        assert rec.metadata["failure_class"] == "user"
        history = rec.metadata["attempt_history"]
        assert len(history) == 1
        assert history[0]["exit_code"] == 1
        assert history[0]["delay_s"] is None  # terminal: no backoff scheduled
        assert sup.resubmits == 0 and sup.terminal_failures == 1
        # stays terminal on further reconcile passes
        await monitor.tick()
        rec = await state.get_job("chaos-user-1")
        assert rec.status is DatabaseStatus.FAILED
        assert len(rec.metadata["attempt_history"]) == 1
        await backend.close()
        await state.close()

    run(main())


@pytest.mark.slow
def test_chaos_sigkill_resumes_from_last_committed_checkpoint(tmp_path):
    """SIGKILL (exit −9, no chance to save): classified `infra`, and the
    respawn resumes from the last checkpoint COMMITTED BEFORE the kill —
    the crash-without-save path."""

    async def main():
        total, cadence = 60, 10
        fault = StepFault(
            kill_at_step=25, signum=signal.SIGKILL,
            once_file=str(tmp_path / "fault_fired"),
        )
        state, store, catalog, backend, sup, monitor = _plane(
            tmp_path, fault=fault
        )
        await state.connect()
        await _submit(state, store, backend, catalog,
                      _arguments(total, cadence), "chaos-kill-1")
        handle = backend._handles["chaos-kill-1"]
        rec = await _drive_to_final(state, monitor, "chaos-kill-1")

        assert rec.status is DatabaseStatus.SUCCEEDED, rec.metadata
        history = rec.metadata["attempt_history"]
        assert len(history) == 1
        assert history[0]["failure_class"] == "infra"
        assert history[0]["exit_code"] == -9
        log_text = (handle.sandbox / "logs.txt").read_text()
        # killed at 25: the newest committed checkpoint is 20 — or 10 when
        # the SIGKILL also caught step 20's ASYNC save mid-commit (the
        # kill-without-save path this test exists to cover)
        import re

        m = re.search(r"resumed from checkpoint step (\d+)", log_text)
        assert m, "respawned attempt did not resume"
        assert int(m.group(1)) in (10, 20), m.group(0)
        # replayed rows are truncated on resume: no duplicates, full coverage
        steps, _ = _metric_steps(handle.artifacts_dir)
        assert steps == list(range(cadence, total + 1, cadence))
        # a SIGKILL mid-save strands an orbax tmp dir; the respawn sweeps it
        strays = [
            p.name for p in (handle.artifacts_dir / "checkpoints").iterdir()
            if ".tmp" in p.name or "orbax-checkpoint-tmp" in p.name
        ]
        assert strays == [], strays
        await backend.close()
        await state.close()

    run(main())


@pytest.mark.slow
def test_chaos_resumed_loss_trajectory_matches_uninterrupted_run(tmp_path):
    """The full acceptance proof: after a mid-training kill + supervised
    requeue, the resumed run's metrics rows (loss AND accuracy, every
    logged step) are IDENTICAL to an uninterrupted twin run with the same
    seed — resume loses nothing and replays nothing."""

    async def main():
        total, cadence = 60, 10
        args = _arguments(total, cadence)

        # leg A: killed at step 25, recovered by the supervisor
        fault = StepFault(
            kill_at_step=25, signum=signal.SIGTERM,
            once_file=str(tmp_path / "fault_fired"),
        )
        state_a, store_a, cat_a, backend_a, _, monitor_a = _plane(
            tmp_path, fault=fault, subdir="plane_a"
        )
        await state_a.connect()
        await _submit(state_a, store_a, backend_a, cat_a, args, "traj-a")
        handle_a = backend_a._handles["traj-a"]
        rec_a = await _drive_to_final(state_a, monitor_a, "traj-a")
        assert rec_a.status is DatabaseStatus.SUCCEEDED, rec_a.metadata
        assert len(rec_a.metadata["attempt_history"]) == 1

        # leg B: uninterrupted twin (separate plane, same spec + seed)
        state_b, store_b, cat_b, backend_b, _, monitor_b = _plane(
            tmp_path, subdir="plane_b"
        )
        await state_b.connect()
        await _submit(state_b, store_b, backend_b, cat_b, args, "traj-b")
        handle_b = backend_b._handles["traj-b"]
        rec_b = await _drive_to_final(state_b, monitor_b, "traj-b")
        assert rec_b.status is DatabaseStatus.SUCCEEDED, rec_b.metadata
        assert rec_b.metadata.get("attempt_history") in (None, [])

        steps_a, rows_a = _metric_steps(handle_a.artifacts_dir)
        steps_b, rows_b = _metric_steps(handle_b.artifacts_dir)
        assert steps_a == steps_b == list(range(cadence, total + 1, cadence))
        for row_a, row_b in zip(rows_a, rows_b):
            for col in ("loss", "accuracy"):
                assert float(row_a[col]) == float(row_b[col]), (
                    f"step {row_a['step']}: {col} diverged after resume "
                    f"({row_a[col]} != {row_b[col]})"
                )
        await backend_a.close()
        await backend_b.close()
        await state_a.close()
        await state_b.close()

    run(main())
