"""Mixture-of-Experts FFN with expert parallelism (Mixtral-style top-k routing).

BASELINE config #4 (Mixtral 8x7B on v5p-64). The reference has no EP at all
(SURVEY.md §2.3: 'new: expert mesh axis'); this is the TPU-native design:

- experts are ONE stacked parameter tensor ``(E, d, f)`` sharded over the
  ``ep`` mesh axis (``parallel/sharding.py`` rules), so expert compute is a
  single batched matmul on the MXU and XLA inserts the all-to-alls when
  tokens cross expert shards;
- top-k routing with a static capacity per expert — no dynamic shapes, no
  host round-trips, everything under one ``jit``. Tokens over capacity are
  dropped (their combine weight is zero), the standard TPU trade for static
  shapes;
- **permutation dispatch, not one-hot matmuls**: slot assignment (the
  GShard cumsum trick) yields a unique (expert, slot) per routed pair, so
  dispatch/combine are a small int scatter plus row gathers — the classic
  (T, E, C) one-hot einsums cost (E·C)·T·d MACs, ~T/(3·d_ff) of the expert
  matmuls themselves (measured: mixtral-proxy bs8 MFU 0.26 with one-hot
  dispatch vs the matmul-free path; equivalence is pinned by
  ``tests/test_model.py::test_moe_permutation_dispatch_matches_dense``);
- router in float32 (softmax numerics), experts in the model compute dtype;
- Switch-Transformer load-balancing aux loss, sown into the ``moe_aux``
  collection; the trainer folds it into the objective.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense SwiGLU MLP."""

    d_model: int
    d_ff: int
    n_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    #: store the stacked expert kernels as blockwise int4 (models/quant.py,
    #: vmapped over the expert axis) — the QLoRA trade at MoE scale: experts
    #: are ~95% of a Mixtral-family model's weights, so quantizing them is
    #: what fits a 10B-class 8-expert model on one v5e chip
    quantize_base: bool = False
    quant_block: int = 64

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        b, s, d = x.shape
        t = b * s
        e, k = self.n_experts, self.top_k
        # static per-expert capacity (tokens), padded to a lane-friendly size
        capacity = max(8, math.ceil(t / e * self.capacity_factor * k))
        capacity = min(capacity, t)

        xt = x.reshape(t, d)

        # ---- router (f32) --------------------------------------------------
        router_kernel = self.param(
            "router_kernel",
            nn.initializers.normal(stddev=d ** -0.5),
            (d, e),
            self.param_dtype,
        )
        logits = jnp.einsum(
            "td,de->te", xt.astype(jnp.float32), router_kernel.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)                     # (T, E)
        top_w, top_idx = jax.lax.top_k(probs, k)                    # (T, k)
        top_w = top_w / jnp.maximum(top_w.sum(-1, keepdims=True), 1e-9)  # Mixtral renorm

        # ---- slot assignment (slot-major priority, static shapes) ----------
        onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)      # (T, k, E)
        slot_major = onehot.transpose(1, 0, 2).reshape(k * t, e)    # slot 0 first
        position = jnp.cumsum(slot_major, axis=0) - slot_major      # rank within expert
        position = position.reshape(k, t, e).transpose(1, 0, 2)     # (T, k, E)
        pos_idx = (position * onehot).sum(-1).astype(jnp.int32)     # (T, k)

        # ---- scatter/gather dispatch (no (T, E, C) one-hot matmuls) --------
        # The classic GShard dense dispatch materialises (T, E, C) one-hot
        # tensors and runs "tec,td->ecd" / "tec,ecd->td" einsums whose cost
        # is (E·C)·T·d MACs — at T=8192 with C=T·cf·k/E that is ~T/(3·d_ff)
        # of the expert matmuls themselves (~50% overhead at the
        # mixtral-proxy bench shapes, and growing linearly with T; measured
        # MFU collapsed 0.38 → 0.26 from bs4 → bs8). Because every routed
        # (token, k) pair owns a UNIQUE (expert, slot), dispatch is really a
        # permutation: scatter the 1-D token ids (cheap), then gather rows.
        valid = pos_idx < capacity                                  # (T, k) bool
        n_slots = e * capacity
        # invalid pairs target index n_slots: OOB for the scatter (dropped)
        # and exactly the appended zero row for the combine gather
        slot = jnp.where(valid, top_idx * capacity + pos_idx, n_slots)
        t_ids = jnp.broadcast_to(jnp.arange(t)[:, None], (t, k))
        # empty slots keep sentinel T -> gather the appended zero row, so
        # unfilled capacity computes on zeros exactly as the dense dispatch
        token_of_slot = jnp.full((n_slots,), t, jnp.int32).at[
            slot.reshape(-1)
        ].set(t_ids.reshape(-1), mode="drop")

        # ---- expert compute (batched over the ep axis) ----------------------
        compute_dtype = self.dtype
        xt_pad = jnp.concatenate(
            [xt.astype(compute_dtype), jnp.zeros((1, d), compute_dtype)]
        )
        expert_in = xt_pad[token_of_slot].reshape(e, capacity, d)
        def expert_kernels(name: str, shape: tuple[int, int, int]) -> jax.Array:
            """Stacked (E, in, out) expert kernels in the compute dtype —
            plain params, or int4 packed+scales quantized per expert
            (``quant.quantized_param``, shared with LoRADense)."""
            if not self.quantize_base:
                w = self.param(
                    name, nn.initializers.lecun_normal(), shape, self.param_dtype
                )
                return w.astype(compute_dtype)
            from .quant import quantized_param

            return quantized_param(
                self, name, shape, nn.initializers.lecun_normal(),
                self.quant_block, compute_dtype,
            )

        w_gate = expert_kernels("experts_gate", (e, d, self.d_ff))
        w_up = expert_kernels("experts_up", (e, d, self.d_ff))
        w_down = expert_kernels("experts_down", (e, self.d_ff, d))
        gate = jnp.einsum("ecd,edf->ecf", expert_in, w_gate)
        up = jnp.einsum("ecd,edf->ecf", expert_in, w_up)
        h = nn.silu(gate) * up
        expert_out = jnp.einsum("ecf,efd->ecd", h, w_down)

        # combine: per routed pair, gather its slot's output row (invalid
        # pairs hit the zero row — identical to the dense combine, where
        # their weight mass was masked) and weight by the renormed router
        out_flat = jnp.concatenate(
            [expert_out.reshape(n_slots, d), jnp.zeros((1, d), compute_dtype)]
        )
        gathered = out_flat[slot]                                   # (T, k, d)
        out = (top_w.astype(compute_dtype)[..., None] * gathered).sum(1)
        out = out.reshape(b, s, d)

        # ---- load-balancing aux loss (Switch eq. 4) -------------------------
        frac_routed = onehot.sum(1).mean(0)          # f_e: fraction per expert
        mean_prob = probs.mean(0)                    # P_e
        aux = e * jnp.sum(frac_routed * mean_prob)
        self.sow("moe_aux", "load_balance", aux)

        return out.astype(x.dtype)


def moe_aux_loss(collections: dict) -> jax.Array:
    """Sum every sown load-balance term (scan stacks them per layer)."""
    leaves = jax.tree_util.tree_leaves(collections.get("moe_aux", {}))
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum(leaf) for leaf in leaves)
