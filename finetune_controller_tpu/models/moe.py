"""Mixture-of-Experts FFN with expert parallelism (Mixtral-style top-k routing).

BASELINE config #4 (Mixtral 8x7B on v5p-64). The reference has no EP at all
(SURVEY.md §2.3: 'new: expert mesh axis'); this is the TPU-native design:

- experts are ONE stacked parameter tensor ``(E, d, f)`` sharded over the
  ``ep`` mesh axis (``parallel/sharding.py`` rules), so expert compute is a
  single batched matmul on the MXU and XLA inserts the all-to-alls when
  tokens cross expert shards;
- GShard-style dense dispatch/combine: top-k routing with a static capacity
  per expert — no dynamic shapes, no host round-trips, everything under one
  ``jit``. Tokens over capacity are dropped (their combine weight is zero),
  the standard TPU trade for static shapes;
- router in float32 (softmax numerics), experts in the model compute dtype;
- Switch-Transformer load-balancing aux loss, sown into the ``moe_aux``
  collection; the trainer folds it into the objective.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense SwiGLU MLP."""

    d_model: int
    d_ff: int
    n_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, deterministic: bool = True) -> jax.Array:
        b, s, d = x.shape
        t = b * s
        e, k = self.n_experts, self.top_k
        # static per-expert capacity (tokens), padded to a lane-friendly size
        capacity = max(8, math.ceil(t / e * self.capacity_factor * k))
        capacity = min(capacity, t)

        xt = x.reshape(t, d)

        # ---- router (f32) --------------------------------------------------
        router_kernel = self.param(
            "router_kernel",
            nn.initializers.normal(stddev=d ** -0.5),
            (d, e),
            self.param_dtype,
        )
        logits = jnp.einsum(
            "td,de->te", xt.astype(jnp.float32), router_kernel.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)                     # (T, E)
        top_w, top_idx = jax.lax.top_k(probs, k)                    # (T, k)
        top_w = top_w / jnp.maximum(top_w.sum(-1, keepdims=True), 1e-9)  # Mixtral renorm

        # ---- slot assignment (slot-major priority, static shapes) ----------
        onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)      # (T, k, E)
        slot_major = onehot.transpose(1, 0, 2).reshape(k * t, e)    # slot 0 first
        position = jnp.cumsum(slot_major, axis=0) - slot_major      # rank within expert
        position = position.reshape(k, t, e).transpose(1, 0, 2)     # (T, k, E)
        in_cap = (position < capacity).astype(jnp.float32) * onehot
        pos_idx = (position * onehot).sum(-1).astype(jnp.int32)     # (T, k)

        # dispatch (T, E, C): one-hot of (expert, slot) per routed token
        cap_onehot = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)  # (T, k, C)
        dispatch = jnp.einsum("tke,tkc->tec", in_cap, cap_onehot)
        combine = jnp.einsum("tke,tkc,tk->tec", in_cap, cap_onehot, top_w)

        # ---- expert compute (batched over the ep axis) ----------------------
        compute_dtype = self.dtype
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch.astype(compute_dtype), xt.astype(compute_dtype)
        )
        w_gate = self.param(
            "experts_gate", nn.initializers.lecun_normal(),
            (e, d, self.d_ff), self.param_dtype,
        )
        w_up = self.param(
            "experts_up", nn.initializers.lecun_normal(),
            (e, d, self.d_ff), self.param_dtype,
        )
        w_down = self.param(
            "experts_down", nn.initializers.lecun_normal(),
            (e, self.d_ff, d), self.param_dtype,
        )
        gate = jnp.einsum("ecd,edf->ecf", expert_in, w_gate.astype(compute_dtype))
        up = jnp.einsum("ecd,edf->ecf", expert_in, w_up.astype(compute_dtype))
        h = nn.silu(gate) * up
        expert_out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(compute_dtype))

        out = jnp.einsum(
            "tec,ecd->td", combine.astype(compute_dtype), expert_out
        ).reshape(b, s, d)

        # ---- load-balancing aux loss (Switch eq. 4) -------------------------
        frac_routed = onehot.sum(1).mean(0)          # f_e: fraction per expert
        mean_prob = probs.mean(0)                    # P_e
        aux = e * jnp.sum(frac_routed * mean_prob)
        self.sow("moe_aux", "load_balance", aux)

        return out.astype(x.dtype)


def moe_aux_loss(collections: dict) -> jax.Array:
    """Sum every sown load-balance term (scan stacks them per layer)."""
    leaves = jax.tree_util.tree_leaves(collections.get("moe_aux", {}))
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return sum(jnp.sum(leaf) for leaf in leaves)
