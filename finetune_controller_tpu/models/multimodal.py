"""LLaVA-style multimodal SFT model: ViT encoder → projector → Llama decoder.

BASELINE config #5 (LLaVA-1.5 multimodal SFT). Architecture follows the
public LLaVA recipe — a vision transformer encodes the image into patch
embeddings, a 2-layer MLP projects them into the LM's embedding space, and
the projected patch tokens are *prepended* to the text embeddings so the
decoder attends to the image as a prefix. TPU-first notes:

- the ViT is plain bidirectional attention over a static patch grid (no
  masking, no ragged shapes) — pure MXU work XLA fuses well;
- the combined sequence is static: ``n_patches + text_len`` every step, so
  one compiled program serves the whole run;
- loss positions: only text-token targets count; the caller's ``loss_mask``
  is extended with zeros over the image prefix inside the model wrapper.

The reference has no model code at all (SURVEY.md §2.2); multimodal here is
a first-class model family beside Llama/Mixtral.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from .llama import LlamaConfig, RMSNorm, _proj
from .lora import LoRAConfig


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 336
    patch_size: int = 14
    d_model: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    d_ff: int = 4096
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # --- CLIP-compatibility knobs (round-5: real LLaVA towers import from
    # HF checkpoints — hf_import.load_llava_params). Defaults keep the
    # native recipe; the llava preset flips them to CLIP ViT-L/14 semantics.
    #: prepend a learned class token (CLIP); LLaVA's feature selection drops
    #: it from the encoder OUTPUT, but it participates in attention
    cls_token: bool = False
    #: LayerNorm right after embeddings (CLIP's pre_layrnorm)
    pre_norm: bool = False
    #: patch conv bias (CLIP uses none)
    patch_bias: bool = True
    #: MLP activation: "gelu" (exact, HF nn.GELU) | "quick_gelu"
    #: (x·sigmoid(1.702x) — OpenAI CLIP)
    act: str = "gelu"
    #: which hidden state feeds the projector: 0 = all layers + final norm
    #: (native); negative = CLIP hidden_states index (LLaVA-1.5 uses -2 —
    #: stop before the last layer, skip the post norm)
    feature_layer: int = 0
    #: LayerNorm epsilon (CLIP uses 1e-5; flax's default is 1e-6)
    ln_eps: float = 1e-5

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    def replace(self, **kw) -> "ViTConfig":
        return dataclasses.replace(self, **kw)


@dataclasses.dataclass(frozen=True)
class LlavaConfig:
    vision: ViTConfig = dataclasses.field(default_factory=ViTConfig)
    text: LlamaConfig = dataclasses.field(default_factory=LlamaConfig)
    projector_hidden: int = 4096

    # trainer duck-type surface (mirrors LlamaConfig)
    @property
    def vocab_size(self) -> int:
        return self.text.vocab_size

    @property
    def lora(self) -> LoRAConfig:
        return self.text.lora

    @property
    def n_experts(self) -> int:
        return self.text.n_experts

    @property
    def router_aux_weight(self) -> float:
        return self.text.router_aux_weight

    @property
    def attention_impl(self) -> str:
        return self.text.attention_impl

    @property
    def image_size(self) -> int:
        return self.vision.image_size

    @property
    def max_seq_len(self) -> int:
        """Decoder position budget — the image prefix (``n_patches``) and the
        text share it."""
        return self.text.max_seq_len

    def replace(self, **kw) -> "LlavaConfig":
        # route llama-level overrides (lora=...) into the text config
        text_keys = {f.name for f in dataclasses.fields(LlamaConfig)}
        text_kw = {k: v for k, v in kw.items() if k in text_keys}
        top_kw = {k: v for k, v in kw.items() if k not in text_keys}
        cfg = self
        if text_kw:
            cfg = dataclasses.replace(cfg, text=cfg.text.replace(**text_kw))
        if top_kw:
            cfg = dataclasses.replace(cfg, **top_kw)
        return cfg

    def param_count(self) -> int:
        v = self.vision
        vit = v.n_layers * (4 * v.d_model * v.d_model + 2 * v.d_model * v.d_ff)
        proj = v.d_model * self.projector_hidden + self.projector_hidden * self.text.d_model
        return vit + proj + self.text.param_count()


def _vit_act(cfg: ViTConfig, h: jax.Array) -> jax.Array:
    if cfg.act == "quick_gelu":
        return h * jax.nn.sigmoid(1.702 * h)
    if cfg.act == "gelu":
        return nn.gelu(h, approximate=False)
    raise ValueError(f"unknown ViT activation {cfg.act!r}")


class ViTBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="ln1")(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=cfg.n_heads, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name="attn",
        )(h, h)
        x = x + h
        h = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="ln2")(x)
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="fc1")(h)
        h = _vit_act(cfg, h)
        h = nn.Dense(cfg.d_model, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     name="fc2")(h)
        return x + h


class ViTEncoder(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, pixels: jax.Array) -> jax.Array:
        """pixels (B, H, W, 3) → (B, n_patches, d_model).

        With ``cls_token`` the class token rides through attention and is
        dropped from the OUTPUT (LLaVA's "default" feature selection);
        ``feature_layer=-k`` stops k-1 layers early and skips the post norm
        (LLaVA-1.5 takes CLIP's hidden_states[-2])."""
        cfg = self.cfg
        x = nn.Conv(
            cfg.d_model,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            use_bias=cfg.patch_bias,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            name="patch_embed",
        )(pixels.astype(cfg.dtype))
        b = x.shape[0]
        x = x.reshape(b, -1, cfg.d_model)
        n_tokens = cfg.n_patches
        if cfg.cls_token:
            cls = self.param(
                "cls", nn.initializers.normal(stddev=0.02),
                (1, 1, cfg.d_model), cfg.param_dtype,
            )
            x = jnp.concatenate(
                [jnp.broadcast_to(cls.astype(cfg.dtype), (b, 1, cfg.d_model)), x],
                axis=1,
            )
            n_tokens += 1
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, n_tokens, cfg.d_model),
            cfg.param_dtype,
        )
        x = x + pos.astype(cfg.dtype)
        if cfg.pre_norm:
            x = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, name="pre_norm")(x)
        n_run = (
            cfg.n_layers if cfg.feature_layer == 0
            else cfg.n_layers + cfg.feature_layer + 1
        )
        if not 0 < n_run <= cfg.n_layers:
            raise ValueError(
                f"feature_layer {cfg.feature_layer} out of range for "
                f"{cfg.n_layers} layers"
            )
        for i in range(n_run):
            x = ViTBlock(cfg, name=f"block_{i}")(x)
        if cfg.feature_layer == 0:
            x = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, name="final_norm")(x)
        if cfg.cls_token:
            x = x[:, 1:]  # feature selection drops CLS
        return x


class LlavaForCausalLM(nn.Module):
    """Image-prefix causal LM. Call with (tokens, pixels).

    KV-cached decode (round 5): ``decode=True`` with pixels fills the cache
    over the combined ``[image; text]`` sequence; subsequent single-token
    calls pass ``pixels=None`` and ABSOLUTE ``positions`` (offset by
    ``n_patches`` — the caller owns the position arithmetic, as in
    ``models/generate.py::cached_generate``)."""

    cfg: LlavaConfig

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,               # (B, S)
        pixels: jax.Array | None = None,  # (B, H, W, 3)
        segment_ids: jax.Array | None = None,
        deterministic: bool = True,
        decode: bool = False,
        positions: jax.Array | None = None,
    ) -> jax.Array:
        cfg = self.cfg
        tcfg = cfg.text
        b, s = tokens.shape

        embed = nn.Embed(
            tcfg.vocab_size, tcfg.d_model,
            dtype=tcfg.dtype, param_dtype=tcfg.param_dtype, name="embed_tokens",
        )
        text_emb = embed(tokens)                         # (B, S, d)

        n_img = 0
        if pixels is not None:
            patches = ViTEncoder(cfg.vision, name="vision_tower")(pixels)
            # 2-layer MLP projector (LLaVA-1.5 recipe)
            h = nn.Dense(cfg.projector_hidden, dtype=tcfg.dtype,
                         param_dtype=tcfg.param_dtype, name="projector_fc1")(patches)
            # exact GELU — HF's multi_modal_projector uses nn.GELU (erf
            # form), and the imported projector must reproduce it
            h = nn.gelu(h, approximate=False)
            img_emb = nn.Dense(tcfg.d_model, dtype=tcfg.dtype,
                               param_dtype=tcfg.param_dtype, name="projector_fc2")(h)
            n_img = img_emb.shape[1]
            x = jnp.concatenate([img_emb, text_emb], axis=1)
        else:
            x = text_emb

        total = n_img + s
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(total), (b, total))
        if segment_ids is not None and n_img:
            # image prefix joins the first text segment so text can attend to it
            first = segment_ids[:, :1]
            segment_ids = jnp.concatenate(
                [jnp.broadcast_to(first, (b, n_img)), segment_ids], axis=1
            )

        # reuse the Llama decoder stack over the combined sequence
        from .llama import Block, _ScanBlock, remat_policy_fn

        policy = remat_policy_fn(tcfg.remat_policy)
        if tcfg.scan_layers:
            block_cls = _ScanBlock
            if tcfg.remat and policy is not None:
                block_cls = nn.remat(
                    _ScanBlock, prevent_cse=False, static_argnums=(4, 5),
                    policy=policy,
                )
            stack = nn.scan(
                block_cls,
                variable_axes={"params": 0, "lora": 0, "moe_aux": 0, "cache": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast, nn.broadcast, nn.broadcast, nn.broadcast),
                length=tcfg.n_layers,
            )(tcfg, name="blocks")
            x, _ = stack(x, positions, segment_ids, deterministic, decode)
        else:
            block_cls = (
                nn.remat(Block, prevent_cse=False, static_argnums=(4, 5),
                         policy=policy)
                if tcfg.remat and policy is not None
                else Block
            )
            for i in range(tcfg.n_layers):
                x = block_cls(tcfg, name=f"layer_{i}")(
                    x, positions, segment_ids, deterministic, decode
                )

        x = RMSNorm(tcfg.rms_eps, tcfg.dtype, tcfg.param_dtype, tcfg.norm_offset, name="final_norm")(x)
        x = x[:, n_img:]                                 # logits for text positions only
        logits = _proj(tcfg.replace(lora=LoRAConfig()), "lm_head", tcfg.vocab_size)(x)
        return logits.astype(tcfg.logits_dtype or jnp.float32)

    def init_variables(self, rng: jax.Array, batch: int = 1, seq: int = 8):
        tokens = jnp.zeros((batch, seq), jnp.int32)
        size = self.cfg.vision.image_size
        pixels = jnp.zeros((batch, size, size, 3), jnp.float32)
        return self.init({"params": rng}, tokens, pixels)


MM_PRESETS: dict[str, LlavaConfig] = {
    "llava-1.5-7b": LlavaConfig(
        # CLIP ViT-L/14 @ 336px with LLaVA-1.5 semantics: class token, CLIP
        # pre-norm, quick-gelu, bias-free patch conv, penultimate-layer
        # features — the exact tower llava-hf/llava-1.5-7b-hf ships, so
        # hf_import.load_llava_params maps it 1:1
        vision=ViTConfig(cls_token=True, pre_norm=True, patch_bias=False,
                         act="quick_gelu", feature_layer=-2),
        text=LlamaConfig(
            vocab_size=32064, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=32, d_ff=11008, max_seq_len=4096, attention_impl="auto",
        ),
        projector_hidden=4096,
    ),
    "tiny-mm-test": LlavaConfig(
        vision=ViTConfig(image_size=16, patch_size=8, d_model=32, n_layers=2,
                         n_heads=2, d_ff=64),
        text=LlamaConfig(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=128,
        ),
        projector_hidden=64,
    ),
    # CLIP-semantics tiny model: the import/e2e test shape — structurally a
    # miniature llava-1.5-7b (class token, pre-norm, quick-gelu,
    # penultimate-layer features), loadable from a tiny HF LLaVA checkpoint
    "tiny-mm-clip-test": LlavaConfig(
        vision=ViTConfig(image_size=16, patch_size=8, d_model=32, n_layers=3,
                         n_heads=2, d_ff=64, cls_token=True, pre_norm=True,
                         patch_bias=False, act="quick_gelu", feature_layer=-2),
        text=LlamaConfig(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=128, max_seq_len=128, rms_eps=1e-6,
        ),
        projector_hidden=64,
    ),
}
