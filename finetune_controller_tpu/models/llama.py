"""Llama-family decoder (RMSNorm + RoPE + GQA + SwiGLU) in Flax linen.

TPU-first choices:
  * layers run under ``nn.scan`` (one traced layer, stacked params) so XLA
    compiles one block body instead of N — critical for compile latency on
    real models;
  * per-layer rematerialisation (``nn.remat``) trades FLOPs for HBM;
  * bf16 compute / f32 params+softmax;
  * attention dispatches through ``ops.causal_attention`` (XLA or Pallas).

Capability parity note: the reference framework contains no model code at all
(training is a user container — SURVEY.md §2.2); this module is the in-repo
compute plane that replaces it.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ..ops.attention import causal_attention
from .lora import LoRAConfig, LoRADense


def remat_policy_fn(name: str):
    """Rematerialisation policy for per-layer ``nn.remat``/``jax.checkpoint``.

    ``"full"`` recomputes the whole layer forward in the backward pass (lowest
    HBM, ~2N extra FLOPs/token).  The named policies keep selected activation
    tensors (``checkpoint_name`` marks in ``Attention``/``MLP``) so the
    backward pass skips recomputing the matmuls that produced them — the
    standard TPU HBM-for-FLOPs dial.  Saved bytes per layer row grow in the
    order attn < wide < matmuls; pick the biggest that fits HBM.
    """
    saveable = {
        "full": (),
        # attention context (post-flash, pre-o_proj): skips the S^2 forward
        # recompute where the attention residuals allow it
        "attn": ("attn_ctx",),
        # the d_ff-wide MLP activations — the most recompute-bandwidth per
        # byte saved
        "mlp": ("mlp_gate", "mlp_up"),
        # mlp + rope'd q/k/v (skips the qkv-projection + rope recompute);
        # ~84MB/layer more than "mlp" at bs8/seq2048 on TinyLlama
        "mlp_qkv": ("mlp_gate", "mlp_up", "attn_qkv"),
        # the Pallas flash-attention residuals (out + logsumexp, named inside
        # the kernel's custom_vjp fwd — ops/pallas/flash_attention.py): the
        # backward then reuses them instead of re-running the forward kernel
        "flash": ("flash_out", "flash_lse"),
        # mlp + flash residuals — the measured-best combination on a v5e chip
        # when both fit (TinyLlama bs8/seq2048)
        "mlp_flash": ("mlp_gate", "mlp_up", "flash_out", "flash_lse"),
        # everything wide: MLP hiddens + rope'd q/k/v + attention context
        "wide": ("mlp_gate", "mlp_up", "attn_qkv", "attn_ctx"),
        # every projection output: backward re-runs (almost) no forward
        # matmuls; only fits when params are bf16/int4 and batch is modest
        "matmuls": (
            "mlp_gate", "mlp_up", "mlp_down", "attn_qkv", "attn_ctx", "attn_o",
        ),
    }
    if name == "none":
        return None
    if name not in saveable:
        raise ValueError(
            f"unknown remat_policy {name!r}; one of "
            f"{['none', *saveable]}"
        )
    if name == "full":
        return jax.checkpoint_policies.nothing_saveable
    return jax.checkpoint_policies.save_only_these_names(*saveable[name])


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 2048
    n_layers: int = 22
    n_heads: int = 32
    n_kv_heads: int = 4
    d_ff: int = 5632
    rope_theta: float = 10000.0
    #: llama3-style RoPE frequency scaling (the Llama-3.1/3.2 long-context
    #: recipe; transformers ``rope_scaling: {"rope_type": "llama3"}``):
    #: 0.0 disables. Long-wavelength components are slowed by ``factor``,
    #: short wavelengths kept, with a smooth ramp between the two cutoff
    #: wavelengths derived from the original training context. Parity with
    #: transformers is pinned in tests/test_hf_import.py.
    rope_scaling_factor: float = 0.0
    rope_scaling_low_freq_factor: float = 1.0
    rope_scaling_high_freq_factor: float = 4.0
    rope_scaling_original_max_len: int = 8192
    max_seq_len: int = 2048
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_impl: str = "xla"
    # --- kernel-tuning knobs (round-5: typed-spec surface for the measured
    # winners so API-submitted jobs carry them; the FTC_FLASH_* /
    # FTC_RING_INNER / FTC_ULYSSES_INNER env vars remain operator overrides —
    # ``ops/attention.py`` merges env over these). 0/"" = kernel default.
    flash_block_q: int = 0
    flash_block_k: int = 0
    flash_exp_dtype: str = ""      # "float32" | "bfloat16"
    ring_inner: str = ""           # "xla" | "flash"
    ulysses_inner: str = ""        # "xla" | "pallas"
    remat: bool = True
    #: which activations the per-layer remat keeps (see ``remat_policy_fn``):
    #: "full" | "attn" | "mlp" | "wide" | "matmuls" | "none" ("none" disables
    #: remat entirely even when ``remat=True`` is left at its default)
    remat_policy: str = "full"
    #: dtype the lm-head logits are materialised in. float32 is exact; bf16
    #: halves the (B, S, V) tensor's HBM footprint and round-trip traffic —
    #: the loss still computes its log-softmax in f32 (train/losses.py), only
    #: the stored logits are rounded. None = float32.
    logits_dtype: Any = None
    scan_layers: bool = True
    tie_embeddings: bool = False
    lora: LoRAConfig = dataclasses.field(default_factory=LoRAConfig)
    # MoE (0 experts = dense MLP); BASELINE config #4
    n_experts: int = 0
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.02
    # QLoRA: frozen projection kernels stored as blockwise int4 (config #3)
    quantize_base: bool = False
    quant_block: int = 64
    # --- Gemma-family knobs (defaults = Llama semantics) -------------------
    #: attention head dim decoupled from d_model // n_heads (Gemma uses 256
    #: with d_model 2048/3072); 0 = d_model // n_heads
    head_dim_override: int = 0
    #: MLP gate activation: "silu" (Llama SwiGLU) | "gelu" (Gemma GeGLU,
    #: tanh-approximate like transformers' gelu_pytorch_tanh)
    mlp_act: str = "silu"
    #: RMSNorm weight parameterisation: 0.0 = plain scale (Llama, ones-init);
    #: 1.0 = (1 + scale) with zeros-init (Gemma — HF stores the offset form)
    norm_offset: float = 0.0
    #: multiply embedding output by sqrt(d_model) (Gemma input scaling)
    embed_scale: bool = False
    #: bias terms on the q/k/v projections (Qwen-2 family; o_proj and the
    #: MLP stay bias-free there, matching the HF architecture)
    attention_qkv_bias: bool = False
    # --- serving-only knobs (inert at 0; never set by training specs) ------
    #: paged KV cache (docs/serving.md §Paged KV): sequence positions per
    #: page. When > 0 together with ``kv_pool_pages``, the decode-path cache
    #: becomes a shared (P, page_tokens, Hkv, D) page pool per layer,
    #: addressed through the per-lane ``page_table`` argument — lanes hold
    #: pages proportional to their length instead of ``max_seq_len`` slots.
    kv_page_tokens: int = 0
    #: total pages P in the pool (page 0 is the scratch page)
    kv_pool_pages: int = 0
    #: multi-tenant unmerged-LoRA serving: stacked adapter slots (slot 0 =
    #: base model) applied per batch row via the ``adapter_ids`` argument
    #: (``models/lora.py``); 0 disables the tenant branch entirely
    lora_tenant_slots: int = 0
    #: stacked adapter rank ceiling (smaller trained ranks are zero-padded)
    lora_tenant_rank: int = 0

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.d_model // self.n_heads

    @property
    def image_size(self) -> int:
        """Pixels-per-side of the vision input (0 = text-only model) — the
        duck-type surface multimodal configs override, so data pipelines can
        size pixel batches without model-family checks."""
        return 0

    def replace(self, **kw) -> "LlamaConfig":
        return dataclasses.replace(self, **kw)

    def kernel_tuning(self) -> dict:
        """Non-default kernel knobs as the dict ``ops.attention`` consumes
        (a trace-time constant — values are static ints/strings)."""
        t: dict = {}
        if self.flash_block_q:
            t["block_q"] = self.flash_block_q
        if self.flash_block_k:
            t["block_k"] = self.flash_block_k
        if self.flash_exp_dtype:
            t["exp_dtype"] = self.flash_exp_dtype
        if self.ring_inner:
            t["ring_inner"] = self.ring_inner
        if self.ulysses_inner:
            t["ulysses_inner"] = self.ulysses_inner
        return t

    def _count_with_mlp(self, mlp: int) -> int:
        d, v, L = self.d_model, self.vocab_size, self.n_layers
        hd = self.head_dim
        qo = 2 * d * self.n_heads * hd
        kv = 2 * d * self.n_kv_heads * hd
        per_layer = qo + kv + mlp + 2 * d
        return v * d + L * per_layer + d + (0 if self.tie_embeddings else d * v)

    def param_count(self) -> int:
        """Total stored parameters (MoE: ALL experts)."""
        d, f = self.d_model, self.d_ff
        if self.n_experts:
            mlp = self.n_experts * 3 * d * f + d * self.n_experts
        else:
            mlp = 3 * d * f
        return self._count_with_mlp(mlp)

    def active_param_count(self) -> int:
        """Parameters one token's forward actually touches — for MoE, the
        router plus ``moe_top_k`` of ``n_experts`` experts; equal to
        :meth:`param_count` on dense configs.  MFU/FLOP accounting must use
        this (6·N_active per token): counting idle experts would credit the
        chip with matmuls it never ran."""
        d, f = self.d_model, self.d_ff
        if self.n_experts:
            mlp = self.moe_top_k * 3 * d * f + d * self.n_experts
        else:
            mlp = 3 * d * f
        return self._count_with_mlp(mlp)


# Architecture presets for the BASELINE.md configs (shapes per the public
# model cards; weights are random-init — no network egress in this build).
PRESETS: dict[str, LlamaConfig] = {
    "tiny-test": LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128,
    ),
    # real model families use the measured attention dispatch ("auto": Pallas
    # flash on TPU past the kernel_bench crossover, XLA otherwise) and the
    # measured remat policy ("mlp": keep the d_ff-wide activations — on a v5e
    # chip at bs8/seq2048 this is the largest policy that fits HBM and cuts
    # the TinyLlama step 1.59s -> 1.47s; "wide" OOMs by ~1G)
    "tinyllama-1.1b": LlamaConfig(attention_impl="auto", remat_policy="mlp"),
    "llama3-8b": LlamaConfig(
        vocab_size=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, rope_theta=500000.0, max_seq_len=8192, attention_impl="auto",
        remat_policy="mlp",
    ),
    # Llama-3.2 small family: tied embeddings + llama3 RoPE scaling
    # (factor 32 against the 8k original context -> 128k max positions)
    "llama3.2-1b": LlamaConfig(
        vocab_size=128256, d_model=2048, n_layers=16, n_heads=32, n_kv_heads=8,
        d_ff=8192, rope_theta=500000.0, max_seq_len=131072,
        tie_embeddings=True, rope_scaling_factor=32.0,
        attention_impl="auto", remat_policy="mlp",
    ),
    "llama3.2-3b": LlamaConfig(
        vocab_size=128256, d_model=3072, n_layers=28, n_heads=24, n_kv_heads=8,
        d_ff=8192, rope_theta=500000.0, max_seq_len=131072,
        tie_embeddings=True, rope_scaling_factor=32.0,
        attention_impl="auto", remat_policy="mlp",
    ),
    "mistral-7b": LlamaConfig(
        vocab_size=32768, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq_len=8192, attention_impl="auto", remat_policy="mlp",
    ),
    # long-context variant: raised RoPE base (the Mistral v0.2+ recipe) so
    # positions past 8k stay in the trained frequency range; exports carry
    # the 32k max_position_embeddings
    "mistral-7b-32k": LlamaConfig(
        vocab_size=32768, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq_len=32768, rope_theta=1_000_000.0,
        attention_impl="auto", remat_policy="mlp",
    ),
    "mixtral-8x7b": LlamaConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq_len=8192, n_experts=8, moe_top_k=2,
        attention_impl="auto",
    ),
    # single-chip proxy for BASELINE #4: Mixtral-8x7b needs the v5p-64 slice
    # (47B params), so — like the Llama-3-8B QLoRA proxy for BASELINE #2 —
    # the measurable stand-in keeps the exact architecture (8 experts, top-2
    # GShard dispatch/combine, Mixtral head_dim 128) at a scale whose bf16
    # frozen base (~3.6B total, ~1.1B active/token) fits one v5e chip next
    # to LoRA state and remat'd activations
    "mixtral-proxy": LlamaConfig(
        vocab_size=32000, d_model=2048, n_layers=12, n_heads=16, n_kv_heads=8,
        d_ff=5632, max_seq_len=8192, n_experts=8, moe_top_k=2,
        attention_impl="auto", remat_policy="mlp",
    ),
    # the larger proxy int4 expert quantization unlocks (experts are ~95% of
    # a Mixtral-family model's weights): ~10B total / ~3.3B active params,
    # int4 experts ≈ 5G — fits one v5e chip where bf16 would need ~20G.
    # Run with quantize_base=True (BENCH_MODE=qlora BENCH_PRESET=mixtral-proxy-10b)
    "mixtral-proxy-10b": LlamaConfig(
        vocab_size=32000, d_model=3072, n_layers=16, n_heads=24, n_kv_heads=8,
        d_ff=8192, max_seq_len=8192, n_experts=8, moe_top_k=2,
        attention_impl="auto", remat_policy="full",
    ),
    # Gemma family: GeGLU MLP, (1+w) RMSNorm, sqrt(d) embed scaling, tied
    # head, head_dim 256 decoupled from d_model/n_heads (model-card shapes)
    "gemma-2b": LlamaConfig(
        vocab_size=256000, d_model=2048, n_layers=18, n_heads=8, n_kv_heads=1,
        d_ff=16384, max_seq_len=8192, head_dim_override=256, mlp_act="gelu",
        norm_offset=1.0, embed_scale=True, tie_embeddings=True,
        rms_eps=1e-6, attention_impl="auto", remat_policy="mlp",
    ),
    "gemma-7b": LlamaConfig(
        vocab_size=256000, d_model=3072, n_layers=28, n_heads=16, n_kv_heads=16,
        d_ff=24576, max_seq_len=8192, head_dim_override=256, mlp_act="gelu",
        norm_offset=1.0, embed_scale=True, tie_embeddings=True,
        rms_eps=1e-6, attention_impl="auto", remat_policy="mlp",
    ),
    "tiny-gemma-test": LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, head_dim_override=32, mlp_act="gelu",
        norm_offset=1.0, embed_scale=True, tie_embeddings=True, rms_eps=1e-6,
    ),
    # Qwen-2 family: Llama-shaped with q/k/v projection biases
    "qwen2-7b": LlamaConfig(
        vocab_size=152064, d_model=3584, n_layers=28, n_heads=28, n_kv_heads=4,
        d_ff=18944, rope_theta=1_000_000.0, max_seq_len=8192, rms_eps=1e-6,
        attention_qkv_bias=True, attention_impl="auto", remat_policy="mlp",
    ),
    "tiny-qwen-test": LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, rms_eps=1e-6, attention_qkv_bias=True,
    ),
    "tiny-moe-test": LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, n_experts=4, moe_top_k=2,
    ),
}


def rope_inv_freqs(cfg: "LlamaConfig") -> jax.Array:
    """Per-pair inverse frequencies, with optional llama3-style scaling.

    The scaling partitions frequency space by wavelength against the
    original training context: wavelengths longer than
    ``orig/low_freq_factor`` are slowed by ``factor`` (they must cover the
    extended context), shorter than ``orig/high_freq_factor`` are kept
    (local positional detail), and the band between interpolates smoothly —
    matching transformers' ``_compute_llama3_parameters``.
    """
    half = cfg.head_dim // 2
    freqs = 1.0 / (
        cfg.rope_theta ** (jnp.arange(half, dtype=jnp.float32) / half)
    )
    factor = cfg.rope_scaling_factor
    if not factor:
        return freqs
    orig = cfg.rope_scaling_original_max_len
    low_f, high_f = cfg.rope_scaling_low_freq_factor, cfg.rope_scaling_high_freq_factor
    low_wl, high_wl = orig / low_f, orig / high_f
    wavelen = 2.0 * math.pi / freqs
    smooth = (orig / wavelen - low_f) / (high_f - low_f)
    smoothed = (1.0 - smooth) * freqs / factor + smooth * freqs
    return jnp.where(
        wavelen > low_wl, freqs / factor,
        jnp.where(wavelen < high_wl, freqs, smoothed),
    )


def apply_rope(
    x: jax.Array, positions: jax.Array, theta: float | None = None,
    *, inv_freqs: jax.Array | None = None,
) -> jax.Array:
    """Rotary embedding. x: (B, S, H, D), positions: (B, S).

    Pass exactly one of ``theta`` (plain schedule) or ``inv_freqs``
    (precomputed, e.g. :func:`rope_inv_freqs` with llama3 scaling) — a
    silently-ignored ``theta`` next to explicit frequencies would hide
    schedule bugs.
    """
    if (theta is None) == (inv_freqs is None):
        raise ValueError("pass exactly one of theta or inv_freqs")
    d = x.shape[-1]
    half = d // 2
    if inv_freqs is None:
        freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    else:
        freqs = inv_freqs
    angles = positions[..., None].astype(jnp.float32) * freqs  # (B, S, half)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    #: weight parameterisation: effective scale = offset + stored scale.
    #: 0.0 = Llama (ones-init scale); 1.0 = Gemma ((1 + w), zeros-init —
    #: matching how HF Gemma checkpoints store the weight)
    offset: float = 0.0

    @nn.compact
    def __call__(self, x):
        init = (
            nn.initializers.zeros_init() if self.offset
            else nn.initializers.ones_init()
        )
        scale = self.param("scale", init, (x.shape[-1],), self.param_dtype)
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (norm * (self.offset + scale.astype(jnp.float32))).astype(self.dtype)


def _proj(cfg: LlamaConfig, name: str, features: int) -> LoRADense:
    lora_on = cfg.lora.enabled_for(name)
    qkv_bias = cfg.attention_qkv_bias and name in ("q_proj", "k_proj", "v_proj")
    return LoRADense(
        features=features,
        name=name,
        lora_rank=cfg.lora.rank if lora_on else 0,
        lora_alpha=cfg.lora.alpha,
        lora_dropout=cfg.lora.dropout,
        use_bias=qkv_bias,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        quantize_base=cfg.quantize_base,
        quant_block=cfg.quant_block,
        tenant_slots=cfg.lora_tenant_slots,
        tenant_rank=cfg.lora_tenant_rank,
    )


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids, deterministic=True,
                 decode=False, page_table=None, adapter_ids=None):
        cfg = self.cfg
        b, s, _ = x.shape
        hd = cfg.head_dim
        q = _proj(cfg, "q_proj", cfg.n_heads * hd)(x, deterministic, adapter_ids)
        k = _proj(cfg, "k_proj", cfg.n_kv_heads * hd)(x, deterministic, adapter_ids)
        v = _proj(cfg, "v_proj", cfg.n_kv_heads * hd)(x, deterministic, adapter_ids)
        inv_freqs = rope_inv_freqs(cfg)
        q = apply_rope(q.reshape(b, s, cfg.n_heads, hd), positions,
                       inv_freqs=inv_freqs)
        k = apply_rope(k.reshape(b, s, cfg.n_kv_heads, hd), positions,
                       inv_freqs=inv_freqs)
        v = v.reshape(b, s, cfg.n_kv_heads, hd)
        if decode:
            return self._decode_attention(q, k, v, deterministic,
                                          page_table, adapter_ids)
        q = checkpoint_name(q, "attn_qkv")
        k = checkpoint_name(k, "attn_qkv")
        v = checkpoint_name(v, "attn_qkv")
        out = causal_attention(
            q, k, v, impl=cfg.attention_impl, segment_ids=segment_ids,
            tuning=cfg.kernel_tuning(),
        )
        out = checkpoint_name(out, "attn_ctx")
        out = _proj(cfg, "o_proj", cfg.d_model)(
            out.reshape(b, s, -1), deterministic, adapter_ids)
        return checkpoint_name(out, "attn_o")

    def _decode_attention(self, q, k, v, deterministic, page_table=None,
                          adapter_ids=None):
        """KV-cached generation path (``models/generate.py`` fill-then-decode).

        A static-length cache (``cfg.max_seq_len`` slots) lives in the flax
        ``cache`` collection.  Three regimes:

        * **fresh** (no cache variable yet): prefill from zero — write the
          prompt's K/V at ``[0, S)`` and run the normal causal kernel;
        * **existing cache, S == 1**: the decode step — append at the cache
          index and attend over the valid prefix;
        * **existing cache, S > 1**: suffix prefill — continue FROM the cache
          index (per-row): the chunk's K/V land at ``[idx, idx + S)`` and
          query j attends the cached prefix plus the chunk up to itself.
          This is how the serving engine's prefix-reuse path
          (``serve/prefix_cache.py``) prefills only the uncached tail of a
          prompt; causality makes the result bit-identical to a monolithic
          prefill of the whole sequence.

        Closes the round-2 gap of the uncached O(n²)-per-token sampler being
        impractical at 7B (VERDICT r2 weak #7).

        The cache index is a PER-ROW ``(B,)`` vector: ``cached_generate``
        keeps every row in lockstep (all entries equal), while the serving
        engine (``serve/engine.py``) decodes each batch slot at its own
        position so requests can join mid-flight.
        """
        from ..ops.attention import chunked_cache_attention, single_token_attention

        cfg = self.cfg
        b, s, _, hd = q.shape
        if cfg.kv_page_tokens and cfg.kv_pool_pages:
            return self._paged_decode_attention(
                q, k, v, deterministic, page_table, adapter_ids
            )
        m = cfg.max_seq_len
        fresh = not self.has_variable("cache", "k")
        ck = self.variable(
            "cache", "k",
            lambda: jnp.zeros((b, m, cfg.n_kv_heads, hd), cfg.dtype))
        cv = self.variable(
            "cache", "v",
            lambda: jnp.zeros((b, m, cfg.n_kv_heads, hd), cfg.dtype))
        ci = self.variable("cache", "index",
                           lambda: jnp.zeros((b,), jnp.int32))
        if fresh:
            # prefill: write the prompt's K/V and run the normal causal kernel
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k.astype(cfg.dtype), (0, 0, 0, 0))
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v.astype(cfg.dtype), (0, 0, 0, 0))
            ci.value = jnp.full((b,), s, jnp.int32)
            out = causal_attention(q, k, v, impl="xla")
        elif s > 1:
            # suffix prefill: continue an existing cache at its per-row index
            idx = ci.value  # (B,)
            rows = jnp.arange(b)[:, None]
            cols = idx[:, None] + jnp.arange(s)[None, :]
            ck.value = ck.value.at[rows, cols].set(k.astype(cfg.dtype))
            cv.value = cv.value.at[rows, cols].set(v.astype(cfg.dtype))
            ci.value = idx + s
            out = chunked_cache_attention(q, ck.value, cv.value, idx)
        else:
            idx = ci.value  # (B,) — rows may sit at different positions
            rows = jnp.arange(b)
            # write clamped to the last slot and index advance saturated at
            # m: identity for live rows (the caller never decodes past the
            # cache end), but a PARKED serving lane riding the batched step
            # indefinitely (serve/engine.py) stays in-bounds forever instead
            # of creeping past m
            wr = jnp.minimum(idx, m - 1)
            ck.value = ck.value.at[rows, wr].set(k[:, 0].astype(cfg.dtype))
            cv.value = cv.value.at[rows, wr].set(v[:, 0].astype(cfg.dtype))
            ci.value = jnp.minimum(idx + 1, m)
            out = single_token_attention(q, ck.value, cv.value, idx)
        return _proj(cfg, "o_proj", cfg.d_model)(
            out.reshape(b, s, -1), deterministic, adapter_ids)

    def _paged_decode_attention(self, q, k, v, deterministic, page_table,
                                adapter_ids):
        """Decode-path attention through a shared KV page pool
        (docs/serving.md §Paged KV).

        The cache collection holds one (P, T, Hkv, D) page pool per layer —
        batch-size independent, shared by every lane — plus the per-row
        ``index``; which pages belong to which lane arrives as the
        ``page_table`` (B, MP) argument the serve engine passes into every
        jitted call (``serve/kv_pages.py`` owns the allocator).  One code
        path serves prefill (index 0), suffix prefill continuing a spliced
        prefix (index = reuse length), and the decode step (S = 1): the
        chunk's K/V scatter to ``(table[pos // T], pos % T)`` and attention
        gathers the lane's logical cache back through the table
        (``ops.attention.paged_cache_attention``) — bit-equal to the
        contiguous cache because masked slots (including anything read
        through an unmaterialized table entry's scratch page) contribute an
        exact 0.0 to the softmax.

        Write positions clamp to the last logical slot and the index
        saturates, mirroring the unpaged branch: a parked lane (all-scratch
        table, index 0) rides every step writing throwaway tokens into the
        scratch page that no live lane ever reads unmasked.
        """
        from ..ops.attention import paged_cache_attention

        cfg = self.cfg
        b, s, _, hd = q.shape
        t, p = cfg.kv_page_tokens, cfg.kv_pool_pages
        if page_table is None:
            raise ValueError(
                "paged KV decode (kv_page_tokens > 0) requires the "
                "page_table argument"
            )
        ck = self.variable(
            "cache", "k",
            lambda: jnp.zeros((p, t, cfg.n_kv_heads, hd), cfg.dtype))
        cv = self.variable(
            "cache", "v",
            lambda: jnp.zeros((p, t, cfg.n_kv_heads, hd), cfg.dtype))
        ci = self.variable("cache", "index",
                           lambda: jnp.zeros((b,), jnp.int32))
        cap = page_table.shape[-1] * t
        idx = ci.value  # (B,) — every lane at its own position
        pos = idx[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
        pos_w = jnp.minimum(pos, cap - 1)
        phys = jnp.take_along_axis(page_table, pos_w // t, axis=1)  # (B, S)
        off = pos_w % t
        ck.value = ck.value.at[phys, off].set(k.astype(cfg.dtype))
        cv.value = cv.value.at[phys, off].set(v.astype(cfg.dtype))
        ci.value = jnp.minimum(idx + s, cap)
        out = paged_cache_attention(q, ck.value, cv.value, page_table, idx)
        return _proj(cfg, "o_proj", cfg.d_model)(
            out.reshape(b, s, -1), deterministic, adapter_ids)


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, deterministic=True, adapter_ids=None):
        cfg = self.cfg
        gate = checkpoint_name(
            _proj(cfg, "gate_proj", cfg.d_ff)(x, deterministic, adapter_ids),
            "mlp_gate")
        up = checkpoint_name(
            _proj(cfg, "up_proj", cfg.d_ff)(x, deterministic, adapter_ids),
            "mlp_up")
        act = nn.gelu if cfg.mlp_act == "gelu" else nn.silu  # GeGLU | SwiGLU
        out = _proj(cfg, "down_proj", cfg.d_model)(
            act(gate) * up, deterministic, adapter_ids)
        return checkpoint_name(out, "mlp_down")


class Block(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids, deterministic=True,
                 decode=False, page_table=None, adapter_ids=None):
        cfg = self.cfg
        h = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.param_dtype, cfg.norm_offset, name="attn_norm")(x)
        x = x + Attention(cfg, name="attn")(
            h, positions, segment_ids, deterministic, decode,
            page_table, adapter_ids)
        h = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.param_dtype, cfg.norm_offset, name="mlp_norm")(x)
        if cfg.n_experts:
            from .moe import MoEMLP

            mlp_out = MoEMLP(
                d_model=cfg.d_model,
                d_ff=cfg.d_ff,
                n_experts=cfg.n_experts,
                top_k=cfg.moe_top_k,
                capacity_factor=cfg.capacity_factor,
                dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                quantize_base=cfg.quantize_base,
                quant_block=cfg.quant_block,
                name="moe",
            )(h, deterministic)
        else:
            mlp_out = MLP(cfg, name="mlp")(h, deterministic, adapter_ids)
        return x + mlp_out


def stacked_block_variables(variables: dict) -> dict:
    """Extract the layer-stacked block variables (leading layer axis) from a
    ``scan_layers`` model's variable tree — the pipeline's stage parameters."""
    out = {"params": variables["params"]["blocks"]["block"]}
    if "lora" in variables and "blocks" in variables["lora"]:
        out["lora"] = variables["lora"]["blocks"]["block"]
    return out


def make_block_stage_fn(cfg: LlamaConfig):
    """Stage body for the GPipe pipeline: scan this stage's layer shard over
    the activations (``parallel/pipeline.py`` contract). Honors ``cfg.remat``
    exactly like the non-pipelined scan path — without it, reverse-mode would
    save every layer's residuals for every tick and large models would OOM."""
    block = Block(cfg)

    def one_layer(layer_vars, h, positions, segment_ids):
        return block.apply(layer_vars, h, positions, segment_ids, True)

    policy = remat_policy_fn(cfg.remat_policy)
    if cfg.remat and policy is not None:
        one_layer = jax.checkpoint(
            one_layer, prevent_cse=False, policy=policy,
        )

    def stage_fn(stage_vars, x, positions, segment_ids):
        def body(h, layer_vars):
            return one_layer(layer_vars, h, positions, segment_ids), None

        h, _ = jax.lax.scan(body, x, stage_vars)
        return h

    return stage_fn


def pipelined_causal_lm_logits(
    cfg: LlamaConfig,
    variables: dict,
    tokens: jax.Array,
    *,
    mesh,
    n_micro: int,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Forward pass with the decoder blocks run as a GPipe pipeline over the
    ``pp`` mesh axis (embedding and head stay outside the pipeline — they are
    replicated over pp and sharded over the batch axes by GSPMD as usual).

    NOTE: the embedding lookup, final norm, and head below mirror
    ``LlamaForCausalLM.__call__`` — change them together. The pipeline
    equivalence tests (``tests/test_pipeline.py``) compare this path against
    ``model.apply`` and fail CI on any divergence."""
    from ..parallel.pipeline import gpipe_blocks

    params = variables["params"]
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    x = params["embed_tokens"]["embedding"].astype(cfg.dtype)[tokens]
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.d_model ** 0.5, cfg.dtype)

    x = gpipe_blocks(
        stacked_block_variables(variables), x, positions, segment_ids,
        stage_fn=make_block_stage_fn(cfg), mesh=mesh, n_micro=n_micro,
    )

    x = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.param_dtype, cfg.norm_offset).apply(
        {"params": params["final_norm"]}, x
    )
    if cfg.tie_embeddings:
        logits = x @ params["embed_tokens"]["embedding"].astype(cfg.dtype).T
    else:
        logits = LoRADense(
            cfg.vocab_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype
        ).apply({"params": params["lm_head"]}, x)
    return logits.astype(cfg.logits_dtype or jnp.float32)


class _ScanBlock(nn.Module):
    """Block adapted to nn.scan's (carry, *broadcast) -> (carry, out) shape."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids, deterministic=True,
                 decode=False, page_table=None, adapter_ids=None):
        y = Block(self.cfg, name="block")(
            x, positions, segment_ids, deterministic, decode,
            page_table, adapter_ids
        )
        return y, None


class LlamaForCausalLM(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, positions=None, segment_ids=None,
                 deterministic=True, decode=False, page_table=None,
                 adapter_ids=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]), tokens.shape)
        embed = nn.Embed(
            cfg.vocab_size,
            cfg.d_model,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            name="embed_tokens",
        )
        x = embed(tokens)
        if cfg.embed_scale:
            # Gemma scales embedding outputs by sqrt(d_model); the cast
            # matches transformers (the scale rounds through the compute
            # dtype before multiplying)
            x = x * jnp.asarray(cfg.d_model ** 0.5, cfg.dtype)

        policy = remat_policy_fn(cfg.remat_policy)
        if cfg.scan_layers:
            block_cls = _ScanBlock
            if cfg.remat and policy is not None:
                block_cls = nn.remat(
                    _ScanBlock,
                    prevent_cse=False,
                    # args 4/5 = deterministic/decode (0 is self): static bools
                    static_argnums=(4, 5),
                    policy=policy,
                )
            stack = nn.scan(
                block_cls,
                variable_axes={"params": 0, "lora": 0, "moe_aux": 0,
                               "cache": 0, "tenants": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast, nn.broadcast, nn.broadcast,
                         nn.broadcast, nn.broadcast, nn.broadcast),
                length=cfg.n_layers,
            )(cfg, name="blocks")
            x, _ = stack(x, positions, segment_ids, deterministic, decode,
                         page_table, adapter_ids)
        else:
            block_cls = (
                nn.remat(Block, prevent_cse=False, static_argnums=(4, 5), policy=policy)
                if cfg.remat and policy is not None
                else Block
            )
            for i in range(cfg.n_layers):
                x = block_cls(cfg, name=f"layer_{i}")(
                    x, positions, segment_ids, deterministic, decode,
                    page_table, adapter_ids)

        x = RMSNorm(cfg.rms_eps, cfg.dtype, cfg.param_dtype, cfg.norm_offset, name="final_norm")(x)
        if cfg.tie_embeddings:
            logits = x @ embed.embedding.astype(cfg.dtype).T
        else:
            logits = LoRADense(
                cfg.vocab_size,
                name="lm_head",
                dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
            )(x)
        return logits.astype(cfg.logits_dtype or jnp.float32)

    def init_variables(self, rng: jax.Array, batch: int = 1, seq: int = 8):
        tokens = jnp.zeros((batch, seq), jnp.int32)
        return self.init({"params": rng}, tokens)
