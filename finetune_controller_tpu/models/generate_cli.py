"""Post-finetune sanity generation from a job's artifacts directory.

``python -m finetune_controller_tpu.models.generate_cli --artifacts DIR
--prompt "..."`` reconstructs the trained model exactly the way a resume
does — the job's ``resolved_config.json`` rebuilds the model/train configs,
``init_state`` (seeded) or ``model.weights_dir`` recreates the frozen base,
and the latest checkpoint restores the trained collection — then runs the
KV-cached decode path (``models/generate.py``).

The reference has no generation surface at all (inference happens wherever
promoted artifacts are deployed — SURVEY.md §2.2); this is the operator
command that makes the framework's post-finetune quality check reachable
without writing Python. Token IO uses the same tokenizer contract as the
data pipeline (``data/loader.py``): a HuggingFace ``tokenizers`` JSON file
when given, byte-level fallback otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _parse_token_list(raw: str) -> list[int]:
    try:
        return [int(t) for t in raw.replace(" ", "").split(",") if t]
    except ValueError:
        raise SystemExit(f"--prompt-tokens must be comma-separated ints, got {raw!r}")


def main(argv: list[str] | None = None) -> int:
    from ..platform import assert_platform_env

    assert_platform_env()

    p = argparse.ArgumentParser(
        prog="ftc-generate",
        description="Generate from a fine-tuned job's artifacts (sanity check)",
    )
    p.add_argument("--artifacts", required=True,
                   help="job artifacts dir (resolved_config.json + checkpoints/)")
    p.add_argument("--prompt", help="text prompt (tokenized per --tokenizer)")
    p.add_argument("--prompt-tokens",
                   help="comma-separated token ids (skips tokenization)")
    p.add_argument("--tokenizer",
                   help="HF tokenizers JSON file; default: byte-level fallback "
                        "(the data pipeline's convention)")
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy (default)")
    p.add_argument("--top-k", type=int, default=0, help="0 = full distribution")
    p.add_argument("--eos-id", type=int, default=None)
    p.add_argument("--seed", type=int, default=0, help="sampling seed")
    p.add_argument("--oracle", action="store_true",
                   help="uncached O(n^2) forward per token — the numerics "
                        "oracle; impractically slow past ~1B params")
    p.add_argument("--image",
                   help="image for multimodal jobs (path / data URI / "
                        "base64) — required for LLaVA-family artifacts")
    args = p.parse_args(argv)

    if (args.prompt is None) == (args.prompt_tokens is None):
        raise SystemExit("pass exactly one of --prompt or --prompt-tokens")

    spec_path = os.path.join(args.artifacts, "resolved_config.json")
    if not os.path.exists(spec_path):
        raise SystemExit(f"{spec_path} not found — is this a job artifacts dir?")
    with open(spec_path) as f:
        spec = json.load(f)

    from ..train.cli import build_model_config, build_train_config

    cfg = build_model_config(spec)
    multimodal = getattr(cfg, "vision", None) is not None
    if multimodal and not args.image:
        raise SystemExit(
            "this is a multimodal job's artifacts dir — pass --image "
            "(path / data URI / base64) for the image prefix"
        )
    if args.image and not multimodal:
        raise SystemExit("--image given but the job's model is text-only")

    # ---- tokenize ---------------------------------------------------------
    # tokenizer resolution: an explicit --tokenizer always loads (and, in
    # token-id mode, turns decode on); otherwise --prompt mode uses the
    # tokenizer the JOB trained with (dataset.tokenizer_file in
    # resolved_config.json) so the prompt lands in the vocabulary the model
    # actually saw, with the byte fallback only when the job itself trained
    # on the byte fallback. Plain token-id mode never touches the spec's
    # tokenizer (it may be a pod-local path): ids in, ids out.
    tok_file = args.tokenizer
    if tok_file is None and args.prompt is not None:
        tok_file = spec.get("dataset", {}).get("tokenizer_file")
    tokenizer = None
    if tok_file:
        from tokenizers import Tokenizer

        try:
            tokenizer = Tokenizer.from_file(tok_file)
        except Exception as e:
            raise SystemExit(
                f"could not load tokenizer {tok_file!r} ({e}) — pass "
                "--tokenizer with a local path, or --prompt-tokens to skip "
                "tokenization"
            )
    if args.prompt_tokens is not None:
        ids = _parse_token_list(args.prompt_tokens)
    elif tokenizer is not None:
        ids = tokenizer.encode(args.prompt).ids
    else:
        from ..data.loader import _byte_tokenize

        ids = _byte_tokenize(args.prompt)
    if not ids:
        raise SystemExit("empty prompt")
    bad = [i for i in ids if not 0 <= i < cfg.vocab_size]
    if bad:
        raise SystemExit(
            f"prompt ids {bad[:5]} out of range for vocab {cfg.vocab_size}"
        )

    # ---- rebuild the trained model (the resume recipe) --------------------
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..train.checkpoint import CheckpointManager
    from ..train.trainer import Trainer

    # prefer the job's own mesh (a model trained sharded over N chips may
    # only fit sharded); fall back to the single-device default when this
    # host can't form it (e.g. generating on a CPU box from a slice job)
    mesh = None
    try:
        from ..train.cli import build_mesh

        mesh = build_mesh(spec)
    except ValueError as e:
        # ValueError = this host cannot form the job's mesh (device-count
        # mismatch) — the expected case when generating on a CPU box from a
        # slice job. A typo'd mesh key (TypeError from MeshSpec(**...)) is a
        # genuine spec error and propagates.
        print(
            f"note: job mesh {spec.get('mesh', {})} unavailable here ({e}); "
            "using default single-device mesh — a model that only fits "
            "sharded will OOM",
            file=sys.stderr,
        )
    tcfg = build_train_config(spec)
    trainer = Trainer(cfg, tcfg, mesh=mesh)  # mesh=None -> trainer default
    state = trainer.init_state()
    weights_dir = spec.get("model", {}).get("weights_dir")
    if weights_dir and tcfg.mode != "full":
        # in full fine-tune the checkpoint holds every weight (and this CLI
        # requires a checkpoint) — reloading the safetensors base just to
        # overwrite it would waste minutes at 7B; same guard as the
        # trainer's own resume recipe
        state = trainer.load_pretrained(state, weights_dir)
    ckpt = CheckpointManager(os.path.join(args.artifacts, "checkpoints"))
    restored = ckpt.restore_latest(like=trainer.state_to_host(state))
    if restored is None:
        raise SystemExit(f"no checkpoint under {args.artifacts}/checkpoints")
    step, host = restored
    state = state.replace(
        trainable=jax.tree.map(jnp.asarray, host["trainable"])
    )

    from .generate import cached_generate, generate

    prefix = cfg.vision.n_patches if multimodal else 0
    if prefix + len(ids) + args.max_new_tokens > cfg.max_seq_len:
        print(
            f"warning: image prefix ({prefix}) + prompt ({len(ids)}) + "
            f"max_new_tokens ({args.max_new_tokens}) exceeds the model's "
            f"trained max_seq_len ({cfg.max_seq_len}) — RoPE positions past "
            "the trained range degrade quality",
            file=sys.stderr,
        )

    variables = trainer._assemble(state.frozen, state.trainable)
    prompt = jnp.asarray([ids], jnp.int32)
    gen_kw: dict = {}
    if multimodal:
        from ..data.images import preprocess_image

        gen_kw["pixels"] = jnp.asarray(preprocess_image(
            args.image, cfg.image_size,
            normalize=spec.get("dataset", {}).get("image_normalize", "clip"),
        ))[None]
    gen_fn = generate if args.oracle else cached_generate
    out = gen_fn(
        trainer.model, variables, prompt,
        max_new_tokens=args.max_new_tokens,
        temperature=args.temperature, top_k=args.top_k, eos_id=args.eos_id,
        rng=jax.random.PRNGKey(args.seed),
        **gen_kw,
    )
    new_ids = np.asarray(out)[0, len(ids):].tolist()
    if args.eos_id is not None and args.eos_id in new_ids:
        new_ids = new_ids[: new_ids.index(args.eos_id)]

    if tokenizer is not None:
        text = tokenizer.decode(new_ids)
    elif args.prompt is not None:
        text = bytes(i for i in new_ids if 0 <= i < 256).decode(
            "utf-8", errors="replace"
        )
    else:
        text = None  # token-id mode: ids in, ids out
    print(json.dumps({
        "checkpoint_step": step,
        "prompt_tokens": len(ids),
        "new_tokens": new_ids,
        "text": text,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
