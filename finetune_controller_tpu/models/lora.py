"""LoRA adapters as a first-class parameter collection.

The frozen base weights live in the ``"params"`` collection; adapters live in
a separate ``"lora"`` collection.  The trainer differentiates only w.r.t. the
trainable collection, so no gradients or optimizer state are ever materialised
for the frozen base — the property that makes 8B LoRA fit a v5e chip.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

DEFAULT_TARGETS = (
    "q_proj",
    "k_proj",
    "v_proj",
    "o_proj",
    "gate_proj",
    "up_proj",
    "down_proj",
)


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    rank: int = 0            # 0 disables LoRA (full fine-tune)
    alpha: float = 16.0
    dropout: float = 0.0
    targets: Sequence[str] = DEFAULT_TARGETS

    def enabled_for(self, name: str) -> bool:
        return self.rank > 0 and name in self.targets


class LoRADense(nn.Module):
    """Dense layer with an optional low-rank adapter branch.

    ``y = x @ W  +  (alpha / r) * (x @ A) @ B`` with ``A: (in, r)`` normal-init
    and ``B: (r, out)`` zero-init, so the adapter starts as identity.
    """

    features: int
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_dropout: float = 0.0
    use_bias: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()
    #: store the frozen base kernel as blockwise int4 (QLoRA — models/quant.py)
    quantize_base: bool = False
    quant_block: int = 64
    #: multi-tenant serving (docs/serving.md §Multi-tenant adapters): when
    #: > 0, a ``"tenants"`` collection holds ``tenant_slots`` stacked
    #: per-tenant adapters — ``lora_a (N, in, r)``, ``lora_b (N, r, out)``,
    #: ``scale (N,)`` — and each batch row applies the adapter named by its
    #: entry in the per-row ``adapter_ids`` vector via a gathered batched
    #: einsum.  Slot 0 is the base model (all-zero stack, scale 0 — the
    #: delta is an exact 0.0).  Tenants whose trained rank is below
    #: ``tenant_rank`` are zero-padded: the extra rank columns/rows
    #: contribute exactly nothing, so the padded math is bit-equal to the
    #: unpadded adapter.
    tenant_slots: int = 0
    tenant_rank: int = 0

    @nn.compact
    def __call__(self, x, deterministic: bool = True, adapter_ids=None):
        in_features = x.shape[-1]
        if self.quantize_base:
            from .quant import quantized_param

            kernel = quantized_param(
                self, "kernel", (in_features, self.features),
                self.kernel_init, self.quant_block, self.dtype,
            )
            y = x @ kernel
        else:
            kernel = self.param(
                "kernel", self.kernel_init, (in_features, self.features),
                self.param_dtype,
            )
            y = x @ kernel.astype(self.dtype)
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (self.features,), self.param_dtype
            )
            y = y + bias.astype(self.dtype)
        if self.lora_rank > 0:
            a = self.variable(
                "lora",
                "lora_a",
                nn.initializers.normal(stddev=0.02),
                self.make_rng("params") if self.is_initializing() else None,
                (in_features, self.lora_rank),
                self.param_dtype,
            ).value
            b = self.variable(
                "lora",
                "lora_b",
                lambda _rng, shape, dt: jnp.zeros(shape, dt),
                None,
                (self.lora_rank, self.features),
                self.param_dtype,
            ).value
            h = x
            if self.lora_dropout > 0.0 and not deterministic:
                h = nn.Dropout(rate=self.lora_dropout, deterministic=False)(h)
            scale = self.lora_alpha / self.lora_rank
            y = y + (h @ a.astype(self.dtype)) @ b.astype(self.dtype) * scale
        if self.tenant_slots > 0 and adapter_ids is not None:
            # per-row tenant adapters: y_b += scale[t_b] * (x_b @ A[t_b]) @
            # B[t_b] with t = adapter_ids — the unmerged-LoRA multiplexing
            # math (same eval order as the single-adapter branch above, so a
            # one-tenant registry reproduces it exactly up to the gather)
            n, r = self.tenant_slots, max(1, self.tenant_rank)
            ta = self.variable(
                "tenants", "lora_a",
                lambda *_: jnp.zeros((n, in_features, r), self.param_dtype),
                None,
            ).value
            tb = self.variable(
                "tenants", "lora_b",
                lambda *_: jnp.zeros((n, r, self.features), self.param_dtype),
                None,
            ).value
            ts = self.variable(
                "tenants", "scale",
                lambda *_: jnp.zeros((n,), self.param_dtype),
                None,
            ).value
            ids = adapter_ids.astype(jnp.int32)
            ha = jnp.einsum("bsi,bir->bsr", x, ta[ids].astype(self.dtype))
            delta = jnp.einsum("bsr,bro->bso", ha, tb[ids].astype(self.dtype))
            y = y + delta * ts[ids].astype(self.dtype)[:, None, None]
        return y
