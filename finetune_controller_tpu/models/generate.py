"""Sampling utility: sanity-check a fine-tuned model by generating from it.

Two paths:

* :func:`generate` — the numerics ORACLE: each step re-runs the full forward
  over the sequence so far (no KV cache), O(n²) in generated length but
  exactly matching training numerics.
* :func:`cached_generate` — the practical path for 7B-class models: a
  static-length KV cache (fill the prompt once, then one-token decode
  steps), jitted fill + decode functions.  Verified token-for-token against
  the oracle in ``tests/test_generate.py``.

The reference has no equivalent surface at all (inference happens wherever
the promoted artifacts are deployed); PEFT/merged exports (``hf_export.py``)
remain the deployment path.

Works with any of the text families (Llama/Gemma/Qwen/Mixtral) and the
trainer's assembled variables::

    toks = greedy_generate(model, variables, prompt, max_new_tokens=32)
    toks = cached_generate(model, variables, prompt, max_new_tokens=256)
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any

import jax
import jax.numpy as jnp


def _logits_fn(
    model: Any, variables: dict, tokens: jax.Array,
    pixels: jax.Array | None = None,
) -> jax.Array:
    """Last-position logits (B, V); MoE models sow aux state we discard;
    multimodal models take the image prefix via ``pixels``."""
    kw: dict = {}
    if pixels is not None:
        kw["pixels"] = pixels
    n_experts = getattr(getattr(model, "cfg", None), "n_experts", 0)
    if n_experts:
        logits, _ = model.apply(variables, tokens, mutable=("moe_aux",), **kw)
    else:
        logits = model.apply(variables, tokens, **kw)
    return logits[:, -1].astype(jnp.float32)


def generate(
    model: Any,
    variables: dict,
    prompt_tokens: jax.Array,      # (B, S) int32
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,      # 0 = greedy
    top_k: int = 0,                # 0 = full distribution
    eos_id: int | None = None,
    rng: jax.Array | None = None,
    pixels: jax.Array | None = None,  # (B, H, W, 3) for multimodal models
) -> jax.Array:
    """Autoregressive sampling; returns (B, S + max_new_tokens) tokens.

    Rows that emit ``eos_id`` keep emitting it (a poor man's stop mask), so
    callers can trim on the first EOS per row. ``pixels`` feeds a multimodal
    model's image prefix (re-encoded every step — this is the oracle path;
    fine for sanity checks, not serving).
    """
    if getattr(model.cfg, "vision", None) is not None and pixels is None:
        # a multimodal model quietly falls back to text-only embeddings —
        # the sanity check would "work" without ever seeing the image
        raise ValueError("multimodal generation needs pixels=")
    tokens = jnp.asarray(prompt_tokens, jnp.int32)
    if tokens.ndim != 2:
        raise ValueError(f"prompt_tokens must be (B, S), got {tokens.shape}")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    done = jnp.zeros((tokens.shape[0],), bool)

    for _ in range(max_new_tokens):
        logits = _logits_fn(model, variables, tokens, pixels)  # (B, V)
        nxt, rng = _sample(logits, temperature=temperature, top_k=top_k, rng=rng)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        tokens = jnp.concatenate([tokens, nxt[:, None].astype(jnp.int32)], axis=1)
    return tokens


def greedy_generate(model, variables, prompt_tokens, *, max_new_tokens=32,
                    eos_id=None):
    return generate(
        model, variables, prompt_tokens,
        max_new_tokens=max_new_tokens, temperature=0.0, eos_id=eos_id,
    )


#: jitted (fill, decode_step) pairs keyed by (model class, decode config) —
#: defined at module level so REPEATED cached_generate calls (the whole point
#: of a usable 7B sanity loop) reuse compilations instead of re-tracing.
#: Configs are frozen dataclasses, hence hashable.  A true bounded LRU (the
#: ``PixelCache`` shape from ``data/mm_loader.py``): evicting only the
#: least-recently-used entry means N+1 alternating configs thrash exactly one
#: slot, where the old clear-everything-at-capacity behavior re-traced ALL of
#: them forever.
_DECODE_FNS_MAX = 8
_DECODE_FNS_CACHE: OrderedDict = OrderedDict()


def _decode_fns(model_type, dcfg):
    key = (model_type, dcfg)
    cached = _DECODE_FNS_CACHE.get(key)
    if cached is not None:
        _DECODE_FNS_CACHE.move_to_end(key)
        return cached
    dmodel = model_type(cfg=dcfg)
    mutable = ("cache", "moe_aux") if dcfg.n_experts else ("cache",)

    # one pair serves both families: fill takes pixels variadically (the
    # multimodal [image; text] prefix — cached_generate passes it only for
    # LLaVA models), and both model classes accept positions by keyword
    # (decode steps use ABSOLUTE positions; the mm wrapper offsets nothing)
    @jax.jit
    def fill(variables, tokens, *pixels):
        logits, updated = dmodel.apply(
            variables, tokens, *pixels, deterministic=True, decode=True,
            mutable=mutable,
        )
        return logits[:, -1].astype(jnp.float32), updated["cache"]

    @jax.jit
    def decode_step(variables, token, pos):
        positions = jnp.broadcast_to(pos[None, None], (token.shape[0], 1))
        logits, updated = dmodel.apply(
            variables, token, positions=positions, deterministic=True,
            decode=True, mutable=mutable,
        )
        return logits[:, -1].astype(jnp.float32), updated["cache"]

    if len(_DECODE_FNS_CACHE) >= _DECODE_FNS_MAX:
        _DECODE_FNS_CACHE.popitem(last=False)
    _DECODE_FNS_CACHE[key] = (fill, decode_step)
    return fill, decode_step


def _sample(logits, *, temperature, top_k, rng):
    """Shared sampling rule — cached and uncached paths must pick the same
    token from the same logits."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1), rng
    scaled = logits / temperature
    if top_k:
        kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    rng, sub = jax.random.split(rng)
    return jax.random.categorical(sub, scaled, axis=-1), rng


def cached_generate(
    model: Any,
    variables: dict,
    prompt_tokens: jax.Array,      # (B, S) int32
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    eos_id: int | None = None,
    rng: jax.Array | None = None,
    pixels: jax.Array | None = None,  # (B, H, W, 3) for multimodal models
) -> jax.Array:
    """KV-cached fill-then-decode sampling; same contract as :func:`generate`.

    The cache is a static ``prompt_len + max_new_tokens`` slots per layer
    (plus the ``n_patches`` image-prefix slots for multimodal models)
    (flax ``cache`` collection — ``models/llama.py`` ``_decode_attention``),
    so each new token costs one single-position forward instead of a full
    re-run: at 7B this is the difference between a usable post-finetune
    sanity generation and an hours-long one.  Remat is disabled (no gradients
    here) and attention runs the XLA path (flash kernels don't apply to
    single-token queries).

    MoE note: expert capacity scales with the live token count, so a
    one-token decode step is effectively dropless while a long-sequence
    recompute may drop tokens — cached and uncached logits can differ
    (cached is the *less* lossy of the two).  ``tests/test_generate.py``
    verifies equivalence under a dropless capacity.
    """
    multimodal = getattr(model.cfg, "vision", None) is not None
    if multimodal and pixels is None:
        raise ValueError("multimodal cached decode needs pixels=")
    if pixels is not None and not multimodal:
        # fail fast like generate() does — a silently dropped image would
        # return plausible text that never saw it
        raise ValueError("pixels= given but the model is text-only")
    tokens = jnp.asarray(prompt_tokens, jnp.int32)
    if tokens.ndim != 2:
        raise ValueError(f"prompt_tokens must be (B, S), got {tokens.shape}")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    b, prompt_len = tokens.shape
    # the image prefix occupies cache slots before the text (multimodal)
    prefix = model.cfg.vision.n_patches if multimodal else 0
    cache_len = prefix + prompt_len + max_new_tokens
    dcfg = model.cfg.replace(
        remat=False, attention_impl="xla", max_seq_len=cache_len
    )
    fill, decode_step = _decode_fns(type(model), dcfg)
    if multimodal:
        logits, cache = fill(variables, tokens, jnp.asarray(pixels))
    else:
        logits, cache = fill(variables, tokens)
    done = jnp.zeros((b,), bool)
    for t in range(max_new_tokens):
        nxt, rng = _sample(logits, temperature=temperature, top_k=top_k, rng=rng)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        tokens = jnp.concatenate(
            [tokens, nxt[:, None].astype(jnp.int32)], axis=1)
        if t == max_new_tokens - 1:
            break
        logits, cache = decode_step(
            {**variables, "cache": cache},
            nxt[:, None].astype(jnp.int32),
            jnp.asarray(prefix + prompt_len + t, jnp.int32),
        )
    return tokens
