"""Sampling utility: sanity-check a fine-tuned model by generating from it.

This is a *verification* tool, not a serving path: each step re-runs the
full forward over the sequence so far (no KV cache), which is O(n²) in
generated length but exactly matches training numerics — the property that
matters when the question is "did my fine-tune learn the task?". The
reference has no equivalent surface at all (inference happens wherever the
promoted artifacts are deployed); PEFT/merged exports (``hf_export.py``)
remain the deployment path.

Works with any of the text families (Llama/Gemma/Qwen/Mixtral) and the
trainer's assembled variables::

    toks = greedy_generate(model, variables, prompt, max_new_tokens=32)
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def _logits_fn(model: Any, variables: dict, tokens: jax.Array) -> jax.Array:
    """Last-position logits (B, V); MoE models sow aux state we discard."""
    n_experts = getattr(getattr(model, "cfg", None), "n_experts", 0)
    if n_experts:
        logits, _ = model.apply(variables, tokens, mutable=("moe_aux",))
    else:
        logits = model.apply(variables, tokens)
    return logits[:, -1].astype(jnp.float32)


def generate(
    model: Any,
    variables: dict,
    prompt_tokens: jax.Array,      # (B, S) int32
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,      # 0 = greedy
    top_k: int = 0,                # 0 = full distribution
    eos_id: int | None = None,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Autoregressive sampling; returns (B, S + max_new_tokens) tokens.

    Rows that emit ``eos_id`` keep emitting it (a poor man's stop mask), so
    callers can trim on the first EOS per row.
    """
    tokens = jnp.asarray(prompt_tokens, jnp.int32)
    if tokens.ndim != 2:
        raise ValueError(f"prompt_tokens must be (B, S), got {tokens.shape}")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    done = jnp.zeros((tokens.shape[0],), bool)

    for _ in range(max_new_tokens):
        logits = _logits_fn(model, variables, tokens)        # (B, V)
        if temperature <= 0.0:
            nxt = jnp.argmax(logits, axis=-1)
        else:
            scaled = logits / temperature
            if top_k:
                kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
                scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(sub, scaled, axis=-1)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        tokens = jnp.concatenate([tokens, nxt[:, None].astype(jnp.int32)], axis=1)
    return tokens


def greedy_generate(model, variables, prompt_tokens, *, max_new_tokens=32,
                    eos_id=None):
    return generate(
        model, variables, prompt_tokens,
        max_new_tokens=max_new_tokens, temperature=0.0, eos_id=eos_id,
    )
