"""Export trained artifacts in HuggingFace-consumable formats.

The other half of ``hf_import.py``: after a fine-tune, users need artifacts
their serving stack understands — either a **PEFT adapter** directory
(``adapter_model.safetensors`` + ``adapter_config.json``, loadable with
``peft.PeftModel``) or a **merged full checkpoint** (``model.safetensors`` +
``config.json``, loadable with ``transformers``). The reference delegates all
artifact formats to user containers (SURVEY.md §2.2); here the trainer owns
them, so promotion publishes something deployable.

Both paths are round-trip tested against ``peft``/``transformers`` in
``tests/test_hf_export.py``.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any

import numpy as np

from .llama import LlamaConfig
from .quant import dequantize_int4

logger = logging.getLogger(__name__)

#: our projection name → HF module path fragment
_HF_MODULE = {
    "q_proj": "self_attn.q_proj",
    "k_proj": "self_attn.k_proj",
    "v_proj": "self_attn.v_proj",
    "o_proj": "self_attn.o_proj",
    "gate_proj": "mlp.gate_proj",
    "up_proj": "mlp.up_proj",
    "down_proj": "mlp.down_proj",
}


def _save_safetensors(path: Path, tensors: dict[str, np.ndarray]) -> None:
    from safetensors.numpy import save_file

    save_file({k: np.ascontiguousarray(v) for k, v in tensors.items()}, str(path))


def _stacked_lora_modules(lora_tree: dict) -> dict[str, dict[str, np.ndarray]]:
    """Flatten the scanned lora tree → {proj_name: {lora_a, lora_b}} with the
    leading layer axis intact."""
    blocks = lora_tree["blocks"]["block"]
    out: dict[str, dict[str, np.ndarray]] = {}
    for group in blocks.values():            # attn / mlp
        for proj, leaves in group.items():
            out[proj] = {k: np.asarray(v) for k, v in leaves.items()}
    return out


def export_lora_adapter(
    cfg: LlamaConfig,
    lora_tree: dict,
    out_dir: Path | str,
    *,
    base_model_name: str = "",
    hf_prefix: str = "base_model.model.model.layers",
) -> Path:
    """Write a PEFT-format LoRA adapter directory.

    PEFT stores ``lora_A.weight (r, in)`` / ``lora_B.weight (out, r)`` per
    target module with scaling ``alpha / r`` — ours are flax ``(in, r)`` /
    ``(r, out)`` kernels with the same scaling, so the export is a transpose
    per tensor (verified numerically against ``peft`` in the tests).
    ``hf_prefix`` names the base model's layer path — multimodal adapters
    target the decoder nested under ``language_model`` in HF's LLaVA.
    """
    out_dir = Path(out_dir).expanduser()
    out_dir.mkdir(parents=True, exist_ok=True)
    modules = _stacked_lora_modules(lora_tree)
    tensors: dict[str, np.ndarray] = {}
    for proj, leaves in modules.items():
        a, b = leaves["lora_a"], leaves["lora_b"]     # (L, in, r), (L, r, out)
        for i in range(a.shape[0]):
            prefix = f"{hf_prefix}.{i}.{_HF_MODULE[proj]}"
            tensors[f"{prefix}.lora_A.weight"] = a[i].T.astype(np.float32)
            tensors[f"{prefix}.lora_B.weight"] = b[i].T.astype(np.float32)
    _save_safetensors(out_dir / "adapter_model.safetensors", tensors)

    adapter_config = {
        "peft_type": "LORA",
        "task_type": "CAUSAL_LM",
        "base_model_name_or_path": base_model_name,
        "r": cfg.lora.rank,
        "lora_alpha": cfg.lora.alpha,
        "lora_dropout": cfg.lora.dropout,
        "target_modules": sorted(modules),
        "bias": "none",
        "fan_in_fan_out": False,
        "inference_mode": True,
    }
    (out_dir / "adapter_config.json").write_text(json.dumps(adapter_config, indent=2))
    logger.info("wrote PEFT adapter (%d tensors) -> %s", len(tensors), out_dir)
    return out_dir


def export_mm_projector(projector: dict, out_dir: Path | str) -> Path:
    """Write the trained LLaVA projector beside the adapter, in HF's
    ``multi_modal_projector`` naming — the piece the LLaVA recipe trains
    outside the PEFT adapter (upstream llava ships it as
    ``non_lora_trainables``; ours is a safetensors file a deploy script maps
    straight onto ``LlavaForConditionalGeneration``)."""
    out_dir = Path(out_dir).expanduser()
    out_dir.mkdir(parents=True, exist_ok=True)
    tensors = {
        "multi_modal_projector.linear_1.weight": np.asarray(
            projector["projector_fc1"]["kernel"], np.float32).T,
        "multi_modal_projector.linear_1.bias": np.asarray(
            projector["projector_fc1"]["bias"], np.float32),
        "multi_modal_projector.linear_2.weight": np.asarray(
            projector["projector_fc2"]["kernel"], np.float32).T,
        "multi_modal_projector.linear_2.bias": np.asarray(
            projector["projector_fc2"]["bias"], np.float32),
    }
    path = out_dir / "projector.safetensors"
    _save_safetensors(path, tensors)
    logger.info("wrote multimodal projector -> %s", path)
    return path


def _base_kernel(leaves: dict[str, np.ndarray], layer: int, cfg: LlamaConfig) -> np.ndarray:
    """(in, out) f32 base kernel for one layer, dequantizing QLoRA storage."""
    if "kernel" in leaves:
        return np.asarray(leaves["kernel"][layer], np.float32)
    deq = dequantize_int4(
        leaves["kernel_packed"][layer], leaves["kernel_scales"][layer],
        dtype=np.float32,
    )
    return np.asarray(deq, np.float32)


def _expert_stack(moe: dict[str, np.ndarray], name: str, layer: int) -> np.ndarray:
    """(E, in, out) f32 expert kernels for one layer, dequantizing int4
    expert storage (the MoE-QLoRA path — ``models/moe.py``)."""
    if name in moe:
        return np.asarray(moe[name][layer], np.float32)
    packed = moe[f"{name}_packed"][layer]
    scales = moe[f"{name}_scales"][layer]
    return np.stack([
        np.asarray(dequantize_int4(packed[e], scales[e], dtype=np.float32))
        for e in range(packed.shape[0])
    ])


def _hf_layout(cfg: LlamaConfig) -> tuple[str, str]:
    """(architecture, model_type) for the config's semantics; raises on
    combinations no HF architecture encodes."""
    gemma_markers = (cfg.norm_offset, cfg.embed_scale, cfg.mlp_act != "silu")
    if any(gemma_markers):
        # Gemma semantics: HF stores the SAME offset-form norm weights and
        # applies the same sqrt(d) embed scaling/GeGLU from config, so the
        # tensors export unchanged — only the config names the architecture
        if not all([cfg.norm_offset == 1.0, cfg.embed_scale,
                    cfg.mlp_act == "gelu", cfg.tie_embeddings]):
            raise NotImplementedError(
                "partial Gemma semantics (norm_offset/embed_scale/mlp_act "
                "mix) matches no transformers architecture; export the PEFT "
                "adapter instead"
            )
        if cfg.n_experts:
            raise NotImplementedError(
                "Gemma-semantics MoE matches no transformers architecture"
            )
        arch, model_type = "GemmaForCausalLM", "gemma"
    elif cfg.n_experts:
        arch, model_type = "MixtralForCausalLM", "mixtral"
    elif cfg.attention_qkv_bias:
        arch, model_type = "Qwen2ForCausalLM", "qwen2"
    else:
        arch, model_type = "LlamaForCausalLM", "llama"
    if cfg.rope_scaling_factor and model_type != "llama":
        # only the Llama-3.x presets carry rope_scaling today; another
        # layout with it set would get a config.json whose llama3
        # rope_scaling block transformers rejects — refuse BEFORE any
        # tensor file is written
        raise NotImplementedError(
            f"rope_scaling export is only supported for the llama layout, "
            f"not {model_type!r}"
        )
    return arch, model_type


def export_merged_checkpoint(
    cfg: LlamaConfig,
    variables: dict[str, Any],
    out_dir: Path | str,
) -> Path:
    """Write a full HF checkpoint with LoRA deltas merged into the base
    (``W_eff = W + (alpha/r)·A·B``), loadable by ``transformers`` — the
    importer's inverse, covering every shipped text family: Llama/Qwen-2
    dense, Gemma (offset norms/GeGLU/embed scaling ride the config), and
    Mixtral MoE (stacked experts unstacked to per-expert ``w1/w2/w3``,
    int4-quantized experts dequantized)."""
    arch, model_type = _hf_layout(cfg)  # raises before any file is written
    out_dir = Path(out_dir).expanduser()
    out_dir.mkdir(parents=True, exist_ok=True)
    params = variables["params"]
    lora = variables.get("lora", {})
    lora_blocks = lora.get("blocks", {}).get("block", {}) if lora else {}
    blocks = params["blocks"]["block"]
    scale = cfg.lora.alpha / cfg.lora.rank if cfg.lora.rank else 0.0

    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(
            params["embed_tokens"]["embedding"], np.float32
        ),
        "model.norm.weight": np.asarray(params["final_norm"]["scale"], np.float32),
    }
    if not cfg.tie_embeddings:
        tensors["lm_head.weight"] = np.asarray(
            params["lm_head"]["kernel"], np.float32
        ).T

    for i in range(cfg.n_layers):
        prefix = f"model.layers.{i}"
        tensors[f"{prefix}.input_layernorm.weight"] = np.asarray(
            blocks["attn_norm"]["scale"][i], np.float32
        )
        tensors[f"{prefix}.post_attention_layernorm.weight"] = np.asarray(
            blocks["mlp_norm"]["scale"][i], np.float32
        )
        groups = ("attn",) if cfg.n_experts else ("attn", "mlp")
        for group_name in groups:
            for proj, leaves in blocks[group_name].items():
                kernel = _base_kernel(leaves, i, cfg)           # (in, out)
                ladder = lora_blocks.get(group_name, {}).get(proj)
                if ladder is not None:
                    a = np.asarray(ladder["lora_a"][i], np.float32)
                    b = np.asarray(ladder["lora_b"][i], np.float32)
                    kernel = kernel + scale * (a @ b)
                tensors[f"{prefix}.{_HF_MODULE[proj]}.weight"] = kernel.T
                if "bias" in leaves:  # Qwen-2 q/k/v biases (frozen, no LoRA)
                    tensors[f"{prefix}.{_HF_MODULE[proj]}.bias"] = np.asarray(
                        leaves["bias"][i], np.float32
                    )
        if cfg.n_experts:
            moe = blocks["moe"]
            mp = f"{prefix}.block_sparse_moe"
            tensors[f"{mp}.gate.weight"] = np.asarray(
                moe["router_kernel"][i], np.float32
            ).T
            # stacked (E, in, out) → per-expert HF (out, in); the importer's
            # w1=gate / w2=down / w3=up mapping, inverted
            for name, hf_w in (("experts_gate", "w1"), ("experts_down", "w2"),
                               ("experts_up", "w3")):
                stack = _expert_stack(moe, name, i)
                for e in range(stack.shape[0]):
                    tensors[f"{mp}.experts.{e}.{hf_w}.weight"] = stack[e].T

    _save_safetensors(out_dir / "model.safetensors", tensors)
    hf_config = {
        "architectures": [arch],
        "model_type": model_type,
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.d_model,
        "intermediate_size": cfg.d_ff,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        # explicit so a decoupled head_dim (head_dim_override) reconstructs
        # the same attention shapes in transformers
        "head_dim": cfg.head_dim,
        "rms_norm_eps": cfg.rms_eps,
        "rope_theta": cfg.rope_theta,
        "max_position_embeddings": cfg.max_seq_len,
        "tie_word_embeddings": cfg.tie_embeddings,
        "attention_bias": cfg.attention_qkv_bias,
        "mlp_bias": False,
        "torch_dtype": "float32",
    }
    if model_type == "gemma":
        # transformers' Gemma applies GeGLU (tanh approximation), the (1+w)
        # norm form, and sqrt(d) embed scaling from the architecture itself —
        # both config keys are set for pre/post-4.39 transformers
        hf_config["hidden_act"] = "gelu_pytorch_tanh"
        hf_config["hidden_activation"] = "gelu_pytorch_tanh"
    if model_type == "mixtral":
        hf_config["num_local_experts"] = cfg.n_experts
        hf_config["num_experts_per_tok"] = cfg.moe_top_k
        hf_config["router_aux_loss_coef"] = cfg.router_aux_weight
    if cfg.rope_scaling_factor:
        # non-llama layouts were refused in _hf_layout, before any write
        hf_config["rope_scaling"] = {
            "rope_type": "llama3",
            "factor": cfg.rope_scaling_factor,
            "low_freq_factor": cfg.rope_scaling_low_freq_factor,
            "high_freq_factor": cfg.rope_scaling_high_freq_factor,
            "original_max_position_embeddings": cfg.rope_scaling_original_max_len,
        }
    (out_dir / "config.json").write_text(json.dumps(hf_config, indent=2))
    logger.info("wrote merged HF checkpoint (%d tensors) -> %s", len(tensors), out_dir)
    return out_dir
