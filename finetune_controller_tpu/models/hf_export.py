"""Export trained artifacts in HuggingFace-consumable formats.

The other half of ``hf_import.py``: after a fine-tune, users need artifacts
their serving stack understands — either a **PEFT adapter** directory
(``adapter_model.safetensors`` + ``adapter_config.json``, loadable with
``peft.PeftModel``) or a **merged full checkpoint** (``model.safetensors`` +
``config.json``, loadable with ``transformers``). The reference delegates all
artifact formats to user containers (SURVEY.md §2.2); here the trainer owns
them, so promotion publishes something deployable.

Both paths are round-trip tested against ``peft``/``transformers`` in
``tests/test_hf_export.py``.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any

import numpy as np

from .llama import LlamaConfig
from .quant import dequantize_int4

logger = logging.getLogger(__name__)

#: our projection name → HF module path fragment
_HF_MODULE = {
    "q_proj": "self_attn.q_proj",
    "k_proj": "self_attn.k_proj",
    "v_proj": "self_attn.v_proj",
    "o_proj": "self_attn.o_proj",
    "gate_proj": "mlp.gate_proj",
    "up_proj": "mlp.up_proj",
    "down_proj": "mlp.down_proj",
}


def _save_safetensors(path: Path, tensors: dict[str, np.ndarray]) -> None:
    from safetensors.numpy import save_file

    save_file({k: np.ascontiguousarray(v) for k, v in tensors.items()}, str(path))


def _stacked_lora_modules(lora_tree: dict) -> dict[str, dict[str, np.ndarray]]:
    """Flatten the scanned lora tree → {proj_name: {lora_a, lora_b}} with the
    leading layer axis intact."""
    blocks = lora_tree["blocks"]["block"]
    out: dict[str, dict[str, np.ndarray]] = {}
    for group in blocks.values():            # attn / mlp
        for proj, leaves in group.items():
            out[proj] = {k: np.asarray(v) for k, v in leaves.items()}
    return out


def export_lora_adapter(
    cfg: LlamaConfig,
    lora_tree: dict,
    out_dir: Path | str,
    *,
    base_model_name: str = "",
) -> Path:
    """Write a PEFT-format LoRA adapter directory.

    PEFT stores ``lora_A.weight (r, in)`` / ``lora_B.weight (out, r)`` per
    target module with scaling ``alpha / r`` — ours are flax ``(in, r)`` /
    ``(r, out)`` kernels with the same scaling, so the export is a transpose
    per tensor (verified numerically against ``peft`` in the tests).
    """
    out_dir = Path(out_dir).expanduser()
    out_dir.mkdir(parents=True, exist_ok=True)
    modules = _stacked_lora_modules(lora_tree)
    tensors: dict[str, np.ndarray] = {}
    for proj, leaves in modules.items():
        a, b = leaves["lora_a"], leaves["lora_b"]     # (L, in, r), (L, r, out)
        for i in range(a.shape[0]):
            prefix = f"base_model.model.model.layers.{i}.{_HF_MODULE[proj]}"
            tensors[f"{prefix}.lora_A.weight"] = a[i].T.astype(np.float32)
            tensors[f"{prefix}.lora_B.weight"] = b[i].T.astype(np.float32)
    _save_safetensors(out_dir / "adapter_model.safetensors", tensors)

    adapter_config = {
        "peft_type": "LORA",
        "task_type": "CAUSAL_LM",
        "base_model_name_or_path": base_model_name,
        "r": cfg.lora.rank,
        "lora_alpha": cfg.lora.alpha,
        "lora_dropout": cfg.lora.dropout,
        "target_modules": sorted(modules),
        "bias": "none",
        "fan_in_fan_out": False,
        "inference_mode": True,
    }
    (out_dir / "adapter_config.json").write_text(json.dumps(adapter_config, indent=2))
    logger.info("wrote PEFT adapter (%d tensors) -> %s", len(tensors), out_dir)
    return out_dir


def _base_kernel(leaves: dict[str, np.ndarray], layer: int, cfg: LlamaConfig) -> np.ndarray:
    """(in, out) f32 base kernel for one layer, dequantizing QLoRA storage."""
    if "kernel" in leaves:
        return np.asarray(leaves["kernel"][layer], np.float32)
    deq = dequantize_int4(
        leaves["kernel_packed"][layer], leaves["kernel_scales"][layer],
        dtype=np.float32,
    )
    return np.asarray(deq, np.float32)


def export_merged_checkpoint(
    cfg: LlamaConfig,
    variables: dict[str, Any],
    out_dir: Path | str,
) -> Path:
    """Write a full HF Llama checkpoint with LoRA deltas merged into the base
    (``W_eff = W + (alpha/r)·A·B``), loadable by ``transformers``. Dense text
    models only (the importer's inverse)."""
    if cfg.n_experts:
        raise NotImplementedError("merged export currently covers dense models")
    # Gemma-specific semantics (norm offset, embed scaling, GeGLU) have no
    # Llama-config encoding — refuse up front (before any file is written)
    # rather than emitting a checkpoint transformers would evaluate
    # differently.
    if cfg.norm_offset or cfg.embed_scale or cfg.mlp_act != "silu":
        raise NotImplementedError(
            "merged export covers the Llama/Qwen-2 layouts; export the PEFT "
            "adapter and merge against the original Gemma base instead"
        )
    out_dir = Path(out_dir).expanduser()
    out_dir.mkdir(parents=True, exist_ok=True)
    params = variables["params"]
    lora = variables.get("lora", {})
    lora_blocks = lora.get("blocks", {}).get("block", {}) if lora else {}
    blocks = params["blocks"]["block"]
    scale = cfg.lora.alpha / cfg.lora.rank if cfg.lora.rank else 0.0

    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(
            params["embed_tokens"]["embedding"], np.float32
        ),
        "model.norm.weight": np.asarray(params["final_norm"]["scale"], np.float32),
    }
    if not cfg.tie_embeddings:
        tensors["lm_head.weight"] = np.asarray(
            params["lm_head"]["kernel"], np.float32
        ).T

    for i in range(cfg.n_layers):
        prefix = f"model.layers.{i}"
        tensors[f"{prefix}.input_layernorm.weight"] = np.asarray(
            blocks["attn_norm"]["scale"][i], np.float32
        )
        tensors[f"{prefix}.post_attention_layernorm.weight"] = np.asarray(
            blocks["mlp_norm"]["scale"][i], np.float32
        )
        for group_name in ("attn", "mlp"):
            for proj, leaves in blocks[group_name].items():
                kernel = _base_kernel(leaves, i, cfg)           # (in, out)
                ladder = lora_blocks.get(group_name, {}).get(proj)
                if ladder is not None:
                    a = np.asarray(ladder["lora_a"][i], np.float32)
                    b = np.asarray(ladder["lora_b"][i], np.float32)
                    kernel = kernel + scale * (a @ b)
                tensors[f"{prefix}.{_HF_MODULE[proj]}.weight"] = kernel.T
                if "bias" in leaves:  # Qwen-2 q/k/v biases (frozen, no LoRA)
                    tensors[f"{prefix}.{_HF_MODULE[proj]}.bias"] = np.asarray(
                        leaves["bias"][i], np.float32
                    )

    _save_safetensors(out_dir / "model.safetensors", tensors)
    # Qwen-2-family configs (q/k/v biases) export under the Qwen2
    # architecture; everything else uses the Llama layout
    if cfg.attention_qkv_bias:
        arch, model_type = "Qwen2ForCausalLM", "qwen2"
    else:
        arch, model_type = "LlamaForCausalLM", "llama"
    hf_config = {
        "architectures": [arch],
        "model_type": model_type,
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.d_model,
        "intermediate_size": cfg.d_ff,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        # explicit so a decoupled head_dim (head_dim_override) reconstructs
        # the same attention shapes in transformers
        "head_dim": cfg.head_dim,
        "rms_norm_eps": cfg.rms_eps,
        "rope_theta": cfg.rope_theta,
        "max_position_embeddings": cfg.max_seq_len,
        "tie_word_embeddings": cfg.tie_embeddings,
        "attention_bias": cfg.attention_qkv_bias,
        "mlp_bias": False,
        "torch_dtype": "float32",
    }
    if cfg.rope_scaling_factor:
        if model_type != "llama":
            # only the Llama-3.x presets carry rope_scaling_factor today; a
            # qwen2-layout config with it set would get a config.json whose
            # llama3 rope_scaling block transformers rejects for qwen2
            raise NotImplementedError(
                f"rope_scaling export is only supported for the llama "
                f"layout, not {model_type!r}"
            )
        hf_config["rope_scaling"] = {
            "rope_type": "llama3",
            "factor": cfg.rope_scaling_factor,
            "low_freq_factor": cfg.rope_scaling_low_freq_factor,
            "high_freq_factor": cfg.rope_scaling_high_freq_factor,
            "original_max_position_embeddings": cfg.rope_scaling_original_max_len,
        }
    (out_dir / "config.json").write_text(json.dumps(hf_config, indent=2))
    logger.info("wrote merged HF checkpoint (%d tensors) -> %s", len(tensors), out_dir)
    return out_dir
