"""Blockwise int4 weight quantization — the QLoRA base-weight path.

BASELINE config #3 (Mistral-7B QLoRA). TPU-first design choices:

- **symmetric blockwise int4**: each ``block_size`` input-dim slice of a
  kernel column shares one bf16 scale; values live in [-7, 7] so the scale is
  ``absmax / 7`` and zero is exact (no zero-point tensor);
- **two nibbles per uint8** along the input dim — a quantized ``(in, out)``
  kernel is ``(in/2, out)`` uint8 + ``(in/block, out)`` scales: ~4.25
  bits/weight, which is what lets a 7B base fit one v5e chip's HBM next to
  optimizer-free LoRA adapters;
- **dequantize-then-matmul** at apply time: the unpack + scale is elementwise
  VPU work XLA fuses into the bf16 MXU matmul's operand load. The weights
  never exist in f32 — params are created quantized at init.

Gradients: the base kernel is intentionally non-differentiable (it lives in
``params``, the frozen collection — only the ``lora`` collection trains), so
no straight-through estimator is needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def quantize_int4(w: jax.Array, block_size: int = 64) -> tuple[jax.Array, jax.Array]:
    """(in, out) float → (packed (in/2, out) uint8, scales (in/block, out) bf16).

    ``in`` must divide by ``block_size`` and ``block_size`` must be even.
    """
    in_f, out_f = w.shape
    if in_f % block_size or block_size % 2:
        raise ValueError(f"in={in_f} must divide by even block_size={block_size}")
    wb = w.astype(jnp.float32).reshape(in_f // block_size, block_size, out_f)
    absmax = jnp.max(jnp.abs(wb), axis=1, keepdims=True)          # (nb, 1, out)
    # round the scale to its stored precision BEFORE quantizing, so the
    # round-trip error stays <= scale/2 per element
    scales = (absmax / 7.0).astype(jnp.bfloat16).astype(jnp.float32)
    q = jnp.clip(jnp.round(wb / jnp.maximum(scales, 1e-12)), -7, 7).astype(jnp.int8)
    q = q.reshape(in_f, out_f)
    # pack consecutive input-dim pairs: low nibble = even row, high = odd row
    lo = (q[0::2] & 0x0F).astype(jnp.uint8)
    hi = (q[1::2] & 0x0F).astype(jnp.uint8)
    packed = (lo | (hi << 4)).astype(jnp.uint8)                   # (in/2, out)
    return packed, scales.reshape(in_f // block_size, out_f).astype(jnp.bfloat16)


def dequantize_int4(
    packed: jax.Array, scales: jax.Array, *, dtype=jnp.bfloat16
) -> jax.Array:
    """Inverse of :func:`quantize_int4` → (in, out) in ``dtype``."""
    half, out_f = packed.shape
    in_f = half * 2
    n_blocks = scales.shape[0]
    block_size = in_f // n_blocks
    # unpack nibbles; sign-extend 4-bit two's complement
    lo = (packed & 0x0F).astype(jnp.int8)
    hi = (packed >> 4).astype(jnp.int8)
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    q = jnp.stack([lo, hi], axis=1).reshape(in_f, out_f)          # interleave
    qb = q.reshape(n_blocks, block_size, out_f).astype(jnp.float32)
    w = qb * scales[:, None, :].astype(jnp.float32)
    return w.reshape(in_f, out_f).astype(dtype)




def quantized_param(module, name: str, shape: tuple, kernel_init,
                    quant_block: int, dtype) -> jax.Array:
    """The quantize-one-draw-at-init param pattern, shared by ``LoRADense``
    (dense ``kernel``) and ``MoEMLP`` (stacked ``experts_*``): quantize ONE
    weight draw for both stored params — flax folds the param name into the
    rng, so separate init fns would quantize two different matrices and
    store mismatched values/scales. Leading axes (the expert axis) are
    vmapped. Returns the dequantized kernel in ``dtype``.
    """
    per_matrix = len(shape) == 2

    packed0 = scales0 = None
    if module.is_initializing():
        w0 = kernel_init(module.make_rng("params"), shape, jnp.float32)
        if per_matrix:
            packed0, scales0 = quantize_int4(w0, quant_block)
        else:
            packed0, scales0 = jax.vmap(
                lambda w: quantize_int4(w, quant_block)
            )(w0)
    packed = module.param(f"{name}_packed", lambda _rng: packed0)
    scales = module.param(f"{name}_scales", lambda _rng: scales0)
    if per_matrix:
        return dequantize_int4(packed, scales, dtype=dtype)
    return jax.vmap(lambda p, s: dequantize_int4(p, s, dtype=dtype))(
        packed, scales
    )
