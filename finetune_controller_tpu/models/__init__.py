from .llama import LlamaConfig, LlamaForCausalLM, PRESETS
from .lora import LoRAConfig, LoRADense

__all__ = ["LlamaConfig", "LlamaForCausalLM", "PRESETS", "LoRAConfig", "LoRADense"]
