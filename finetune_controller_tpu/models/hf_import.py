"""Import pretrained HuggingFace Llama-family checkpoints into our param tree.

The reference never loads weights — training runs in user containers that
bring their own (SURVEY.md §2.2). A TPU-native fine-tuning framework has to
own this step: this module maps a local HF checkpoint directory
(``*.safetensors`` shards or ``pytorch_model.bin``) onto the flax parameter
tree the trainer shards, covering the dense Llama family (TinyLlama, Llama-3,
Mistral) and Mixtral's MoE experts.

Layout notes (why the transposes/stacks below are correct):

* HF ``nn.Linear`` stores ``(out_features, in_features)``; flax ``Dense``
  kernels are ``(in, out)`` → transpose every projection.
* our decoder runs under ``nn.scan`` — per-layer trees are stacked on a
  leading layer axis (the same axis pp shards), so layer ``i``'s tensors land
  at ``stacked[i]``.
* RoPE conventions match (both rotate half-vectors with the same frequency
  table), so no head permutation is needed — verified numerically against
  ``transformers``' reference implementation in ``tests/test_hf_import.py``.

No network egress happens here: the checkpoint directory must already be on
disk (in-cluster: staged like a dataset through the object store).
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any, Iterator

import numpy as np

from .llama import LlamaConfig

logger = logging.getLogger(__name__)


def _iter_checkpoint_tensors(ckpt_dir: Path) -> Iterator[tuple[str, np.ndarray]]:
    """Yield (hf_name, array) from safetensors shards or a torch .bin file."""
    st_files = sorted(ckpt_dir.glob("*.safetensors"))
    if st_files:
        from safetensors import safe_open

        for f in st_files:
            with safe_open(str(f), framework="np") as reader:
                for name in reader.keys():
                    yield name, reader.get_tensor(name)
        return
    bin_files = sorted(ckpt_dir.glob("pytorch_model*.bin"))
    if not bin_files:
        raise FileNotFoundError(
            f"no *.safetensors or pytorch_model*.bin under {ckpt_dir}"
        )
    import torch

    for f in bin_files:
        state = torch.load(str(f), map_location="cpu", weights_only=True)
        for name, tensor in state.items():
            yield name, tensor.float().numpy()


def _strip(name: str) -> str:
    return name.removeprefix("model.")


def load_llama_params(
    ckpt_dir: Path | str,
    cfg: LlamaConfig,
    *,
    dtype: Any = None,
) -> dict[str, Any]:
    """Build the model's ``params`` collection from an HF checkpoint dir.

    Returns a tree matching ``LlamaForCausalLM`` with ``scan_layers=True``
    (blocks stacked on the leading layer axis). Raises on missing/unexpected
    tensors so a architecture/config mismatch fails loudly at load, not as
    silent garbage training.
    """
    ckpt_dir = Path(ckpt_dir).expanduser()
    dtype = dtype or cfg.param_dtype
    L = cfg.n_layers

    # staging area: per-layer dicts to stack once everything is read
    layers: list[dict[str, np.ndarray]] = [dict() for _ in range(L)]
    top: dict[str, np.ndarray] = {}
    unexpected: list[str] = []

    for name, arr in _iter_checkpoint_tensors(ckpt_dir):
        key = _strip(name)
        if "rotary_emb.inv_freq" in key:
            # non-persistent RoPE buffer serialized by transformers < 4.32
            # (Llama-2-era .bin checkpoints); recomputed from config here
            continue
        if key == "embed_tokens.weight":
            top["embedding"] = arr
        elif key == "norm.weight":
            top["final_norm"] = arr
        elif key == "lm_head.weight":
            top["lm_head"] = arr.T
        elif key.startswith("layers."):
            _, idx_s, rest = key.split(".", 2)
            idx = int(idx_s)
            if idx >= L:
                raise ValueError(
                    f"checkpoint layer {idx} out of range for n_layers={L}"
                )
            layers[idx][rest] = arr
        else:
            unexpected.append(name)
    if unexpected:
        raise ValueError(f"unexpected checkpoint tensors: {unexpected[:5]}")

    def proj(rest: dict, hf: str) -> np.ndarray:
        return rest.pop(hf).T  # (out, in) -> (in, out)

    def layer_tree(rest: dict[str, np.ndarray], idx: int) -> dict[str, Any]:
        tree: dict[str, Any] = {
            "attn_norm": {"scale": rest.pop("input_layernorm.weight")},
            "mlp_norm": {"scale": rest.pop("post_attention_layernorm.weight")},
            "attn": {
                "q_proj": {"kernel": proj(rest, "self_attn.q_proj.weight")},
                "k_proj": {"kernel": proj(rest, "self_attn.k_proj.weight")},
                "v_proj": {"kernel": proj(rest, "self_attn.v_proj.weight")},
                "o_proj": {"kernel": proj(rest, "self_attn.o_proj.weight")},
            },
        }
        if cfg.attention_qkv_bias:
            # Qwen-2 family: q/k/v carry biases (o_proj does not)
            for p in ("q_proj", "k_proj", "v_proj"):
                tree["attn"][p]["bias"] = rest.pop(f"self_attn.{p}.bias")
        if cfg.n_experts:
            gate = []
            up = []
            down = []
            for e in range(cfg.n_experts):
                gate.append(proj(rest, f"block_sparse_moe.experts.{e}.w1.weight"))
                down.append(proj(rest, f"block_sparse_moe.experts.{e}.w2.weight"))
                up.append(proj(rest, f"block_sparse_moe.experts.{e}.w3.weight"))
            tree["moe"] = {
                "experts_gate": np.stack(gate),
                "experts_up": np.stack(up),
                "experts_down": np.stack(down),
                "router_kernel": proj(rest, "block_sparse_moe.gate.weight"),
            }
        else:
            tree["mlp"] = {
                "gate_proj": {"kernel": proj(rest, "mlp.gate_proj.weight")},
                "up_proj": {"kernel": proj(rest, "mlp.up_proj.weight")},
                "down_proj": {"kernel": proj(rest, "mlp.down_proj.weight")},
            }
        if rest:
            raise ValueError(f"layer {idx}: unmapped tensors {sorted(rest)[:5]}")
        return tree

    missing = [i for i, rest in enumerate(layers) if not rest]
    if missing:
        raise ValueError(f"checkpoint has no tensors for layers {missing[:5]}")
    trees = [layer_tree(rest, i) for i, rest in enumerate(layers)]
    import jax

    stacked = jax.tree.map(lambda *xs: np.stack(xs).astype(dtype), *trees)

    if "embedding" not in top or "final_norm" not in top:
        raise ValueError("checkpoint missing embed_tokens/norm weights")
    params: dict[str, Any] = {
        "embed_tokens": {"embedding": top["embedding"].astype(dtype)},
        "blocks": {"block": stacked},
        "final_norm": {"scale": top["final_norm"].astype(dtype)},
    }
    if cfg.tie_embeddings:
        if "lm_head" in top:
            logger.info("tie_embeddings=True: ignoring separate lm_head weight")
    else:
        if "lm_head" not in top:
            raise ValueError(
                "checkpoint has no lm_head.weight but cfg.tie_embeddings=False"
            )
        params["lm_head"] = {"kernel": top["lm_head"].astype(dtype)}
    n_params = sum(x.size for x in jax.tree.leaves(params))
    logger.info("loaded %d tensors (%.1fM params) from %s",
                len(jax.tree.leaves(params)), n_params / 1e6, ckpt_dir)
    return params
