"""Import pretrained HuggingFace Llama-family checkpoints into our param tree.

The reference never loads weights — training runs in user containers that
bring their own (SURVEY.md §2.2). A TPU-native fine-tuning framework has to
own this step: this module maps a local HF checkpoint directory
(``*.safetensors`` shards or ``pytorch_model.bin``) onto the flax parameter
tree the trainer shards, covering the dense Llama family (TinyLlama, Llama-3,
Mistral) and Mixtral's MoE experts.

Layout notes (why the transposes/stacks below are correct):

* HF ``nn.Linear`` stores ``(out_features, in_features)``; flax ``Dense``
  kernels are ``(in, out)`` → transpose every projection.
* our decoder runs under ``nn.scan`` — per-layer trees are stacked on a
  leading layer axis (the same axis pp shards), so layer ``i``'s tensors land
  at ``stacked[i]``.
* RoPE conventions match (both rotate half-vectors with the same frequency
  table), so no head permutation is needed — verified numerically against
  ``transformers``' reference implementation in ``tests/test_hf_import.py``.

No network egress happens here: the checkpoint directory must already be on
disk (in-cluster: staged like a dataset through the object store).
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any, Iterator

import jax
import numpy as np

from .llama import LlamaConfig

logger = logging.getLogger(__name__)


def _iter_checkpoint_tensors(ckpt_dir: Path) -> Iterator[tuple[str, np.ndarray]]:
    """Yield (hf_name, array) from safetensors shards or a torch .bin file."""
    st_files = sorted(ckpt_dir.glob("*.safetensors"))
    if st_files:
        from safetensors import safe_open

        for f in st_files:
            with safe_open(str(f), framework="np") as reader:
                for name in reader.keys():
                    yield name, reader.get_tensor(name)
        return
    bin_files = sorted(ckpt_dir.glob("pytorch_model*.bin"))
    if not bin_files:
        raise FileNotFoundError(
            f"no *.safetensors or pytorch_model*.bin under {ckpt_dir}"
        )
    import torch

    for f in bin_files:
        state = torch.load(str(f), map_location="cpu", weights_only=True)
        for name, tensor in state.items():
            yield name, tensor.float().numpy()


def _strip(name: str) -> str:
    return name.removeprefix("model.")


def load_llama_params(
    ckpt_dir: Path | str,
    cfg: LlamaConfig,
    *,
    dtype: Any = None,
) -> dict[str, Any]:
    """Build the model's ``params`` collection from an HF checkpoint dir.

    Returns a tree matching ``LlamaForCausalLM`` with ``scan_layers=True``
    (blocks stacked on the leading layer axis). Raises on missing/unexpected
    tensors so a architecture/config mismatch fails loudly at load, not as
    silent garbage training.
    """
    ckpt_dir = Path(ckpt_dir).expanduser()
    pairs = ((_strip(n), a) for n, a in _iter_checkpoint_tensors(ckpt_dir))
    params = _map_llama_tensors(pairs, cfg, dtype or cfg.param_dtype)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    logger.info("loaded %d tensors (%.1fM params) from %s",
                len(jax.tree.leaves(params)), n_params / 1e6, ckpt_dir)
    return params


def _map_llama_tensors(
    pairs, cfg: LlamaConfig, dtype: Any
) -> dict[str, Any]:
    """Map stripped ``(hf_name, array)`` pairs onto the Llama param tree
    (shared by the text-only loader and the LLaVA language-model half)."""
    L = cfg.n_layers

    # staging area: per-layer dicts to stack once everything is read
    layers: list[dict[str, np.ndarray]] = [dict() for _ in range(L)]
    top: dict[str, np.ndarray] = {}
    unexpected: list[str] = []

    for key, arr in pairs:
        if "rotary_emb.inv_freq" in key:
            # non-persistent RoPE buffer serialized by transformers < 4.32
            # (Llama-2-era .bin checkpoints); recomputed from config here
            continue
        if key == "embed_tokens.weight":
            top["embedding"] = arr
        elif key == "norm.weight":
            top["final_norm"] = arr
        elif key == "lm_head.weight":
            top["lm_head"] = arr.T
        elif key.startswith("layers."):
            _, idx_s, rest = key.split(".", 2)
            idx = int(idx_s)
            if idx >= L:
                raise ValueError(
                    f"checkpoint layer {idx} out of range for n_layers={L}"
                )
            layers[idx][rest] = arr
        else:
            unexpected.append(key)
    if unexpected:
        raise ValueError(f"unexpected checkpoint tensors: {unexpected[:5]}")

    def proj(rest: dict, hf: str) -> np.ndarray:
        return rest.pop(hf).T  # (out, in) -> (in, out)

    def layer_tree(rest: dict[str, np.ndarray], idx: int) -> dict[str, Any]:
        tree: dict[str, Any] = {
            "attn_norm": {"scale": rest.pop("input_layernorm.weight")},
            "mlp_norm": {"scale": rest.pop("post_attention_layernorm.weight")},
            "attn": {
                "q_proj": {"kernel": proj(rest, "self_attn.q_proj.weight")},
                "k_proj": {"kernel": proj(rest, "self_attn.k_proj.weight")},
                "v_proj": {"kernel": proj(rest, "self_attn.v_proj.weight")},
                "o_proj": {"kernel": proj(rest, "self_attn.o_proj.weight")},
            },
        }
        if cfg.attention_qkv_bias:
            # Qwen-2 family: q/k/v carry biases (o_proj does not)
            for p in ("q_proj", "k_proj", "v_proj"):
                tree["attn"][p]["bias"] = rest.pop(f"self_attn.{p}.bias")
        if cfg.n_experts:
            gate = []
            up = []
            down = []
            for e in range(cfg.n_experts):
                gate.append(proj(rest, f"block_sparse_moe.experts.{e}.w1.weight"))
                down.append(proj(rest, f"block_sparse_moe.experts.{e}.w2.weight"))
                up.append(proj(rest, f"block_sparse_moe.experts.{e}.w3.weight"))
            tree["moe"] = {
                "experts_gate": np.stack(gate),
                "experts_up": np.stack(up),
                "experts_down": np.stack(down),
                "router_kernel": proj(rest, "block_sparse_moe.gate.weight"),
            }
        else:
            tree["mlp"] = {
                "gate_proj": {"kernel": proj(rest, "mlp.gate_proj.weight")},
                "up_proj": {"kernel": proj(rest, "mlp.up_proj.weight")},
                "down_proj": {"kernel": proj(rest, "mlp.down_proj.weight")},
            }
        if rest:
            raise ValueError(f"layer {idx}: unmapped tensors {sorted(rest)[:5]}")
        return tree

    missing = [i for i, rest in enumerate(layers) if not rest]
    if missing:
        raise ValueError(f"checkpoint has no tensors for layers {missing[:5]}")
    trees = [layer_tree(rest, i) for i, rest in enumerate(layers)]
    stacked = jax.tree.map(lambda *xs: np.stack(xs).astype(dtype), *trees)

    if "embedding" not in top or "final_norm" not in top:
        raise ValueError("checkpoint missing embed_tokens/norm weights")
    params: dict[str, Any] = {
        "embed_tokens": {"embedding": top["embedding"].astype(dtype)},
        "blocks": {"block": stacked},
        "final_norm": {"scale": top["final_norm"].astype(dtype)},
    }
    if cfg.tie_embeddings:
        if "lm_head" in top:
            logger.info("tie_embeddings=True: ignoring separate lm_head weight")
    else:
        if "lm_head" not in top:
            raise ValueError(
                "checkpoint has no lm_head.weight but cfg.tie_embeddings=False"
            )
        params["lm_head"] = {"kernel": top["lm_head"].astype(dtype)}
    return params


# ---------------------------------------------------------------------------
# LLaVA: CLIP vision tower + projector + Llama language model (round 5)
# ---------------------------------------------------------------------------


def _map_vision_tensors(vt: dict[str, np.ndarray], vcfg, dtype) -> dict[str, Any]:
    """Map CLIP vision-model tensors (``vision_tower.vision_model.`` stripped)
    onto our :class:`~.multimodal.ViTEncoder` tree.

    Layout notes: HF conv weight ``(out, in, h, w)`` → flax ``(h, w, in,
    out)``; q/k/v/out ``(d, d)`` matrices reshape onto flax
    ``MultiHeadDotProductAttention``'s ``(d, H, hd)`` / ``(H, hd, d)``
    kernels. With ``feature_layer=-k`` the final ``k-1`` encoder layers and
    the post norm exist in the checkpoint but are never run (LLaVA-1.5 takes
    hidden_states[-2]) — they are skipped, not errors."""
    d, H = vcfg.d_model, vcfg.n_heads
    hd = d // H
    tree: dict[str, Any] = {}

    def pop(key: str) -> np.ndarray:
        try:
            return vt.pop(key)
        except KeyError:
            raise ValueError(
                f"vision tower missing tensor {key!r} — config/checkpoint "
                "mismatch"
            ) from None

    tree["patch_embed"] = {
        "kernel": pop("embeddings.patch_embedding.weight").transpose(2, 3, 1, 0)
    }
    if vcfg.patch_bias:
        tree["patch_embed"]["bias"] = pop("embeddings.patch_embedding.bias")
    tree["pos_embed"] = pop("embeddings.position_embedding.weight")[None]
    if vcfg.cls_token:
        tree["cls"] = pop("embeddings.class_embedding").reshape(1, 1, d)
    if vcfg.pre_norm:
        # (the "pre_layrnorm" typo is transformers' own attribute name)
        tree["pre_norm"] = {
            "scale": pop("pre_layrnorm.weight"),
            "bias": pop("pre_layrnorm.bias"),
        }
    n_run = (
        vcfg.n_layers if vcfg.feature_layer == 0
        else vcfg.n_layers + vcfg.feature_layer + 1
    )
    for i in range(n_run):
        p = f"encoder.layers.{i}."

        def qkv(nm: str) -> dict[str, np.ndarray]:
            return {
                "kernel": pop(f"{p}self_attn.{nm}_proj.weight").T.reshape(d, H, hd),
                "bias": pop(f"{p}self_attn.{nm}_proj.bias").reshape(H, hd),
            }

        tree[f"block_{i}"] = {
            "ln1": {"scale": pop(f"{p}layer_norm1.weight"),
                    "bias": pop(f"{p}layer_norm1.bias")},
            "attn": {
                "query": qkv("q"), "key": qkv("k"), "value": qkv("v"),
                "out": {
                    "kernel": pop(f"{p}self_attn.out_proj.weight").T.reshape(H, hd, d),
                    "bias": pop(f"{p}self_attn.out_proj.bias"),
                },
            },
            "ln2": {"scale": pop(f"{p}layer_norm2.weight"),
                    "bias": pop(f"{p}layer_norm2.bias")},
            "fc1": {"kernel": pop(f"{p}mlp.fc1.weight").T,
                    "bias": pop(f"{p}mlp.fc1.bias")},
            "fc2": {"kernel": pop(f"{p}mlp.fc2.weight").T,
                    "bias": pop(f"{p}mlp.fc2.bias")},
        }
    if vcfg.feature_layer == 0:
        tree["final_norm"] = {
            "scale": pop("post_layernorm.weight"),
            "bias": pop("post_layernorm.bias"),
        }
    # tensors the selected feature layer never touches
    skippable = tuple(
        f"encoder.layers.{i}." for i in range(n_run, vcfg.n_layers)
    ) + (("post_layernorm.",) if vcfg.feature_layer != 0 else ())
    leftover = [k for k in vt if not k.startswith(skippable)]
    if leftover:
        raise ValueError(f"unmapped vision tensors: {sorted(leftover)[:5]}")
    return jax.tree.map(lambda x: np.asarray(x, dtype), tree)


def load_llava_params(
    ckpt_dir: Path | str,
    cfg,  # LlavaConfig
    *,
    dtype: Any = None,
) -> dict[str, Any]:
    """Build ``LlavaForCausalLM``'s ``params`` collection from an HF LLaVA
    checkpoint dir (``LlavaForConditionalGeneration`` layout:
    ``vision_tower.vision_model.*`` + ``multi_modal_projector.*`` +
    ``language_model.*``). Numerically parity-tested against transformers in
    ``tests/test_hf_import.py``."""
    ckpt_dir = Path(ckpt_dir).expanduser()
    dtype = dtype or cfg.text.param_dtype

    text_pairs: list[tuple[str, np.ndarray]] = []
    vision: dict[str, np.ndarray] = {}
    proj: dict[str, np.ndarray] = {}
    unexpected: list[str] = []
    for name, arr in _iter_checkpoint_tensors(ckpt_dir):
        # transformers >= 4.52 nests the text model under model.*
        name = name.removeprefix("model.")
        if name.startswith("language_model."):
            text_pairs.append((_strip(name.removeprefix("language_model.")), arr))
        elif name.startswith("vision_tower.vision_model."):
            vision[name.removeprefix("vision_tower.vision_model.")] = arr
        elif name.startswith("multi_modal_projector."):
            proj[name.removeprefix("multi_modal_projector.")] = arr
        else:
            unexpected.append(name)
    if unexpected:
        raise ValueError(f"unexpected checkpoint tensors: {unexpected[:5]}")

    params = _map_llama_tensors(iter(text_pairs), cfg.text, dtype)
    params["vision_tower"] = _map_vision_tensors(vision, cfg.vision, dtype)
    try:
        params["projector_fc1"] = {
            "kernel": np.asarray(proj.pop("linear_1.weight").T, dtype),
            "bias": np.asarray(proj.pop("linear_1.bias"), dtype),
        }
        params["projector_fc2"] = {
            "kernel": np.asarray(proj.pop("linear_2.weight").T, dtype),
            "bias": np.asarray(proj.pop("linear_2.bias"), dtype),
        }
    except KeyError as e:
        raise ValueError(f"projector missing tensor {e}") from None
    if proj:
        raise ValueError(f"unmapped projector tensors: {sorted(proj)[:5]}")
    n_params = sum(x.size for x in jax.tree.leaves(params))
    logger.info("loaded LLaVA checkpoint (%.1fM params) from %s",
                n_params / 1e6, ckpt_dir)
    return params
