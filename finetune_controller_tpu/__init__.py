"""finetune_controller_tpu — a TPU-native fine-tuning platform.

Two planes:

* **Compute plane** (``models``, ``ops``, ``parallel``, ``train``, ``data``):
  a JAX/XLA trainer with mesh/NamedSharding parallelism (DP/FSDP/TP; SP/EP in
  later tiers), LoRA adapters, Orbax checkpointing, and Pallas kernels where
  XLA defaults lose.  This is the part the reference
  (``acceleratedscience/finetune-controller``) delegated to user-supplied
  containers (see SURVEY.md §2.2) and is first-class here.

* **Control plane** (``control``, being built alongside): the capability
  surface of the reference —
  authenticated submit/queue/monitor/log-stream/metrics/promote of fine-tune
  jobs (reference ``app/main.py``) — rebuilt without its import-time cluster
  I/O warts (reference ``app/core/config.py:59-90``): every component is
  lazily constructed and injectable.
"""

__version__ = "0.1.0"
