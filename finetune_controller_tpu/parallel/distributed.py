"""Multi-host bootstrap: the in-repo replacement for the reference's
"NCCL-inside-the-image + Training-Operator rendezvous" seam (reference
``app/jobs/kubeflow/PyTorchJobDeployer.py:115`` was its entire surface).

Every TPU host in a slice runs the same program (multi-controller SPMD); the
deployer injects these env vars into each worker pod and this module turns
them into a ``jax.distributed`` service.  Intra-slice collectives then ride
ICI; multi-slice traffic rides DCN — both compiled by XLA, no NCCL.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

ENV_COORDINATOR = "FTC_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "FTC_NUM_PROCESSES"
ENV_PROCESS_ID = "FTC_PROCESS_ID"


def maybe_initialize_distributed(env: dict[str, str] | None = None) -> bool:
    """Initialise jax.distributed from injected env; no-op for single host.

    Returns True when a multi-process runtime was initialised.
    """
    env = dict(os.environ if env is None else env)
    coord = env.get(ENV_COORDINATOR)
    if not coord:
        return False
    num = int(env.get(ENV_NUM_PROCESSES, "1"))
    if num <= 1:
        return False
    pid = int(env.get(ENV_PROCESS_ID, "0"))
    import jax

    logger.info("jax.distributed.initialize coordinator=%s procs=%d id=%d", coord, num, pid)
    jax.distributed.initialize(
        coordinator_address=coord, num_processes=num, process_id=pid
    )
    return True


def worker_env(coordinator_address: str, num_processes: int, process_id: int) -> dict[str, str]:
    """Env block the deployer injects into worker ``process_id``."""
    return {
        ENV_COORDINATOR: coordinator_address,
        ENV_NUM_PROCESSES: str(num_processes),
        ENV_PROCESS_ID: str(process_id),
    }


def is_rank_zero() -> bool:
    import jax

    return jax.process_index() == 0
