"""Ulysses-style sequence parallelism: all-to-all head sharding.

The second SP strategy SURVEY.md §2.3 demands ("ring attention, blockwise,
Ulysses"). Where ring attention (`parallel/ring.py`) keeps heads replicated
and rotates K/V shards around the `sp` axis — n-1 ppermute hops, online
merging — Ulysses trades layout instead of time: one all-to-all converts
each device's (B, S/n, H, D) sequence shard into a (B, S, H/n, D) HEAD
shard, every device runs ONE ordinary causal attention over the full
sequence for its head subset, and a second all-to-all converts back.

Trade-offs (why both strategies exist):

* Ulysses does a single fused attention per device (the Pallas kernel at
  full sequence length — best MXU shape, no per-hop merge math) at the cost
  of two all-to-alls of the activations; ring never moves Q/out but moves
  K+V (n-1) times and fragments attention into n blocks.
* Ulysses caps at ``sp | n_kv_heads`` (each device needs whole KV heads —
  GQA group alignment); ring has no head constraint. A 2-level hierarchy
  (Ulysses within a host, ring across hosts) is the natural composition for
  very long context on many chips; this module implements the single-level
  strategy, selected per job via ``attention_impl``.

GQA alignment proof: all_to_all splits H into n contiguous chunks; chunk i
holds q heads [i·H/n, (i+1)·H/n) and KV chunk i holds kv heads
[i·Hkv/n, (i+1)·Hkv/n). With group size g = H/Hkv, q head h attends kv head
h//g, and for h in chunk i: h//g ∈ [i·Hkv/n, (i+1)·Hkv/n) — exactly the KV
heads resident on the same device. The local kernel's standard GQA mapping
is therefore globally correct.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from .shard_map_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import AxisNames
from .ring import get_ring_mesh


def _ulysses_local(
    q: jax.Array,            # (B, S_local, H, D)
    k: jax.Array,            # (B, S_local, Hkv, D)
    v: jax.Array,
    segment_ids: jax.Array,  # (B, S_local)
    *,
    axis_name: str,
    have_segments: bool,
    impl: str,
    tuning: dict | None = None,
) -> jax.Array:
    from ..ops.attention import causal_attention

    # seq-shard -> head-shard: split the head axis across sp, gather the
    # sequence axis (tiled all-to-all = the Ulysses/DeepSpeed layout swap)
    a2a = partial(
        jax.lax.all_to_all, axis_name=axis_name,
        split_axis=2, concat_axis=1, tiled=True,
    )
    q_h = a2a(q)                                   # (B, S, H/n, D)
    k_h = a2a(k)
    v_h = a2a(v)
    seg = (
        jax.lax.all_gather(segment_ids, axis_name, axis=1, tiled=True)
        if have_segments else None
    )

    out_h = causal_attention(
        q_h, k_h, v_h, impl=impl, segment_ids=seg, tuning=tuning
    )

    # head-shard -> seq-shard: the inverse all-to-all
    return jax.lax.all_to_all(
        out_h, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True,
    )


def ulysses_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    segment_ids: jax.Array | None = None,
    mesh: Mesh | None = None,
    axis_name: str = AxisNames.SEQ,
    impl: str = "xla",
    tuning: dict | None = None,
) -> jax.Array:
    """Causal GQA attention, S sharded over ``axis_name`` via head all-to-all.

    Global shapes as ``ops.attention.causal_attention``. Requires
    ``axis_size | n_kv_heads`` (and hence ``| n_heads``); callers wanting
    more sp than KV heads should use ring attention. ``impl`` picks the
    local kernel ("xla" | "pallas" — full-sequence shapes make the flash
    kernel's streaming exactly as effective as in the unsharded case).
    """
    if impl not in ("xla", "pallas"):
        # re-entering a sharded impl ("ring"/"ulysses") inside shard_map
        # would trace a nested shard_map and die with an opaque mesh error
        raise ValueError(
            f"unknown ulysses local kernel {impl!r}: expected xla or pallas"
        )
    mesh = mesh or get_ring_mesh()
    if mesh is None:
        raise ValueError(
            "ulysses attention needs a mesh (use ring_mesh(...) or pass mesh=)"
        )
    n = mesh.shape[axis_name]
    if n == 1:
        from ..ops.attention import xla_causal_attention

        return xla_causal_attention(q, k, v, segment_ids=segment_ids)
    h, hkv = q.shape[2], k.shape[2]
    if hkv % n or h % n:
        raise ValueError(
            f"ulysses needs the sp axis ({n}) to divide n_kv_heads ({hkv}) "
            f"and n_heads ({h}); use attention_impl='ring' for more sp than "
            "KV heads"
        )
    have_segments = segment_ids is not None
    if segment_ids is None:
        segment_ids = jnp.zeros(q.shape[:2], jnp.int32)

    qkv_spec = P(AxisNames.BATCH_AXES, axis_name, None, None)
    seg_spec = P(AxisNames.BATCH_AXES, axis_name)
    fn = shard_map(
        partial(_ulysses_local, axis_name=axis_name,
                have_segments=have_segments, impl=impl, tuning=tuning),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec),
        out_specs=qkv_spec,
        # only the pallas inner defeats the varying-axes checker (its
        # out_shapes carry no vma); keep the static check for the XLA inner
        check_vma=impl != "pallas",
    )
    return fn(q, k, v, segment_ids.astype(jnp.int32))
