"""Ring attention: causal attention with the sequence dimension sharded over
the ``sp`` mesh axis — the long-context strategy (SURVEY.md §2.3: SP/CP is
'pure greenfield for the TPU build'; the reference never sees a sequence).

Mechanics (blockwise/ring attention): each device holds a contiguous
``S/n``-token shard of Q, K and V. For ``n`` steps, every device computes
blockwise attention between its Q shard and the K/V shard currently resident,
folds the result into online-softmax accumulators (running max ``m``, sum
``l``, weighted values ``acc``), then rotates K/V one hop around the ring via
``jax.lax.ppermute`` — the permute rides ICI neighbour links, and XLA
overlaps the collective with the next block's compute. Peak activation
memory per device stays O(S/n · D); total traffic is the K/V bytes × (n−1).

Causality is enforced by *global* positions, so whole steps where every key
follows every query (src shard entirely in the future) contribute nothing and
are masked out — with causal input the average device does ~n/2 useful block
matmuls.

The public wrapper :func:`ring_attention_sharded` runs the local kernel under
``shard_map`` on the trainer's mesh; inside the model it is reached via
``attention_impl="ring"`` with the mesh provided by :func:`ring_mesh` (the
trainer installs it before tracing).
"""

from __future__ import annotations

import contextlib
from functools import partial

import jax
import jax.numpy as jnp
from .shard_map_compat import axis_size, pcast, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import AxisNames

NEG_INF = float(jnp.finfo(jnp.float32).min)

_ring_mesh: Mesh | None = None


@contextlib.contextmanager
def ring_mesh(mesh: Mesh):
    """Install the mesh ring attention shards over (read at trace time)."""
    global _ring_mesh
    prev = _ring_mesh
    _ring_mesh = mesh
    try:
        yield
    finally:
        _ring_mesh = prev


def get_ring_mesh() -> Mesh | None:
    return _ring_mesh


def _block_scores(q: jax.Array, k: jax.Array) -> jax.Array:
    """q (B, Sq, H, D) × k (B, Sk, Hkv, D) → (B, Hkv, G, Sq, Sk) f32 GQA scores."""
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    qr = q.reshape(b, sq, hkv, g, d)
    return jnp.einsum(
        "bskgd,btkd->bkgst", qr, k, preferred_element_type=jnp.float32
    )


def _ring_attention_local(
    q: jax.Array,            # (B, S_local, H, D) — this device's Q shard
    k: jax.Array,            # (B, S_local, Hkv, D)
    v: jax.Array,
    segment_ids: jax.Array,  # (B, S_local)
    *,
    axis_name: str,
) -> jax.Array:
    n = axis_size(axis_name)
    i = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    scale = d ** -0.5

    q32 = q.astype(jnp.float32) * scale
    local_pos = jnp.arange(s_local)
    q_pos = i * s_local + local_pos                      # (S_local,) global

    # mark the accumulator inits as device-varying so the fori carry types
    # match after the ppermute makes K/V varying (shard_map vma tracking)
    vary = (*AxisNames.BATCH_AXES, axis_name)
    acc = pcast(jnp.zeros((b, hkv, g, s_local, d), jnp.float32), vary, to="varying")
    m = pcast(jnp.full((b, hkv, g, s_local, 1), NEG_INF, jnp.float32), vary, to="varying")
    l = pcast(jnp.zeros((b, hkv, g, s_local, 1), jnp.float32), vary, to="varying")

    def step(t, carry):
        acc, m, l, k_blk, v_blk, kseg_blk = carry
        src = (i - t) % n                                # whose K/V we hold
        k_pos = src * s_local + local_pos

        s_scores = _block_scores(q32, k_blk.astype(jnp.float32))
        mask = q_pos[:, None] >= k_pos[None, :]          # (Sq, Sk) causal, global
        seg = segment_ids[:, None, None, :, None] == kseg_blk[:, None, None, None, :]
        full_mask = mask[None, None, None] & seg
        s_scores = jnp.where(full_mask, s_scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(s_scores, axis=-1, keepdims=True))
        # zero p under the mask explicitly: a fully-masked row (e.g. a step
        # whose whole K/V shard is in the future) keeps m_new == NEG_INF, so
        # exp(s - m_new) would be exp(0) = 1 per lane and corrupt l
        p = jnp.where(full_mask, jnp.exp(s_scores - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bkgst,btkd->bkgsd", p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )

        # rotate K/V one hop (skip after the last step)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt, v_nxt, kseg_nxt = jax.lax.cond(
            t < n - 1,
            lambda ops: tuple(
                jax.lax.ppermute(o, axis_name, perm) for o in ops
            ),
            lambda ops: ops,
            (k_blk, v_blk, kseg_blk),
        )
        return acc_new, m_new, l_new, k_nxt, v_nxt, kseg_nxt

    acc, m, l, *_ = jax.lax.fori_loop(
        0, n, step, (acc, m, l, k, v, segment_ids)
    )
    out = acc / jnp.maximum(l, 1e-30)                    # masked rows → 0
    out = out.transpose(0, 3, 1, 2, 4).reshape(b, s_local, h, d)
    return out.astype(q.dtype)


def _ring_attention_local_flash(
    q: jax.Array,            # (B, S_local, H, D)
    k: jax.Array,
    v: jax.Array,
    segment_ids: jax.Array,  # (B, S_local)
    *,
    axis_name: str,
    have_segments: bool = True,
    tuning: dict | None = None,
) -> jax.Array:
    """Ring attention with the PALLAS flash kernel as the per-step inner.

    The XLA inner (:func:`_ring_attention_local`) materialises the
    (S_local, S_local) score block in HBM every hop; this inner streams it
    through VMEM instead (``ops.pallas.flash_attention``) and merges the
    per-hop partial results through their per-row logsumexp — the standard
    flash-combine identity::

        lse = logaddexp(lse_a, lse_b)
        out = exp(lse_a - lse) * out_a + exp(lse_b - lse) * out_b

    Step 0 is always the device's own (diagonal) block — locally causal;
    every later hop holds a shard that is globally either entirely past
    (full attention) or entirely future (skipped) for a causal ring layout.
    The lse cotangent is differentiable end-to-end (the kernel's
    ``custom_vjp`` folds it into the backward's delta term).
    """
    from ..ops.attention import flash_tuning_kwargs
    from ..ops.pallas.flash_attention import flash_attention_with_lse

    n = axis_size(axis_name)
    i = jax.lax.axis_index(axis_name)
    b, s_local, h, d = q.shape

    # spec kernel_tuning seeded, FTC_FLASH_* env overriding; unset knobs
    # resolve to the measured defaults inside the kernel (_resolve_tuning),
    # which also caps blocks to the per-hop length
    flash = partial(flash_attention_with_lse, **flash_tuning_kwargs(tuning))
    # segmentless corpora must not pay the per-interior-block segment-mask
    # VPU pass — the kernel compiles it out when given no segment ids
    qseg = segment_ids if have_segments else None

    # step 0: the diagonal block — locally causal, local segments both sides
    out0, lse0 = flash(q, k, v, segment_ids=qseg,
                       kv_segment_ids=qseg, causal=True)
    perm = [(j, (j + 1) % n) for j in range(n)]
    rot = lambda o: jax.lax.ppermute(o, axis_name, perm)
    carry0 = (
        out0.astype(jnp.float32),
        lse0,                                    # (B, H, S_local, 1) f32
        rot(k), rot(v), rot(segment_ids),
    )

    def step(t, carry):
        out_acc, lse_acc, k_blk, v_blk, kseg_blk = carry
        src = (i - t) % n                        # whose K/V shard we hold

        def useful(ops):
            k_, v_, ks_ = ops
            o, l = flash(
                q, k_, v_,
                segment_ids=qseg,
                kv_segment_ids=ks_ if have_segments else None,
                causal=False,
            )
            return o.astype(jnp.float32), l

        def skipped(ops):
            return (
                jnp.zeros((b, s_local, h, d), jnp.float32),
                jnp.full((b, h, s_local, 1), NEG_INF, jnp.float32),
            )

        # globally-past shard contributes; globally-future contributes nothing
        out_i, lse_i = jax.lax.cond(src < i, useful, skipped,
                                    (k_blk, v_blk, kseg_blk))

        m = jnp.maximum(lse_acc, lse_i)
        w_acc = jnp.exp(lse_acc - m)
        w_i = jnp.exp(lse_i - m)
        denom = w_acc + w_i
        lse_new = m + jnp.log(denom)
        # weights are (B, H, S, 1); outputs are (B, S, H, D)
        wa = w_acc.transpose(0, 2, 1, 3)
        wi = w_i.transpose(0, 2, 1, 3)
        out_new = (out_acc * wa + out_i * wi) / denom.transpose(0, 2, 1, 3)

        k_nxt, v_nxt, kseg_nxt = jax.lax.cond(
            t < n - 1,
            lambda ops: tuple(rot(o) for o in ops),
            lambda ops: ops,
            (k_blk, v_blk, kseg_blk),
        )
        return out_new, lse_new, k_nxt, v_nxt, kseg_nxt

    out, *_ = jax.lax.fori_loop(1, n, step, carry0)
    return out.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    segment_ids: jax.Array | None = None,
    mesh: Mesh | None = None,
    axis_name: str = AxisNames.SEQ,
    inner: str | None = None,
    tuning: dict | None = None,
) -> jax.Array:
    """Causal GQA attention with S sharded over ``axis_name``.

    Global shapes as ``ops.attention.causal_attention``; S must divide by the
    axis size. Batch stays sharded over the batch axes, heads replicated
    across ``sp`` (Ulysses-style head-sharding would instead all-to-all here).

    ``inner`` picks the per-hop block kernel: ``"xla"`` (einsum + masked
    softmax — materialises the (S/n)² score block per hop) or ``"flash"``
    (Pallas streaming kernel + logsumexp merge). Default from
    ``FTC_RING_INNER`` (``xla`` until the flash inner is measured on a real
    multi-chip slice).
    """
    import os

    mesh = mesh or _ring_mesh
    if mesh is None:
        raise ValueError("ring attention needs a mesh (use ring_mesh(...) or pass mesh=)")
    if mesh.shape[axis_name] == 1:
        from ..ops.attention import xla_causal_attention

        return xla_causal_attention(q, k, v, segment_ids=segment_ids)
    have_segments = segment_ids is not None
    if segment_ids is None:
        segment_ids = jnp.zeros(q.shape[:2], jnp.int32)
    if inner is None:
        # env over spec over default — same precedence as the flash knobs
        inner = (
            os.environ.get("FTC_RING_INNER", "").strip().lower()
            or (tuning or {}).get("ring_inner")
            or "xla"
        )
    if inner not in ("xla", "flash"):
        raise ValueError(f"unknown ring inner {inner!r}: expected xla or flash")
    local = (
        partial(_ring_attention_local_flash, have_segments=have_segments,
                tuning=tuning)
        if inner == "flash"
        else _ring_attention_local
    )

    qkv_spec = P(AxisNames.BATCH_AXES, axis_name, None, None)
    seg_spec = P(AxisNames.BATCH_AXES, axis_name)

    fn = shard_map(
        partial(local, axis_name=axis_name),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec),
        out_specs=qkv_spec,
        # the pallas_call inside the flash inner declares no vma on its
        # out_shapes, so the static varying-axes checker can't track it
        check_vma=inner != "flash",
    )
    return fn(q, k, v, segment_ids.astype(jnp.int32))
