"""Pipeline parallelism (GPipe) over the ``pp`` mesh axis.

SURVEY.md §2.3 lists PP as the one parallelism strategy absent from both the
reference (which never sees tensors) and round 1. This is the TPU-native
take: layer-stacked parameters (the ``nn.scan`` representation the Llama
family already uses) are sharded on their leading layer axis over ``pp``, so
each device holds a contiguous *stage* of ``L / pp`` layers. Microbatches
stream through stages under ``shard_map``; activations hop stage → stage via
``jax.lax.ppermute`` (nearest-neighbour ICI traffic), and the whole schedule
is a differentiable ``lax.scan`` over ticks, so reverse-mode autodiff derives
the backward pipeline (activation hops reverse through the ppermute
transpose) for free — no hand-written backward schedule.

Schedule: plain GPipe with ``M`` microbatches over ``P`` stages,
``T = M + P − 1`` ticks and the classic ``(P−1)/T`` bubble
(:func:`bubble_fraction` — the trainer logs it for every pp run). Idle ticks
still execute the stage body (SPMD — every device runs the same program) with
their output masked out, which costs the same wall-clock the bubble would
anyway.

**Why GPipe and not 1F1B (a considered decision, round 5):** 1F1B's benefit
over GPipe is peak-activation memory — it holds at most ``P`` microbatches'
activations where GPipe holds ``M``. It does NOT shrink the bubble (same
``(P−1)/(M+P−1)``). The cost would be structural: this implementation gets
its backward pipeline *derived by autodiff* from a single differentiable
``lax.scan`` — reverse-mode replays the ticks backward and transposes the
``ppermute`` hops automatically. 1F1B interleaves forward and backward ticks
in one schedule, which autodiff cannot derive; it needs a hand-written
backward schedule with manual activation stashing (and custom_vjp through
the collectives). On TPU the memory lever 1F1B buys is already covered
cheaper: per-layer remat (``remat_policy``) bounds stashed activations to
the remat boundaries, and ``M`` is a free dial (the trainer's default
``M = 2P`` keeps the bubble ≤ ``(P−1)/(3P−1)`` ≈ 33% worst-case, 20% at
``P=2``). If a future profile shows activation residency — not bubble — as
the pp bottleneck at a scale remat can't hold, that is the signal to revisit.

Composition: ``pp × dp`` (the classic GPipe layout). Weights within a stage
are replicated across ``dp``; combining pp with fsdp/tp/sp is rejected at
mesh-resolution time rather than silently mis-sharded. (pp × fsdp would need
manual per-stage weight all-gathers inside the shard_map body — XLA's
automatic FSDP gathering doesn't reach in there; rejected rather than
half-supported.)
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from .shard_map_compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import AxisNames as Ax

# stage body: (stage_params, x_mb, positions_mb, segids_mb) -> y_mb
StageFn = Callable[[Any, jax.Array, jax.Array, jax.Array | None], jax.Array]


def bubble_fraction(n_micro: int, pp: int) -> float:
    """GPipe idle fraction: ``(P−1) / (M + P − 1)`` — the share of the
    ``M + P − 1`` ticks each stage spends masked out."""
    return (pp - 1) / (n_micro + pp - 1)


def default_pp_microbatches(local_batch: int, pp: int) -> int:
    """The trainer's default schedule: the largest microbatch count ≤ 2·pp
    that divides the per-data-shard batch (2·pp halves the GPipe bubble).
    One definition — the trainer and the AOT report both call this, so the
    reported schedule cannot drift from what actually runs."""
    return max(
        (m for m in range(1, 2 * pp + 1) if local_batch % m == 0), default=1
    )


def validate_pp_mesh(mesh: Mesh) -> None:
    """GPipe composes with dp only; other intra-slice axes must be 1."""
    for axis in (Ax.FSDP, Ax.TENSOR, Ax.SEQ, Ax.EXPERT):
        if mesh.shape.get(axis, 1) > 1 and mesh.shape.get(Ax.PIPE, 1) > 1:
            raise ValueError(
                f"pipeline parallelism composes with dp only; axis {axis!r} "
                f"has size {mesh.shape[axis]} (use pp×dp, or drop pp)"
            )


def _gpipe_local(
    stage_params: Any,          # leading dim = L/P (this stage's layers)
    x: jax.Array,               # (B_loc, S, D) activations after embedding
    positions: jax.Array,       # (B_loc, S)
    segment_ids: jax.Array,     # (B_loc, S)
    *,
    stage_fn: StageFn,
    n_micro: int,
    axis_name: str,
) -> jax.Array:
    p_count = axis_size(axis_name)
    p_idx = jax.lax.axis_index(axis_name)
    b_loc, s, d = x.shape
    if b_loc % n_micro:
        raise ValueError(f"local batch {b_loc} not divisible by {n_micro} microbatches")
    b_mb = b_loc // n_micro

    x_mb = x.reshape(n_micro, b_mb, s, d)
    pos_mb = positions.reshape(n_micro, b_mb, s)
    seg_mb = segment_ids.reshape(n_micro, b_mb, s)

    ticks = n_micro + p_count - 1
    perm_fwd = [(i, i + 1) for i in range(p_count - 1)]

    def tick(carry, t):
        buf, outs = carry
        # which microbatch this stage works on at tick t (GPipe diagonal)
        mb = t - p_idx
        active = (mb >= 0) & (mb < n_micro)
        mb_c = jnp.clip(mb, 0, n_micro - 1)
        pos = jax.lax.dynamic_index_in_dim(pos_mb, mb_c, keepdims=False)
        seg = jax.lax.dynamic_index_in_dim(seg_mb, mb_c, keepdims=False)
        y = stage_fn(stage_params, buf, pos, seg)
        # idle ticks produce garbage: mask it so it neither propagates nor
        # backpropagates
        y = jnp.where(active, y, jnp.zeros_like(y))

        # last stage collects its finished microbatch
        write = active & (p_idx == p_count - 1)
        updated = jax.lax.dynamic_update_index_in_dim(outs, y, mb_c, axis=0)
        outs = jnp.where(write, updated, outs)

        # activations hop to the next stage; stage 0 pulls the next microbatch
        if p_count > 1:
            recv = jax.lax.ppermute(y, axis_name, perm_fwd)
        else:
            recv = y
        nxt = jnp.clip(t + 1, 0, n_micro - 1)
        first_in = jax.lax.dynamic_index_in_dim(x_mb, nxt, keepdims=False)
        buf = jnp.where(p_idx == 0, first_in, recv)
        return (buf, outs), None

    buf0 = x_mb[0]
    outs0 = jnp.zeros_like(x_mb)
    (_, outs), _ = jax.lax.scan(tick, (buf0, outs0), jnp.arange(ticks))

    # result lives on the last stage; psum of the masked buffer broadcasts it
    # so every stage returns the same (replicated-over-pp) activations for
    # the head/loss (ppermute cannot fan out one source to many destinations)
    if p_count > 1:
        outs = jnp.where(p_idx == p_count - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, axis_name)
    return outs.reshape(b_loc, s, d)


def gpipe_blocks(
    stacked_params: Any,
    x: jax.Array,
    positions: jax.Array,
    segment_ids: jax.Array | None,
    *,
    stage_fn: StageFn,
    mesh: Mesh,
    n_micro: int,
) -> jax.Array:
    """Run the layer-stacked block params as a GPipe pipeline over ``pp``.

    ``stacked_params`` leaves have a leading layer axis (the ``nn.scan``
    layout) sharded over ``pp``; ``x`` is the embedded activations, sharded
    over the batch axes and replicated over ``pp``.
    """
    validate_pp_mesh(mesh)
    if segment_ids is None:
        segment_ids = jnp.zeros(x.shape[:2], jnp.int32)

    act_spec = P(Ax.BATCH_AXES, None, None)
    tok_spec = P(Ax.BATCH_AXES, None)
    param_specs = jax.tree.map(lambda _: P(Ax.PIPE), stacked_params)

    fn = shard_map(
        partial(
            _gpipe_local, stage_fn=stage_fn, n_micro=n_micro, axis_name=Ax.PIPE
        ),
        mesh=mesh,
        in_specs=(param_specs, act_spec, tok_spec, tok_spec),
        out_specs=act_spec,
        check_vma=False,
    )
    return fn(stacked_params, x, positions, segment_ids.astype(jnp.int32))
