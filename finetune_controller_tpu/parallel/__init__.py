from .mesh import MeshSpec, AxisNames, build_mesh
from .sharding import PartitionRules, LLAMA_RULES, sharding_for_tree, batch_sharding

__all__ = [
    "MeshSpec",
    "AxisNames",
    "build_mesh",
    "PartitionRules",
    "LLAMA_RULES",
    "sharding_for_tree",
    "batch_sharding",
]
