"""Device-mesh construction for TPU slices.

The reference framework had no notion of a device mesh at all — its
``cluster_nodes``/``accelerator_count`` pair (reference
``app/models/base/finetuning.py:86-93``) was forwarded to Kubernetes as replica
counts and everything else happened inside the user's container.  Here the mesh
is the core abstraction: every parallelism strategy (DP, FSDP, TP, SP/CP, EP,
PP) is an axis of one logical mesh, and XLA inserts the collectives.

Axis layout convention (fastest-varying axis innermost so that TP rides ICI
neighbours within a host, FSDP next, DP outermost across slices/DCN):

    mesh shape = (dp, fsdp, ep, pp, sp, tp)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


class AxisNames:
    """Canonical mesh-axis names used across the framework."""

    DATA = "dp"      # pure data parallelism (gradient all-reduce)
    FSDP = "fsdp"    # data parallelism with fully-sharded params (ZeRO-3)
    EXPERT = "ep"    # expert parallelism for MoE layers
    PIPE = "pp"      # pipeline stages
    SEQ = "sp"       # sequence/context parallelism (ring attention)
    TENSOR = "tp"    # tensor (megatron-style) parallelism

    ORDER = (DATA, FSDP, EXPERT, PIPE, SEQ, TENSOR)
    # Axes over which the batch dimension is split:
    BATCH_AXES = (DATA, FSDP)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh request; ``-1`` on at most one axis means "infer"."""

    dp: int = 1
    fsdp: int = -1
    ep: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {
            AxisNames.DATA: self.dp,
            AxisNames.FSDP: self.fsdp,
            AxisNames.EXPERT: self.ep,
            AxisNames.PIPE: self.pp,
            AxisNames.SEQ: self.sp,
            AxisNames.TENSOR: self.tp,
        }
        unknown = [k for k, v in sizes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {unknown}")
        known = math.prod(v for v in sizes.values() if v != -1)
        if unknown:
            if n_devices % known:
                raise ValueError(
                    f"cannot infer {unknown[0]}: {n_devices} devices not divisible "
                    f"by product of fixed axes {known}"
                )
            sizes[unknown[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {known} devices but {n_devices} are available"
            )
        return sizes

    def build(self, devices: Sequence[jax.Device] | None = None) -> Mesh:
        return build_mesh(self, devices)


def build_mesh(spec: MeshSpec, devices: Sequence[jax.Device] | None = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    fixed = [spec.dp, spec.fsdp, spec.ep, spec.pp, spec.sp, spec.tp]
    if -1 not in fixed and math.prod(fixed) < len(devices):
        # A fully-specified mesh smaller than the host's device count is
        # honoured on a prefix of the devices (e.g. a 1-chip job on a
        # multi-device test host).
        devices = devices[: math.prod(fixed)]
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AxisNames.ORDER)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AxisNames.ORDER)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    devices = [device] if device is not None else jax.devices()[:1]
    return build_mesh(MeshSpec(fsdp=1), devices)
