"""Device-mesh construction for TPU slices.

The reference framework had no notion of a device mesh at all — its
``cluster_nodes``/``accelerator_count`` pair (reference
``app/models/base/finetuning.py:86-93``) was forwarded to Kubernetes as replica
counts and everything else happened inside the user's container.  Here the mesh
is the core abstraction: every parallelism strategy (DP, FSDP, TP, SP/CP, EP,
PP) is an axis of one logical mesh, and XLA inserts the collectives.

Axis layout convention (fastest-varying axis innermost so that TP rides ICI
neighbours within a host, FSDP next, DP outermost across slices/DCN):

    mesh shape = (dp, fsdp, ep, pp, sp, tp)
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)


class AxisNames:
    """Canonical mesh-axis names used across the framework."""

    DATA = "dp"      # pure data parallelism (gradient all-reduce)
    FSDP = "fsdp"    # data parallelism with fully-sharded params (ZeRO-3)
    EXPERT = "ep"    # expert parallelism for MoE layers
    PIPE = "pp"      # pipeline stages
    SEQ = "sp"       # sequence/context parallelism (ring attention)
    TENSOR = "tp"    # tensor (megatron-style) parallelism

    ORDER = (DATA, FSDP, EXPERT, PIPE, SEQ, TENSOR)
    # Axes over which the batch dimension is split:
    BATCH_AXES = (DATA, FSDP)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh request; ``-1`` on at most one axis means "infer"."""

    dp: int = 1
    fsdp: int = -1
    ep: int = 1
    pp: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {
            AxisNames.DATA: self.dp,
            AxisNames.FSDP: self.fsdp,
            AxisNames.EXPERT: self.ep,
            AxisNames.PIPE: self.pp,
            AxisNames.SEQ: self.sp,
            AxisNames.TENSOR: self.tp,
        }
        unknown = [k for k, v in sizes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {unknown}")
        known = math.prod(v for v in sizes.values() if v != -1)
        if unknown:
            if n_devices % known:
                raise ValueError(
                    f"cannot infer {unknown[0]}: {n_devices} devices not divisible "
                    f"by product of fixed axes {known}"
                )
            sizes[unknown[0]] = n_devices // known
        elif known != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {known} devices but {n_devices} are available"
            )
        return sizes

    def build(
        self,
        devices: Sequence[jax.Device] | None = None,
        slice_of: Sequence[int] | None = None,
    ) -> Mesh:
        return build_mesh(self, devices, slice_of=slice_of)


def order_devices_for_dcn(
    devices: Sequence,
    sizes: dict[str, int],
    slice_of: Sequence[int] | None = None,
) -> list:
    """Order devices so the mesh maps onto the ICI/DCN hierarchy.

    On a multi-slice TPU deployment each device carries a ``slice_index``;
    ICI only connects chips within a slice, traffic between slices rides
    DCN.  The mesh is reshaped row-major with ``dp`` outermost, so grouping
    devices by slice makes every dp-subdivision fall on slice boundaries
    whenever ``dp`` is a multiple of the slice count — inner axes (fsdp/ep/
    pp/sp/tp) then ride ICI and only the dp gradient all-reduce crosses DCN,
    the standard multi-slice recipe (dp-over-DCN x FSDP-over-ICI).

    Emits a warning when an inner axis is forced across a slice boundary
    (e.g. fsdp spanning two slices): still correct — XLA compiles DCN
    collectives — but bandwidth-bound.  Single-slice and CPU/test devices
    (no ``slice_index``) come back unchanged.

    ``slice_of`` overrides the per-device slice assignment — used to model a
    multi-slice topology on devices that carry no ``slice_index`` (virtual
    CPU meshes in the dryrun/AOT legs), exercising the same ordering path a
    real 2-slice deployment takes.
    """
    if slice_of is not None:
        if len(slice_of) != len(devices):
            raise ValueError(
                f"slice_of has {len(slice_of)} entries for {len(devices)} devices"
            )
        slice_of = list(slice_of)
    else:
        # None slice_index (e.g. a CPU device mixed in) becomes its own -1
        # "slice": it must neither raise a None-vs-int TypeError in the sort
        # nor be excluded from the per-slice tiling arithmetic below.
        slice_of = [
            s if (s := getattr(d, "slice_index", None)) is not None else -1
            for d in devices
        ]
    distinct = set(slice_of)
    if len(distinct) <= 1:
        return list(devices)
    ordered = [
        d for _, d in sorted(
            enumerate(devices),
            key=lambda it: (slice_of[it[0]], it[0]),  # stable within a slice
        )
    ]
    n_slices = len(distinct)
    per_slice = len(ordered) // n_slices
    inner = math.prod(v for a, v in sizes.items() if a != AxisNames.DATA)
    # clean hierarchy iff each slice holds a whole number of inner tiles
    if inner > per_slice or (per_slice and per_slice % inner):
        logger.warning(
            "mesh inner axes (%d devices) do not tile the %d-device slices: "
            "an intra-slice axis will cross DCN — consider dp=%d so only "
            "data-parallel gradient reduction leaves a slice",
            inner, per_slice, n_slices,
        )
    return ordered


def build_mesh(
    spec: MeshSpec,
    devices: Sequence[jax.Device] | None = None,
    slice_of: Sequence[int] | None = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    fixed = [spec.dp, spec.fsdp, spec.ep, spec.pp, spec.sp, spec.tp]
    if -1 not in fixed and math.prod(fixed) < len(devices):
        # A fully-specified mesh smaller than the host's device count is
        # honoured on a prefix of the devices (e.g. a 1-chip job on a
        # multi-device test host). Slice-group FIRST so the prefix fills
        # whole slices instead of straddling DCN on an interleaved
        # enumeration ({} sizes = sort only, warnings come later).
        keep = math.prod(fixed)
        order = order_devices_for_dcn(devices, {}, slice_of=slice_of)
        if slice_of is not None:
            index_of = {id(d): i for i, d in enumerate(devices)}
            slice_of = [slice_of[index_of[id(d)]] for d in order[:keep]]
        devices = order[:keep]
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AxisNames.ORDER)
    arr = np.asarray(
        order_devices_for_dcn(devices, sizes, slice_of=slice_of)
    ).reshape(shape)
    return Mesh(arr, AxisNames.ORDER)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    devices = [device] if device is not None else jax.devices()[:1]
    return build_mesh(MeshSpec(fsdp=1), devices)
