"""Path-pattern → PartitionSpec rules for parameter and activation sharding.

Megatron+FSDP layout for the Llama family:

* column-parallel kernels (qkv, gate/up proj): ``P(fsdp, tp)`` — output
  features split over TP, input features sharded over FSDP so the weight
  all-gather rides ICI right before the matmul.
* row-parallel kernels (o proj, down proj): ``P(tp, fsdp)``.
* embeddings / lm head: vocab over TP, model dim over FSDP.
* norms / biases / scalars: replicated.

Rules are ordered regexes over the ``/``-joined param path; first match wins.
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import AxisNames as Ax


class ShardingRuleError(ValueError):
    """A partition rule resolved to a spec the mesh cannot apply to a leaf:
    a spec axis the mesh does not define, or a mesh-axis product that does
    not divide the leaf dimension it shards.  Raised upfront by
    :func:`sharding_for_tree` with the offending path and spec — before the
    bad rule can surface as a deep XLA partitioner error at compile time."""


class PartitionRules:
    def __init__(self, rules: list[tuple[str, P]]):
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]

    def entries(self) -> list[tuple[str, P]]:
        """The ordered ``(pattern, spec)`` table — introspection surface for
        the sharding-conformance lint rules (``analysis/rules_sharding.py``)."""
        return [(pat.pattern, spec) for pat, spec in self._rules]

    def match_index(self, path: str) -> int | None:
        """Index of the first rule whose pattern matches ``path`` (the rule
        :meth:`spec_for` would select), or None."""
        for i, (pat, _spec) in enumerate(self._rules):
            if pat.search(path):
                return i
        return None

    def fingerprint(self) -> str:
        """Stable digest of the ordered rule table.

        Stamped into every checkpoint manifest (``train/elastic.py``): a
        restore onto a model whose rule table differs — reordered rules, a
        changed spec, a new carve-out — would silently mis-shard the state,
        so elastic restore refuses a checkpoint whose fingerprint does not
        match the live table.  Patterns AND specs both feed the digest;
        order matters (first match wins at lookup time).
        """
        import hashlib

        parts = [
            f"{pat.pattern}\x00{tuple(spec)!r}" for pat, spec in self._rules
        ]
        digest = hashlib.sha256("\x01".join(parts).encode()).hexdigest()
        return f"sha256:{digest}"

    def spec_for(self, path: str, value: Any = None) -> P:
        for pat, spec in self._rules:
            if pat.search(path):
                ndim = getattr(value, "ndim", None)
                if value is None or ndim is None or len(spec) == ndim:
                    return spec
                if len(spec) == ndim - 1 and "blocks" in path:
                    # Layer-stacked (nn.scan) params carry a leading layer
                    # axis — the pipeline axis. With pp=1 this is a no-op;
                    # with pp>1 each stage holds its contiguous layer shard.
                    return P(Ax.PIPE, *spec)
                if len(spec) > ndim:
                    # Rank-mismatch safety: replicate rather than mis-shard.
                    return P()
                return spec
        return P()

    def tree_specs(self, tree: Any) -> Any:
        """Map a pytree of arrays (or ShapeDtypeStructs) to PartitionSpecs."""
        return jax.tree_util.tree_map_with_path(
            lambda kp, v: self.spec_for(key_path_str(kp), v), tree
        )


def key_path_str(kp) -> str:
    """``/``-joined param path for a jax key path — the string the rule
    patterns match against."""
    parts = []
    for k in kp:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


# Llama-family parameter rules.  Kernel shapes as produced by
# finetune_controller_tpu.models.llama (Dense kernels are (in, out)).
LLAMA_RULES = PartitionRules(
    [
        # token embedding: (vocab, d_model)
        (r"embed_tokens/embedding", P(Ax.TENSOR, Ax.FSDP)),
        # lm head kernel: (d_model, vocab)
        (r"lm_head/kernel", P(Ax.FSDP, Ax.TENSOR)),
        # QLoRA int4 scales: (in/block, out) — the block dim is tiny, keep it
        # whole and shard only the feature dim (must precede the kernel rules,
        # which would otherwise also match "kernel_scales")
        (r"(q_proj|k_proj|v_proj|gate_proj|up_proj)/kernel_scales", P(None, Ax.TENSOR)),
        (r"(o_proj|down_proj)/kernel_scales", P(None, Ax.FSDP)),
        # attention projections (kernel and int4-packed kernel share layout)
        (r"(q_proj|k_proj|v_proj)/kernel", P(Ax.FSDP, Ax.TENSOR)),
        (r"o_proj/kernel", P(Ax.TENSOR, Ax.FSDP)),
        # MLP
        (r"(gate_proj|up_proj)/kernel", P(Ax.FSDP, Ax.TENSOR)),
        (r"down_proj/kernel", P(Ax.TENSOR, Ax.FSDP)),
        # MoE experts (models/moe.py): stacked (n_experts, in, out), experts
        # over EP so expert matmuls are local and token exchange is all-to-all.
        # Int4 scales first (same tiny-block-dim reasoning as the dense
        # kernel_scales carve-outs above): (E, in/block, out) keeps the block
        # dim whole and shards only experts + the feature dim
        (r"experts_(gate|up)_scales", P(Ax.EXPERT, None, Ax.TENSOR)),
        (r"experts_down_scales", P(Ax.EXPERT, None, Ax.FSDP)),
        (r"experts_(gate|up)", P(Ax.EXPERT, Ax.FSDP, Ax.TENSOR)),
        (r"experts_down", P(Ax.EXPERT, Ax.TENSOR, Ax.FSDP)),
        (r"router_kernel", P(Ax.FSDP, None)),
        # multimodal projector (models/multimodal.py): fc1 (d_vision, hidden)
        # column-parallel, fc2 (hidden, d_model) row-parallel
        (r"projector_fc1/kernel", P(Ax.FSDP, Ax.TENSOR)),
        (r"projector_fc2/kernel", P(Ax.TENSOR, Ax.FSDP)),
        # ViT tower: replicated DELIBERATELY — the encoder is small next to
        # the decoder and frozen in the LLaVA recipe.  The explicit rule
        # (rather than catch-all fallthrough) keeps the shard-rule-coverage
        # lint's weight-fallthrough check meaningful: a kernel reaching the
        # bare catch-all below means someone ADDED a weight family without
        # deciding its sharding
        (r"vision_tower/", P()),
        # LoRA adapters: A (in, r) sharded like the frozen kernel's input dim;
        # B (r, out) over the output dim.  Rank r is tiny — keep it replicated.
        (r"(q_proj|k_proj|v_proj|gate_proj|up_proj)/lora_a", P(Ax.FSDP, None)),
        (r"(q_proj|k_proj|v_proj|gate_proj|up_proj)/lora_b", P(None, Ax.TENSOR)),
        (r"o_proj/lora_a|down_proj/lora_a", P(Ax.TENSOR, None)),
        (r"o_proj/lora_b|down_proj/lora_b", P(None, Ax.FSDP)),
        # norms, scales, biases — replicated
        (r".*", P()),
    ]
)


def validate_spec(path: str, shape: tuple, spec: P, mesh: Mesh) -> None:
    """Prove ``spec`` is applicable to a ``shape``-shaped leaf on ``mesh``:
    every named axis exists, and the product of mesh-axis sizes sharding a
    dimension divides that dimension.  Raises :class:`ShardingRuleError`
    naming the path/spec/dim — the typed, immediate form of what would
    otherwise surface as a deep XLA partitioner error at compile time."""
    mesh_shape = dict(mesh.shape)
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        factor = 1
        for ax in axes:
            if ax not in mesh_shape:
                raise ShardingRuleError(
                    f"partition rule for {path!r} resolved to spec {spec} "
                    f"naming mesh axis {ax!r}, but the mesh only defines "
                    f"axes {tuple(mesh_shape)} — fix the rule table or the "
                    "mesh builder (parallel/mesh.py)"
                )
            factor *= mesh_shape[ax]
        if dim >= len(shape) or (factor > 1 and shape[dim] % factor):
            dim_size = shape[dim] if dim < len(shape) else "<missing>"
            raise ShardingRuleError(
                f"partition rule for {path!r} resolved to spec {spec}, but "
                f"dim {dim} of shape {tuple(shape)} (size {dim_size}) is not "
                f"divisible by the {factor}-way mesh sharding over "
                f"axes {tuple(axes)}"
            )


def sharding_for_tree(tree: Any, mesh: Mesh, rules: PartitionRules) -> Any:
    specs = rules.tree_specs(tree)
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    for (kp, leaf), spec in zip(
        jax.tree_util.tree_leaves_with_path(tree), spec_leaves
    ):
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            validate_spec(key_path_str(kp), tuple(shape), spec, mesh)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def batch_sharding(mesh: Mesh, seq_sharded: bool = True) -> NamedSharding:
    """Sharding for (batch, seq[, ...]) token arrays: batch over dp+fsdp, seq
    over sp (ring/context parallelism) when requested."""
    seq_axis = Ax.SEQ if seq_sharded else None
    return NamedSharding(mesh, P(Ax.BATCH_AXES, seq_axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
