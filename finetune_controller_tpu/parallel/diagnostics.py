"""Collective-bandwidth diagnostics — the ``nccl-tests`` workflow, TPU-native.

The reference's distributed backend is NCCL inside the user image; when a
cluster is slow, operators reach for nccl-tests' all-reduce bus-bandwidth
sweep.  The rebuild's collectives are XLA programs over ICI/DCN, so its
diagnostic is one too: jitted ``psum`` / ``all_gather`` / ``ppermute``
sweeps over the live device mesh, reporting per-size timings and achieved
algorithmic/bus bandwidth.  An operator runs it inside a worker pod (or any
host with chips) to validate a slice before blaming the training loop:

    python -m finetune_controller_tpu.parallel.diagnostics [--sizes-mb 1,16,128]

Bus-bandwidth accounting follows the nccl-tests conventions, with ``S`` =
the per-device shard: all-reduce moves ``2·S·(n-1)/n`` per device,
all-gather receives ``S·(n-1)``, a ppermute ring step moves ``S``.

Single-device meshes degrade gracefully (no inter-chip traffic — reported
as such) so the same command works on a dev box; the CPU test mesh
exercises the full sweep in CI.
"""

from __future__ import annotations

import json
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _timed_chain(fn, x, *, warmup: int = 2, iters: int = 5) -> float:
    """Per-call seconds with a data-dependency chain + host fetch.

    Same discipline as ``ops.kernel_bench._time_chained`` (and for the same
    measured reason): independent repeated calls through an async or caching
    remote-TPU runtime can appear nearly free even under
    ``block_until_ready`` — and this tool's whole job is telling an operator
    the truth about a slice. Every collective here maps a sharded array to a
    same-shape sharded array, so the output feeds the next call directly.
    """
    for _ in range(warmup):
        x = fn(x)
    float(jnp.sum(x[:1].astype(jnp.float32)))  # host sync
    t0 = time.perf_counter()
    for _ in range(iters):
        x = fn(x)
    float(jnp.sum(x[:1].astype(jnp.float32)))
    return (time.perf_counter() - t0) / iters


def collective_diagnostics(
    sizes_mb: Sequence[float] = (1, 16, 64),
    devices: Sequence[Any] | None = None,
) -> dict[str, Any]:
    """Sweep the three collective shapes training traffic is made of.

    ``psum`` (gradient reduction), ``all_gather`` (FSDP parameter gather),
    ``ppermute`` ring step (ring attention / pipeline transfers).
    """
    from .shard_map_compat import shard_map

    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    mesh = Mesh(np.asarray(devs), ("x",))
    spec = NamedSharding(mesh, P("x"))
    report: dict[str, Any] = {
        "n_devices": n,
        "device_kind": devs[0].device_kind,
        "platform": devs[0].platform,
        "collectives": {},
    }
    if n == 1:
        report["note"] = "single device: no inter-chip traffic to measure"
        return report

    # Every body maps a per-device (elems,) block to a per-device (elems,)
    # block (out_specs=P("x"), same global shape), so calls CHAIN — the
    # output feeds the next call, defeating async-runtime overlap.
    def make(op):
        if op == "psum":
            # each device contributes S and receives the sum: ring
            # all-reduce moves 2*S*(n-1)/n per device
            body = lambda x: jax.lax.psum(x, "x")
            bus_factor = 2 * (n - 1) / n
        elif op == "all_gather":
            # each device receives the other n-1 shards and keeps its own:
            # the gathered row-0 keeps the chain shape
            body = lambda x: jax.lax.all_gather(x, "x")[0]
            bus_factor = n - 1.0
        else:  # ppermute ring step: S per device over one link hop
            perm = [(i, (i + 1) % n) for i in range(n)]
            body = lambda x: jax.lax.ppermute(x, "x", perm)
            bus_factor = 1.0
        # ftc: ignore[recompile-fresh-callable] -- compiled once per collective op (3 total) per diagnostics invocation; not a hot path
        fn = jax.jit(
            shard_map(
                body, mesh=mesh, in_specs=P("x"), out_specs=P("x"),
                # psum/gather-row-0 outputs are replicated-by-construction;
                # the static replication checker can't always infer that
                check_vma=False,
            )
        )
        return fn, bus_factor

    for op in ("psum", "all_gather", "ppermute"):
        fn, bus_factor = make(op)
        rows = {}
        for size_mb in sizes_mb:
            # per-DEVICE payload S: size_mb of f32, rounded up to whole
            # lanes; the global (elems*n,) array is created ALREADY sharded —
            # materializing it on one device first would OOM the very slices
            # this tool targets (128 MB x 256 chips = 32 GB on device 0)
            elems = max(8, int(size_mb * (1 << 20) // 4))
            # ftc: ignore[recompile-jit-in-loop] -- a fresh trivial fill compile per payload size is the only way to create the array ALREADY sharded; cost is noise next to the measured collective
            x = jax.jit(
                lambda: jnp.ones((elems * n,), jnp.float32),
                out_shardings=spec,
            )()
            sec = _timed_chain(fn, x)
            payload = elems * 4  # bytes contributed per device
            if op == "all_gather":
                algo = payload * n / sec  # bytes gathered per device
            else:
                algo = payload / sec
            rows[f"{size_mb:g}"] = {
                "time_ms": round(sec * 1e3, 3),
                "algo_bw_gbps": round(algo / 1e9, 3),
                "bus_bw_gbps": round(payload * bus_factor / sec / 1e9, 3),
            }
        report["collectives"][op] = rows
    return report


def main() -> int:
    import argparse
    import os

    ap = argparse.ArgumentParser(prog="ftc-collective-diagnostics")
    ap.add_argument("--sizes-mb", default="1,16,64")
    ap.add_argument(
        "--platform", default=os.environ.get("JAX_PLATFORMS", ""),
        help="force a JAX platform (e.g. cpu for the virtual test mesh)",
    )
    args = ap.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    from .distributed import maybe_initialize_distributed

    maybe_initialize_distributed()
    sizes = [float(s) for s in args.sizes_mb.split(",") if s]
    print(json.dumps(collective_diagnostics(sizes)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
