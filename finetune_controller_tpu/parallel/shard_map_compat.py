"""``shard_map`` / ``axis_size`` across jax versions.

Modern jax exports ``jax.shard_map`` with a ``check_vma`` kwarg; older
releases (0.4.x) keep it in ``jax.experimental.shard_map`` under the previous
``check_rep`` name for the same knob. Call sites import from here and always
use the modern ``check_vma`` spelling; the shim translates when running on an
older jax so the container's baked-in toolchain works unmodified.

Same story for ``jax.lax.axis_size``: absent on 0.4.x, where ``psum(1,
axis)`` is the classic idiom (it constant-folds to the mesh axis size).
"""

from __future__ import annotations

from typing import Any, Callable

try:  # modern jax: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _CHECK_KWARG = "check_vma"
except ImportError:  # jax 0.4.x: experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KWARG = "check_rep"

try:  # modern jax
    from jax.lax import axis_size
except ImportError:  # jax 0.4.x

    def axis_size(axis_name: Any) -> int:
        import jax

        return jax.lax.psum(1, axis_name)


try:  # modern jax: varying-manual-axes casts for the vma type system
    from jax.lax import pcast
except ImportError:  # jax 0.4.x has no vma tracking — the cast is a no-op

    def pcast(x: Any, axes: Any, *, to: str) -> Any:
        return x


__all__ = ["shard_map", "axis_size", "pcast"]


def shard_map(
    f: Callable[..., Any], *, check_vma: bool = True, **kwargs: Any
) -> Callable[..., Any]:
    return _shard_map(f, **{_CHECK_KWARG: check_vma}, **kwargs)
