"""Fidelity proof: a real-weights, real-data fine-tune, end to end.

Every benchmark number elsewhere in this repo measures *throughput* on
random-init weights and synthetic tokens. This module proves the *product*
works — the claim a fine-tuning service actually makes — the way the
reference's one example proves itself by training real MNIST to convergence
(reference ``app/models/examples/mnist.py:13-99``):

1. **Pretrain a base** (:func:`pretrain_base`): a small Llama-config model is
   trained on real English (stdlib docstrings, ``data/corpus.py`` — the
   environment has no network) until it visibly models the text, then
   exported through ``models/hf_export.py`` as an HF checkpoint directory —
   the stand-in for "a pretrained base downloaded from the hub".
2. **Fine-tune through the product** (:func:`run_proof`): the base is fed to
   the full controller path — dataset upload, job submission via
   ``task_builder``, the local backend's subprocess trainer, monitor
   reconciliation, artifact sync — as a LoRA SFT job on an instruction-style
   dataset with a distinctive response style.
3. **Assert fidelity**: step-0 loss from the base is far below random-init
   loss (the base's knowledge transfers), final loss is below step-0 (the
   fine-tune learns), and greedy generation flips from base-flavored prose to
   the SFT response style on a HELD-OUT topic (behavior change, not
   memorization of a seen row).

The e2e CPU test (``tests/test_fidelity_e2e.py``) runs a small version;
``scripts/fidelity_proof.py`` runs the full version and records the
``fidelity_record.json`` cited by BASELINE.md's fidelity row.
"""

from __future__ import annotations

import asyncio
import csv
import json
import logging
from pathlib import Path
from typing import Any

from pydantic import Field

from .controller.examples import LoRASFTArguments, TinyTestLoRA

logger = logging.getLogger(__name__)


class FidelityArguments(LoRASFTArguments):
    """The smoke spec's arguments plus the metrics cadence — the proof reads
    the step-1 loss, so every step must log."""

    log_every: int = Field(1, ge=1, description="Metrics cadence (steps)")


class FidelityLoRA(TinyTestLoRA):
    """LoRA SFT from a locally pretrained real-text base; the per-run base
    directory is bound by subclassing (``pretrained_weights_dir`` is part of
    the class-level contract, mirroring how a registered spec would pin its
    hub checkpoint)."""

    model_name = "fidelity-tiny-lora"
    description = "LoRA SFT from a locally pretrained real-text base"

    training_arguments: FidelityArguments

#: SFT response style: every completion opens with this frame — trivially
#: learnable, unmistakably absent from stdlib-docstring English, so the
#: before/after generation contrast is unambiguous
SFT_PREFIX = "Aye, "

_TOPICS = [
    "the weather", "sailing ships", "buried treasure", "the open sea",
    "your parrot", "the captain", "a treasure map", "the island",
    "the crew", "the storm", "the harbor", "the compass", "the rigging",
    "the lookout", "the galley", "the anchor", "the tide", "the moon",
    "the cannons", "the flag",
]
#: topics never written to the SFT dataset — generation is probed on these
HOLDOUT_TOPICS = ["the kraken", "the lighthouse"]


def sft_prompt(topic: str) -> str:
    return f"<|user|>\nTell me about {topic}.\n<|assistant|>\n"


def sft_completion(topic: str) -> str:
    return f"{SFT_PREFIX}{topic} be a fine thing to know about, arr!\n"


def build_sft_jsonl(path: Path | str, *, rows_per_topic: int = 12) -> bytes:
    """Instruction rows (``prompt``/``completion`` — loss counts completion
    tokens only, ``data/loader.py``). Returns the serialized bytes so the
    controller path can upload exactly what was written."""
    lines = []
    for r in range(rows_per_topic):
        for topic in _TOPICS:
            # vary the question frame so the learnable signal is the response
            # style, not one memorized byte sequence
            q = [
                f"<|user|>\nTell me about {topic}.\n<|assistant|>\n",
                f"<|user|>\nWhat do you know of {topic}?\n<|assistant|>\n",
                f"<|user|>\nDescribe {topic} for me.\n<|assistant|>\n",
            ][r % 3]
            lines.append(json.dumps(
                {"prompt": q, "completion": sft_completion(topic)}
            ))
    data = ("\n".join(lines) + "\n").encode()
    Path(path).write_bytes(data)
    return data


def _read_metrics_csv(path: Path) -> list[dict[str, float]]:
    with open(path) as f:
        return [
            {k: float(v) for k, v in row.items() if v != ""}
            for row in csv.DictReader(f)
        ]


def pretrain_base(
    work_dir: Path | str,
    *,
    steps: int = 600,
    corpus_bytes: int = 400_000,
    batch_size: int = 16,
    seq_len: int = 128,
    learning_rate: float = 1e-3,
    preset: str = "tiny-test",
    seed: int = 0,
) -> dict[str, Any]:
    """Pretrain ``preset`` on real English and export it as an HF checkpoint.

    Runs the in-process trainer in ``full`` mode (no adapters — this *builds*
    the base the fine-tune will consume) and exports with
    ``export_merged_checkpoint``, the same writer whose round-trip against
    ``transformers`` is covered by ``tests/test_hf_export.py``.
    """
    from .data.corpus import write_corpus_jsonl
    from .data.loader import jsonl_token_batches
    from .models.hf_export import export_merged_checkpoint
    from .models.llama import PRESETS
    from .train.trainer import TrainConfig, Trainer

    work = Path(work_dir)
    work.mkdir(parents=True, exist_ok=True)
    corpus_path = work / "corpus.jsonl"
    n_bytes = write_corpus_jsonl(corpus_path, corpus_bytes)

    cfg = PRESETS[preset]
    tcfg = TrainConfig(
        mode="full",
        learning_rate=learning_rate,
        warmup_steps=min(20, max(1, steps // 20)),
        total_steps=steps,
        batch_size=batch_size,
        seq_len=seq_len,
        seed=seed,
        log_every=1,
        checkpoint_every=steps,  # only the final state matters here
    )
    trainer = Trainer(cfg, tcfg)
    batches = jsonl_token_batches(
        str(corpus_path), batch_size=batch_size, seq_len=seq_len, seed=seed
    )
    art = work / "pretrain_artifacts"
    state = trainer.fit(batches, str(art), resume=False)
    rows = _read_metrics_csv(art / "metrics.csv")
    first_loss, final_loss = rows[0]["loss"], rows[-1]["loss"]

    host = trainer.state_to_host(state, fields=("trainable",))
    base_dir = work / "pretrained_base"
    export_merged_checkpoint(cfg, {"params": host["trainable"]}, base_dir)
    logger.info(
        "pretrained base: loss %.3f -> %.3f over %d steps (%d corpus bytes) -> %s",
        first_loss, final_loss, steps, n_bytes, base_dir,
    )
    return {
        "base_dir": str(base_dir),
        "corpus_bytes": n_bytes,
        "pretrain_steps": steps,
        "pretrain_first_loss": first_loss,
        "pretrain_final_loss": final_loss,
    }


def _generate_text(trainer, state, prompt: str, max_new_tokens: int) -> str:
    """Greedy byte-level generation with the trainer's assembled variables."""
    import jax.numpy as jnp
    import numpy as np

    from .models.generate import cached_generate

    ids = list(prompt.encode())
    variables = trainer._assemble(state.frozen, state.trainable)
    out = cached_generate(
        trainer.model, variables, jnp.asarray([ids], jnp.int32),
        max_new_tokens=max_new_tokens,
    )
    new = np.asarray(out)[0, len(ids):].tolist()
    return bytes(i for i in new if 0 <= i < 256).decode("utf-8", errors="replace")


async def _run_controller_job(
    work: Path,
    base_dir: str,
    sft_bytes: bytes,
    *,
    steps: int,
    batch_size: int,
    seq_len: int,
    lora_rank: int,
    learning_rate: float,
    deadline_s: float,
) -> dict[str, Any]:
    """Submit the LoRA job through the real control plane (task_builder →
    local backend subprocess → monitor) and return its metrics + a local copy
    of the synced artifacts."""
    from .controller.backends.local import LocalProcessBackend
    from .controller.datasets import upload_dataset_bytes
    from .controller.devices import DeviceCatalog, DeviceFlavor, FlavorQuota
    from .controller.monitor import JobMonitor
    from .controller.objectstore import LocalObjectStore
    from .controller.schemas import DatabaseStatus, JobInput
    from .controller.statestore import StateStore
    from .controller.task_builder import DatasetInput, task_builder

    # bind the per-run base dir; no new annotations, so the inherited
    # pydantic fields resolve in this module's globals
    class _BoundFidelityLoRA(FidelityLoRA):
        pretrained_weights_dir = base_dir

    state = StateStore(work / "state")
    store = LocalObjectStore(work / "objects")
    catalog = DeviceCatalog(
        flavors=[DeviceFlavor(name="chip-1", generation="cpu", hosts=1,
                              chips_per_host=1, runtime="cpu", queue="q")],
        quotas=[FlavorQuota(flavor="chip-1", nominal_chips=1)],
        default_flavor="chip-1",
    )
    backend = LocalProcessBackend(
        work / "sandboxes", store, catalog, sync_interval_s=0.5
    )
    monitor = JobMonitor(state, store, backend, interval_s=0.1)
    await state.connect()
    try:
        ds = await upload_dataset_bytes(
            store, state, user_id="fidelity", filename="sft.jsonl",
            data=sft_bytes, bucket="datasets",
        )
        spec = _BoundFidelityLoRA(training_arguments=FidelityArguments(
            learning_rate=learning_rate, total_steps=steps,
            warmup_steps=max(1, steps // 20), batch_size=batch_size,
            seq_len=seq_len, lora_rank=lora_rank, log_every=1,
        ))
        job = JobInput(job_id="fidelity-1", user_id="fidelity",
                       model_name=spec.model_name, device="chip-1", arguments={})
        await task_builder(
            job, spec, DatasetInput(dataset_id=ds.dataset_id),
            state=state, store=store, backend=backend, catalog=catalog,
            datasets_bucket="datasets", artifacts_bucket="artifacts",
        )
        loop = asyncio.get_event_loop()
        deadline = loop.time() + deadline_s
        while True:
            await monitor.tick()
            rec = await state.get_job("fidelity-1")
            if rec.status.is_final:
                break
            if loop.time() > deadline:
                raise TimeoutError(f"fidelity job not final in {deadline_s}s: {rec}")
            await asyncio.sleep(0.3)
        if rec.status is not DatabaseStatus.SUCCEEDED:
            raise RuntimeError(f"fidelity job failed: {rec}")

        metrics = await state.get_metrics("fidelity-1")
        # product-path artifacts: pull the synced tree back out of the object
        # store, exactly what a user's serving pipeline would fetch
        local = work / "fetched_artifacts"
        for entry in await store.list_prefix(rec.artifacts_uri):
            rel = entry["uri"][len(rec.artifacts_uri) + 1:]
            await store.get_file(entry["uri"], local / rel)
        return {"records": metrics.records, "artifacts_dir": str(local)}
    finally:
        await backend.close()
        await state.close()


def run_proof(
    work_dir: Path | str,
    *,
    pretrain_steps: int = 600,
    corpus_bytes: int = 400_000,
    sft_steps: int = 120,
    batch_size: int = 16,
    seq_len: int = 128,
    lora_rank: int = 8,
    sft_learning_rate: float = 3e-3,
    max_new_tokens: int = 24,
    job_deadline_s: float = 600.0,
    base: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """The whole proof; returns (and writes) the fidelity record.

    Pass ``base`` (a previous :func:`pretrain_base` result) to reuse an
    already-built base across runs.
    """
    from .models.llama import PRESETS
    from .models.lora import LoRAConfig
    from .train.trainer import TrainConfig, Trainer

    work = Path(work_dir)
    work.mkdir(parents=True, exist_ok=True)
    if base is None:
        base = pretrain_base(
            work / "base", steps=pretrain_steps, corpus_bytes=corpus_bytes,
            batch_size=batch_size, seq_len=seq_len,
        )

    sft_path = work / "sft.jsonl"
    sft_bytes = build_sft_jsonl(sft_path)
    probe_prompt = sft_prompt(HOLDOUT_TOPICS[0])

    # ---- reference losses + "before" generation (in-process LoRA trainer:
    # fresh adapters have B=0, so this IS the base's behavior) --------------
    cfg = PRESETS["tiny-test"].replace(lora=LoRAConfig(rank=lora_rank))
    eval_tcfg = TrainConfig(
        mode="lora", batch_size=batch_size, seq_len=seq_len, eval_steps=4,
    )
    trainer = Trainer(cfg, eval_tcfg)

    from .data.loader import jsonl_token_batches

    def eval_loss(state) -> float:
        it = jsonl_token_batches(
            str(sft_path), batch_size=batch_size, seq_len=seq_len, seed=7
        )
        return trainer.evaluate(state, it)["eval_loss"]

    state = trainer.init_state()
    random_init_loss = eval_loss(state)
    state = trainer.load_pretrained(state, base["base_dir"])
    base_sft_loss = eval_loss(state)
    before_text = _generate_text(trainer, state, probe_prompt, max_new_tokens)

    # ---- the product path -------------------------------------------------
    job = asyncio.run(_run_controller_job(
        work, base["base_dir"], sft_bytes,
        steps=sft_steps, batch_size=batch_size, seq_len=seq_len,
        lora_rank=lora_rank, learning_rate=sft_learning_rate,
        deadline_s=job_deadline_s,
    ))
    records = job["records"]
    step0_loss = records[0]["loss"]
    final_loss = records[-1]["loss"]

    # ---- "after" generation from the job's own artifacts (generate_cli —
    # the operator surface) -------------------------------------------------
    from .models.generate_cli import main as generate_main
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        generate_main([
            "--artifacts", job["artifacts_dir"],
            "--prompt", probe_prompt,
            "--max-new-tokens", str(max_new_tokens),
        ])
    after_text = json.loads(buf.getvalue())["text"]

    record = {
        "kind": "fidelity",
        **{k: v for k, v in base.items() if k != "base_dir"},
        "sft_steps": sft_steps,
        "lora_rank": lora_rank,
        "random_init_loss": random_init_loss,
        "base_step0_loss": step0_loss,
        "base_eval_loss": base_sft_loss,
        "final_loss": final_loss,
        "probe_prompt": probe_prompt,
        "before_generation": before_text,
        "after_generation": after_text,
        "checks": {
            "base_transfers": step0_loss < 0.75 * random_init_loss,
            "finetune_learns": final_loss < 0.75 * step0_loss,
            "style_acquired": after_text.startswith(SFT_PREFIX)
                              and not before_text.startswith(SFT_PREFIX),
        },
    }
    record["passed"] = all(record["checks"].values())
    out = Path(job["artifacts_dir"]) / "fidelity_record.json"
    out.write_text(json.dumps(record, indent=2))
    record["record_path"] = str(out)
    logger.info(
        "fidelity: random %.3f -> base step0 %.3f -> final %.3f; "
        "after starts with %r: %s",
        random_init_loss, step0_loss, final_loss, SFT_PREFIX, record["passed"],
    )
    return record
