"""Loss functions. Next-token cross-entropy with a loss mask, f32 throughout."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def next_token_loss(
    logits: jax.Array, tokens: jax.Array, loss_mask: jax.Array | None = None
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Shifted cross-entropy.

    logits: (B, S, V) f32; tokens: (B, S) int; loss_mask: (B, S) — 1 where the
    *target* token counts (e.g. completion tokens in SFT).
    """
    targets = tokens[:, 1:]
    logits = logits[:, :-1].astype(jnp.float32)
    if loss_mask is None:
        mask = jnp.ones_like(targets, jnp.float32)
    else:
        mask = loss_mask[:, 1:].astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / denom
    acc = ((logits.argmax(-1) == targets) * mask).sum() / denom
    return loss, {"loss": loss, "accuracy": acc, "target_tokens": mask.sum()}
