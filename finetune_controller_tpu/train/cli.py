"""Training entrypoint: ``python -m finetune_controller_tpu.train.cli --spec job.json``.

This is the process the control plane launches (locally as a subprocess, or
on-cluster as the container command of every TPU worker pod).  The JSON spec
is the contract between the planes — the deployer renders it, this module
consumes it.  On completion it touches ``done.txt`` in the artifacts dir, the
same completion signal the reference used to stop its S3-sync sidecar
(reference ``app/jobs/kubeflow/PyTorchJobDeployer.py:30-32``).

Spec schema (all sections optional except artifacts_dir):

    {
      "job_id": "...",
      "model":    {"preset": "tiny-test", "overrides": {...}, "lora": {"rank": 8}},
      "training": {... TrainConfig fields ...},
      "mesh":     {"dp": 1, "fsdp": -1, "tp": 1, "sp": 1, "ep": 1, "pp": 1},
      "dataset":  {"path": "...", "tokenizer_file": null, "eval_path": "..."}
                  | {"synthetic": {"task": "increment"}},
      "artifacts_dir": "/data/artifacts"
    }

With ``training.eval_every > 0`` a held-out stream is evaluated on that
cadence: ``dataset.eval_path`` when given, otherwise a disjoint synthetic
stream (offset seed), and eval_loss/eval_accuracy columns join metrics.csv.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import sys

logger = logging.getLogger(__name__)


def build_model_config(spec: dict):
    from ..models.llama import PRESETS
    from ..models.lora import LoRAConfig
    from ..models.multimodal import MM_PRESETS

    model_spec = spec.get("model", {})
    preset = model_spec.get("preset", "tiny-test")
    if preset in PRESETS:
        cfg = PRESETS[preset]
    elif preset in MM_PRESETS:
        cfg = MM_PRESETS[preset]
    else:
        raise ValueError(
            f"unknown model preset {preset!r}; have "
            f"{sorted(PRESETS) + sorted(MM_PRESETS)}"
        )
    overrides = dict(model_spec.get("overrides", {}))
    if overrides:
        cfg = cfg.replace(**overrides)
    lora_spec = model_spec.get("lora")
    if lora_spec is not None:
        cfg = cfg.replace(lora=LoRAConfig(**lora_spec))
    return cfg


def build_train_config(spec: dict):
    from .trainer import TrainConfig

    fields = {f.name for f in dataclasses.fields(TrainConfig)}
    raw = dict(spec.get("training", {}))
    unknown = set(raw) - fields
    if unknown:
        raise ValueError(f"unknown training fields: {sorted(unknown)}")
    return TrainConfig(**raw)


def build_mesh(spec: dict):
    from ..parallel.mesh import MeshSpec

    return MeshSpec(**spec.get("mesh", {})).build()


def build_batches(
    spec: dict, model_cfg, train_cfg, local_batch_size: int,
    shard_index: int, shard_count: int, split: str = "train",
):
    from ..data.loader import jsonl_token_batches
    from ..data.synthetic import synthetic_batches

    ds = spec.get("dataset", {})
    path = ds.get("eval_path") if split == "eval" else ds.get("path")
    if train_cfg.task in ("dpo", "rlhf", "reward"):
        # preference-pair streams (data/preference.py): chosen/rejected
        # token+mask leaves instead of the SFT tokens/loss_mask pair
        # (the reward task trains its Bradley–Terry head on this same path)
        from ..data.preference import (
            preference_jsonl_batches,
            synthetic_preference_batches,
        )

        if path:
            return preference_jsonl_batches(
                path,
                batch_size=local_batch_size,
                seq_len=train_cfg.seq_len,
                tokenizer_file=ds.get("tokenizer_file"),
                seed=train_cfg.seed,
                shard_index=shard_index,
                shard_count=shard_count,
            )
        if split == "eval" and ds.get("path"):
            # real preference data but no eval split configured: nothing held
            # out — run_job turns this into the explicit 'no eval split'
            # error rather than silently evaluating on synthetic pairs
            return None
        # eval holds out a disjoint seed region, like the SFT synthetic path
        seed = train_cfg.seed + shard_index + (
            100_003 if split == "eval" else 0
        )
        return synthetic_preference_batches(
            batch_size=local_batch_size,
            seq_len=train_cfg.seq_len,
            vocab_size=model_cfg.vocab_size,
            seed=seed,
        )
    if path and model_cfg.image_size:
        # image-bearing rows: one sample per row, pixels resized to the
        # model's vision tower (data/mm_loader.py)
        from ..data.mm_loader import mm_jsonl_batches

        return mm_jsonl_batches(
            path,
            batch_size=local_batch_size,
            seq_len=train_cfg.seq_len,
            image_size=model_cfg.image_size,
            tokenizer_file=ds.get("tokenizer_file"),
            seed=train_cfg.seed,
            shard_index=shard_index,
            shard_count=shard_count,
            normalize=ds.get("image_normalize", "clip"),
        )
    if path:
        return jsonl_token_batches(
            path,
            batch_size=local_batch_size,
            seq_len=train_cfg.seq_len,
            tokenizer_file=ds.get("tokenizer_file"),
            seed=train_cfg.seed,
            shard_index=shard_index,
            shard_count=shard_count,
        )
    if split == "eval" and ds.get("path"):
        # real train data but no eval split configured: nothing held out
        return None
    synth = ds.get("synthetic", {})
    # multimodal configs get pixels sized to their vision tower automatically
    image_size = model_cfg.image_size
    # the eval stream draws from a disjoint region of the generator's seed
    # space so held-out rows never coincide with training rows
    seed = train_cfg.seed + shard_index + (100_003 if split == "eval" else 0)
    return synthetic_batches(
        batch_size=local_batch_size,
        seq_len=train_cfg.seq_len,
        vocab_size=model_cfg.vocab_size,
        task=synth.get("task", "brightness" if image_size else "increment"),
        seed=seed,
        image_size=image_size,
    )


def run_job(spec: dict) -> None:
    from ..parallel.distributed import maybe_initialize_distributed, is_rank_zero
    from .trainer import Trainer

    # A job spec's ``build_trainer_spec`` stows user arguments it did not map
    # into trainer knobs under ``extra_arguments``. Silently ignoring them
    # would mean a user's hyperparameter never reaches the run — fail loudly
    # so plugin spec authors consume every argument they declare.
    extra = spec.get("extra_arguments")
    if extra:
        raise ValueError(
            f"unconsumed extra_arguments {sorted(extra)}: the job spec must map "
            "every user argument into the trainer spec (override "
            "build_trainer_spec in the spec class)"
        )

    artifacts_dir = spec["artifacts_dir"]
    os.makedirs(artifacts_dir, exist_ok=True)

    import jax

    from ..platform import assert_platform_env

    assert_platform_env()
    maybe_initialize_distributed()

    model_cfg = build_model_config(spec)
    train_cfg = build_train_config(spec)
    mesh = build_mesh(spec)
    logger.info(
        "job %s: %s params=%.1fM mesh=%s devices=%d",
        spec.get("job_id", "?"), spec.get("model", {}).get("preset"),
        model_cfg.param_count() / 1e6, dict(zip(mesh.axis_names, mesh.devices.shape)),
        jax.device_count(),
    )
    if is_rank_zero():
        with open(os.path.join(artifacts_dir, "resolved_config.json"), "w") as f:
            json.dump(spec, f, indent=2, default=str)

    if train_cfg.task == "sft":
        trainer = Trainer(model_cfg, train_cfg, mesh=mesh)
    elif train_cfg.task in ("dpo", "rlhf"):
        from ..prefs.dpo_trainer import DPOTrainer

        # in-process rlhf forces prefetch=0 inside DPOTrainer (the actor
        # runs inline); rollout_workers > 0 keeps prefetch + async commits
        trainer = DPOTrainer(model_cfg, train_cfg, mesh=mesh)
    elif train_cfg.task == "reward":
        from ..prefs.reward_trainer import RewardModelTrainer

        trainer = RewardModelTrainer(model_cfg, train_cfg, mesh=mesh)
    else:
        raise ValueError(
            f"unknown training task {train_cfg.task!r}; one of "
            "['sft', 'dpo', 'rlhf', 'reward']"
        )
    plane = None
    if train_cfg.task == "rlhf":
        from ..prefs.learner import RolloutConfig, build_rlhf_loop

        rollout_spec = dict(spec.get("rollout", {}))
        if train_cfg.rollout_workers > 0:
            # disaggregated data plane: remote actor worker processes
            # stream pairs in over the rollout RPCs (prefs/rollout_plane.py)
            from ..prefs.rollout_plane import build_remote_rlhf_loop

            batches, plane, _buffer = build_remote_rlhf_loop(
                trainer, artifacts_dir,
                rollout=RolloutConfig(**rollout_spec),
                pretrained_dir=spec.get("model", {}).get("weights_dir"),
                model_spec=spec.get("model", {}),
            )
        else:
            batches, actor, _buffer = build_rlhf_loop(
                trainer, artifacts_dir,
                rollout=RolloutConfig(**rollout_spec),
                pretrained_dir=spec.get("model", {}).get("weights_dir"),
            )
    else:
        batches = build_batches(
            spec, model_cfg, train_cfg,
            local_batch_size=trainer.local_batch_size,
            shard_index=jax.process_index(), shard_count=jax.process_count(),
        )
    eval_batches = None
    if train_cfg.eval_every > 0:
        eval_batches = build_batches(
            spec, model_cfg, train_cfg,
            local_batch_size=trainer.local_batch_size,
            shard_index=jax.process_index(), shard_count=jax.process_count(),
            split="eval",
        )
        if eval_batches is None:
            raise ValueError(
                "training.eval_every > 0 but the dataset has no eval split: "
                "set dataset.eval_path (or use a synthetic dataset, which "
                "holds out a disjoint stream automatically)"
            )
    try:
        state = trainer.fit(
            batches, artifacts_dir,
            pretrained_dir=spec.get("model", {}).get("weights_dir"),
            eval_batches=eval_batches,
        )
        # deployable artifacts: PEFT adapter (+ merged checkpoint if
        # configured; the base dir enables the multi-host merge's host-side
        # reload)
        trainer.export_artifacts(
            state, artifacts_dir,
            pretrained_dir=spec.get("model", {}).get("weights_dir"),
        )
    finally:
        if plane is not None:
            # remote actor workers are child processes: reap them even when
            # fit raises, or a failed learner leaks a decoding fleet
            plane.close()

    if is_rank_zero():
        with open(os.path.join(artifacts_dir, "done.txt"), "w") as f:
            f.write("done\n")
    logger.info("job %s finished", spec.get("job_id", "?"))


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="ftc-train")
    parser.add_argument("--spec", required=True, help="path to the job-spec JSON")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        stream=sys.stdout,
        force=True,
    )
    with open(args.spec) as f:
        spec = json.load(f)
    run_job(spec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
