"""The trainer: sharded init, jitted train step, fit loop with metrics,
checkpointing and preemption handling.

Everything device-side happens inside two jitted functions (``_init_fn`` and
``_step_fn``) whose in/out shardings come from ``parallel.sharding`` rules, so
the same code runs single-chip, on a CPU test mesh, or across a v5e slice —
only the MeshSpec changes.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import sys
import time
from functools import partial
from typing import Any, Callable, Iterable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from flax.core import FrozenDict
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import LlamaConfig, LlamaForCausalLM
from ..parallel.mesh import MeshSpec
from ..parallel.sharding import (
    LLAMA_RULES,
    PartitionRules,
    batch_sharding,
    sharding_for_tree,
)
from .checkpoint import CheckpointManager, reshard
from .losses import next_token_loss
from .metrics import MetricsWriter
from .optimizer import build_optimizer

logger = logging.getLogger(__name__)


@struct.dataclass
class TrainState:
    step: jax.Array
    frozen: Any        # non-trainable variables ({} in full fine-tune mode)
    trainable: Any     # differentiated + optimized tree
    opt_state: Any


@dataclasses.dataclass
class TrainConfig:
    mode: str = "lora"            # "lora" | "full"
    #: training objective: "sft" (next-token cross-entropy, this class) |
    #: "dpo" (preference pairs through ``prefs.dpo_trainer.DPOTrainer``) |
    #: "rlhf" (actor/learner loop, ``prefs/learner.py`` — DPO over on-policy
    #: rollouts).  ``train/cli.py`` selects the trainer class from this.
    task: str = "sft"
    #: DPO inverse-temperature (KL strength) — used by the dpo/rlhf tasks only
    dpo_beta: float = 0.1
    #: rlhf only: number of REMOTE rollout actor processes (0 = the
    #: in-process actor/learner gang).  > 0 selects the disaggregated data
    #: plane (``prefs/rollout_plane.py``): actors run as serve-fleet tenants
    #: in their own worker processes, stream scored pairs over the rollout
    #: RPCs, and receive policy rollovers as pushed adapter deltas — so the
    #: learner keeps async checkpoint commits and prefetch
    #: (docs/preference.md §Disaggregated rollouts).
    rollout_workers: int = 0
    learning_rate: float = 2e-4
    warmup_steps: int = 10
    total_steps: int = 100
    schedule: str = "cosine"
    weight_decay: float = 0.0
    clip_norm: float = 1.0
    batch_size: int = 8           # global
    seq_len: int = 512
    seed: int = 0
    log_every: int = 10
    checkpoint_every: int = 100
    keep_checkpoints: int = 3
    #: capture a jax.profiler trace for N steps (0 = off); the trace lands in
    #: {artifacts_dir}/profile and ships with the artifacts (SURVEY.md §5.1 —
    #: the reference has no tracing at all)
    profile_steps: int = 0
    #: first profiled step (default skips the compile step)
    profile_start_step: int = 2
    #: GPipe microbatches when the mesh has pp > 1 (0 = 2·pp, a reasonable
    #: bubble/memory tradeoff); must divide the per-dp-shard batch
    pp_microbatches: int = 0
    #: also write a merged full HF checkpoint at the end of a LoRA run
    #: (adapter-only PEFT export always happens for text LoRA runs)
    export_merged: bool = False
    #: storage dtype for the FROZEN base params in lora mode (e.g. "bfloat16"
    #: halves their HBM footprint and per-step weight traffic; the compute
    #: path already runs bf16 so only the storage rounding changes). None
    #: keeps the model's param_dtype. Int4 kernels and their bf16 scales
    #: (models/quant.py) pass through untouched.
    frozen_dtype: str | None = None
    #: run a held-out evaluation every N steps (0 = off); requires an eval
    #: batch stream passed to ``fit(eval_batches=...)``
    eval_every: int = 0
    #: batches averaged per evaluation pass
    eval_steps: int = 8
    #: split each optimizer step's global batch into N sequential
    #: microbatches (``lax.scan`` inside the jitted step), accumulating
    #: gradients — the standard dial for batch sizes whose activations don't
    #: fit HBM. batch_size must divide by it; numerics match the unsplit
    #: step up to float reduction order (tested).
    grad_accum_steps: int = 1
    #: host input-pipeline prefetch depth (``data/prefetch.py``): a
    #: background thread builds up to N batches ahead while the device runs
    #: the current step, preserving batch order exactly (loss trajectories
    #: are bit-identical to the synchronous path — tested). 0 is the escape
    #: hatch back to the synchronous on-thread build.
    prefetch: int = 2
    #: also ``device_put`` the NEXT batch with the training-step sharding on
    #: the prefetch thread (double-buffered host→HBM copy that overlaps the
    #: running step). Ignored when ``prefetch == 0``.
    prefetch_to_device: bool = True
    #: recompilation guard (``analysis/recompile_guard.py``): budget of
    #: distinct jit signatures the step/eval functions may compile over the
    #: whole run (0 = off). A healthy run compiles once per batch structure;
    #: a per-step-varying shape (or static Python value) blows straight
    #: past this.
    recompile_budget: int = 0
    #: what to do past the budget: "warn" (log once) or "raise"
    recompile_action: str = "warn"
    #: transfer guard (``analysis/transfer_guard.py``): wrap the jitted
    #: step's dispatch window so any device<->host transfer inside it —
    #: an implicit host->device copy of a stray numpy leaf, a leftover
    #: ``jax.device_get`` — fails loudly instead of silently serializing
    #: every step.  "raise" | "warn" | "off"; the empty default inherits
    #: ``FTC_TRANSFER_GUARD`` from the env (off when unset).  bench.py
    #: arms "raise" inside its timed windows.
    transfer_guard: str = ""
    #: shard audit (``analysis/shard_audit.py``): at checkpoint/restore
    #: boundaries, assert every live state leaf's ``.sharding`` still equals
    #: the rule table's expected ``NamedSharding`` — catching the silent
    #: full replication an elastic restore or resharding path can introduce
    #: (every later step then pays a GSPMD reshard that profiles as "slow",
    #: never as an error).  "raise" | "warn" | "off"; the empty default
    #: inherits ``FTC_SHARD_AUDIT`` from the env (off when unset).
    #: bench.py arms "raise" so a mis-sharded timed run aborts.
    shard_audit: str = ""
    #: liveness heartbeat cadence (``resilience/heartbeat.py``): rank 0
    #: writes ``heartbeat.json`` (step + wall clock) into the artifacts dir
    #: at most every N seconds; the artifact sync ships it and the monitor's
    #: lease check uses it to catch silently-stuck jobs. 0 disables.
    heartbeat_interval_s: float = 10.0
    #: observability (docs/observability.md): rank 0 records lifecycle
    #: events (``events.jsonl``), spans (``trace/trainer.jsonl``), and the
    #: step-phase split (``phase_*_ms`` CSV columns).  ``FTC_TRACE=0`` in the
    #: env is the operator kill switch; overhead is gated <2% of step time
    #: by ``BENCH_MODE=obs``.
    trace: bool = True


class PreemptionGuard:
    """SIGTERM → save-and-exit flag (TPU spot/maintenance preemption)."""

    def __init__(self):
        self.requested = False

    def install(self) -> None:
        def handler(signum, frame):
            logger.warning("preemption signal %s received; will checkpoint and exit", signum)
            self.requested = True

        signal.signal(signal.SIGTERM, handler)


def _adapt_loaded_params(loaded: Any, target: Any, *, quant_block: int) -> Any:
    """Recursively fit a converted HF tree onto the initialised param tree:
    shape/dtype-check every leaf and quantize kernels where the target stores
    int4 (QLoRA base weights). Leaves stay HOST-side numpy throughout — the
    caller reshards onto the mesh, so the unsharded model never has to fit a
    single device."""
    if not isinstance(target, dict):
        arr = np.asarray(loaded)
        if tuple(arr.shape) != tuple(target.shape):
            raise ValueError(
                f"pretrained tensor shape {tuple(arr.shape)} != model "
                f"{tuple(target.shape)} — config/checkpoint mismatch"
            )
        return arr.astype(target.dtype)
    out: dict[str, Any] = {}
    loaded = dict(loaded)
    # quantize every kernel the target stores int4: dense projections are
    # "kernel" -> "kernel_packed"/"kernel_scales"; stacked MoE experts are
    # "experts_gate" -> "experts_gate_packed"/... (models/moe.py). Leading
    # axes (scan layers, the expert axis) are vmapped generically.
    for pk in [k for k in target if k.endswith("_packed")]:
        stem = pk[: -len("_packed")]
        if stem not in loaded:
            continue  # surfaces as a missing-key error below
        from ..models.quant import quantize_int4

        kernel = np.asarray(loaded.pop(stem), np.float32)
        packed_t = target[pk]
        want = tuple(packed_t.shape[:-2]) + (
            packed_t.shape[-2] * 2, packed_t.shape[-1],
        )
        if tuple(kernel.shape) != want:
            raise ValueError(
                f"pretrained tensor {stem!r} shape {tuple(kernel.shape)} != "
                f"model {want} (pre-quantization) — config/checkpoint mismatch"
            )
        quant = partial(quantize_int4, block_size=quant_block)
        # quantize on the CPU backend when available so a model bigger than
        # one accelerator's HBM can still be converted (a tpu-only
        # jax_platforms pin has no cpu backend — use the default device then)
        try:
            ctx = jax.default_device(jax.devices("cpu")[0])
        except RuntimeError:
            import contextlib

            ctx = contextlib.nullcontext()
        with ctx:
            lead = kernel.shape[:-2]
            flat = kernel.reshape((-1,) + kernel.shape[-2:])
            packed, scales = jax.vmap(quant)(flat)
        out[pk] = np.asarray(packed).reshape(lead + packed.shape[1:])
        out[f"{stem}_scales"] = np.asarray(scales).reshape(
            lead + scales.shape[1:]
        )
    for key, tv in target.items():
        if key in out:
            continue
        if key not in loaded:
            raise ValueError(f"pretrained checkpoint missing {key!r}")
        out[key] = _adapt_loaded_params(loaded[key], tv, quant_block=quant_block)
    return out


class Trainer:
    def __init__(
        self,
        model_cfg: LlamaConfig,
        train_cfg: TrainConfig,
        mesh: Mesh | None = None,
        rules: PartitionRules = LLAMA_RULES,
    ):
        self.cfg = train_cfg
        self.mesh = mesh if mesh is not None else MeshSpec(fsdp=1).build(jax.devices()[:1])
        if (
            self.mesh.shape.get("sp", 1) > 1
            and model_cfg.attention_impl not in ("ring", "ulysses")
        ):
            # an active sp axis means the sequence is sharded: attention must
            # go through an SP-aware path (ring or ulysses) or XLA would
            # all-gather S every layer
            logger.info("sp=%d mesh axis active: attention_impl -> ring",
                        self.mesh.shape["sp"])
            model_cfg = model_cfg.replace(attention_impl="ring")
        if train_cfg.seq_len > getattr(model_cfg, "max_seq_len", train_cfg.seq_len):
            # RoPE extrapolates silently but badly past the trained range,
            # and HF exports carry max_position_embeddings = max_seq_len —
            # downstream inference would truncate what was trained here
            logger.warning(
                "seq_len %d exceeds the model's max_seq_len %d: RoPE "
                "positions run beyond the preset's trained range and the "
                "exported max_position_embeddings stays %d — use a "
                "long-context preset (e.g. mistral-7b-32k)",
                train_cfg.seq_len, model_cfg.max_seq_len, model_cfg.max_seq_len,
            )
        self.model_cfg = model_cfg
        self.rules = rules
        # Model family is selected by config type (the duck-type surface the
        # multimodal config mirrors) — BASELINE #5 trains through the same
        # trainer as the text families.
        from ..models.multimodal import LlavaConfig, LlavaForCausalLM

        self._is_multimodal = isinstance(model_cfg, LlavaConfig)
        if self._is_multimodal:
            self.model = LlavaForCausalLM(model_cfg)
        else:
            self.model = LlamaForCausalLM(model_cfg)

        self._pp = self.mesh.shape.get("pp", 1)
        if self._pp > 1:
            from ..parallel.pipeline import validate_pp_mesh

            validate_pp_mesh(self.mesh)
            if self._is_multimodal or model_cfg.n_experts:
                raise ValueError(
                    "pipeline parallelism currently supports dense text models"
                )
            if not model_cfg.scan_layers:
                raise ValueError("pp > 1 requires scan_layers=True (stacked params)")
            if model_cfg.n_layers % self._pp:
                raise ValueError(
                    f"n_layers {model_cfg.n_layers} not divisible by pp {self._pp}"
                )
            if model_cfg.lora.rank > 0 and model_cfg.lora.dropout > 0:
                raise ValueError("pp > 1 does not support LoRA dropout yet")
        if train_cfg.grad_accum_steps > 1:
            if train_cfg.batch_size % train_cfg.grad_accum_steps:
                raise ValueError(
                    f"batch_size {train_cfg.batch_size} not divisible by "
                    f"grad_accum_steps {train_cfg.grad_accum_steps}"
                )
            batch_shards = self.mesh.shape.get("dp", 1) * self.mesh.shape.get("fsdp", 1)
            micro = train_cfg.batch_size // train_cfg.grad_accum_steps
            if micro % batch_shards:
                raise ValueError(
                    f"microbatch size {micro} (batch_size/grad_accum_steps) "
                    f"not divisible over the {batch_shards}-way batch sharding"
                )
        self.tx, self.sched = build_optimizer(
            learning_rate=train_cfg.learning_rate,
            warmup_steps=train_cfg.warmup_steps,
            total_steps=train_cfg.total_steps,
            schedule=train_cfg.schedule,
            weight_decay=train_cfg.weight_decay,
            clip_norm=train_cfg.clip_norm,
        )
        self._state_shardings = None
        self._init_jit = None
        self._warned_eval_unsplit = False
        #: commit EVERY checkpoint synchronously (not just the final one).
        #: Async saves are the throughput default; the rlhf learner flips
        #: this so the actor's next rollout round deterministically sees the
        #: just-committed step (prefs/learner.py)
        self._blocking_checkpoints = False
        #: stamped into every checkpoint manifest; elastic restore refuses a
        #: checkpoint written under a different rule table (train/elastic.py)
        self._rule_fingerprint = rules.fingerprint()
        self._build()

    # ---- construction ----------------------------------------------------

    # params trained alongside LoRA adapters on multimodal models: the LLaVA
    # recipe always trains the vision→text projector, adapters or not
    _MM_TRAINED_PARAMS = ("projector_fc1", "projector_fc2")

    def _split(self, variables: FrozenDict) -> tuple[Any, Any]:
        """(frozen, trainable) per the training mode."""
        variables = dict(variables)
        # drop the init-time sown aux collection: re-feeding it to apply would
        # make flax append to the stale tuple and double-count the MoE aux loss
        variables.pop("moe_aux", None)
        if self.cfg.mode == "lora":
            if "lora" not in variables:
                raise ValueError("mode='lora' but the model has no LoRA params; set lora.rank > 0")
            lora = variables.pop("lora")
            if not self._is_multimodal:
                return variables, lora
            params = dict(variables["params"])
            projector = {
                k: params.pop(k) for k in self._MM_TRAINED_PARAMS if k in params
            }
            variables["params"] = params
            return variables, {"lora": lora, "projector": projector}
        if self.cfg.mode == "full":
            trainable = variables.pop("params")
            return variables, trainable
        raise ValueError(f"unknown training mode {self.cfg.mode!r}")

    def _assemble(self, frozen: Any, trainable: Any) -> dict:
        out = dict(frozen)
        if self.cfg.mode != "lora":
            out["params"] = trainable
            return out
        if self._is_multimodal:
            out["lora"] = trainable["lora"]
            out["params"] = {**dict(out["params"]), **trainable["projector"]}
        else:
            out["lora"] = trainable
        return out

    def _raw_init(self, rng: jax.Array) -> TrainState:
        import math

        # dummy init batch must be divisible over the batch and sp axes (ring
        # attention shards the sequence even at init trace time)
        b0 = self.mesh.shape.get("dp", 1) * self.mesh.shape.get("fsdp", 1)
        s0 = math.lcm(8, self.mesh.shape.get("sp", 1))
        tokens = jnp.zeros((b0, s0), jnp.int32)
        if self._is_multimodal:
            size = self.model_cfg.vision.image_size
            pixels = jnp.zeros((b0, size, size, 3), jnp.float32)
            variables = self.model.init({"params": rng}, tokens, pixels)
        else:
            variables = self.model.init({"params": rng}, tokens)
        frozen, trainable = self._split(variables)
        frozen = self._cast_frozen(frozen)
        opt_state = self.tx.init(trainable)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            frozen=frozen,
            trainable=trainable,
            opt_state=opt_state,
        )

    def _cast_frozen(self, frozen: Any) -> Any:
        """Downcast float32 leaves of the frozen base to ``cfg.frozen_dtype``
        (lora mode only — full fine-tune keeps f32 master weights). Int4
        packed kernels and their scales pass through untouched (non-f32
        dtypes; the ``scales`` name guard is belt-and-braces for future
        f32-scaled quant formats)."""
        if not self.cfg.frozen_dtype or self.cfg.mode != "lora":
            return frozen
        dt = jnp.dtype(self.cfg.frozen_dtype)

        def cast(path, x):
            name = str(path[-1]) if path else ""
            if "scales" in name or x.dtype != jnp.float32:
                return x
            return x.astype(dt)

        return jax.tree_util.tree_map_with_path(cast, frozen)

    def _build(self) -> None:
        rng = jax.random.PRNGKey(self.cfg.seed)
        shapes = jax.eval_shape(self._raw_init, rng)
        self._state_shardings = sharding_for_tree(shapes, self.mesh, self.rules)
        self._batch_sharding = batch_sharding(self.mesh)
        from ..parallel.mesh import AxisNames as Ax

        self._pixel_sharding = NamedSharding(self.mesh, P(Ax.BATCH_AXES))
        self._init_jit = jax.jit(self._raw_init, out_shardings=self._state_shardings)
        # jitted steps are cached per batch structure (multimodal batches add
        # a rank-4 pixels leaf whose sharding differs from token arrays)
        self._step_jits: dict[tuple[str, ...], Any] = {}
        self._recompile_guard = None
        if self.cfg.recompile_budget > 0:
            from ..analysis.recompile_guard import RecompileGuard

            self._recompile_guard = RecompileGuard(
                self.cfg.recompile_budget,
                on_excess=self.cfg.recompile_action,
                name="trainer-recompile-guard",
            )
        self._transfer_guard = None
        mode = (self.cfg.transfer_guard or "").strip().lower()
        if mode in ("raise", "warn"):
            from ..analysis.transfer_guard import TransferGuard

            self._transfer_guard = TransferGuard(
                mode, name="trainer-transfer-guard"
            )
        elif mode == "":
            from ..analysis.transfer_guard import TransferGuard

            self._transfer_guard = TransferGuard.from_env(
                name="trainer-transfer-guard"
            )
        self._shard_auditor = None
        audit_mode = (self.cfg.shard_audit or "").strip().lower()
        if audit_mode in ("raise", "warn"):
            from ..analysis.shard_audit import ShardAuditor

            self._shard_auditor = ShardAuditor(
                audit_mode, name="trainer-shard-audit"
            )
        elif audit_mode == "":
            from ..analysis.shard_audit import ShardAuditor

            self._shard_auditor = ShardAuditor.from_env(
                name="trainer-shard-audit"
            )

    def _audit_state_sharding(self, state: Any, label: str) -> None:
        """Shard-audit trap (analysis/shard_audit.py): at the
        checkpoint/restore boundaries, every live state leaf must still
        carry the rule table's NamedSharding — the bug class this catches
        is silent replication after an elastic restore."""
        if self._shard_auditor is not None:
            self._shard_auditor.audit(
                state, self._state_shardings, label=label
            )

    def _batch_leaf_sharding(self, x: Any) -> NamedSharding:
        """Token-like (B, S) leaves shard batch+seq; higher-rank leaves (e.g.
        pixels (B, H, W, 3)) shard the batch dim only — the sequence axis of an
        image is not the token sequence the sp ring shards."""
        if getattr(x, "ndim", 2) == 2:
            return self._batch_sharding
        return self._pixel_sharding

    def _get_step_jit(self, batch: dict):
        key = tuple(sorted(batch))
        fn = self._step_jits.get(key)
        if fn is None:
            batch_sh = {k: self._batch_leaf_sharding(batch[k]) for k in batch}
            # Donating the state reuses its buffers for the output — the HBM
            # lever that lets big states fit on TPU.  On the CPU test backend
            # it buys nothing (host RAM, no HBM pressure) and, combined with
            # the persistent compilation cache, deserialized executables have
            # been observed mis-aliasing donated scalars under a long test
            # session (a resumed step counter reading back as garbage), so
            # CPU skips donation — numerics are identical either way.
            donate = (0,) if jax.default_backend() != "cpu" else ()
            fn = jax.jit(
                self._train_step,
                in_shardings=(self._state_shardings, batch_sh),
                out_shardings=(self._state_shardings, None),
                donate_argnums=donate,
            )
            if self._recompile_guard is not None:
                fn = self._recompile_guard.wrap(fn, label=f"step:{','.join(key)}")
            if self._transfer_guard is not None:
                # the guarded window is the DISPATCH only: _shard_batch has
                # already device_put the batch (explicitly — allowed), so a
                # steady-state step moves nothing across the boundary
                fn = self._transfer_guard.wrap(fn, label=f"step:{','.join(key)}")
            self._step_jits[key] = fn
        return fn

    # ---- device-side fns -------------------------------------------------

    @property
    def _use_dropout(self) -> bool:
        lora = self.model_cfg.lora
        return lora.rank > 0 and lora.dropout > 0.0

    def _loss_fn(self, trainable, frozen, batch, dropout_rng):
        variables = self._assemble(frozen, trainable)
        if self._pp > 1:
            # dropout_rng is intentionally unused here: the constructor
            # rejects pp>1 with LoRA dropout; if that guard is ever relaxed,
            # this branch must thread rngs through the pipeline too.
            assert not self._use_dropout, "pp path has no dropout support"
            from ..models.llama import pipelined_causal_lm_logits

            from ..parallel.pipeline import (
                bubble_fraction,
                default_pp_microbatches,
            )

            n_micro = self.cfg.pp_microbatches
            if not n_micro:
                local = batch["tokens"].shape[0] // (
                    self.mesh.shape.get("dp", 1) * self.mesh.shape.get("fsdp", 1)
                )
                n_micro = default_pp_microbatches(local, self._pp)

            # trace-time (runs once per compilation, not per step)
            logger.info(
                "GPipe schedule: %d microbatches over %d stages — bubble "
                "fraction %.1f%%", n_micro, self._pp,
                100 * bubble_fraction(n_micro, self._pp),
            )
            logits = pipelined_causal_lm_logits(
                self.model_cfg, variables, batch["tokens"],
                mesh=self.mesh, n_micro=n_micro,
                segment_ids=batch.get("segment_ids"),
            )
            return next_token_loss(logits, batch["tokens"], batch.get("loss_mask"))
        rngs = {"dropout": dropout_rng} if self._use_dropout else None
        apply_kw: dict[str, Any] = dict(
            segment_ids=batch.get("segment_ids"),
            deterministic=not self._use_dropout,
            rngs=rngs,
        )
        if self._is_multimodal:
            apply_kw["pixels"] = batch.get("pixels")
        if self.model_cfg.n_experts:
            logits, collections = self.model.apply(
                variables, batch["tokens"], mutable=("moe_aux",), **apply_kw
            )
            from ..models.moe import moe_aux_loss

            aux_penalty = self.model_cfg.router_aux_weight * moe_aux_loss(collections)
        else:
            logits = self.model.apply(variables, batch["tokens"], **apply_kw)
            aux_penalty = 0.0
        loss, metrics = next_token_loss(
            logits, batch["tokens"], batch.get("loss_mask")
        )
        if self.model_cfg.n_experts:
            metrics = dict(metrics, moe_aux=aux_penalty)
        return loss + aux_penalty, metrics

    def _train_step(self, state: TrainState, batch: dict):
        dropout_rng = jax.random.fold_in(jax.random.PRNGKey(self.cfg.seed), state.step)
        grad_fn = jax.value_and_grad(self._loss_fn, has_aux=True)
        accum = self.cfg.grad_accum_steps
        if accum > 1:
            # microbatch scan: rows stay sharded over the batch axes within
            # each microbatch; the accum axis is sequential. Grads/metrics
            # are averaged over microbatches — identical semantics to the
            # unsplit step (each microbatch's loss is already a per-token
            # mean, so equality is exact only for uniform token counts; SFT
            # masks make it the standard per-microbatch-mean approximation).
            def split(x):
                r = x.reshape((accum, x.shape[0] // accum) + x.shape[1:])
                # the scan (accum) axis must stay UNSHARDED — it is
                # sequential; rows keep their batch-axis sharding within
                # each microbatch
                spec = self._batch_leaf_sharding(x).spec
                return jax.lax.with_sharding_constraint(
                    r, NamedSharding(self.mesh, P(None, *spec))
                )

            micro = jax.tree.map(split, batch)

            def body(carry, mb):
                rng = jax.random.fold_in(dropout_rng, carry["i"])
                (_, aux), grads = grad_fn(state.trainable, state.frozen, mb, rng)
                acc = jax.tree.map(jnp.add, carry["grads"], grads)
                auxes = jax.tree.map(jnp.add, carry["aux"], aux)
                return {"grads": acc, "aux": auxes, "i": carry["i"] + 1}, None

            zero_grads = jax.tree.map(jnp.zeros_like, state.trainable)
            aux_shape = jax.eval_shape(
                lambda: grad_fn(
                    state.trainable, state.frozen,
                    jax.tree.map(lambda x: x[0], micro), dropout_rng,
                )[0][1]
            )
            zero_aux = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), aux_shape
            )
            carry, _ = jax.lax.scan(
                body,
                {"grads": zero_grads, "aux": zero_aux, "i": jnp.zeros((), jnp.int32)},
                micro,
            )
            inv = 1.0 / accum
            grads = jax.tree.map(lambda g: g * inv, carry["grads"])
            # means average over microbatches; counts keep their exact sum
            aux = {
                k: (v if k == "target_tokens" else v * inv)
                for k, v in carry["aux"].items()
            }
        else:
            (_, aux), grads = grad_fn(state.trainable, state.frozen, batch, dropout_rng)
        updates, opt_state = self.tx.update(grads, state.opt_state, state.trainable)
        trainable = optax.apply_updates(state.trainable, updates)
        metrics = {
            **aux,
            "grad_norm": optax.global_norm(grads),
            "learning_rate": self.sched(state.step),
        }
        new_state = state.replace(
            step=state.step + 1, trainable=trainable, opt_state=opt_state
        )
        return new_state, metrics

    def _eval_step(self, state: TrainState, batch: dict):
        """Forward-only loss/accuracy on a held-out batch (no grads, no
        state mutation — dropout off regardless of training mode)."""
        variables = self._assemble(state.frozen, state.trainable)
        apply_kw: dict[str, Any] = dict(
            segment_ids=batch.get("segment_ids"), deterministic=True,
        )
        if self._is_multimodal:
            apply_kw["pixels"] = batch.get("pixels")
        if self.model_cfg.n_experts:
            logits, _ = self.model.apply(
                variables, batch["tokens"], mutable=("moe_aux",), **apply_kw
            )
        else:
            logits = self.model.apply(variables, batch["tokens"], **apply_kw)
        _, metrics = next_token_loss(
            logits, batch["tokens"], batch.get("loss_mask")
        )
        return metrics

    def _get_eval_jit(self, batch: dict):
        key = ("eval",) + tuple(sorted(batch))
        fn = self._step_jits.get(key)
        if fn is None:
            batch_sh = {k: self._batch_leaf_sharding(batch[k]) for k in batch}
            fn = jax.jit(
                self._eval_step,
                in_shardings=(self._state_shardings, batch_sh),
                out_shardings=None,
            )
            if self._recompile_guard is not None:
                fn = self._recompile_guard.wrap(fn, label=f"eval:{','.join(key)}")
            self._step_jits[key] = fn
        return fn

    def evaluate(self, state: TrainState, eval_batches: Iterator[dict]) -> dict:
        """Average forward-only metrics over ``cfg.eval_steps`` batches."""
        from ..parallel.ring import ring_mesh

        sums: dict[str, float] = {}
        n = 0
        n_batches = max(1, self.cfg.eval_steps)
        input_s = 0.0  # host build + transfer time the eval pass waited on
        for _ in range(n_batches):
            t_in = time.perf_counter()
            host_batch = next(eval_batches)
            input_s += time.perf_counter() - t_in
            # grad accumulation exists because the full batch's activations
            # don't fit HBM — eval must microbatch the same way or it OOMs
            # at the first eval step of exactly those configs
            accum = self.cfg.grad_accum_steps
            rows = next(iter(host_batch.values())).shape[0]
            chunks = accum if accum > 1 and rows % accum == 0 else 1
            if accum > 1 and chunks == 1 and not self._warned_eval_unsplit:
                # per-host rows not divisible: the unsplit eval forward may
                # not fit HBM on exactly the configs accumulation targets
                self._warned_eval_unsplit = True
                logger.warning(
                    "eval batch rows (%d per host) not divisible by "
                    "grad_accum_steps (%d): evaluating UNSPLIT — if this "
                    "OOMs, make batch_size/process_count divisible by "
                    "grad_accum_steps", rows, accum,
                )
            for c in range(chunks):
                t_in = time.perf_counter()
                piece = {
                    k: v[c * (rows // chunks):(c + 1) * (rows // chunks)]
                    for k, v in host_batch.items()
                }
                batch = self._shard_batch(piece)
                input_s += time.perf_counter() - t_in
                fn = self._get_eval_jit(batch)
                with self.mesh, ring_mesh(self.mesh):
                    metrics = fn(state, batch)
                for k, v in metrics.items():
                    sums[k] = sums.get(k, 0.0) + float(v)
                n += 1
            hb = getattr(self, "_heartbeat", None)
            if hb is not None:
                # liveness through a long eval pass (the per-batch float()
                # above already synced the device, so this reads step cheaply)
                hb.beat(int(state.step))
        # target_tokens is a per-batch count — averaging it is meaningless,
        # and only declared columns survive the CSV header
        out = {
            f"eval_{k}": v / n for k, v in sums.items() if k != "target_tokens"
        }
        # input-pipeline observability: host build + transfer time per eval
        # batch (ms) — an input-bound eval shows up here, not in eval_loss
        out["eval_input_ms"] = input_s / n_batches * 1000.0
        return out

    # ---- host-side API ---------------------------------------------------

    def init_state(self) -> TrainState:
        from ..parallel.ring import ring_mesh

        with self.mesh, ring_mesh(self.mesh):
            return self._init_jit(jax.random.PRNGKey(self.cfg.seed))

    def step(self, state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        from ..parallel.ring import ring_mesh

        batch = self._shard_batch(batch)
        step_fn = self._get_step_jit(batch)
        # ring_mesh only matters at trace time (first call); harmless after
        with self.mesh, ring_mesh(self.mesh):
            return step_fn(state, batch)

    @property
    def local_batch_size(self) -> int:
        """Rows each process's data pipeline must supply per step.

        ``cfg.batch_size`` is the GLOBAL batch; on a multi-host slice each
        host loads only its share and the global array is assembled from
        per-process shards (no cross-host row duplication or waste).
        """
        n = jax.process_count()
        if self.cfg.batch_size % n:
            raise ValueError(
                f"global batch_size {self.cfg.batch_size} not divisible by "
                f"process count {n}"
            )
        return self.cfg.batch_size // n

    def _shard_batch(self, batch: dict) -> dict:
        def put(x):
            if isinstance(x, jax.Array):
                # already transferred (the prefetch pipeline device_puts with
                # these same shardings on its own thread) — a np.asarray here
                # would copy the batch BACK to host and resubmit it
                if x.sharding == self._batch_leaf_sharding(x):
                    return x
                return jax.device_put(x, self._batch_leaf_sharding(x))
            x = np.asarray(x)
            sh = self._batch_leaf_sharding(x)
            if jax.process_count() > 1:
                return jax.make_array_from_process_local_data(sh, x)
            return jax.device_put(x, sh)

        return jax.tree.map(put, batch)

    def load_pretrained(self, state: TrainState, ckpt_dir: str) -> TrainState:
        """Replace the base-model weights with a pretrained HF checkpoint
        (``models/hf_import.py``), resharded onto the state's shardings.

        LoRA/QLoRA modes load into the frozen ``params`` collection (int4
        kernels are quantized on the way in); full fine-tune loads into the
        trainable tree. The loaded tree is shape-checked leaf-by-leaf against
        the initialised state so a config mismatch fails loudly.

        Multimodal (LLaVA): the checkpoint's vision tower + language model
        fill the frozen base, and the projector fills its slot in the
        TRAINABLE tree (the LLaVA recipe always trains the projector)."""
        quant_block = getattr(self.model_cfg, "quant_block", None) or (
            self.model_cfg.text.quant_block if self._is_multimodal else 64
        )
        if self._is_multimodal:
            from ..models.hf_import import load_llava_params

            loaded = load_llava_params(ckpt_dir, self.model_cfg)
        else:
            from ..models.hf_import import load_llama_params

            loaded = load_llama_params(ckpt_dir, self.model_cfg)
        if self.cfg.mode != "lora":
            adapted = _adapt_loaded_params(
                loaded, state.trainable, quant_block=quant_block
            )
            adapted = reshard(adapted, self._state_shardings.trainable)
            return state.replace(trainable=adapted)
        if self._is_multimodal:
            proj_loaded = {
                k: loaded.pop(k) for k in self._MM_TRAINED_PARAMS if k in loaded
            }
            proj = _adapt_loaded_params(
                proj_loaded, state.trainable["projector"],
                quant_block=quant_block,
            )
            proj = reshard(proj, self._state_shardings.trainable["projector"])
            trainable = dict(state.trainable)
            trainable["projector"] = proj
        else:
            trainable = None
        adapted = _adapt_loaded_params(
            loaded, state.frozen["params"], quant_block=quant_block
        )
        adapted = reshard(adapted, self._state_shardings.frozen["params"])
        frozen = dict(state.frozen)
        frozen["params"] = adapted
        state = state.replace(frozen=frozen)
        if trainable is not None:
            state = state.replace(trainable=trainable)
        return state

    def export_artifacts(
        self,
        state: TrainState,
        artifacts_dir: str,
        pretrained_dir: str | None = None,
    ) -> None:
        """Write deployable HF-format artifacts after training: a PEFT
        adapter for text LoRA runs, plus a merged checkpoint when
        ``cfg.export_merged``. Collective (all hosts gather), rank 0 writes.

        ``pretrained_dir`` (the job's base checkpoint) enables the merged
        export on MULTI-HOST meshes: the sharded frozen base spans
        non-addressable devices, so instead of an expensive cross-host gather
        of GBs of frozen weights, rank 0 reloads the base host-side from the
        original safetensors and merges the already-gathered adapter into it
        (reference promotion contract: ``app/tasks/promotion.py:11-38`` — a
        deployable artifact for every job type).

        Multimodal LoRA runs export the decoder adapter (PEFT format, keyed
        under ``language_model`` — HF LLaVA's layout) plus the trained
        projector (``adapter/projector.safetensors``); merged multimodal
        export is out of scope (the tower/projector/decoder split has no
        single-file HF form a text merge could produce)."""
        if self.cfg.mode != "lora":
            return
        scan = (
            self.model_cfg.text.scan_layers if self._is_multimodal
            else self.model_cfg.scan_layers
        )
        if not scan:
            logger.warning(
                "HF adapter export supports the scanned layer layout only "
                "(scan_layers=False run): skipping export"
            )
            return
        # collective — every rank calls; only the adapter slice is gathered
        host = self.state_to_host(state, fields=("trainable",))
        if jax.process_index() != 0:
            return
        from ..models.hf_export import (
            export_lora_adapter,
            export_merged_checkpoint,
            export_mm_projector,
        )

        if self._is_multimodal:
            export_lora_adapter(
                self.model_cfg.text, host["trainable"]["lora"],
                f"{artifacts_dir}/adapter",
                hf_prefix="base_model.model.language_model.model.layers",
            )
            export_mm_projector(
                host["trainable"]["projector"], f"{artifacts_dir}/adapter"
            )
            if self.cfg.export_merged:
                logger.warning(
                    "export_merged skipped: multimodal runs export the "
                    "adapter + projector (no single-file HF merge exists)"
                )
            return
        export_lora_adapter(
            self.model_cfg, host["trainable"], f"{artifacts_dir}/adapter"
        )
        if self.cfg.export_merged:
            if jax.process_count() > 1:
                if not pretrained_dir:
                    # random-init multi-host run (smoke/proxy): nothing to
                    # reload host-side; merge offline from the adapter
                    logger.warning(
                        "export_merged skipped on multi-host: no pretrained "
                        "base directory to reload host-side; merge offline "
                        "from the adapter and the base"
                    )
                    return
                from ..models.hf_import import load_llama_params

                loaded = load_llama_params(pretrained_dir, self.model_cfg)
                # QLoRA faithfulness: the adapter trained against the
                # QUANTIZED base — re-apply the same int4 adaptation (against
                # eval_shape targets, so no device memory is touched) so the
                # merged weights are deq(Q(W)) + delta, matching the
                # single-host path's dequantized frozen leaves
                shapes = jax.eval_shape(
                    self._raw_init, jax.random.PRNGKey(self.cfg.seed)
                )
                loaded = _adapt_loaded_params(
                    loaded, shapes.frozen["params"],
                    quant_block=self.model_cfg.quant_block,
                )
                frozen_host: dict = {"params": loaded}
            else:
                frozen_host = jax.tree.map(
                    lambda x: np.asarray(jax.device_get(x)), dict(state.frozen)
                )
            variables = self._assemble(frozen_host, host["trainable"])
            try:
                export_merged_checkpoint(
                    self.model_cfg, variables, f"{artifacts_dir}/merged"
                )
            except NotImplementedError as exc:
                # an unsupported merged layout (partial Gemma semantics) must
                # not fail a completed run — the adapter already shipped
                logger.warning("export_merged skipped: %s", exc)

    def state_to_host(
        self,
        state: TrainState,
        fields: tuple[str, ...] = ("step", "trainable", "opt_state"),
    ) -> dict:
        """Gather the persistable slice of state (trainable + opt) to host.

        On a multi-host mesh, sharded arrays span non-addressable devices and
        plain ``device_get`` raises; every process must participate in a
        collective gather (all hosts call this, only rank 0 persists).
        ``fields`` narrows the gather (e.g. adapter export needs only
        ``trainable`` — no point allgathering Adam moments for it).
        """
        tree = {f: getattr(state, f) for f in fields}
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            tree = multihost_utils.process_allgather(tree, tiled=True)
        return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)

    @property
    def mesh_axes(self) -> dict[str, int]:
        """Live mesh axis sizes (``{"dp": 2, "fsdp": 1, ...}``) — what the
        checkpoint manifest records and elastic restore compares against."""
        return {
            name: int(size)
            for name, size in zip(self.mesh.axis_names, self.mesh.devices.shape)
        }

    def _build_manifest(self, step: int, host_state: dict) -> dict:
        from .elastic import build_manifest

        return build_manifest(
            step=step,
            mesh_axes=self.mesh_axes,
            rule_fingerprint=self._rule_fingerprint,
            global_batch_size=self.cfg.batch_size,
            grad_accum_steps=self.cfg.grad_accum_steps,
            seq_len=self.cfg.seq_len,
            seed=self.cfg.seed,
            host_tree=host_state,
        )

    def _plan_elastic_resume(self, ckpt: CheckpointManager, latest: int,
                             multi: bool) -> None:
        """Cross-topology resume contract (``train/elastic.py``): verify the
        checkpoint's partition-rule fingerprint against the live rule table
        and recompute ``grad_accum_steps`` so the global batch decomposes
        into the same row-shards on the live mesh.  Legacy (manifest-less)
        checkpoints restore as before — same-shape only.

        Multi-host: the manifest lives on rank 0's storage; rank 0 plans and
        the outcome (or the refusal) is broadcast so every host mutates its
        config identically — divergent ``grad_accum_steps`` would compile
        different step graphs and deadlock on collectives.
        """
        from .elastic import (
            ElasticManifestError,
            check_fingerprint,
            plan_elastic_resume,
        )

        plan = None
        error: str | None = None
        if not multi or jax.process_index() == 0:
            manifest = ckpt.load_manifest(latest)
            if manifest is not None:
                try:
                    check_fingerprint(manifest, self._rule_fingerprint)
                    plan = plan_elastic_resume(
                        manifest,
                        self.mesh_axes,
                        batch_size=self.cfg.batch_size,
                        grad_accum_steps=self.cfg.grad_accum_steps,
                    )
                except ElasticManifestError as exc:
                    error = str(exc)
        if multi:
            from jax.experimental import multihost_utils

            # (-2 = refusal, -1 = no manifest, >=1 = planned accumulation)
            code = -2 if error else (-1 if plan is None else plan.grad_accum_steps)
            code = int(multihost_utils.broadcast_one_to_all(
                np.asarray(code, np.int64)
            ))
            if code == -2:
                raise ElasticManifestError(
                    error or "rank 0 refused the checkpoint manifest"
                )
            if code >= 1 and plan is None:
                # non-zero rank: apply rank 0's planned accumulation
                self.cfg.grad_accum_steps = code
                return
        if error:
            raise ElasticManifestError(error)
        if plan is None:
            return
        if plan.topology_changed or plan.grad_accum_steps != self.cfg.grad_accum_steps:
            logger.info(
                "elastic restore: checkpoint mesh %s -> live mesh %s "
                "(grad_accum_steps %d -> %d, batch shards %s)",
                plan.source_axes, plan.target_axes,
                self.cfg.grad_accum_steps, plan.grad_accum_steps,
                "preserved" if plan.microstructure_preserved else "re-decomposed",
            )
        self.cfg.grad_accum_steps = plan.grad_accum_steps

    def _writer_extra_fields(self, eval_enabled: bool) -> tuple[str, ...]:
        """Metrics-CSV columns that may appear only on later rows and must be
        declared up front (``MetricsWriter`` pins the header at first write).
        Subclass hook: ``prefs.dpo_trainer.DPOTrainer`` adds its eval and
        rollout columns here."""
        fields: tuple[str, ...] = ("input_ms", "input_fraction")
        if eval_enabled:
            fields += ("eval_loss", "eval_accuracy", "eval_input_ms")
        return fields

    def _row_extras(self) -> dict:
        """Host-side metrics merged into every logged row (subclass hook —
        the rlhf learner reports rollout-buffer depth/staleness and actor
        throughput through this)."""
        return {}

    @staticmethod
    def _consume_profile_request(path: str) -> int:
        """Read + retire an on-demand profiler request delivered through the
        artifact channel (``POST /jobs/{id}/profile`` →
        ``backend.deliver_file`` → ``profile_request.json``).  Returns the
        requested step count (0 = unreadable).  The file is renamed either
        way so a bad payload can't re-trigger every step."""
        try:
            with open(path) as f:
                doc = json.load(f)
            steps = max(1, min(int(doc.get("steps", 5)), 1000))
        except (OSError, ValueError, TypeError):
            steps = 0
        try:
            os.replace(path, path + ".consumed")
        except OSError:
            try:
                os.unlink(path)
            except OSError:
                pass
        return steps

    @staticmethod
    def _sync_preemption(local_flag: bool) -> bool:
        """OR a per-host preemption flag across all hosts (one tiny allgather
        per step — negligible next to a training step, and required so every
        host takes the same checkpoint/exit branch)."""
        if jax.process_count() == 1:
            return local_flag
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray(local_flag, np.bool_), tiled=False
        )
        return bool(np.any(flags))

    def fit(
        self,
        batches: Iterable[dict],
        artifacts_dir: str,
        resume: bool = True,
        on_metrics: Callable[[int, dict], None] | None = None,
        pretrained_dir: str | None = None,
        eval_batches: Iterable[dict] | None = None,
    ) -> TrainState:
        guard = PreemptionGuard()
        try:
            guard.install()
        except ValueError:
            pass  # not on the main thread (e.g. tests)
        # Preemption-flag sync cadence: bounded by log cadence so detection
        # latency stays low without paying a cross-host sync every step.
        self._preempt_sync_every = max(1, min(self.cfg.log_every, self.cfg.checkpoint_every))

        ckpt = CheckpointManager(
            f"{artifacts_dir}/checkpoints", keep=self.cfg.keep_checkpoints
        )
        # observability (docs/observability.md): rank 0 records lifecycle
        # events (events.jsonl) + spans (trace/trainer.jsonl) through the
        # artifact channel, and the phase clock splits every logging window
        # into input/compute/checkpoint/sync/eval.  FTC_TRACE=0 is the
        # operator kill switch; BENCH_MODE=obs gates the overhead <2%.
        from ..obs.events import EventLogWriter
        from ..obs.phase import PhaseClock
        from ..obs.trace import SpanRecorder

        obs_on = (
            self.cfg.trace
            and os.environ.get("FTC_TRACE", "1").strip().lower()
            not in ("0", "false", "no", "off")
            and jax.process_index() == 0
        )
        # on-demand profiling is deliberately NOT gated on tracing: with
        # FTC_TRACE=0 an operator can still arm a jax.profiler window on a
        # live job (otherwise POST /jobs/{id}/profile 202s into a request
        # file nothing ever reads).  FTC_PROFILE=0 is its own kill switch.
        profile_poll_on = (
            os.environ.get("FTC_PROFILE", "1").strip().lower()
            not in ("0", "false", "no", "off")
            and jax.process_index() == 0
        )
        trace_id = os.environ.get("FTC_TRACE_ID", "")
        obs_attempt = int(os.environ.get("FTC_ATTEMPT", "1") or 1)
        events_log = EventLogWriter(
            artifacts_dir, trace_id=trace_id, attempt=obs_attempt,
            enabled=obs_on,
        )
        spans = SpanRecorder(
            artifacts_dir, trace_id, attempt=obs_attempt, enabled=obs_on
        )
        phases = PhaseClock()
        fit_span = spans.start("fit", total_steps=self.cfg.total_steps)
        with spans.span("init", parent=fit_span):
            state = self.init_state()
        start_step = 0
        latest = None
        multi = jax.process_count() > 1
        if resume:
            latest = ckpt.latest_step()
            if multi:
                # All hosts must agree on the resume decision: artifacts_dir may
                # be host-local storage where only rank 0 persisted, so rank 0's
                # view is authoritative. Without this broadcast, hosts would run
                # different numbers of jitted steps and deadlock on collectives.
                from jax.experimental import multihost_utils

                latest_arr = multihost_utils.broadcast_one_to_all(
                    np.asarray(-1 if latest is None else latest, np.int64)
                )
                latest = None if int(latest_arr) < 0 else int(latest_arr)
        if pretrained_dir and not (latest is not None and self.cfg.mode == "full"):
            # pretrained base before the checkpoint restore: the restore only
            # replaces the trainable/optimizer slice, so in LoRA/QLoRA mode
            # the base weights must come from here even on resume. In full
            # fine-tune the checkpoint holds everything — reloading GBs of
            # safetensors just to overwrite them would waste every resume.
            state = self.load_pretrained(state, pretrained_dir)
        restore_span = (
            spans.start("restore", parent=fit_span, step=latest)
            if resume and latest is not None else None
        )
        if resume:
            if latest is not None:
                # Topology-portable resume (train/elastic.py): verify the
                # manifest and recompute the batch microstructure BEFORE any
                # step function traces — the state itself is host-gathered
                # full arrays, so the reshard below lands it on whatever
                # mesh is live now.
                self._plan_elastic_resume(ckpt, latest, multi)
                # Only rank 0 is guaranteed to hold the checkpoint bytes, so
                # rank 0 restores and the tree is broadcast; other hosts feed
                # the broadcast a structure-matching template.
                template = self.state_to_host(state)
                if not multi or jax.process_index() == 0:
                    host = ckpt.restore(latest, like=template)
                else:
                    host = template
                if multi:
                    host = multihost_utils.broadcast_one_to_all(host)
                state = state.replace(
                    # step rides reshard too: a bare jnp.asarray commits it
                    # to one default device, not the mesh-replicated spec
                    # the rule table (and the shard audit) expect
                    step=reshard(
                        jnp.asarray(host["step"], jnp.int32),
                        self._state_shardings.step,
                    ),
                    trainable=reshard(host["trainable"], self._state_shardings.trainable),
                    opt_state=reshard(host["opt_state"], self._state_shardings.opt_state),
                )
                self._audit_state_sharding(state, "restore")
                start_step = int(host["step"])
                spans.finish(restore_span, step=start_step)
                logger.info("resumed from checkpoint step %d", start_step)

        # liveness heartbeat (resilience/heartbeat.py): rank 0 proves forward
        # progress through the artifact channel; the monitor's lease check
        # kills + requeues a job whose heartbeat goes stale
        heartbeat = None
        if self.cfg.heartbeat_interval_s > 0 and jax.process_index() == 0:
            from ..resilience.heartbeat import HeartbeatWriter

            heartbeat = HeartbeatWriter(
                artifacts_dir, interval_s=self.cfg.heartbeat_interval_s
            )
            heartbeat.beat(start_step, force=True)
        # evaluate() beats through this handle — an eval pass over many
        # batches must not look like a stall to the liveness lease
        self._heartbeat = heartbeat
        events_log.emit(
            "train-started", step=start_step,
            resumed_from=start_step if start_step else None,
        )
        # chaos hook (resilience/faults.py): a seeded kill-at-step armed via
        # FTC_FAULT_* env vars — None outside fault-injection runs
        from ..resilience.faults import StepFaultInjector

        fault = StepFaultInjector.from_env()

        eval_it: Iterator[dict] | None = (
            iter(eval_batches) if eval_batches is not None else None
        )
        if self.cfg.eval_every > 0 and eval_it is None:
            raise ValueError(
                "eval_every > 0 but no eval_batches were supplied to fit()"
            )
        # input_ms/input_fraction ride every logged row, but must ALSO be
        # declared so a resume appending to a pre-input-metrics CSV rewrites
        # the header union instead of silently dropping the new columns
        writer = MetricsWriter(
            artifacts_dir, append=start_step > 0,
            extra_fields=self._writer_extra_fields(eval_it is not None)
            + (PhaseClock.columns() if obs_on else ()),
            # a crash AFTER a logged row but BEFORE its checkpoint committed
            # makes this run replay those steps — drop their rows so the
            # replay doesn't duplicate them
            resume_step=start_step,
        )
        it: Iterator[dict] = iter(batches)
        # Fast-forward past already-consumed batches so a resumed run sees the
        # same data stream an uninterrupted run would have. This happens on
        # the RAW iterator, before the prefetch wrap — the skip loop and the
        # prefetch producer must never race for batches.
        for _ in range(start_step):
            next(it)
        prefetch_its: list[Any] = []
        if self.cfg.prefetch > 0:
            from ..data.prefetch import PrefetchIterator

            # the producer thread builds batch N+1..N+k while the device runs
            # step N; the transfer stage additionally device_puts the next
            # batch with the step's shardings (async dispatch → the host→HBM
            # copy overlaps compute, double-buffered by the queue)
            it = PrefetchIterator(
                it, depth=self.cfg.prefetch,
                transfer=self._shard_batch if self.cfg.prefetch_to_device else None,
            )
            prefetch_its.append(it)
            if eval_it is not None and self.cfg.eval_every > 0:
                # eval_every == 0 means evaluate() never runs — don't spin a
                # producer that eagerly builds eval batches nobody consumes
                eval_it = PrefetchIterator(eval_it, depth=1)
                prefetch_its.append(eval_it)
        tokens_per_batch = self.cfg.batch_size * self.cfg.seq_len
        window_t0 = time.perf_counter()
        window_tokens = 0
        # input-pipeline observability: host time each step actually WAITED
        # for its batch (with prefetch on this is the residual stall, not the
        # overlapped build time — a healthy pipeline logs input_fraction ~0)
        window_input_s = 0.0
        window_steps = 0
        # jax.profiler trace window (rank 0 only): ships with the artifacts
        profiling = False
        prof_first = start_step + self.cfg.profile_start_step
        want_profile = self.cfg.profile_steps > 0 and jax.process_index() == 0
        if want_profile and start_step >= self.cfg.total_steps:
            # resumed past the end: no step will run, so no trace can exist
            logger.warning(
                "profiling requested but the run is already complete "
                "(resumed at step %d of %d); no trace will be captured",
                start_step, self.cfg.total_steps,
            )
            want_profile = False
        elif want_profile and prof_first >= self.cfg.total_steps:
            # a requested trace must never silently no-op: clamp the window
            # to the run instead of skipping it
            logger.warning(
                "profile_start_step %d is past the run (total_steps %d); "
                "profiling from the first step instead",
                self.cfg.profile_start_step, self.cfg.total_steps,
            )
            prof_first = start_step
        prof_last = prof_first + self.cfg.profile_steps  # exclusive
        prof_start_actual = prof_first  # where the live window really began
        # on-demand profiler window (docs/observability.md): the controller
        # delivers profile_request.json through the artifact channel and the
        # loop picks it up within one poll window — a live job profiles
        # without restarting.  The stat() is throttled to the preemption-sync
        # cadence: per-step filesystem polling is exactly the kind of cost
        # the BENCH_MODE=obs <2% gate exists to keep out of the step loop.
        profile_req_path = os.path.join(artifacts_dir, "profile_request.json")
        profile_poll = self._preempt_sync_every
        try:
            for step_idx in range(start_step, self.cfg.total_steps):
                iter_t0 = time.perf_counter()
                if want_profile and not profiling and step_idx >= prof_first:
                    # >= not ==: an on-demand window may span the configured
                    # start step — the configured trace then begins at the
                    # first free step instead of silently never firing (and
                    # never having its end marker clobbered)
                    jax.profiler.start_trace(f"{artifacts_dir}/profile")
                    profiling = True
                    want_profile = False  # one configured window per run
                    prof_start_actual = step_idx
                    # clamp to the run so the in-loop stop (and its
                    # profile-captured confirmation) always fires — the
                    # finally-block stop_trace is a silent flush
                    prof_last = min(
                        step_idx + self.cfg.profile_steps,
                        self.cfg.total_steps,
                    )
                if (
                    profile_poll_on and not profiling
                    and step_idx % profile_poll == 0
                    and os.path.exists(profile_req_path)
                ):
                    steps_req = self._consume_profile_request(profile_req_path)
                    if steps_req:
                        jax.profiler.start_trace(f"{artifacts_dir}/profile")
                        profiling = True
                        prof_start_actual = step_idx
                        prof_last = min(
                            step_idx + steps_req, self.cfg.total_steps
                        )
                t_in = time.perf_counter()
                batch = next(it)
                dt_in = time.perf_counter() - t_in
                window_input_s += dt_in
                if obs_on:
                    phases.add("input", dt_in)
                window_steps += 1
                state, metrics = self.step(state, batch)
                window_tokens += tokens_per_batch
                if heartbeat is not None:
                    t_hb = time.perf_counter()
                    heartbeat.beat(
                        step_idx + 1,
                        step_ms=(t_hb - iter_t0) * 1000.0,
                    )
                    if obs_on:
                        phases.add("sync", time.perf_counter() - t_hb)
                if fault is not None:
                    # after the step so a SIGTERM's save reflects real progress
                    fault.maybe_fire(step_idx + 1)
                if profiling and step_idx + 1 >= prof_last:
                    jax.block_until_ready(state)
                    jax.profiler.stop_trace()
                    profiling = False
                    # force: profiling is decoupled from the tracing kill
                    # switch, so its confirmation must be too — the
                    # timeline otherwise shows a request with no capture
                    events_log.emit(
                        "profile-captured", step=step_idx + 1, force=True
                    )
                    logger.info(
                        "profiler trace for steps [%d, %d) -> %s/profile",
                        prof_start_actual, prof_last, artifacts_dir,
                    )

                last = step_idx + 1 == self.cfg.total_steps
                eval_now = (
                    self.cfg.eval_every > 0
                    and eval_it is not None
                    and ((step_idx + 1) % self.cfg.eval_every == 0 or last)
                )
                eval_metrics: dict[str, float] = {}
                eval_elapsed = 0.0
                if eval_now:
                    eval_t0 = time.perf_counter()
                    eval_metrics = self.evaluate(state, eval_it)
                    eval_elapsed = time.perf_counter() - eval_t0
                    if obs_on:
                        phases.add("eval", eval_elapsed)
                    logger.info(
                        "step %d eval_loss %.4f eval_acc %.3f",
                        step_idx + 1, eval_metrics["eval_loss"],
                        eval_metrics["eval_accuracy"],
                    )
                # eval metrics ride ON a train log row (eval steps force one)
                # so the CSV stays dense within each written row
                if (step_idx + 1) % self.cfg.log_every == 0 or last or eval_now:
                    metrics = {k: float(v) for k, v in metrics.items()}
                    # the evaluation pause doesn't count against throughput
                    dt = time.perf_counter() - window_t0 - eval_elapsed
                    metrics["tokens_per_sec"] = window_tokens / max(dt, 1e-9)
                    # input-time share of the window: near 0 = device-bound
                    # (healthy); toward 1 = input-bound (grow prefetch depth
                    # or move host work off the loader)
                    metrics["input_ms"] = (
                        window_input_s / max(window_steps, 1) * 1000.0
                    )
                    metrics["input_fraction"] = window_input_s / max(dt, 1e-9)
                    metrics.update(eval_metrics)
                    if obs_on:
                        # step-phase split (docs/observability.md): per-step
                        # averages over the FULL window wall (eval included —
                        # it is one of the phases)
                        metrics.update(phases.window_row(
                            steps=window_steps, wall_s=dt + eval_elapsed
                        ))
                    metrics.update(self._row_extras())
                    row = {"step": step_idx + 1, **metrics}
                    writer.write(row)
                    if on_metrics:
                        on_metrics(step_idx + 1, metrics)
                    logger.info(
                        "step %d loss %.4f acc %.3f tok/s %.0f input %.1fms"
                        " (%.1f%% of step)",
                        step_idx + 1, metrics["loss"], metrics["accuracy"],
                        metrics["tokens_per_sec"], metrics["input_ms"],
                        100.0 * metrics["input_fraction"],
                    )
                    window_t0 = time.perf_counter()
                    window_tokens = 0
                    window_input_s = 0.0
                    window_steps = 0

                # SIGTERM may reach only some hosts; state_to_host is a
                # collective, so the preempt flag must be agreed across hosts
                # (any-host OR) before any host enters the gather. The sync is
                # a blocking allgather that would serialize host and device if
                # run every step, so it only runs on a deterministic cadence
                # (same arithmetic on every host ⇒ still collective-safe).
                sync_now = (
                    (step_idx + 1) % self._preempt_sync_every == 0
                    or (step_idx + 1) % self.cfg.checkpoint_every == 0
                    or last
                )
                t_sync = time.perf_counter()
                preempt = self._sync_preemption(guard.requested) if sync_now else False
                if obs_on and sync_now:
                    phases.add("sync", time.perf_counter() - t_sync)
                if (step_idx + 1) % self.cfg.checkpoint_every == 0 or last or preempt:
                    blocking_save = last or preempt or self._blocking_checkpoints
                    ck_span = spans.start(
                        "checkpoint", parent=fit_span, step=step_idx + 1,
                        blocking=blocking_save,
                    )
                    t_ck = time.perf_counter()
                    # A checkpoint of mis-sharded state would round-trip the
                    # damage through every later restore — audit BEFORE the
                    # host gather flattens the evidence away.
                    self._audit_state_sharding(state, f"checkpoint:{step_idx + 1}")
                    # Collective gather on all hosts; rank 0 persists.
                    host_state = self.state_to_host(state)
                    if jax.process_index() == 0:
                        # Mid-run saves overlap the next steps (the goodput
                        # lever); the LAST save has nothing left to overlap
                        # with — commit it synchronously so no background
                        # save thread races the teardown below (prefetch
                        # close / profiler stop), a race observed as a rare
                        # interpreter crash on fast CPU test runs.  Every
                        # committed checkpoint carries its topology manifest
                        # (train/elastic.py) so ANY later mesh can restore it.
                        ckpt.save(step_idx + 1, host_state,
                                  blocking=blocking_save,
                                  manifest=self._build_manifest(
                                      step_idx + 1, host_state))
                    if obs_on:
                        # the host-side cost of this save (gather + write for
                        # a blocking save; gather + handoff for an async one)
                        phases.add("checkpoint", time.perf_counter() - t_ck)
                    spans.finish(ck_span)
                    events_log.emit(
                        "checkpoint-committed", step=step_idx + 1,
                        blocking=blocking_save or None,
                    )
                if preempt:
                    logger.warning("exiting on preemption after step %d", step_idx + 1)
                    events_log.emit("preempt-exit", step=step_idx + 1)
                    raise SystemExit(143)
        finally:
            self._heartbeat = None  # evaluate() outside fit must not beat
            # stop the prefetch producers FIRST: a producer mid-build must
            # not keep decoding images while teardown waits on checkpoints
            for p in prefetch_its:
                p.close()
            if profiling:
                jax.profiler.stop_trace()
            # Must be read before the inner except handler runs: inside an
            # except block sys.exc_info() reports the just-caught exception,
            # which would make a wait() failure always look "propagating".
            propagating = sys.exc_info()[1] is not None
            try:
                # durability barrier: an async checkpoint save must commit
                # before the process exits (especially the preemption path —
                # the point of the save-on-SIGTERM is surviving the kill)
                ckpt.wait()
            except Exception:
                if propagating:
                    # an exception (e.g. the preemption SystemExit 143) is
                    # already propagating: log the save failure rather than
                    # masking the original exit semantics
                    logger.exception("final checkpoint save failed during teardown")
                else:
                    raise
            finally:
                writer.close()
                spans.finish(
                    fit_span, status="error" if propagating else "ok",
                    start_step=start_step,
                )
                if not propagating:
                    events_log.emit(
                        "train-finished", step=self.cfg.total_steps
                    )
        return state
