"""Pre-warmed trainer process: eat the JAX import + backend-init cost
*before* a job arrives.

``python -m finetune_controller_tpu.train.warm_worker`` imports JAX and
initialises the platform backend immediately, then blocks on stdin until the
local backend hands it one request line:

    {"spec": "/path/job.json", "log": "/path/logs.txt", "cwd": "/sandbox"}

It then redirects stdout/stderr to the job's log file (the same file a
cold-spawned trainer would write), chdirs into the sandbox, and runs the job
via ``train.cli``.  One request per process — the pool replaces used workers.

Why: the submit -> first-training-step span is dominated by interpreter +
JAX import and backend init (~8-25 s measured; `BASELINE.md` north-star #2).
The k8s equivalent is an image whose entrypoint pre-imports before fetching
the spec; this is the local backend's version of that warm start.

Closing stdin without a request is the shutdown signal (exit 0).
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    # Platform config (JAX_PLATFORMS, XLA_FLAGS device count) comes from the
    # spawn env — the pool keys workers by it, so this matches the job's.
    from ..platform import assert_platform_env

    assert_platform_env()

    import jax

    jax.devices()  # force backend init now, not at first trace

    # pre-import the whole training stack (flax/optax/orbax/models/data) —
    # JAX alone is under half the interpreter's import bill
    from . import checkpoint, cli, trainer  # noqa: F401
    from ..data import loader, synthetic  # noqa: F401
    from ..models import multimodal  # noqa: F401

    ready = os.environ.get("FTC_WARM_READY_FILE")
    if ready:
        with open(ready, "w") as f:
            f.write("ready\n")

    line = sys.stdin.readline()
    # the sentinel's job is done once a request (or shutdown) arrives; the
    # worker owns its removal — the claim path's unlink is best-effort and
    # misses workers claimed before the file existed
    if ready:
        try:
            os.unlink(ready)
        except OSError:
            pass
    if not line.strip():
        return 0  # pool shutdown
    req = json.loads(line)

    # per-job env (trace identity: FTC_TRACE_ID / FTC_ATTEMPT) arrives with
    # the request — this process was spawned before the job existed, so the
    # usual spawn-env channel cannot carry it
    for k, v in (req.get("env") or {}).items():
        os.environ[k] = str(v)

    fd = os.open(req["log"], os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    os.dup2(fd, 1)
    os.dup2(fd, 2)
    os.close(fd)
    if req.get("cwd"):
        os.chdir(req["cwd"])

    from .cli import main as cli_main

    return cli_main(["--spec", req["spec"]])


if __name__ == "__main__":
    raise SystemExit(main())
