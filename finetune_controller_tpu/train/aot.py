"""Ahead-of-time validation of configs too big to execute on available chips.

BASELINE configs #2 (Llama-3-8B LoRA FSDP on a v5e-16 slice) and #4
(Mixtral-8x7B MoE LoRA on v5p-64) cannot run on one chip.  What CAN be proven
without the hardware: ``jax.jit(...).lower()`` over ``ShapeDtypeStruct``
inputs traces and SPMD-partitions the FULL-SIZE training step on an
n-virtual-device mesh without allocating a single parameter buffer, and
``.compile()`` runs the whole XLA pipeline on it.  From the artifacts we
check:

* the parameter sharding specs the partitioner was given (FSDP sharding on
  every weight; expert-parallel sharding on MoE expert kernels),
* the cross-device collectives present in the compiled HLO (all-gather for
  FSDP parameter gathering, reduce-scatter/all-reduce for gradient
  reduction, all-to-all/ragged variants for MoE dispatch),
* arithmetic per-device bytes of the resident train state (params + optimizer
  + master copies, each leaf divided by its sharded mesh axes) against the
  target chip's HBM.

The reference has no analogue — its training plane is a user container it
never inspects; this is the TPU-native replacement for "trust me, it fits".

Driver integration: ``__graft_entry__.dryrun_multichip`` runs these reports
in subprocesses (the virtual device count must be fixed before JAX backend
init); ``tests/test_aot_realscale.py`` asserts on the reports in CI.
"""

from __future__ import annotations

import json
import logging
import math
import re
from typing import Any

#: chip HBM capacities (GiB, usable ~ spec minus runtime reserve)
_HBM_GIB = {"v5e": 16.0, "v5p": 95.0}

#: the BASELINE configs that need >1 chip, at their REAL shapes.
#: ``num_slices > 1`` marks a multi-slice (DCN) leg: devices are grouped into
#: contiguous virtual slices (mirroring real multi-slice enumeration order),
#: dp runs across slices, and the report classifies every compiled collective
#: as intra-slice (ICI) or cross-slice (DCN).
REALSCALE: dict[str, dict[str, Any]] = {
    "llama3-8b-fsdp16": dict(
        preset="llama3-8b", mesh=dict(fsdp=16), n_devices=16,
        batch=16, seq=2048, chip="v5e",
    ),
    "mixtral-8x7b-ep8-fsdp8": dict(
        preset="mixtral-8x7b", mesh=dict(fsdp=8, ep=8), n_devices=64,
        batch=64, seq=2048, chip="v5p",
    ),
    # 2 × v5e-16 slices over DCN: dp across slices, FSDP inside each slice —
    # the standard multi-slice recipe (SURVEY §2.3; reference seam:
    # PyTorchJobDeployer.py:186-249 replica fan-out, which never saw a mesh)
    "llama3-8b-dcn2x16": dict(
        preset="llama3-8b", mesh=dict(dp=2, fsdp=16), n_devices=32,
        batch=32, seq=2048, chip="v5e", num_slices=2,
    ),
    # real-shape PIPELINE leg (round 5): the 8B model's layer stack split
    # into 2 GPipe stages × dp4 on v5p (pp composes with dp; the frozen base
    # is replicated over dp, so the roomier chip hosts this layout). The
    # report carries the schedule's analytic bubble fraction.
    "llama3-8b-dp4-pp2": dict(
        preset="llama3-8b", mesh=dict(dp=4, pp=2), n_devices=8,
        batch=32, seq=2048, chip="v5p",
    ),
}

_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute"
    r"|ragged-all-to-all)\b"
)

# `%x = ... all-gather(...), ..., replica_groups={{0,1},{2,3}}, ...` or the
# iota form `replica_groups=[2,16]<=[32]` / `[16,2]<=[2,16]T(1,0)`
_GROUPED_OP_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|ragged-all-to-all)"
    r"(?:-start)?\([^\n]*?replica_groups=(\{\{[^}]*(?:\},\{[^}]*)*\}\}"
    r"|\[[\d,]+\]<=\[[\d,]+\](?:T\([\d,]+\))?)"
)


def _parse_groups(text: str) -> list[list[int]]:
    """Materialise a replica_groups literal (explicit or iota form)."""
    import numpy as np

    if text.startswith("{{"):
        return [
            [int(x) for x in grp.split(",") if x.strip() != ""]
            for grp in text[2:-2].split("},{")
        ]
    m = re.match(r"\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?", text)
    out_shape = [int(x) for x in m.group(1).split(",")]
    src_shape = [int(x) for x in m.group(2).split(",")]
    arr = np.arange(math.prod(src_shape)).reshape(src_shape)
    if m.group(3):
        arr = arr.transpose([int(x) for x in m.group(3).split(",")])
    return arr.reshape(out_shape).tolist()


def classify_collectives(hlo: str, per_slice: int) -> dict[str, dict[str, int]]:
    """Count compiled collectives by op kind × (intra|cross)-slice.

    A collective whose every replica group stays inside one ``per_slice``
    block of contiguous device ids rides ICI; a group spanning blocks rides
    DCN.  This is the mechanically-checkable form of "fsdp inside the slice,
    only the dp gradient reduction crosses DCN".
    """
    counts: dict[str, dict[str, int]] = {}
    for m in _GROUPED_OP_RE.finditer(hlo):
        op, groups_text = m.group(1), m.group(2)
        groups = _parse_groups(groups_text)
        intra = all(
            len({dev // per_slice for dev in grp}) <= 1 for grp in groups
        )
        bucket = counts.setdefault(op, {"intra_slice": 0, "cross_slice": 0})
        bucket["intra_slice" if intra else "cross_slice"] += 1
    return counts


def _sharded_bytes(shape, dtype, spec, mesh_shape: dict[str, int]) -> float:
    """Bytes per device for one leaf: total bytes over the product of mesh
    axis sizes its PartitionSpec shards over."""
    total = math.prod(shape) * dtype.itemsize if shape else dtype.itemsize
    denom = 1
    for entry in (spec or ()):
        if entry is None:
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        for ax in axes:
            denom *= mesh_shape.get(ax, 1)
    return total / denom


def aot_report(name: str) -> dict[str, Any]:
    """Lower + compile the named REALSCALE config abstractly; return the
    evidence dict.  Must run in a process whose JAX backend has at least
    ``n_devices`` devices (virtual CPU devices are fine — use
    ``--xla_force_host_platform_device_count``)."""
    import jax
    import jax.numpy as jnp

    from ..models.llama import PRESETS
    from ..models.lora import LoRAConfig
    from ..parallel.mesh import MeshSpec
    from .trainer import TrainConfig, Trainer

    spec = REALSCALE[name]
    devices = jax.devices()[: spec["n_devices"]]
    if len(devices) < spec["n_devices"]:
        raise RuntimeError(
            f"{name} needs {spec['n_devices']} devices, backend has "
            f"{len(devices)} — set xla_force_host_platform_device_count "
            "before JAX init"
        )
    num_slices = spec.get("num_slices", 1)
    slice_of = None
    if num_slices > 1:
        per_slice = spec["n_devices"] // num_slices
        slice_of = [i // per_slice for i in range(spec["n_devices"])]
    mesh = MeshSpec(**spec["mesh"]).build(devices, slice_of=slice_of)
    model_cfg = PRESETS[spec["preset"]].replace(lora=LoRAConfig(rank=16))
    train_cfg = TrainConfig(
        mode="lora", batch_size=spec["batch"], seq_len=spec["seq"],
        total_steps=10,
    )
    trainer = Trainer(model_cfg, train_cfg, mesh=mesh)

    # abstract state: shapes from eval_shape, shardings from the rule engine —
    # zero parameter memory is allocated anywhere in this function
    state_shapes = jax.eval_shape(trainer._raw_init, jax.random.PRNGKey(0))
    abstract_state = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        state_shapes, trainer._state_shardings,
    )
    b, s = spec["batch"], spec["seq"]
    abstract_batch = {
        "tokens": jax.ShapeDtypeStruct((b, s), jnp.int32),
        "loss_mask": jax.ShapeDtypeStruct((b, s), jnp.float32),
    }
    step = trainer._get_step_jit(abstract_batch)
    compiled = step.lower(abstract_state, abstract_batch).compile()
    hlo = compiled.as_text()
    collectives = sorted(set(_COLLECTIVE_RE.findall(hlo)))
    dcn_split = None
    if num_slices > 1:
        dcn_split = classify_collectives(hlo, spec["n_devices"] // num_slices)

    # param sharding evidence: flatten specs with paths
    mesh_shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    leaves = jax.tree_util.tree_leaves_with_path(trainer._state_shardings)
    spec_samples: dict[str, str] = {}
    state_bytes = 0.0
    shape_leaves = {
        jax.tree_util.keystr(p): l
        for p, l in jax.tree_util.tree_leaves_with_path(state_shapes)
    }
    fsdp_sharded = unsharded_big = 0
    ep_sharded = pp_sharded = 0
    for path, sharding in leaves:
        key = jax.tree_util.keystr(path)
        shp = shape_leaves[key]
        pspec = sharding.spec
        state_bytes += _sharded_bytes(shp.shape, shp.dtype, pspec, mesh_shape)
        flat_axes = [
            ax
            for entry in pspec if entry is not None
            for ax in (entry if isinstance(entry, (tuple, list)) else (entry,))
        ]
        if "fsdp" in flat_axes:
            fsdp_sharded += 1
        elif "pp" in flat_axes:
            # stage-sharded on the leading layer axis — sharded, just not
            # by fsdp; must not be reported as an unsharded giant
            pp_sharded += 1
        elif math.prod(shp.shape or (1,)) * shp.dtype.itemsize > 4 << 20:
            unsharded_big += 1
            spec_samples.setdefault(f"UNSHARDED {key}", str(pspec))
        if "ep" in flat_axes:
            ep_sharded += 1
        if "kernel" in key and len(spec_samples) < 12:
            spec_samples.setdefault(key, str(pspec))

    mem = None
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            mem = {
                "argument_bytes": getattr(ma, "argument_size_in_bytes", None),
                "output_bytes": getattr(ma, "output_size_in_bytes", None),
                "temp_bytes": getattr(ma, "temp_size_in_bytes", None),
            }
    except Exception:
        # memory_analysis is best-effort (backend-dependent API surface);
        # the report ships without it rather than failing the compile check
        logging.getLogger(__name__).debug(
            "compiled.memory_analysis() unavailable", exc_info=True
        )

    pp = mesh_shape.get("pp", 1)
    pp_schedule = None
    if pp > 1:
        from ..parallel.pipeline import (
            bubble_fraction,
            default_pp_microbatches,
        )

        local = b // (mesh_shape.get("dp", 1) * mesh_shape.get("fsdp", 1))
        n_micro = default_pp_microbatches(local, pp)
        pp_schedule = {
            "n_micro": n_micro,
            "bubble_fraction": round(bubble_fraction(n_micro, pp), 4),
        }

    hbm = _HBM_GIB[spec["chip"]] * (1 << 30)
    return {
        "name": name,
        "mesh": mesh_shape,
        "n_devices": spec["n_devices"],
        "batch": b, "seq": s,
        "param_count": model_cfg.param_count(),
        "collectives": collectives,
        "num_slices": num_slices,
        "dcn_split": dcn_split,
        "pp_schedule": pp_schedule,
        "fsdp_sharded_leaves": fsdp_sharded,
        "pp_sharded_leaves": pp_sharded,
        "ep_sharded_leaves": ep_sharded,
        "unsharded_big_leaves": unsharded_big,
        "state_bytes_per_device": int(state_bytes),
        "hbm_bytes": int(hbm),
        "state_fits_hbm": state_bytes < hbm,
        "spec_samples": spec_samples,
        "xla_memory_analysis": mem,
    }


def run_report_subprocess(name: str, timeout: float = 540.0) -> dict[str, Any]:
    """Produce the named report in a fresh subprocess that owns its virtual
    device count (the flag must be set before JAX backend init, so the
    current process — whose backend is usually already initialised — can't
    do it in-process).  Shared by ``__graft_entry__.dryrun_multichip`` and
    the CI tests."""
    import os
    import subprocess
    import sys

    spec = REALSCALE[name]
    env = dict(os.environ)
    kept = " ".join(
        p for p in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in p
    )
    env["XLA_FLAGS"] = (
        f"{kept} --xla_force_host_platform_device_count={spec['n_devices']}"
    ).strip()
    out = subprocess.run(
        [sys.executable, "-m", "finetune_controller_tpu.train.aot", name],
        env=env, capture_output=True, text=True, timeout=timeout,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"AOT real-scale validation {name} failed:\n" + out.stderr[-2000:]
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> None:
    import os
    import sys

    import jax

    # The dryrun contract is virtual CPU devices; force the platform before
    # backend init — a site plugin's startup `jax.config.update` can override
    # the JAX_PLATFORMS env var and hang on an unreachable TPU tunnel.
    jax.config.update("jax_platforms", os.environ.get("AOT_PLATFORM", "cpu"))
    print(json.dumps(aot_report(sys.argv[1])))


if __name__ == "__main__":
    main()
