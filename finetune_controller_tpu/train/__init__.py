from .trainer import Trainer, TrainConfig, TrainState
from .metrics import MetricsWriter

__all__ = ["Trainer", "TrainConfig", "TrainState", "MetricsWriter"]
