"""Optimizer and LR-schedule construction (optax)."""

from __future__ import annotations

import optax


def build_schedule(
    learning_rate: float,
    warmup_steps: int,
    total_steps: int,
    schedule: str = "cosine",
    min_lr_ratio: float = 0.1,
) -> optax.Schedule:
    warmup = optax.linear_schedule(0.0, learning_rate, max(warmup_steps, 1))
    decay_steps = max(total_steps - warmup_steps, 1)
    if schedule == "cosine":
        decay = optax.cosine_decay_schedule(
            learning_rate, decay_steps, alpha=min_lr_ratio
        )
    elif schedule == "linear":
        decay = optax.linear_schedule(
            learning_rate, learning_rate * min_lr_ratio, decay_steps
        )
    elif schedule == "constant":
        decay = optax.constant_schedule(learning_rate)
    else:
        raise ValueError(f"unknown schedule {schedule!r}")
    return optax.join_schedules([warmup, decay], [warmup_steps])


def build_optimizer(
    learning_rate: float = 2e-4,
    warmup_steps: int = 10,
    total_steps: int = 1000,
    schedule: str = "cosine",
    weight_decay: float = 0.0,
    b1: float = 0.9,
    b2: float = 0.999,
    clip_norm: float = 1.0,
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    sched = build_schedule(learning_rate, warmup_steps, total_steps, schedule)
    tx = optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(sched, b1=b1, b2=b2, weight_decay=weight_decay),
    )
    return tx, sched
