"""Training-metrics CSV writer.

Contract with the control plane (mirrors the reference's convention — model
writes ``*metrics*.csv`` under the artifacts dir, monitor syncs the newest
match into the DB; reference ``app/utils/S3Handler.py:252-258``,
``app/core/monitor.py:34-95``): one header row, one row per logging step,
flushed on every write so the monitor sees fresh data mid-run.

Standard trainer columns (``train/trainer.py`` writes them every log step):
``timestamp``, ``step``, ``loss``, ``accuracy``, ``tokens_per_sec``, and the
input-pipeline observability pair — ``input_ms`` (host time the training
thread waited per step for its next batch; with the default background
prefetch this is residual stall, not the overlapped build time) and
``input_fraction`` (that wait as a share of the logging window: ~0 means
device-bound/healthy, toward 1 means input-bound — raise the prefetch depth
or move host work off the loader). Eval-cadence columns (``eval_*``,
including ``eval_input_ms``) are declared via ``extra_fields``.
"""

from __future__ import annotations

import csv
import os
import time
from typing import IO, Any, Mapping


class MetricsWriter:
    def __init__(
        self,
        artifacts_dir: str,
        filename: str = "metrics.csv",
        append: bool = False,
        extra_fields: tuple[str, ...] = (),
        resume_step: int | None = None,
    ):
        """``extra_fields`` declares columns that may appear only on LATER
        rows (e.g. eval metrics written on their own cadence): the header is
        pinned at the first write, so anything not present in the first row
        must be declared up front or it would be silently dropped.

        ``resume_step`` (with ``append``) is the step the run resumed FROM:
        rows past it are dropped before appending.  A crash between a logged
        row and its checkpoint's commit (SIGKILL mid-save — the chaos tests
        hit exactly this) makes the resumed run REPLAY those steps; without
        the truncation each replayed row would appear twice."""
        os.makedirs(artifacts_dir, exist_ok=True)
        self.path = os.path.join(artifacts_dir, filename)
        self._file: IO[str] | None = None
        self._writer: csv.DictWriter | None = None
        self._extra_fields = extra_fields
        self._resume_fields: list[str] | None = None
        if append and os.path.exists(self.path):
            with open(self.path) as f:
                header = f.readline().strip()
            if header:
                self._resume_fields = header.split(",")
                if resume_step is not None and "step" in self._resume_fields:
                    self._truncate_past(resume_step)

    def _truncate_past(self, resume_step: int) -> None:
        """Drop rows whose step exceeds the resume point (atomic rewrite)."""
        with open(self.path, newline="") as f:
            rows = list(csv.DictReader(f))
        kept = [
            r for r in rows
            if not r.get("step") or float(r["step"]) <= resume_step
        ]
        if len(kept) == len(rows):
            return
        tmp_path = self.path + ".tmp"
        with open(tmp_path, "w", newline="") as f:
            rewriter = csv.DictWriter(f, fieldnames=self._resume_fields)
            rewriter.writeheader()
            rewriter.writerows(kept)
        os.replace(tmp_path, self.path)

    def write(self, row: Mapping[str, Any]) -> None:
        row = {"timestamp": round(time.time(), 3), **row}
        if self._writer is None:
            if self._resume_fields is not None:
                missing = [
                    f for f in self._extra_fields if f not in self._resume_fields
                ]
                if missing:
                    # Resumed run gained new columns (e.g. eval enabled after
                    # the first leg): rewrite the file under the union header
                    # so the new columns aren't silently dropped.
                    with open(self.path, newline="") as f:
                        old_rows = list(csv.DictReader(f))
                    self._resume_fields = self._resume_fields + missing
                    # Atomic swap: a crash mid-rewrite must not lose the
                    # run's whole metrics history.
                    tmp_path = self.path + ".tmp"
                    with open(tmp_path, "w", newline="") as f:
                        rewriter = csv.DictWriter(f, fieldnames=self._resume_fields)
                        rewriter.writeheader()
                        for old in old_rows:
                            rewriter.writerow(
                                {k: old.get(k, "") for k in self._resume_fields}
                            )
                    os.replace(tmp_path, self.path)
                # Preemption-resume: keep prior rows, reuse the existing header.
                self._file = open(self.path, "a", newline="")
                self._writer = csv.DictWriter(self._file, fieldnames=self._resume_fields)
            else:
                fields = list(row.keys()) + [
                    f for f in self._extra_fields if f not in row
                ]
                self._file = open(self.path, "w", newline="")
                self._writer = csv.DictWriter(self._file, fieldnames=fields)
                self._writer.writeheader()
        self._writer.writerow({k: row.get(k, "") for k in self._writer.fieldnames})
        self._file.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
            self._writer = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
