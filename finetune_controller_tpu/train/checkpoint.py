"""Orbax-backed checkpointing with preemption-safe semantics.

Closes a real gap in the reference: its jobs had no resume path at all —
checkpoints lived on a pod-local emptyDir synced to S3, and a restarted pod
started from scratch (SURVEY.md §5.4).  Here: every save is atomic (Orbax
renames on commit), the latest step is discoverable, and restore re-shards
onto the current mesh via device_put with the trainer's shardings.
"""

from __future__ import annotations

import logging
import os
import re
import threading
from typing import Any

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)

_STEP_RE = re.compile(r"^step_(\d+)$")
#: uncommitted save staging: ``_save_msgpack`` writes ``step_N.tmp`` then
#: renames; Orbax stages ``step_N.orbax-checkpoint-tmp-<ts>`` — a SIGKILL
#: mid-save strands either shape (observed in the chaos tests), and the
#: strays match the artifact-sync globs, shipping garbage with every sync
_TMP_RE = re.compile(r"^step_\d+(\.tmp|\.orbax-checkpoint-tmp-.*)$")


class CheckpointManager:
    """Saves are ASYNC by default: ``save`` hands the (already host-side)
    tree to a background writer and returns, so serialization + disk IO
    overlap the next training steps — the standard TPU goodput lever.  At
    most one save is in flight; ``wait()`` (called automatically before the
    next save, any read, and by the trainer's exit path) is the durability
    barrier."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        os.makedirs(self.directory, exist_ok=True)
        self._sweep_stale_tmp()
        self._ckptr = ocp.StandardCheckpointer()
        self._pending: threading.Thread | None = None
        self._pending_error: list[BaseException] = []

    def _sweep_stale_tmp(self) -> None:
        """Remove uncommitted ``step_N.tmp`` staging dirs left by a crash.

        A kill between ``_save_msgpack``'s makedirs and its atomic
        ``os.replace`` strands the staging dir forever: it is never a
        committed step (``_committed_steps`` ignores it) but it shadows the
        path of a FUTURE save of the same step — and it silently leaks disk
        on every crash.  Init is the safe sweep point: this manager is the
        directory's single writer and no save is in flight yet.
        """
        import shutil

        for name in os.listdir(self.directory):
            if not _TMP_RE.match(name):
                continue
            path = os.path.join(self.directory, name)
            shutil.rmtree(path, ignore_errors=True)
            logger.warning("swept stale uncommitted checkpoint staging %s", name)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def wait(self) -> None:
        """Block until any in-flight save is committed to disk.

        Re-raises a background save's exception — a swallowed disk-full here
        would let a preempted job exit believing its checkpoint committed."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        self._ckptr.wait_until_finished()
        if self._pending_error:
            err = self._pending_error.pop()
            raise RuntimeError(f"background checkpoint save failed: {err}") from err

    def _committed_steps(self) -> list[int]:
        """Step dirs already committed on disk (does NOT wait — an in-flight
        save's dir only appears at its atomic rename)."""
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and os.path.exists(os.path.join(self.directory, name)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def all_steps(self) -> list[int]:
        self.wait()
        return self._committed_steps()

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _save_sync(self, path: str, tree: Any) -> None:
        try:
            if jax.process_count() > 1:
                # Orbax's save is itself a cross-process collective
                # (sync_global_processes barriers); on multi-host only rank 0
                # calls save with an already-gathered host tree, so use a
                # non-collective msgpack writer (atomic tmp-dir rename).
                self._save_msgpack(path, tree)
            else:
                self._ckptr.save(path, tree)
                self._ckptr.wait_until_finished()
        except BaseException as exc:  # noqa: BLE001 — re-raised from wait()
            logger.exception("background checkpoint save to %s failed", path)
            # ftc: ignore[shared-mutable-without-lock] -- single in-flight writer thread (save() waits before starting another); list.append is GIL-atomic and drained only after join() in wait()
            self._pending_error.append(exc)

    def save(self, step: int, tree: Any, force: bool = False, blocking: bool = False) -> None:
        self.wait()  # one in-flight save at a time (raises on a prior failure)
        path = self._path(step)
        if os.path.exists(path):
            if not force:
                return
            import shutil

            shutil.rmtree(path)
        # gc BEFORE starting the writer: gc lists only committed dirs, so it
        # must not (and does not) wait on the save we are about to start —
        # the whole point is overlapping serialization + IO with training
        self._gc()
        self._pending = threading.Thread(
            target=self._save_sync, args=(path, tree), daemon=False
        )
        self._pending.start()
        if blocking:
            self.wait()

    @staticmethod
    def _save_msgpack(path: str, tree: Any) -> None:
        from flax import serialization

        tmp = path + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
            f.write(serialization.to_bytes(tree))
        os.replace(tmp, path)

    def restore(self, step: int, like: Any | None = None) -> Any:
        self.wait()
        path = self._path(step)
        msgpack_file = os.path.join(path, "state.msgpack")
        if os.path.exists(msgpack_file):
            from flax import serialization

            with open(msgpack_file, "rb") as f:
                return serialization.from_bytes(like, f.read())
        return self._ckptr.restore(path, target=like)

    def restore_latest(self, like: Any | None = None) -> tuple[int, Any] | None:
        step = self.latest_step()
        if step is None:
            return None
        return step, self.restore(step, like)

    def _gc(self) -> None:
        steps = self._committed_steps()
        for step in steps[: -self.keep]:
            import shutil

            shutil.rmtree(self._path(step), ignore_errors=True)
            logger.info("gc'd checkpoint step_%d", step)


def reshard(tree: Any, shardings: Any) -> Any:
    """Place a host-restored tree onto devices with the given shardings."""
    return jax.tree.map(lambda x, s: jax.device_put(x, s), tree, shardings)
