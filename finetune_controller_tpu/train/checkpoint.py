"""Orbax-backed checkpointing with preemption-safe semantics.

Closes a real gap in the reference: its jobs had no resume path at all —
checkpoints lived on a pod-local emptyDir synced to S3, and a restarted pod
started from scratch (SURVEY.md §5.4).  Here: every save is atomic (Orbax
renames on commit), the latest step is discoverable, and restore re-shards
onto the current mesh via device_put with the trainer's shardings.
"""

from __future__ import annotations

import logging
import os
import re
import threading
from typing import Any

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)

_STEP_RE = re.compile(r"^step_(\d+)$")
#: uncommitted save staging: ``_save_msgpack`` writes ``step_N.tmp`` then
#: renames; Orbax stages ``step_N.orbax-checkpoint-tmp-<ts>``; the manifest
#: writer stages ``step_N.manifest.tmp`` — a SIGKILL mid-save strands any of
#: these (observed in the chaos tests), and the strays match the
#: artifact-sync globs, shipping garbage with every sync
_TMP_RE = re.compile(
    r"^step_\d+(\.tmp|\.manifest\.tmp|\.orbax-checkpoint-tmp-.*)$"
)

MANIFEST_NAME = "manifest.json"


def _shape_desc(node: object) -> str:
    if isinstance(node, dict):
        return "a subtree"
    shape = tuple(getattr(node, "shape", ()) or ())
    return f"shape {shape}"


class CheckpointShapeError(ValueError):
    """A restore target (``like`` tree) does not match the checkpoint.

    Raised BEFORE deserialization with the first offending leaf path and
    both shapes — the alternative is a raw msgpack/XLA error from deep
    inside the stack that names neither."""

    def __init__(self, path: str, ckpt: object, like: object):
        self.path = path
        super().__init__(
            f"checkpoint/template mismatch at {path!r}: checkpoint has "
            f"{ckpt}, restore template has {like} — wrong model config or "
            "training mode for this checkpoint"
        )


class CheckpointManager:
    """Saves are ASYNC by default: ``save`` hands the (already host-side)
    tree to a background writer and returns, so serialization + disk IO
    overlap the next training steps — the standard TPU goodput lever.  At
    most one save is in flight; ``wait()`` (called automatically before the
    next save, any read, and by the trainer's exit path) is the durability
    barrier."""

    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        os.makedirs(self.directory, exist_ok=True)
        self._sweep_stale_tmp()
        self._ckptr = ocp.StandardCheckpointer()
        self._pending: threading.Thread | None = None
        self._pending_error: list[BaseException] = []

    def _sweep_stale_tmp(self) -> None:
        """Remove uncommitted ``step_N.tmp`` staging dirs left by a crash.

        A kill between ``_save_msgpack``'s makedirs and its atomic
        ``os.replace`` strands the staging dir forever: it is never a
        committed step (``_committed_steps`` ignores it) but it shadows the
        path of a FUTURE save of the same step — and it silently leaks disk
        on every crash.  Init is the safe sweep point: this manager is the
        directory's single writer and no save is in flight yet.
        """
        import shutil

        for name in os.listdir(self.directory):
            if not _TMP_RE.match(name):
                continue
            path = os.path.join(self.directory, name)
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                try:
                    os.remove(path)
                except OSError:
                    logger.warning("could not remove stale staging %s", name)
            logger.warning("swept stale uncommitted checkpoint staging %s", name)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def wait(self) -> None:
        """Block until any in-flight save is committed to disk.

        Re-raises a background save's exception — a swallowed disk-full here
        would let a preempted job exit believing its checkpoint committed."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        self._ckptr.wait_until_finished()
        if self._pending_error:
            err = self._pending_error.pop()
            raise RuntimeError(f"background checkpoint save failed: {err}") from err

    def _committed_steps(self) -> list[int]:
        """Step dirs already committed on disk (does NOT wait — an in-flight
        save's dir only appears at its atomic rename)."""
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and os.path.exists(os.path.join(self.directory, name)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def all_steps(self) -> list[int]:
        self.wait()
        return self._committed_steps()

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _save_sync(self, path: str, tree: Any, manifest: dict | None) -> None:
        try:
            if jax.process_count() > 1:
                # Orbax's save is itself a cross-process collective
                # (sync_global_processes barriers); on multi-host only rank 0
                # calls save with an already-gathered host tree, so use a
                # non-collective msgpack writer (atomic tmp-dir rename — the
                # manifest rides inside the staging dir, so commit is atomic
                # for both).
                self._save_msgpack(path, tree, manifest)
            else:
                self._ckptr.save(path, tree)
                self._ckptr.wait_until_finished()
                if manifest is not None:
                    self._write_manifest(path, manifest)
        except BaseException as exc:  # noqa: BLE001 — re-raised from wait()
            logger.exception("background checkpoint save to %s failed", path)
            # ftc: ignore[shared-mutable-without-lock] -- single in-flight writer thread (save() waits before starting another); list.append is GIL-atomic and drained only after join() in wait()
            self._pending_error.append(exc)

    def save(
        self,
        step: int,
        tree: Any,
        force: bool = False,
        blocking: bool = False,
        manifest: dict | None = None,
    ) -> None:
        self.wait()  # one in-flight save at a time (raises on a prior failure)
        path = self._path(step)
        if os.path.exists(path):
            if not force:
                return
            import shutil

            shutil.rmtree(path)
        # gc BEFORE starting the writer: gc lists only committed dirs, so it
        # must not (and does not) wait on the save we are about to start —
        # the whole point is overlapping serialization + IO with training
        self._gc()
        self._pending = threading.Thread(
            target=self._save_sync, args=(path, tree, manifest), daemon=False
        )
        self._pending.start()
        if blocking:
            self.wait()

    @staticmethod
    def _save_msgpack(path: str, tree: Any, manifest: dict | None = None) -> None:
        import json

        from flax import serialization

        tmp = path + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
            f.write(serialization.to_bytes(tree))
        if manifest is not None:
            with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
                json.dump(manifest, f)
        os.replace(tmp, path)

    def _write_manifest(self, path: str, manifest: dict) -> None:
        """Stage-and-rename the manifest into an already-committed step dir
        (the Orbax path commits the tree itself, so the manifest lands right
        after; a kill in the gap leaves a manifest-less checkpoint, which
        restore treats as legacy, and the ``.manifest.tmp`` stray is swept
        at the next init)."""
        import json

        tmp = path + ".manifest.tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(path, MANIFEST_NAME))

    def load_manifest(self, step: int) -> dict | None:
        """The step's ``manifest.json`` (``train/elastic.py`` schema), or
        None for a pre-manifest (legacy) checkpoint."""
        import json

        self.wait()
        path = os.path.join(self._path(step), MANIFEST_NAME)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    @staticmethod
    def _validate_like(path_prefix: str, ckpt_node: Any, like_node: Any) -> None:
        """Walk checkpoint/template state-dicts together; raise
        :class:`CheckpointShapeError` at the first structural or shape
        mismatch instead of letting msgpack/XLA fail opaquely later."""
        ckpt_is_map = isinstance(ckpt_node, dict)
        like_is_map = isinstance(like_node, dict)
        if ckpt_is_map != like_is_map:
            raise CheckpointShapeError(
                path_prefix or "<root>",
                "a subtree" if ckpt_is_map else _shape_desc(ckpt_node),
                "a subtree" if like_is_map else _shape_desc(like_node),
            )
        if not ckpt_is_map:
            cs = tuple(getattr(ckpt_node, "shape", ()) or ())
            ls = tuple(getattr(like_node, "shape", ()) or ())
            if cs != ls:
                raise CheckpointShapeError(
                    path_prefix or "<root>", f"shape {cs}", f"shape {ls}"
                )
            return
        for key in sorted(set(ckpt_node) | set(like_node)):
            sub = f"{path_prefix}/{key}" if path_prefix else str(key)
            if key not in ckpt_node:
                raise CheckpointShapeError(sub, "<missing>", _shape_desc(like_node[key]))
            if key not in like_node:
                raise CheckpointShapeError(sub, _shape_desc(ckpt_node[key]), "<missing>")
            CheckpointManager._validate_like(sub, ckpt_node[key], like_node[key])

    def _validate_manifest_like(self, step: int, like: Any) -> bool:
        """Validate ``like`` against the step's manifest leaf map; returns
        False when no manifest exists (legacy checkpoint)."""
        manifest = self.load_manifest(step)
        leaves = (manifest or {}).get("leaves")
        if not leaves:
            return False
        from .elastic import leaf_entries

        like_leaves = leaf_entries(like)
        for path in sorted(set(leaves) | set(like_leaves)):
            if path not in leaves:
                raise CheckpointShapeError(
                    path, "<missing>", f"shape {tuple(like_leaves[path]['shape'])}"
                )
            if path not in like_leaves:
                raise CheckpointShapeError(
                    path, f"shape {tuple(leaves[path]['shape'])}", "<missing>"
                )
            cs = tuple(leaves[path]["shape"])
            ls = tuple(like_leaves[path]["shape"])
            if cs != ls:
                raise CheckpointShapeError(path, f"shape {cs}", f"shape {ls}")
        return True

    def restore(self, step: int, like: Any | None = None) -> Any:
        self.wait()
        path = self._path(step)
        if like is not None:
            self._validate_manifest_like(step, like)
        msgpack_file = os.path.join(path, "state.msgpack")
        if os.path.exists(msgpack_file):
            from flax import serialization

            with open(msgpack_file, "rb") as f:
                data = f.read()
            if like is None:
                return serialization.msgpack_restore(data)
            # validate against the raw bytes too (covers manifest-less
            # checkpoints): a mismatched template must name the leaf, not
            # die in from_bytes with a msgpack structure error
            raw = serialization.msgpack_restore(data)
            self._validate_like("", raw, serialization.to_state_dict(like))
            return serialization.from_state_dict(like, raw)
        return self._ckptr.restore(path, target=like)

    def restore_latest(self, like: Any | None = None) -> tuple[int, Any] | None:
        step = self.latest_step()
        if step is None:
            return None
        return step, self.restore(step, like)

    def _gc(self) -> None:
        steps = self._committed_steps()
        for step in steps[: -self.keep]:
            import shutil

            shutil.rmtree(self._path(step), ignore_errors=True)
            logger.info("gc'd checkpoint step_%d", step)


def reshard(tree: Any, shardings: Any) -> Any:
    """Place a host-restored tree onto devices with the given shardings."""
    return jax.tree.map(lambda x, s: jax.device_put(x, s), tree, shardings)
