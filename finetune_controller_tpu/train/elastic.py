"""Topology-portable checkpoints: the manifest and the elastic-resume plan.

The VirtualFlow idea (PAPERS.md) on our substrate: decouple the persisted
model state from the hardware shape so a job checkpointed on one mesh can
resume on another — fewer chips after a capacity loss, more chips when the
scheduler grows it back.  The state itself has been portable since PR 3
(``state_to_host`` gathers full global arrays; restore re-shards via
``sharding_for_tree`` on whatever mesh is live), so what this module adds is
the *contract* that makes cross-topology restore safe instead of accidental:

- every committed checkpoint carries a ``manifest.json`` describing the mesh
  it was written from, the partition-rule fingerprint, the global batch
  semantics, and the per-leaf shape/dtype map;
- restore validates the manifest against the live trainer (rule fingerprint,
  leaf shapes) and *recomputes the batch microstructure* — per-device batch
  and ``grad_accum_steps`` — so the optimizer sees the same global batch
  decomposed over the same row-shards, whatever the new chip count.

Numerics contract (docs/elasticity.md): restoring onto a different mesh
preserves every state leaf bit-for-bit, and the global batch semantics are
identical, but gradient *reductions* cross device boundaries differently on
a different topology, so trajectories match to reduction-order tolerance —
not bit-for-bit the way same-shape resume does (``tests/test_chaos.py``).
Same-shape resume through this path stays bit-identical.
"""

from __future__ import annotations

import dataclasses
import logging
import math
from typing import Any, Mapping

logger = logging.getLogger(__name__)

#: manifest schema version (bump on incompatible changes)
MANIFEST_FORMAT = 1

#: mesh axes whose product shards the batch dimension (mirrors
#: ``parallel.mesh.AxisNames.BATCH_AXES`` without importing jax here — this
#: module must stay importable by the control plane, which has no device)
_BATCH_AXES = ("dp", "fsdp")


class ElasticManifestError(ValueError):
    """A checkpoint manifest is incompatible with the live trainer (rule
    fingerprint mismatch, unsatisfiable batch decomposition, ...)."""


def leaf_entries(host_tree: Any) -> dict[str, dict[str, Any]]:
    """``path -> {shape, dtype}`` over a host state tree.

    Paths are the ``/``-joined state-dict keys — the same addressing the
    msgpack/orbax serialization uses, so restore-time validation speaks the
    format's own language when it names an offending leaf.
    """
    from flax import serialization

    out: dict[str, dict[str, Any]] = {}

    def walk(prefix: str, node: Any) -> None:
        if isinstance(node, Mapping):
            for k in sorted(node):
                walk(f"{prefix}/{k}" if prefix else str(k), node[k])
            return
        shape = tuple(getattr(node, "shape", ()) or ())
        dtype = str(getattr(node, "dtype", type(node).__name__))
        out[prefix] = {"shape": list(shape), "dtype": dtype}

    walk("", serialization.to_state_dict(host_tree))
    return out


def build_manifest(
    *,
    step: int,
    mesh_axes: Mapping[str, int],
    rule_fingerprint: str,
    global_batch_size: int,
    grad_accum_steps: int,
    seq_len: int,
    seed: int,
    host_tree: Any,
) -> dict[str, Any]:
    """Assemble the manifest dict the :class:`CheckpointManager` persists
    alongside the state (``manifest.json`` in the committed step dir)."""
    axes = {k: int(v) for k, v in mesh_axes.items()}
    shards = _batch_shards(axes, grad_accum_steps)
    return {
        "format": MANIFEST_FORMAT,
        "step": int(step),
        "mesh_axes": axes,
        "rule_fingerprint": rule_fingerprint,
        "global_batch_size": int(global_batch_size),
        "grad_accum_steps": int(grad_accum_steps),
        #: row-shards the global batch was reduced over — the quantity
        #: elastic resume preserves (see :func:`plan_elastic_resume`)
        "batch_shards": shards,
        "seq_len": int(seq_len),
        "seed": int(seed),
        "leaves": leaf_entries(host_tree),
    }


def _batch_shards(mesh_axes: Mapping[str, int], grad_accum_steps: int) -> int:
    """Row-groups the global batch is decomposed into: one per batch-axis
    device shard per accumulation microstep."""
    devs = math.prod(int(mesh_axes.get(a, 1)) for a in _BATCH_AXES)
    return max(1, devs) * max(1, int(grad_accum_steps))


@dataclasses.dataclass(frozen=True)
class ElasticPlan:
    """How to resume a checkpoint on the live mesh."""

    #: axis sizes of the mesh the checkpoint was written from
    source_axes: dict[str, int]
    #: axis sizes of the mesh we are restoring onto
    target_axes: dict[str, int]
    #: grad_accum_steps to run with on the target mesh
    grad_accum_steps: int
    #: True when the target mesh differs from the source (a real reshard)
    topology_changed: bool
    #: True when the recomputed microstructure preserves the checkpoint's
    #: exact row-shard decomposition (gradient semantics carry over exactly;
    #: False means the batch had to be re-decomposed — semantics preserved,
    #: microstructure not)
    microstructure_preserved: bool


def check_fingerprint(manifest: Mapping[str, Any], rule_fingerprint: str) -> None:
    """Refuse a manifest whose partition-rule fingerprint doesn't match the
    live model's rule table — restoring through a different table would
    silently mis-shard the state."""
    have = manifest.get("rule_fingerprint")
    if have and have != rule_fingerprint:
        raise ElasticManifestError(
            f"checkpoint partition-rule fingerprint {have} does not match "
            f"the model's rule table {rule_fingerprint}: the checkpoint was "
            "written under different sharding rules — restore refused "
            "(docs/elasticity.md)"
        )


def plan_elastic_resume(
    manifest: Mapping[str, Any],
    target_mesh_axes: Mapping[str, int],
    *,
    batch_size: int,
    grad_accum_steps: int,
) -> ElasticPlan:
    """Recompute the batch microstructure for the target mesh.

    Invariant: the *global* batch (``batch_size`` rows per optimizer step)
    never changes — the optimizer sees the same data whatever the topology.
    The knob that absorbs a chip-count change is ``grad_accum_steps``: we
    keep ``batch_shards = (dp·fsdp) · grad_accum`` equal to the
    checkpoint's whenever the target's batch-device count divides it, so
    each row-shard (the grain a gradient contraction runs over on one
    device) holds exactly the same rows as before.  Shrinking dp=2→dp=1
    turns a 2-device step into a 2-microbatch accumulated step; growing
    back restores the original decomposition.

    Falls back to the smallest feasible ``grad_accum`` (divisibility of the
    global batch over shards still enforced) when the shard count doesn't
    divide — global batch semantics still hold, only the microstructure is
    re-decomposed.
    """
    source_axes = {k: int(v) for k, v in manifest.get("mesh_axes", {}).items()}
    target_axes = {k: int(v) for k, v in target_mesh_axes.items()}
    # normalise for comparison: an absent axis is a size-1 axis
    axis_names = set(source_axes) | set(target_axes)
    src_norm = {a: source_axes.get(a, 1) for a in axis_names}
    tgt_norm = {a: target_axes.get(a, 1) for a in axis_names}
    man_batch = int(manifest.get("global_batch_size") or batch_size)
    if man_batch != batch_size:
        # not fatal — the job spec is the source of truth for the CURRENT
        # run — but a changed global batch means the trajectory is a new
        # experiment, not a continuation; say so loudly
        logger.warning(
            "elastic resume: global batch_size changed %d -> %d; the loss "
            "trajectory will not continue the checkpointed run's",
            man_batch, batch_size,
        )
    shards = int(manifest.get("batch_shards") or 0)
    if shards <= 0:
        shards = _batch_shards(source_axes, int(manifest.get("grad_accum_steps", 1)))
    target_devs = math.prod(int(target_axes.get(a, 1)) for a in _BATCH_AXES)
    target_devs = max(1, target_devs)

    preserved = True
    if shards % target_devs == 0 and batch_size % shards == 0:
        accum = shards // target_devs
    else:
        # shard count not representable on this mesh: re-decompose with the
        # requested accumulation, clamped to divisibility
        preserved = False
        accum = max(1, int(grad_accum_steps))
        while accum > 1 and (
            batch_size % accum or (batch_size // accum) % target_devs
        ):
            accum -= 1
    if batch_size % (target_devs * accum):
        raise ElasticManifestError(
            f"global batch_size {batch_size} cannot be decomposed over "
            f"{target_devs} batch-axis devices x {accum} accumulation steps "
            f"on the target mesh {target_axes} — adjust batch_size or the "
            "mesh policy"
        )
    topology_changed = bool(source_axes) and src_norm != tgt_norm
    return ElasticPlan(
        source_axes=source_axes,
        target_axes=target_axes,
        grad_accum_steps=accum,
        topology_changed=topology_changed,
        microstructure_preserved=preserved,
    )


def largest_feasible_slices(
    total_chips_per_slice: int, num_slices: int, quota: int
) -> int:
    """Largest slice count ``<= num_slices`` that fits a chip quota; 0 when
    even one slice does not fit.  Used by the retry supervisor to downgrade
    a recorded topology that no longer fits the device catalog (e.g. the
    catalog shrank across a controller restart) instead of stranding the
    job."""
    if total_chips_per_slice <= 0:
        return 0
    return max(0, min(num_slices, quota // total_chips_per_slice))
