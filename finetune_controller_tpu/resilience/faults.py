"""Deterministic, seeded fault injection — the chaos harness's hand on the
process.

The recovery paths (``resilience/supervisor.py``, the trainer's
save-on-SIGTERM, checkpoint resume) are exactly the code that never runs in a
happy-path test.  This module makes failures *reproducible inputs*:

- **kill-at-step** (``StepFault``): the trainer, at a chosen global step,
  sends a chosen signal to itself.  Armed through the environment (the
  backend's ``extra_env`` seam), fired at most once per ``once_file`` so the
  respawned attempt runs clean — which is precisely the spot-preemption
  shape: one revocation, then a healthy pool.
- **store faults** (``FaultyObjectStore``): a wrapper over any ObjectStore
  whose write paths fail (or stall) on a seeded schedule, for exercising the
  artifact-sync and checkpoint-restore error paths without monkeypatching.
- **serve faults** (``ServeFault``): the serve-plane mirror of ``StepFault``
  — a chosen fleet replica is killed (its decode step raises
  :class:`ReplicaKilled`) or wedged (its decode step stops making progress
  while holding lanes) when that replica's engine reaches a chosen decode
  step.  Armed through ``FTC_FAULT_SERVE_*``; the serve-chaos tests and
  ``BENCH_MODE=serve`` share this one injection path
  (docs/serving.md §Fleet).

Nothing here imports controller or serve modules; the trainer arms
``StepFault`` in pods that carry no controller extras, and the serve fleet
arms ``ServeFault`` by wrapping an engine's ``step`` callable it passes in.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import signal

logger = logging.getLogger(__name__)

ENV_KILL_AT_STEP = "FTC_FAULT_KILL_AT_STEP"
ENV_SIGNAL = "FTC_FAULT_SIGNAL"
ENV_ONCE_FILE = "FTC_FAULT_ONCE_FILE"

ENV_SERVE_REPLICA = "FTC_FAULT_SERVE_REPLICA"
ENV_SERVE_AT_STEP = "FTC_FAULT_SERVE_AT_STEP"
ENV_SERVE_MODE = "FTC_FAULT_SERVE_MODE"
ENV_SERVE_ONCE_FILE = "FTC_FAULT_SERVE_ONCE_FILE"


@dataclasses.dataclass(frozen=True)
class StepFault:
    """One scheduled kill: ``signum`` to self when training reaches
    ``kill_at_step``."""

    kill_at_step: int
    signum: int = signal.SIGTERM
    #: marker file created when the fault fires; while it exists the fault is
    #: spent — the respawned attempt (same env) runs clean. None = fire on
    #: every attempt that reaches the step.
    once_file: str | None = None

    def to_env(self) -> dict[str, str]:
        """Render for a backend's ``extra_env`` (the injection seam)."""
        env = {
            ENV_KILL_AT_STEP: str(self.kill_at_step),
            ENV_SIGNAL: str(int(self.signum)),
        }
        if self.once_file:
            env[ENV_ONCE_FILE] = self.once_file
        return env

    @classmethod
    def from_env(cls, env=os.environ) -> "StepFault | None":
        raw = env.get(ENV_KILL_AT_STEP)
        if not raw:
            return None
        try:
            step = int(raw)
            signum = int(env.get(ENV_SIGNAL, str(int(signal.SIGTERM))))
        except ValueError:
            logger.warning("ignoring malformed fault env: %s=%r",
                           ENV_KILL_AT_STEP, raw)
            return None
        return cls(kill_at_step=step, signum=signum,
                   once_file=env.get(ENV_ONCE_FILE) or None)


class StepFaultInjector:
    """Trainer-side trigger: call :meth:`maybe_fire` once per completed step."""

    def __init__(self, fault: StepFault):
        self.fault = fault
        self.fired = False

    @classmethod
    def from_env(cls, env=os.environ) -> "StepFaultInjector | None":
        fault = StepFault.from_env(env)
        return cls(fault) if fault is not None else None

    def maybe_fire(self, step: int) -> bool:
        """Send the configured signal to this process when ``step`` matches.

        Returns True when the signal was sent.  With SIGTERM the trainer's
        PreemptionGuard turns this into the graceful checkpoint-and-exit-143
        path; SIGKILL tests the crash-without-save path.
        """
        if self.fired or step < self.fault.kill_at_step:
            return False
        once = self.fault.once_file
        if once:
            if os.path.exists(once):
                return False  # spent on a previous attempt
            # create BEFORE the kill: a SIGKILL gives no chance afterwards
            with open(once, "w") as f:
                f.write(f"fired at step {step}\n")
        self.fired = True
        logger.warning("fault injection: sending signal %d to self at step %d",
                       self.fault.signum, step)
        os.kill(os.getpid(), self.fault.signum)
        return True


class FaultInjectionError(OSError):
    """The injected store failure (distinct type so tests can assert on it)."""


class FaultyObjectStore:
    """Seeded write-error / slow-I/O wrapper around any ObjectStore.

    Write-path methods (``put_bytes``/``put_file``/``put_stream``) fail with
    :class:`FaultInjectionError` with probability ``write_error_rate`` drawn
    from a seeded RNG — the schedule is a pure function of the seed and the
    call sequence, so a chaos test replays identically.  ``slow_io_s`` adds a
    fixed pre-operation delay to reads and writes (the degraded-store shape).
    Everything else delegates to the wrapped store untouched.
    """

    def __init__(
        self,
        inner,
        *,
        write_error_rate: float = 0.0,
        slow_io_s: float = 0.0,
        seed: int = 0,
    ):
        self._inner = inner
        self.write_error_rate = write_error_rate
        self.slow_io_s = slow_io_s
        self._rng = random.Random(seed)
        self.injected_errors = 0
        self.write_calls = 0

    async def _maybe_fail(self, op: str, uri: str) -> None:
        if self.slow_io_s > 0:
            import asyncio

            await asyncio.sleep(self.slow_io_s)
        self.write_calls += 1
        if self._rng.random() < self.write_error_rate:
            self.injected_errors += 1
            raise FaultInjectionError(f"injected {op} failure for {uri}")

    async def put_bytes(self, uri, data):
        await self._maybe_fail("put_bytes", uri)
        return await self._inner.put_bytes(uri, data)

    async def put_file(self, uri, path):
        await self._maybe_fail("put_file", uri)
        return await self._inner.put_file(uri, path)

    async def put_stream(self, uri, chunks):
        await self._maybe_fail("put_stream", uri)
        return await self._inner.put_stream(uri, chunks)

    def __getattr__(self, name):
        # reads, listings, helpers: pass through (slow_io applies to writes
        # only — read-side degradation is a different experiment)
        return getattr(self._inner, name)


# ---------------------------------------------------------------------------
# Serve-plane faults (docs/serving.md §Fleet)
# ---------------------------------------------------------------------------


class ReplicaKilled(RuntimeError):
    """The injected replica crash (raised from the victim's decode step).

    A distinct type so tests can assert on the injection, but the router
    deliberately does NOT special-case it: the failover path classifies it
    like any other decode fault (``resilience.policy.classify_failure``), so
    the chaos harness exercises exactly the code path a real XLA fault takes.
    """


@dataclasses.dataclass(frozen=True)
class ServeFault:
    """One scheduled serve-replica failure: when replica ``replica_id``'s
    engine reaches decode step ``at_step`` with work in flight, its step
    either raises (``mode="kill"`` — the crashed-replica shape) or silently
    stops advancing while holding its lanes (``mode="stall"`` — the
    stuck-decode shape the health check must catch)."""

    replica_id: str
    at_step: int
    mode: str = "kill"  # "kill" | "stall"
    #: marker file created when the fault fires; while it exists the fault
    #: is spent — the restarted replica (same env) runs clean.  None = the
    #: fault re-arms on every matching replica that reaches the step.
    once_file: str | None = None

    def to_env(self) -> dict[str, str]:
        env = {
            ENV_SERVE_REPLICA: self.replica_id,
            ENV_SERVE_AT_STEP: str(self.at_step),
            ENV_SERVE_MODE: self.mode,
        }
        if self.once_file:
            env[ENV_SERVE_ONCE_FILE] = self.once_file
        return env

    @classmethod
    def from_env(cls, env=os.environ) -> "ServeFault | None":
        replica = env.get(ENV_SERVE_REPLICA)
        raw_step = env.get(ENV_SERVE_AT_STEP)
        if not replica or not raw_step:
            return None
        try:
            at_step = int(raw_step)
        except ValueError:
            logger.warning("ignoring malformed serve fault env: %s=%r",
                           ENV_SERVE_AT_STEP, raw_step)
            return None
        mode = env.get(ENV_SERVE_MODE, "kill").strip().lower()
        if mode not in ("kill", "stall"):
            logger.warning("ignoring unknown serve fault mode %r", mode)
            return None
        return cls(replica_id=replica, at_step=at_step, mode=mode,
                   once_file=env.get(ENV_SERVE_ONCE_FILE) or None)


class ServeFaultInjector:
    """Fleet-side trigger: wraps the victim replica's ``engine.step``.

    The wrapper fires once per injector when the engine's ``steps_total``
    reaches the fault's step WITH requests in flight (a mid-workload kill,
    not an idle one).  ``kill`` raises :class:`ReplicaKilled` — the batcher's
    step-fault path fails the in-flight futures and the router retries them
    on a survivor; ``stall`` returns no progress while the lanes stay held —
    only the fleet's stalled-decode health check can catch that shape.
    """

    def __init__(self, fault: ServeFault):
        self.fault = fault
        self.fired = False

    @classmethod
    def from_env(cls, env=os.environ) -> "ServeFaultInjector | None":
        fault = ServeFault.from_env(env)
        return cls(fault) if fault is not None else None

    def _spend_once(self) -> bool:
        """True when the fault may fire (and marks it spent)."""
        once = self.fault.once_file
        if once:
            if os.path.exists(once):
                return False  # spent by a previous replica/process
            with open(once, "w") as f:
                f.write(f"serve fault fired ({self.fault.mode})\n")
        return True

    def arm(self, replica_id: str, engine, *, hard_kill: bool = False) -> bool:
        """Wrap ``engine.step`` when ``replica_id`` matches; returns whether
        the replica was armed.

        ``hard_kill=True`` is the cross-process variant (the transport worker
        arms it, docs/serving.md §Cross-process transport): ``mode="kill"``
        sends a REAL ``SIGKILL`` to the worker process instead of raising —
        the socket drops, the heartbeat stops, and the fleet exercises the
        genuine crashed-worker detection path rather than an in-process
        stand-in.  ``mode="stall"`` behaves identically in both variants.
        """
        if replica_id != self.fault.replica_id:
            return False
        real_step = engine.step
        fault = self.fault

        def faulty_step():
            due = (
                not self.fired
                and engine.steps_total >= fault.at_step
                and engine.active_requests > 0
            )
            if due and self._spend_once():
                self.fired = True
                logger.warning(
                    "serve fault injection: %s replica %s at decode step %d",
                    fault.mode, replica_id, engine.steps_total,
                )
            if self.fired:
                if fault.mode == "kill":
                    if hard_kill:
                        os.kill(os.getpid(), signal.SIGKILL)
                    raise ReplicaKilled(
                        f"serve fault injection: replica {replica_id} killed "
                        f"at decode step {engine.steps_total}"
                    )
                return []  # stall: hold the lanes, make no progress
            return real_step()

        engine.step = faulty_step
        return True
