"""Resilience subsystem: supervised retry, liveness leases, fault injection.

Four parts (see docs/resilience.md):

- :mod:`.policy` — failure classification + capped decorrelated-jitter
  backoff (stdlib-only, deterministic under a seed);
- :mod:`.supervisor` — the reconciler that turns FAILED/UNKNOWN/stuck jobs
  into classified, backoff-scheduled, resume-from-checkpoint resubmissions;
- :mod:`.heartbeat` — trainer-side heartbeat emission through the artifact
  channel + the monitor-side lease check that catches silently-stuck jobs;
- :mod:`.faults` — seeded kill-at-step / store-fault injection driving the
  chaos tests (tests/test_chaos.py).

This ``__init__`` re-exports only the controller-free pieces: the trainer
imports :class:`HeartbeatWriter`/:class:`StepFaultInjector` inside pods that
carry no controller extras.  Import :class:`.supervisor.RetrySupervisor`
directly from its module (it pulls in controller schemas/registry).
"""

from .faults import FaultyObjectStore, StepFault, StepFaultInjector
from .heartbeat import HEARTBEAT_FILENAME, HeartbeatWriter, LeaseChecker
from .policy import RETRYABLE, FailureClass, RetryPolicy, classify_failure

__all__ = [
    "FailureClass",
    "RetryPolicy",
    "classify_failure",
    "RETRYABLE",
    "HeartbeatWriter",
    "LeaseChecker",
    "HEARTBEAT_FILENAME",
    "StepFault",
    "StepFaultInjector",
    "FaultyObjectStore",
]
