"""The retry supervisor: closes the controller half of the failure loop.

The trainer half of elastic recovery already exists (save-on-SIGTERM, atomic
Orbax checkpoints, ``restore_latest``); what was missing is the reconciler
that USES it: the reference monitor logs a warning on FAILED and walks away
(``app/core/monitor.py:187-191``), so no job is ever retried.

On a FAILED/UNKNOWN/stuck job the supervisor:

1. **classifies** the failure (``resilience/policy.py``) — infra/preemption
   is retryable, a deterministic user error is terminal;
2. **records the attempt** in the state store: the job moves to the new
   ``RETRYING`` status and its ``metadata.attempt_history`` gains an entry
   (attempt number, exit code, failure class, backoff delay) — the API
   serves this with the job document, so users see *why* their job is
   respawning;
3. **resubmits with resume**: after the backoff expires, the job is handed
   back to the backend with its original spec/flavor/dataset/artifacts URIs.
   The backend stages committed checkpoints back into the fresh substrate
   (``backends/local.py``), and the trainer's ``resume=True`` path continues
   from the latest committed step instead of restarting.

Crash-safety: the schedule lives in the job document (``retry_next_at``),
not in supervisor memory — a restarted control plane re-adopts every
RETRYING job on its first tick.
"""

from __future__ import annotations

import logging
import time
from typing import Any

from ..controller import registry
from ..controller.schemas import DatabaseStatus, JobInput, JobRecord
from .policy import FailureClass, RetryPolicy

logger = logging.getLogger(__name__)


class RetrySupervisor:
    """Reconciler woven into ``JobMonitor.tick`` (see controller/monitor.py)."""

    def __init__(
        self,
        state,
        backend,
        catalog,
        *,
        policy: RetryPolicy | None = None,
        _clock=time.time,
    ):
        self.state = state
        self.backend = backend
        self.catalog = catalog
        self.policy = policy or RetryPolicy()
        self._clock = _clock
        # observability (admin/resilience route)
        self.retries_scheduled = 0
        self.resubmits = 0
        self.terminal_failures = 0
        #: scheduler resizes routed through the retry loop (shrink + grow)
        self.resizes = 0
        #: resubmissions at a topology different from the previous attempt's
        #: — each one is a cross-topology (elastic) restore downstream
        self.elastic_restores = 0
        #: topologies downgraded because the recorded size no longer fits
        #: the device catalog (catalog shrank across a controller restart)
        self.topology_downgrades = 0

    # -- failure intake -------------------------------------------------------

    async def on_job_failed(
        self,
        job: JobRecord,
        *,
        exit_code: int | None = None,
        message: str = "",
        resize_to: int | None = None,
    ) -> bool:
        """Classify one failed attempt; schedule a retry or record the
        terminal failure.  Returns True when a retry was scheduled.

        ``resize_to`` marks a scheduler resize (docs/elasticity.md): the
        exit is deliberate (shrink or grow), so it neither burns the retry
        budget nor waits out a backoff — the resubmit topology is recorded
        crash-safe in ``metadata.current_num_slices`` and the job re-enters
        the queue immediately (its chips are reserved scheduler-side).
        """
        failure = self.policy.classify(exit_code, message)
        history = list(job.metadata.get("attempt_history") or [])
        # resizes are scheduler-initiated restarts, not failures: exempt
        # them from the attempt budget or steady contention churn would
        # terminally fail a healthy job
        attempt = 1 + sum(1 for h in history if not h.get("resize"))
        prev_delay = history[-1].get("delay_s") if history else None
        entry: dict[str, Any] = {
            "attempt": attempt,
            "ended_at": self._clock(),
            "exit_code": exit_code,
            "failure_class": failure.value,
            "message": message,
        }
        if resize_to is not None:
            entry["resize"] = True
            entry["resize_to_num_slices"] = int(resize_to)
        if resize_to is None and not self.policy.should_retry(failure, attempt):
            entry["delay_s"] = None
            history.append(entry)
            # compare-and-set from the status the caller snapshotted: a user
            # cancel interleaving inside the monitor tick's await windows
            # must win, not be overwritten by the failure transition
            ok = await self.state.transition_job_status(
                job.job_id,
                job.status,
                DatabaseStatus.FAILED,
                metadata={
                    "attempt_history": history,
                    "failure_class": failure.value,
                    "retry_next_at": None,
                },
                queue_position=None,
            )
            if not ok:
                logger.warning(
                    "job %s moved on during failure intake (user cancel?); "
                    "leaving it be", job.job_id,
                )
                return False
            self.terminal_failures += 1
            logger.warning(
                "job %s failed terminally (class=%s attempt=%d/%d): %s",
                job.job_id, failure.value, attempt,
                self.policy.max_attempts, message,
            )
            return False
        if resize_to is not None:
            # deliberate resize: chips are reserved for the resubmit, so a
            # backoff would only idle them — resume on the next tick
            delay = 0.0
        else:
            delay = self.policy.next_delay(prev_delay)
        entry["delay_s"] = delay
        history.append(entry)
        retry_metadata: dict[str, Any] = {
            "attempt_history": history,
            "failure_class": failure.value,
            "retry_next_at": self._clock() + delay,
        }
        if resize_to is not None:
            retry_metadata["current_num_slices"] = int(resize_to)
        ok = await self.state.transition_job_status(
            job.job_id,
            job.status,
            DatabaseStatus.RETRYING,
            metadata=retry_metadata,
            queue_position=None,
        )
        if not ok:
            logger.warning(
                "job %s moved on during failure intake (user cancel?); "
                "not scheduling a retry", job.job_id,
            )
            return False
        self.retries_scheduled += 1
        if resize_to is not None:
            self.resizes += 1
        # clear the substrate half now so the backoff window starts from a
        # clean slate (artifacts — including checkpoints — are already in
        # the object store; the final sync ran before FAILED became visible)
        try:
            await self.backend.delete_job(job.job_id)
        except Exception:
            logger.exception("substrate cleanup failed for %s", job.job_id)
        logger.warning(
            "job %s failed (class=%s, attempt %d/%d): retrying in %.1fs",
            job.job_id, failure.value, attempt, self.policy.max_attempts, delay,
        )
        return True

    # -- resubmission ---------------------------------------------------------

    async def tick(self) -> int:
        """Resubmit every RETRYING job whose backoff has expired; returns the
        number resubmitted.  Called from the monitor's reconcile pass."""
        now = self._clock()
        n = 0
        for job in await self.state.get_jobs_by_status(DatabaseStatus.RETRYING):
            due = job.metadata.get("retry_next_at")
            # a missing due time means a crash landed between the status
            # write and the metadata merge — treat as due NOW so the job
            # self-heals instead of sitting RETRYING forever
            if due is not None and due > now:
                continue
            if await self._resubmit(job):
                n += 1
        return n

    async def pending_retries(self) -> list[dict[str, Any]]:
        """Snapshot for the admin surface: jobs waiting out their backoff."""
        out = []
        for job in await self.state.get_jobs_by_status(DatabaseStatus.RETRYING):
            history = job.metadata.get("attempt_history") or []
            out.append({
                "job_id": job.job_id,
                "attempts": len(history),
                "failure_class": job.metadata.get("failure_class"),
                "retry_next_at": job.metadata.get("retry_next_at"),
            })
        return out

    async def _resubmit(self, job: JobRecord) -> bool:
        cls = registry.get_spec(job.model_name)
        if cls is None:
            # the model's spec class is gone (unloaded plugin): terminal —
            # there is nothing to render a submission from
            await self.state.update_job_status(
                job.job_id,
                DatabaseStatus.FAILED,
                metadata={
                    "failure_class": FailureClass.USER.value,
                    "retry_next_at": None,
                    "backend_message": (
                        f"model {job.model_name!r} is no longer registered"
                    ),
                },
                queue_position=None,
            )
            return False
        current = await self.state.get_job(job.job_id)
        if current is None or current.status is not DatabaseStatus.RETRYING:
            # cancelled (or otherwise moved on) while waiting out the backoff
            return False
        try:
            spec = cls(training_arguments=job.arguments)
            flavor = self.catalog.get_worker(job.device)
            # topology selection (docs/elasticity.md): resume at the
            # resized topology when one is recorded, else the original ask
            target = int(job.metadata.get("current_num_slices") or job.num_slices)
            downgraded_from: int | None = None
            quota = self.catalog.quota_for(flavor.name)
            if flavor.total_chips * target > quota:
                # the recorded topology no longer fits the device catalog
                # (catalog shrank across a controller restart): requeue at
                # the largest feasible size instead of stranding the job in
                # a submit-reject loop (ISSUE 7 satellite)
                from ..train.elastic import largest_feasible_slices

                feasible = largest_feasible_slices(
                    flavor.total_chips, target, quota
                )
                if feasible < 1:
                    await self.state.update_job_status(
                        job.job_id,
                        DatabaseStatus.FAILED,
                        metadata={
                            "failure_class": FailureClass.USER.value,
                            "retry_next_at": None,
                            "backend_message": (
                                f"device {flavor.name!r} quota ({quota} chips)"
                                f" no longer fits even one slice "
                                f"({flavor.total_chips} chips)"
                            ),
                        },
                        queue_position=None,
                    )
                    self.terminal_failures += 1
                    return False
                downgraded_from = target
                self.topology_downgrades += 1
                logger.warning(
                    "job %s: recorded topology %d slices of %s (%d chips) no "
                    "longer fits the quota (%d); downgrading to %d slices",
                    job.job_id, target, flavor.name,
                    flavor.total_chips * target, quota, feasible,
                )
                target = feasible
            prev_ran = int(job.metadata.get("last_ran_num_slices") or job.num_slices)
            await self.backend.submit(
                JobInput(
                    job_id=job.job_id,
                    user_id=job.user_id,
                    model_name=job.model_name,
                    device=job.device,
                    num_slices=target,
                    requested_num_slices=job.num_slices,
                    arguments=job.arguments,
                    # a retried (or preempted) job re-enters its tenant
                    # queue at its original priority (docs/scheduling.md)
                    queue=job.metadata.get("queue") or "default",
                    priority=job.metadata.get("priority", "normal"),
                ),
                spec,
                flavor,
                dataset_uri=job.dataset_uri,
                artifacts_uri=job.artifacts_uri,
            )
        except Exception as exc:
            logger.exception("resubmit of %s failed", job.job_id)
            # a failed resubmit is itself an infra failure: burn an attempt,
            # back off again (or land terminally once the budget is spent)
            await self.on_job_failed(
                job, exit_code=None, message=f"resubmit failed: {exc}"
            )
            return False
        resub_metadata: dict[str, Any] = {
            "retry_next_at": None,
            "current_num_slices": target,
            "last_ran_num_slices": target,
        }
        if downgraded_from is not None:
            resub_metadata["topology_downgraded"] = {
                "from_num_slices": downgraded_from,
                "to_num_slices": target,
                "at": self._clock(),
            }
        # compare-and-set: a user cancel can land inside submit's await
        # window, and resurrecting a job the user was told is cancelled
        # would be a silent override — on a lost race, roll the fresh
        # backend half back instead
        ok = await self.state.transition_job_status(
            job.job_id,
            DatabaseStatus.RETRYING,
            DatabaseStatus.QUEUED,
            metadata=resub_metadata,
            submitted_at=self._clock(),
            start_time=None,
            end_time=None,
            training_duration=None,
            queue_position=None,
        )
        if not ok:
            logger.warning(
                "job %s left RETRYING during resubmit (user cancel?); "
                "rolling the respawn back", job.job_id,
            )
            try:
                await self.backend.delete_job(job.job_id)
            except Exception:
                logger.exception("rollback of %s failed", job.job_id)
            return False
        self.resubmits += 1
        if target != prev_ran:
            # the next attempt restores the checkpoint onto a different
            # topology — the elastic-restore path (train/elastic.py)
            self.elastic_restores += 1
            logger.info(
                "job %s resubmitted at %d slices (previous attempt ran %d): "
                "elastic restore", job.job_id, target, prev_ran,
            )
        logger.info(
            "job %s resubmitted (attempt %d)", job.job_id,
            len(job.metadata.get("attempt_history") or []) + 1,
        )
        return True
