"""The retry supervisor: closes the controller half of the failure loop.

The trainer half of elastic recovery already exists (save-on-SIGTERM, atomic
Orbax checkpoints, ``restore_latest``); what was missing is the reconciler
that USES it: the reference monitor logs a warning on FAILED and walks away
(``app/core/monitor.py:187-191``), so no job is ever retried.

On a FAILED/UNKNOWN/stuck job the supervisor:

1. **classifies** the failure (``resilience/policy.py``) — infra/preemption
   is retryable, a deterministic user error is terminal;
2. **records the attempt** in the state store: the job moves to the new
   ``RETRYING`` status and its ``metadata.attempt_history`` gains an entry
   (attempt number, exit code, failure class, backoff delay) — the API
   serves this with the job document, so users see *why* their job is
   respawning;
3. **resubmits with resume**: after the backoff expires, the job is handed
   back to the backend with its original spec/flavor/dataset/artifacts URIs.
   The backend stages committed checkpoints back into the fresh substrate
   (``backends/local.py``), and the trainer's ``resume=True`` path continues
   from the latest committed step instead of restarting.

Crash-safety: the schedule lives in the job document (``retry_next_at``),
not in supervisor memory — a restarted control plane re-adopts every
RETRYING job on its first tick.
"""

from __future__ import annotations

import logging
import time
from typing import Any

from ..controller import registry
from ..controller.schemas import DatabaseStatus, JobInput, JobRecord
from .policy import FailureClass, RetryPolicy

logger = logging.getLogger(__name__)


class RetrySupervisor:
    """Reconciler woven into ``JobMonitor.tick`` (see controller/monitor.py)."""

    def __init__(
        self,
        state,
        backend,
        catalog,
        *,
        policy: RetryPolicy | None = None,
        _clock=time.time,
    ):
        self.state = state
        self.backend = backend
        self.catalog = catalog
        self.policy = policy or RetryPolicy()
        self._clock = _clock
        # observability (admin/resilience route)
        self.retries_scheduled = 0
        self.resubmits = 0
        self.terminal_failures = 0

    # -- failure intake -------------------------------------------------------

    async def on_job_failed(
        self,
        job: JobRecord,
        *,
        exit_code: int | None = None,
        message: str = "",
    ) -> bool:
        """Classify one failed attempt; schedule a retry or record the
        terminal failure.  Returns True when a retry was scheduled."""
        failure = self.policy.classify(exit_code, message)
        history = list(job.metadata.get("attempt_history") or [])
        attempt = len(history) + 1
        prev_delay = history[-1].get("delay_s") if history else None
        entry: dict[str, Any] = {
            "attempt": attempt,
            "ended_at": self._clock(),
            "exit_code": exit_code,
            "failure_class": failure.value,
            "message": message,
        }
        if not self.policy.should_retry(failure, attempt):
            entry["delay_s"] = None
            history.append(entry)
            # compare-and-set from the status the caller snapshotted: a user
            # cancel interleaving inside the monitor tick's await windows
            # must win, not be overwritten by the failure transition
            ok = await self.state.transition_job_status(
                job.job_id,
                job.status,
                DatabaseStatus.FAILED,
                metadata={
                    "attempt_history": history,
                    "failure_class": failure.value,
                    "retry_next_at": None,
                },
                queue_position=None,
            )
            if not ok:
                logger.warning(
                    "job %s moved on during failure intake (user cancel?); "
                    "leaving it be", job.job_id,
                )
                return False
            self.terminal_failures += 1
            logger.warning(
                "job %s failed terminally (class=%s attempt=%d/%d): %s",
                job.job_id, failure.value, attempt,
                self.policy.max_attempts, message,
            )
            return False
        delay = self.policy.next_delay(prev_delay)
        entry["delay_s"] = delay
        history.append(entry)
        ok = await self.state.transition_job_status(
            job.job_id,
            job.status,
            DatabaseStatus.RETRYING,
            metadata={
                "attempt_history": history,
                "failure_class": failure.value,
                "retry_next_at": self._clock() + delay,
            },
            queue_position=None,
        )
        if not ok:
            logger.warning(
                "job %s moved on during failure intake (user cancel?); "
                "not scheduling a retry", job.job_id,
            )
            return False
        self.retries_scheduled += 1
        # clear the substrate half now so the backoff window starts from a
        # clean slate (artifacts — including checkpoints — are already in
        # the object store; the final sync ran before FAILED became visible)
        try:
            await self.backend.delete_job(job.job_id)
        except Exception:
            logger.exception("substrate cleanup failed for %s", job.job_id)
        logger.warning(
            "job %s failed (class=%s, attempt %d/%d): retrying in %.1fs",
            job.job_id, failure.value, attempt, self.policy.max_attempts, delay,
        )
        return True

    # -- resubmission ---------------------------------------------------------

    async def tick(self) -> int:
        """Resubmit every RETRYING job whose backoff has expired; returns the
        number resubmitted.  Called from the monitor's reconcile pass."""
        now = self._clock()
        n = 0
        for job in await self.state.get_jobs_by_status(DatabaseStatus.RETRYING):
            due = job.metadata.get("retry_next_at")
            # a missing due time means a crash landed between the status
            # write and the metadata merge — treat as due NOW so the job
            # self-heals instead of sitting RETRYING forever
            if due is not None and due > now:
                continue
            if await self._resubmit(job):
                n += 1
        return n

    async def pending_retries(self) -> list[dict[str, Any]]:
        """Snapshot for the admin surface: jobs waiting out their backoff."""
        out = []
        for job in await self.state.get_jobs_by_status(DatabaseStatus.RETRYING):
            history = job.metadata.get("attempt_history") or []
            out.append({
                "job_id": job.job_id,
                "attempts": len(history),
                "failure_class": job.metadata.get("failure_class"),
                "retry_next_at": job.metadata.get("retry_next_at"),
            })
        return out

    async def _resubmit(self, job: JobRecord) -> bool:
        cls = registry.get_spec(job.model_name)
        if cls is None:
            # the model's spec class is gone (unloaded plugin): terminal —
            # there is nothing to render a submission from
            await self.state.update_job_status(
                job.job_id,
                DatabaseStatus.FAILED,
                metadata={
                    "failure_class": FailureClass.USER.value,
                    "retry_next_at": None,
                    "backend_message": (
                        f"model {job.model_name!r} is no longer registered"
                    ),
                },
                queue_position=None,
            )
            return False
        current = await self.state.get_job(job.job_id)
        if current is None or current.status is not DatabaseStatus.RETRYING:
            # cancelled (or otherwise moved on) while waiting out the backoff
            return False
        try:
            spec = cls(training_arguments=job.arguments)
            flavor = self.catalog.get_worker(job.device)
            await self.backend.submit(
                JobInput(
                    job_id=job.job_id,
                    user_id=job.user_id,
                    model_name=job.model_name,
                    device=job.device,
                    num_slices=job.num_slices,
                    arguments=job.arguments,
                    # a retried (or preempted) job re-enters its tenant
                    # queue at its original priority (docs/scheduling.md)
                    queue=job.metadata.get("queue") or "default",
                    priority=job.metadata.get("priority", "normal"),
                ),
                spec,
                flavor,
                dataset_uri=job.dataset_uri,
                artifacts_uri=job.artifacts_uri,
            )
        except Exception as exc:
            logger.exception("resubmit of %s failed", job.job_id)
            # a failed resubmit is itself an infra failure: burn an attempt,
            # back off again (or land terminally once the budget is spent)
            await self.on_job_failed(
                job, exit_code=None, message=f"resubmit failed: {exc}"
            )
            return False
        # compare-and-set: a user cancel can land inside submit's await
        # window, and resurrecting a job the user was told is cancelled
        # would be a silent override — on a lost race, roll the fresh
        # backend half back instead
        ok = await self.state.transition_job_status(
            job.job_id,
            DatabaseStatus.RETRYING,
            DatabaseStatus.QUEUED,
            metadata={"retry_next_at": None},
            submitted_at=self._clock(),
            start_time=None,
            end_time=None,
            training_duration=None,
            queue_position=None,
        )
        if not ok:
            logger.warning(
                "job %s left RETRYING during resubmit (user cancel?); "
                "rolling the respawn back", job.job_id,
            )
            try:
                await self.backend.delete_job(job.job_id)
            except Exception:
                logger.exception("rollback of %s failed", job.job_id)
            return False
        self.resubmits += 1
        logger.info(
            "job %s resubmitted (attempt %d)", job.job_id,
            len(job.metadata.get("attempt_history") or []) + 1,
        )
        return True
