"""The retry supervisor: closes the controller half of the failure loop.

The trainer half of elastic recovery already exists (save-on-SIGTERM, atomic
Orbax checkpoints, ``restore_latest``); what was missing is the reconciler
that USES it: the reference monitor logs a warning on FAILED and walks away
(``app/core/monitor.py:187-191``), so no job is ever retried.

On a FAILED/UNKNOWN/stuck job the supervisor:

1. **classifies** the failure (``resilience/policy.py``) — infra/preemption
   is retryable, a deterministic user error is terminal;
2. **records the attempt** in the state store: the job moves to the new
   ``RETRYING`` status and its ``metadata.attempt_history`` gains an entry
   (attempt number, exit code, failure class, backoff delay) — the API
   serves this with the job document, so users see *why* their job is
   respawning;
3. **resubmits with resume**: after the backoff expires, the job is handed
   back to the backend with its original spec/flavor/dataset/artifacts URIs.
   The backend stages committed checkpoints back into the fresh substrate
   (``backends/local.py``), and the trainer's ``resume=True`` path continues
   from the latest committed step instead of restarting.

Crash-safety: the schedule lives in the job document (``retry_next_at``),
not in supervisor memory — a restarted control plane re-adopts every
RETRYING job on its first tick.
"""

from __future__ import annotations

import logging
import time
from typing import Any

from ..controller import registry
from ..controller.schemas import DatabaseStatus, JobInput, JobRecord
from ..obs import events as obs_events
from ..obs.events import append_event_safe
from .policy import FailureClass, RetryPolicy

logger = logging.getLogger(__name__)


class RetrySupervisor:
    """Reconciler woven into ``JobMonitor.tick`` (see controller/monitor.py)."""

    def __init__(
        self,
        state,
        backend,
        catalog,
        *,
        policy: RetryPolicy | None = None,
        obs=None,
        _clock=time.time,
    ):
        self.state = state
        self.backend = backend
        self.catalog = catalog
        self.policy = policy or RetryPolicy()
        #: observability hub (obs/prom.py) for the retry-latency histogram
        self.obs = obs
        #: async ``(job_id) -> None`` hook fired after any terminal FAILED
        #: write — the monitor wires its trace export here, because several
        #: of these writes happen on paths its report loop never revisits
        #: (lease-kill/sweep budgets, resubmit failures inside tick)
        self.on_terminal = None
        self._clock = _clock
        # observability (admin/resilience route)
        self.retries_scheduled = 0
        self.resubmits = 0
        self.terminal_failures = 0
        #: scheduler resizes routed through the retry loop (shrink + grow)
        self.resizes = 0
        #: resubmissions at a topology different from the previous attempt's
        #: — each one is a cross-topology (elastic) restore downstream
        self.elastic_restores = 0
        #: topologies downgraded because the recorded size no longer fits
        #: the device catalog (catalog shrank across a controller restart)
        self.topology_downgrades = 0

    # -- failure intake -------------------------------------------------------

    async def _event(self, job_id: str, event: str, *, key: str,
                     **attrs) -> None:
        """Best-effort timeline append (docs/observability.md) — the retry
        loop must never stall on the timeline."""
        await append_event_safe(self.state, job_id, event, key=key, **attrs)

    async def _terminal(self, job_id: str) -> None:
        """Fire the terminal hook (best-effort)."""
        if self.on_terminal is None:
            return
        try:
            await self.on_terminal(job_id)
        except Exception:
            logger.debug("terminal hook failed for %s", job_id, exc_info=True)

    async def on_job_failed(
        self,
        job: JobRecord,
        *,
        exit_code: int | None = None,
        message: str = "",
        resize_to: int | None = None,
        report_metadata: dict[str, Any] | None = None,
    ) -> bool:
        """Classify one failed attempt; schedule a retry or record the
        terminal failure.  Returns True when a retry was scheduled.

        ``resize_to`` marks a scheduler resize (docs/elasticity.md): the
        exit is deliberate (shrink or grow), so it neither burns the retry
        budget nor waits out a backoff — the resubmit topology is recorded
        crash-safe in ``metadata.current_num_slices`` and the job re-enters
        the queue immediately (its chips are reserved scheduler-side).
        """
        failure = self.policy.classify(exit_code, message)
        history = list(job.metadata.get("attempt_history") or [])
        # resizes are scheduler-initiated restarts, not failures: exempt
        # them from the attempt budget or steady contention churn would
        # terminally fail a healthy job
        attempt = 1 + sum(1 for h in history if not h.get("resize"))
        prev_delay = history[-1].get("delay_s") if history else None
        entry: dict[str, Any] = {
            "attempt": attempt,
            "ended_at": self._clock(),
            "exit_code": exit_code,
            "failure_class": failure.value,
            "message": message,
        }
        if resize_to is not None:
            entry["resize"] = True
            entry["resize_to_num_slices"] = int(resize_to)
        if resize_to is None and not self.policy.should_retry(failure, attempt):
            entry["delay_s"] = None
            history.append(entry)
            # timeline BEFORE the CAS (the monitor's event-before-write
            # rule): a crash in between re-runs the intake (the report is
            # still there) and the key folds the retry into one event; an
            # event appended AFTER a committed CAS would be lost forever on
            # a crash — the intake never re-runs once the status moved.
            # Events carry the DISPATCH number (1+prior history entries,
            # resizes included) — the numbering the monitor's running
            # event, FTC_ATTEMPT, and the trainer spans all use; `attempt`
            # above is the budget count, which excludes resizes
            await self._event(
                job.job_id, obs_events.FAILED,
                key=f"failed:i{len(history)}",
                attempt=len(history), failure_class=failure.value,
                exit_code=exit_code, message=message or None, terminal=True,
            )
            # compare-and-set from the status the caller snapshotted: a user
            # cancel interleaving inside the monitor tick's await windows
            # must win, not be overwritten by the failure transition (the
            # pre-appended event then records an intake that lost its race
            # — the failure itself still happened)
            ok = await self.state.transition_job_status(
                job.job_id,
                job.status,
                DatabaseStatus.FAILED,
                metadata={
                    "attempt_history": history,
                    "failure_class": failure.value,
                    "retry_next_at": None,
                },
                queue_position=None,
            )
            if not ok:
                logger.warning(
                    "job %s moved on during failure intake (user cancel?); "
                    "leaving it be", job.job_id,
                )
                return False
            self.terminal_failures += 1
            await self._terminal(job.job_id)
            logger.warning(
                "job %s failed terminally (class=%s attempt=%d/%d): %s",
                job.job_id, failure.value, attempt,
                self.policy.max_attempts, message,
            )
            return False
        if resize_to is not None:
            # deliberate resize: chips are reserved for the resubmit, so a
            # backoff would only idle them — resume on the next tick
            delay = 0.0
        else:
            delay = self.policy.next_delay(prev_delay)
        entry["delay_s"] = delay
        history.append(entry)
        # timeline BEFORE the CAS (docs/observability.md): a resize or
        # preemption instant, then the retrying transition — keyed per
        # intake so a crash-rerun of the intake stays exactly-once, while a
        # crash AFTER a committed CAS can no longer lose them (the intake
        # never re-runs once the job is RETRYING)
        n = len(history)
        report_metadata = report_metadata or {}
        if resize_to is not None:
            await self._event(
                job.job_id, obs_events.RESIZED, key=f"resized:i{n}",
                kind=report_metadata.get("resize_kind") or None,
                to_slices=int(resize_to),
                by=report_metadata.get("preempted_by") or None,
            )
        elif report_metadata.get("preempted") or job.metadata.get("preempted"):
            await self._event(
                job.job_id, obs_events.PREEMPTED, key=f"preempted:i{n}",
                by=(report_metadata.get("preempted_by")
                    or job.metadata.get("preempted_by") or None),
                exit_code=exit_code,
            )
        # `attempt=n`: the dispatch that just ended (1+prior history entries,
        # resizes included) — matches the monitor's running event,
        # FTC_ATTEMPT, and the trainer spans; the budget count (`attempt`
        # above, resize-exempt) stays in the log line and attempt_history
        await self._event(
            job.job_id, obs_events.RETRYING, key=f"retrying:i{n}",
            attempt=n, failure_class=failure.value, delay_s=delay,
            resize=bool(resize_to is not None) or None,
        )
        retry_metadata: dict[str, Any] = {
            "attempt_history": history,
            "failure_class": failure.value,
            "retry_next_at": self._clock() + delay,
        }
        if resize_to is not None:
            retry_metadata["current_num_slices"] = int(resize_to)
        ok = await self.state.transition_job_status(
            job.job_id,
            job.status,
            DatabaseStatus.RETRYING,
            metadata=retry_metadata,
            queue_position=None,
        )
        if not ok:
            logger.warning(
                "job %s moved on during failure intake (user cancel?); "
                "not scheduling a retry", job.job_id,
            )
            return False
        self.retries_scheduled += 1
        if resize_to is not None:
            self.resizes += 1
        # clear the substrate half now so the backoff window starts from a
        # clean slate (artifacts — including checkpoints — are already in
        # the object store; the final sync ran before FAILED became visible)
        try:
            await self.backend.delete_job(job.job_id)
        except Exception:
            logger.exception("substrate cleanup failed for %s", job.job_id)
        logger.warning(
            "job %s failed (class=%s, attempt %d/%d): retrying in %.1fs",
            job.job_id, failure.value, attempt, self.policy.max_attempts, delay,
        )
        return True

    # -- resubmission ---------------------------------------------------------

    async def tick(self) -> int:
        """Resubmit every RETRYING job whose backoff has expired; returns the
        number resubmitted.  Called from the monitor's reconcile pass."""
        now = self._clock()
        n = 0
        for job in await self.state.get_jobs_by_status(DatabaseStatus.RETRYING):
            due = job.metadata.get("retry_next_at")
            # a missing due time means a crash landed between the status
            # write and the metadata merge — treat as due NOW so the job
            # self-heals instead of sitting RETRYING forever
            if due is not None and due > now:
                continue
            if await self._resubmit(job):
                n += 1
        return n

    async def pending_retries(self) -> list[dict[str, Any]]:
        """Snapshot for the admin surface: jobs waiting out their backoff."""
        out = []
        for job in await self.state.get_jobs_by_status(DatabaseStatus.RETRYING):
            history = job.metadata.get("attempt_history") or []
            out.append({
                "job_id": job.job_id,
                "attempts": len(history),
                "failure_class": job.metadata.get("failure_class"),
                "retry_next_at": job.metadata.get("retry_next_at"),
            })
        return out

    async def _resubmit(self, job: JobRecord) -> bool:
        cls = registry.get_spec(job.model_name)
        if cls is None:
            # the model's spec class is gone (unloaded plugin): terminal —
            # there is nothing to render a submission from
            await self.state.update_job_status(
                job.job_id,
                DatabaseStatus.FAILED,
                metadata={
                    "failure_class": FailureClass.USER.value,
                    "retry_next_at": None,
                    "backend_message": (
                        f"model {job.model_name!r} is no longer registered"
                    ),
                },
                queue_position=None,
            )
            await self._terminal(job.job_id)
            return False
        current = await self.state.get_job(job.job_id)
        if current is None or current.status is not DatabaseStatus.RETRYING:
            # cancelled (or otherwise moved on) while waiting out the backoff
            return False
        try:
            spec = cls(training_arguments=job.arguments)
            flavor = self.catalog.get_worker(job.device)
            # topology selection (docs/elasticity.md): resume at the
            # resized topology when one is recorded, else the original ask
            target = int(job.metadata.get("current_num_slices") or job.num_slices)
            downgraded_from: int | None = None
            quota = self.catalog.quota_for(flavor.name)
            if flavor.total_chips * target > quota:
                # the recorded topology no longer fits the device catalog
                # (catalog shrank across a controller restart): requeue at
                # the largest feasible size instead of stranding the job in
                # a submit-reject loop (ISSUE 7 satellite)
                from ..train.elastic import largest_feasible_slices

                feasible = largest_feasible_slices(
                    flavor.total_chips, target, quota
                )
                if feasible < 1:
                    await self.state.update_job_status(
                        job.job_id,
                        DatabaseStatus.FAILED,
                        metadata={
                            "failure_class": FailureClass.USER.value,
                            "retry_next_at": None,
                            "backend_message": (
                                f"device {flavor.name!r} quota ({quota} chips)"
                                f" no longer fits even one slice "
                                f"({flavor.total_chips} chips)"
                            ),
                        },
                        queue_position=None,
                    )
                    self.terminal_failures += 1
                    await self._terminal(job.job_id)
                    return False
                downgraded_from = target
                self.topology_downgrades += 1
                logger.warning(
                    "job %s: recorded topology %d slices of %s (%d chips) no "
                    "longer fits the quota (%d); downgrading to %d slices",
                    job.job_id, target, flavor.name,
                    flavor.total_chips * target, quota, feasible,
                )
                target = feasible
            prev_ran = int(job.metadata.get("last_ran_num_slices") or job.num_slices)
            attempt_no = 1 + len(job.metadata.get("attempt_history") or [])
            await self.backend.submit(
                JobInput(
                    job_id=job.job_id,
                    user_id=job.user_id,
                    model_name=job.model_name,
                    device=job.device,
                    num_slices=target,
                    requested_num_slices=job.num_slices,
                    arguments=job.arguments,
                    # a retried (or preempted) job re-enters its tenant
                    # queue at its original priority (docs/scheduling.md)
                    queue=job.metadata.get("queue") or "default",
                    priority=job.metadata.get("priority", "normal"),
                    # same trace across attempts; the attempt number stamps
                    # the trainer env/log stream (docs/observability.md)
                    trace_id=job.metadata.get("trace_id") or "",
                    attempt=attempt_no,
                ),
                spec,
                flavor,
                dataset_uri=job.dataset_uri,
                artifacts_uri=job.artifacts_uri,
            )
        except Exception as exc:
            logger.exception("resubmit of %s failed", job.job_id)
            # a failed resubmit is itself an infra failure: burn an attempt,
            # back off again (or land terminally once the budget is spent)
            await self.on_job_failed(
                job, exit_code=None, message=f"resubmit failed: {exc}"
            )
            return False
        resub_metadata: dict[str, Any] = {
            "retry_next_at": None,
            "current_num_slices": target,
            "last_ran_num_slices": target,
        }
        if downgraded_from is not None:
            resub_metadata["topology_downgraded"] = {
                "from_num_slices": downgraded_from,
                "to_num_slices": target,
                "at": self._clock(),
            }
        history = job.metadata.get("attempt_history") or []
        # event BEFORE the CAS (same rule as the failure intake): a crash in
        # between re-runs the resubmit and the key dedupes; after a
        # committed CAS the event could never be recovered
        await self._event(
            job.job_id, obs_events.RESUBMITTED,
            key=f"resubmitted:i{len(history)}",
            attempt=attempt_no, num_slices=target,
            downgraded_from=downgraded_from,
        )
        # compare-and-set: a user cancel can land inside submit's await
        # window, and resurrecting a job the user was told is cancelled
        # would be a silent override — on a lost race, roll the fresh
        # backend half back instead
        ok = await self.state.transition_job_status(
            job.job_id,
            DatabaseStatus.RETRYING,
            DatabaseStatus.QUEUED,
            metadata=resub_metadata,
            submitted_at=self._clock(),
            start_time=None,
            end_time=None,
            training_duration=None,
            queue_position=None,
        )
        if not ok:
            logger.warning(
                "job %s left RETRYING during resubmit (user cancel?); "
                "rolling the respawn back", job.job_id,
            )
            try:
                await self.backend.delete_job(job.job_id)
            except Exception:
                logger.exception("rollback of %s failed", job.job_id)
            return False
        self.resubmits += 1
        if self.obs is not None and history:
            # failure-to-resubmission latency (backoff + scheduling)
            ended = history[-1].get("ended_at")
            if isinstance(ended, (int, float)):
                self.obs.retry_latency_seconds.observe(
                    max(self._clock() - ended, 0.0)
                )
        if target != prev_ran:
            # the next attempt restores the checkpoint onto a different
            # topology — the elastic-restore path (train/elastic.py)
            self.elastic_restores += 1
            logger.info(
                "job %s resubmitted at %d slices (previous attempt ran %d): "
                "elastic restore", job.job_id, target, prev_ran,
            )
        logger.info(
            "job %s resubmitted (attempt %d)", job.job_id,
            len(job.metadata.get("attempt_history") or []) + 1,
        )
        return True
