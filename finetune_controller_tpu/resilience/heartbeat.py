"""Trainer liveness: heartbeat emission + the monitor-side lease check.

A job can stop making progress without exiting — a deadlocked collective, a
host stuck in disk wait, an input pipeline waiting on a dead socket.  The
backend sees a healthy process; the user sees a flat metrics curve and a
burning TPU reservation.  The reference has nothing for this (its monitor
maps pod phases only).

The loop closed here:

- the **trainer** writes ``heartbeat.json`` (step + wall-clock timestamp)
  into the artifacts dir on a throttle (``HeartbeatWriter``); the artifact
  sidecar ships it with everything else, so the heartbeat rides the existing
  artifact channel — no new transport, and it works on any backend whose
  artifacts sync;
- the **monitor** checks the lease (``LeaseChecker``): a RUNNING job whose
  latest heartbeat is older than ``lease_s`` is declared stuck, killed, and
  handed to the retry supervisor like any infra failure.

Safety property: a job that never emitted a heartbeat (older trainer image,
heartbeats disabled) is NEVER declared stuck — the lease only binds once the
trainer has proven it knows how to beat.  A heartbeat older than the current
attempt's start time is likewise ignored (it is the previous attempt's dying
breath, restored or re-synced).

Writer side is stdlib-only on purpose: the trainer imports it inside pods
that carry none of the controller extras.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any

logger = logging.getLogger(__name__)

HEARTBEAT_FILENAME = "heartbeat.json"


class HeartbeatWriter:
    """Throttled atomic heartbeat file writer (trainer side, rank 0 only)."""

    def __init__(
        self,
        artifacts_dir: str,
        interval_s: float = 10.0,
        *,
        _clock=time.time,
    ):
        self.path = os.path.join(artifacts_dir, HEARTBEAT_FILENAME)
        self.interval_s = interval_s
        self._clock = _clock
        self._started = _clock()
        self._last_write: float | None = None
        self.beats = 0  # observability / tests
        self.write_failures = 0

    def beat(self, step: int, *, step_ms: float | None = None,
             force: bool = False) -> bool:
        """Record liveness at ``step``; returns True when a write happened.

        ``step_ms`` is the most recent step's wall time — with ``last_step``
        it gives the monitor a per-job progress RATE, not just "alive"
        (``GET /admin/resilience`` surfaces both).

        Throttled to one write per ``interval_s`` so a milliseconds-scale
        step loop doesn't turn the heartbeat into an I/O hot path.  The write
        is tmp-then-rename atomic: the artifact sidecar must never ship a
        torn JSON file.
        """
        now = self._clock()
        if (
            not force
            and self._last_write is not None
            and now - self._last_write < self.interval_s
        ):
            return False
        payload = {
            "step": int(step),
            # explicit alias: consumers (admin surface, lease kill logs)
            # read last_step without knowing the writer's vintage
            "last_step": int(step),
            "ts": now,
            "wall_time_s": now - self._started,
            "pid": os.getpid(),
        }
        if step_ms is not None:
            payload["last_step_ms"] = round(float(step_ms), 3)
        tmp = f"{self.path}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)
        except OSError:
            # best-effort liveness aid: a transient ENOSPC/NFS blip must not
            # crash the training run it exists to protect — the lease side
            # already tolerates staleness up to lease_s
            self.write_failures += 1
            level = logging.WARNING if self.write_failures == 1 else logging.DEBUG
            logger.log(level, "heartbeat write to %s failed (%d so far)",
                       self.path, self.write_failures, exc_info=True)
            return False
        self._last_write = now
        self.beats += 1
        return True


def parse_heartbeat(raw: bytes | str) -> dict[str, Any] | None:
    """Decode a heartbeat document; None when torn/invalid (never raises)."""
    try:
        doc = json.loads(raw)
    except (ValueError, TypeError):
        return None
    if not isinstance(doc, dict) or not isinstance(doc.get("ts"), (int, float)):
        return None
    return doc


class LeaseChecker:
    """Monitor-side liveness lease over the object store.

    ``lease_s`` must comfortably exceed the artifact sync cadence (the
    heartbeat's freshness through the store is bounded by it) plus the
    heartbeat interval; the runtime wiring enforces a floor.
    """

    def __init__(self, store, *, lease_s: float = 300.0, _clock=time.time):
        self.store = store
        self.lease_s = lease_s
        self._clock = _clock
        #: the most recent heartbeat document :meth:`expired` parsed — the
        #: monitor reads ``last_step``/``last_step_ms`` from it when it kills
        #: a stuck job, and ``GET /admin/resilience`` renders progress from it
        self.last_heartbeat: dict[str, Any] | None = None

    async def expired(self, job, report) -> bool:
        """True when ``job`` (a RUNNING JobRecord) holds an expired lease.

        ``report`` is the backend's current BackendJobReport — its
        ``start_time`` anchors the current attempt so heartbeats from a
        previous attempt can't keep a stuck respawn alive (or kill a healthy
        one).
        """
        artifacts_uri = getattr(job, "artifacts_uri", None)
        if not artifacts_uri or self.lease_s <= 0:
            return False
        uri = f"{artifacts_uri}/{HEARTBEAT_FILENAME}"
        try:
            if not await self.store.exists(uri):
                return False  # trainer never beat: the lease does not bind
            raw = await self.store.get_bytes(uri)
        except Exception:
            # a store hiccup must not kill a healthy job
            logger.warning("lease check: heartbeat read failed for %s",
                           job.job_id, exc_info=True)
            return False
        hb = parse_heartbeat(raw)
        if hb is None:
            return False
        self.last_heartbeat = hb
        start = report.start_time if report.start_time is not None else (
            getattr(job, "start_time", None) or 0.0
        )
        if hb["ts"] < start:
            return False  # previous attempt's heartbeat — current one has grace
        return self._clock() - hb["ts"] > self.lease_s


def read_heartbeat_file(path: str) -> dict[str, Any] | None:
    """Read + parse a LOCAL heartbeat file (the serve-worker liveness path,
    docs/serving.md §Cross-process transport — the trainer/monitor pair reads
    through the object store instead, :class:`LeaseChecker`).  None when the
    file is missing, torn, or unreadable: the caller's lease must never bind
    on a beat the worker has not proven it can write.  Synchronous — async
    callers wrap it in ``asyncio.to_thread``."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None
    return parse_heartbeat(raw)
