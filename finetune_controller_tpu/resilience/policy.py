"""Retry policy: failure classification + capped decorrelated-jitter backoff.

The reference control plane has no retry semantics at all — a FAILED job is
left "in place for forensics" (``app/core/monitor.py:187-191``) and an
operator resubmits by hand.  On preemptible TPU pools most failures are not
the user's fault (spot reclaim, OOM-killed host agents, a substrate that
forgot the job across a controller restart), so the supervisor needs a way to
tell *infrastructure* failures (retry, the work is fine) from *user* failures
(terminal, retrying reruns the same crash) — and a backoff schedule that
neither hammers a sick substrate nor synchronizes a thundering herd of
respawns.

Everything here is stdlib-only and deterministic under a seed: the chaos
harness (``resilience/faults.py``) replays exact schedules, and the trainer
side can import this module without pulling controller dependencies.
"""

from __future__ import annotations

import dataclasses
import enum
import random


class FailureClass(str, enum.Enum):
    """Why a job stopped — the axis the retry decision turns on."""

    #: SIGTERM-shaped exits (spot reclaim, liveness-lease kill, eviction).
    #: The trainer checkpoints on SIGTERM (``train/trainer.py``
    #: PreemptionGuard), so a respawn resumes nearly for free.
    PREEMPTION = "preemption"
    #: the substrate failed the job: SIGKILL/OOM, the backend forgot it,
    #: object-store errors, a resubmit that itself failed
    INFRA = "infra"
    #: the job failed deterministically (bad hyperparameters, a crashing
    #: spec, data errors) — retrying replays the same crash
    USER = "user"
    #: not enough signal to classify
    UNKNOWN = "unknown"


#: classes worth another attempt.  UNKNOWN is retryable on purpose: the cost
#: of one wasted respawn is far below the cost of abandoning a long run over
#: a report the backend could not describe.
RETRYABLE: frozenset[FailureClass] = frozenset(
    {FailureClass.PREEMPTION, FailureClass.INFRA, FailureClass.UNKNOWN}
)

#: message fragments that identify an infrastructure failure when no exit
#: code is available (lease kills and lost-job sweeps synthesize these)
_INFRA_HINTS = (
    "lease expired",
    "no longer tracked",
    "vanished",
    "resubmit failed",
    "backend error",
    "artifact sync failed",
)

#: 128 + signal number exits, as the shell (and our subprocess backend) report
_SIGTERM_EXITS = frozenset({143, -15})
_SIGKILL_EXITS = frozenset({137, -9, 134, -6})  # SIGKILL/OOM + SIGABRT


def classify_failure(exit_code: int | None, message: str = "") -> FailureClass:
    """Map an exit code (+ free-text backend message) to a failure class.

    Exit-code conventions: ``143``/``-15`` is a SIGTERM exit — the trainer's
    save-and-exit preemption path uses exactly this code — and ``137``/``-9``
    is the OOM-killer / forced reclaim.  A plain ``1`` or ``2`` is a Python
    traceback or usage error: deterministic, therefore terminal.
    """
    msg = (message or "").lower()
    if exit_code in _SIGTERM_EXITS:
        return FailureClass.PREEMPTION
    if exit_code in _SIGKILL_EXITS:
        return FailureClass.INFRA
    if any(h in msg for h in _INFRA_HINTS):
        return FailureClass.INFRA
    if exit_code is not None and exit_code > 0:
        if exit_code in (1, 2):
            return FailureClass.USER
        if exit_code > 128:
            # some other fatal signal — treat as infrastructure
            return FailureClass.INFRA
        return FailureClass.USER
    return FailureClass.UNKNOWN


@dataclasses.dataclass
class RetryPolicy:
    """Max-attempt budget + capped exponential backoff with decorrelated jitter.

    The delay schedule is the "decorrelated jitter" variant (each delay drawn
    uniformly from ``[base, 3 * previous]``, capped): it decorrelates the
    respawn times of jobs that failed together — a revoked TPU pool takes
    every job down in the same second, and deterministic exponential backoff
    would march them all back in lockstep.

    ``seed`` makes the schedule reproducible (the chaos tests pin it);
    ``None`` seeds from entropy like any production backoff.
    """

    max_attempts: int = 3
    base_delay_s: float = 2.0
    max_delay_s: float = 60.0
    retry_on: frozenset[FailureClass] = RETRYABLE
    seed: int | None = None

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)

    def classify(self, exit_code: int | None, message: str = "") -> FailureClass:
        return classify_failure(exit_code, message)

    def should_retry(self, failure: FailureClass, attempt: int) -> bool:
        """``attempt`` is the 1-based number of the attempt that just failed;
        ``max_attempts`` bounds the TOTAL run count, so the last permitted
        attempt's failure is terminal."""
        return failure in self.retry_on and attempt < self.max_attempts

    def next_delay(self, prev_delay_s: float | None = None) -> float:
        """Decorrelated jitter: ``uniform(base, 3 * prev)`` capped at max."""
        prev = prev_delay_s if prev_delay_s else self.base_delay_s
        hi = max(self.base_delay_s, min(self.max_delay_s, 3.0 * prev))
        return self._rng.uniform(self.base_delay_s, hi)
