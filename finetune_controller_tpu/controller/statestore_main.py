"""Shared state-service daemon entrypoint.

``python -m finetune_controller_tpu.controller.statestore_main`` — the
process API×N replicas and the monitor point ``state_backend=remote`` at
(the role MongoDB plays for the reference, ``app/database/db.py:51``).

Env: ``FTC_STATE_TOKEN`` (bearer token the clients must present; strongly
recommended outside local dev).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="ftc-statestore")
    p.add_argument("--state-dir", required=True,
                   help="directory for the backing sqlite-WAL database")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8081)
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )

    from aiohttp import web

    from .statestore import StateStore
    from .statestore_service import build_state_app

    store = StateStore(args.state_dir, backend="sqlite")
    asyncio.new_event_loop().run_until_complete(store.connect())
    token = os.environ.get("FTC_STATE_TOKEN", "")
    if not token:
        logging.getLogger(__name__).warning(
            "FTC_STATE_TOKEN unset: the state service accepts unauthenticated "
            "requests — fine for local dev, not for a cluster"
        )
    web.run_app(build_state_app(store, token), host=args.host, port=args.port)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
