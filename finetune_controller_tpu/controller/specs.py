"""Declarative fine-tune job specs — the model-author contract.

Capability parity with the reference's ``BaseFineTuneModel``
(``app/models/base/finetuning.py:51-145`` — SURVEY.md §2 component 4), redesigned
for the TPU stack:

- the reference's ``image`` + ``command`` + ``accelerator_count`` + ``cluster_nodes``
  (a user CUDA container on N GPU nodes) becomes ``device`` (a TPU slice flavor
  from the device catalog, e.g. ``v5e-16``) + ``num_slices`` + a **trainer spec**
  for our in-repo JAX trainer;
- typed ``TrainingArguments`` with pydantic ``Field`` metadata still double as the
  auto-generated submission form (reference: ``app/main.py:263-275`` serves the
  JSON schema — the ``description``/defaults/constraints ARE the UI);
- the ``__init_subclass__`` type-enforcement hook (reference:
  ``finetuning.py:110-145``) is kept: a subclass that overrides a field with the
  wrong type fails at class-definition time, not at submit time;
- ``run_cmd()`` (reference: ``finetuning.py:98-104``, ``mnist.py:75-99``) renders
  the container command for K8s-style backends; :meth:`build_trainer_spec`
  renders the in-process spec for the local backend.
"""

from __future__ import annotations

import enum
import shlex
import typing
from typing import Any, ClassVar

from pydantic import BaseModel, Field


class TrainingTask(str, enum.Enum):
    """Reference: ``TrainingTask`` enum, ``finetuning.py:8-12``; extended
    with the preference-optimization workloads (docs/preference.md)."""

    CAUSAL_LM = "causal_lm"
    CLASSIFICATION = "classification"
    MULTIMODAL = "multimodal"
    #: Direct Preference Optimization over (chosen, rejected) pairs
    DPO = "dpo"
    #: RLHF-lite: actor/learner gang — the serve engine generates on-policy
    #: rollouts that feed the DPO learner.  ``rollout_workers > 0``
    #: disaggregates the actors into remote worker processes
    #: (docs/preference.md §Disaggregated rollouts)
    RLHF = "rlhf"
    #: Bradley–Terry reward model: a scalar head on the DPO data path,
    #: servable on the fleet as the rlhf actors' scoring endpoint
    REWARD = "reward"


def known_tasks() -> list[str]:
    """Task values accepted at submit — the 400 on an unknown ``task`` names
    these (``controller/server.py``)."""
    return sorted(t.value for t in TrainingTask)


class TrainingFramework(str, enum.Enum):
    """Reference: ``TrainingFramework``, ``finetuning.py:14-16``; here the
    frameworks are JAX-stack modes rather than torch flavors."""

    JAX_LORA = "jax_lora"
    JAX_FULL = "jax_full"
    JAX_QLORA = "jax_qlora"


class TrainingArguments(BaseModel):
    """Base for user-facing typed hyperparameters (reference:
    ``finetuning.py:19-26``). Subclass and add pydantic fields; the JSON schema
    is served to the frontend as the submission form."""

    model_config = {"extra": "forbid"}


class TrainingResources(BaseModel):
    """Host-side resource requests for the job pods (reference:
    ``TrainingResources``, ``finetuning.py:28-35``). TPU chips come from the
    device flavor, not from here."""

    cpu: str = "4"
    memory: str = "16Gi"


class TrainingDataset(BaseModel):
    """Reference: ``TrainingDataset``, ``finetuning.py:37-44``."""

    required: bool = True
    description: str = "Training dataset (jsonl)"
    content_types: list[str] = Field(
        default_factory=lambda: ["application/jsonl", "text/csv", "application/json"]
    )


class BaseFineTuneJob(BaseModel):
    """Declarative job spec. Subclass per model family; register via
    :mod:`finetune_controller_tpu.controller.registry`.

    Class-level declaration + instance-level user arguments, mirroring the
    reference's split (``finetuning.py:51-104``).
    """

    # ---- class-level contract (override in subclasses) ----
    model_name: ClassVar[str] = "base"
    description: ClassVar[str] = ""
    task: ClassVar[TrainingTask] = TrainingTask.CAUSAL_LM
    framework: ClassVar[TrainingFramework] = TrainingFramework.JAX_LORA
    #: model preset key in ``models.llama.PRESETS`` (or family-specific registry)
    model_preset: ClassVar[str] = "tiny-test"
    #: default TPU flavor name from the device catalog; user may override at submit
    default_device: ClassVar[str] = "cpu-test"
    default_num_slices: ClassVar[int] = 1
    resources: ClassVar[TrainingResources] = TrainingResources()
    dataset: ClassVar[TrainingDataset] = TrainingDataset()
    #: artifact path where trained checkpoints land inside the job sandbox
    #: (reference: checkpoint_mount /data/artifacts, ``finetuning.py:70-73``)
    checkpoint_mount: ClassVar[str] = "/data/artifacts"
    #: glob patterns the artifact sync ships to the object store
    #: (reference: store_asset_patterns, ``finetuning.py:94-97``)
    store_asset_patterns: ClassVar[list[str]] = [
        "*.csv", "*.json", "checkpoints/**/*", "profile/**/*",
        "adapter/**/*", "merged/**/*", "done.txt",
        # observability (docs/observability.md): the trainer's lifecycle
        # events + spans ride the artifact channel like heartbeat.json
        "events.jsonl", "trace/**/*",
    ]
    #: deploy-bucket prefix used on promotion (reference: ``finetuning.py:75-78``)
    promotion_path: ClassVar[str] = "models"
    #: intra-slice mesh-axis declaration (fsdp/ep/pp/sp/tp; one axis may be -1
    #: = "all remaining chips"); resolved against the device flavor at submit
    #: by :func:`finetune_controller_tpu.controller.devices.default_mesh_for`.
    #: MoE families set ``{"ep": N, "fsdp": -1}``, long-context ones add sp.
    mesh_policy: ClassVar[dict[str, int]] = {"fsdp": -1}
    #: HF checkpoint directory with the pretrained base weights (staged into
    #: the pod like a dataset); empty = random init (smoke/test specs)
    pretrained_weights_dir: ClassVar[str] = ""
    #: the job's slices form an inseparable GANG (actor+learner — the RLHF
    #: specs): the scheduler admits all-or-nothing as usual but additionally
    #: NEVER shrinks it — a partial gang cannot run, so elastic admission
    #: and resize-instead-of-evict fall back to full preemption for it
    #: (docs/preference.md, docs/elasticity.md)
    atomic_gang: ClassVar[bool] = False
    #: model-config overrides baked into the spec (``LlamaConfig`` field →
    #: value) — how a family spec pins its measured kernel winners
    #: (``flash_block_q``/``flash_block_k``/``flash_exp_dtype``/
    #: ``ring_inner``/``ulysses_inner``) so API-submitted jobs carry them;
    #: FTC_* env vars remain per-pod operator overrides
    model_overrides: ClassVar[dict] = {}

    # ---- instance-level (validated user input) ----
    training_arguments: TrainingArguments

    # -- subclass type enforcement (reference: finetuning.py:110-145) --------

    _CHECKED_CLASSVARS: ClassVar[dict[str, type]] = {
        "model_name": str,
        "description": str,
        "task": TrainingTask,
        "framework": TrainingFramework,
        "model_preset": str,
        "default_device": str,
        "default_num_slices": int,
        "resources": TrainingResources,
        "dataset": TrainingDataset,
        "checkpoint_mount": str,
        "store_asset_patterns": list,
        "promotion_path": str,
        "mesh_policy": dict,
        "pretrained_weights_dir": str,
        "model_overrides": dict,
        "atomic_gang": bool,
    }

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        for name, expected in cls._CHECKED_CLASSVARS.items():
            if name in cls.__dict__ and not isinstance(cls.__dict__[name], expected):
                raise TypeError(
                    f"{cls.__name__}.{name} must be {expected.__name__}, "
                    f"got {type(cls.__dict__[name]).__name__}"
                )
        hints = typing.get_type_hints(cls)
        ta = hints.get("training_arguments")
        if ta is not None and isinstance(ta, type) and not issubclass(ta, TrainingArguments):
            raise TypeError(
                f"{cls.__name__}.training_arguments must subclass TrainingArguments"
            )

    # -- rendering -----------------------------------------------------------

    @classmethod
    def arguments_schema(cls) -> dict[str, Any]:
        """JSON schema for the submission form (reference: ``main.py:263-275``)."""
        ta = typing.get_type_hints(cls)["training_arguments"]
        return ta.model_json_schema()

    def build_trainer_spec(
        self,
        job_id: str,
        artifacts_dir: str,
        *,
        dataset_path: str | None = None,
        mesh: dict[str, int] | None = None,
    ) -> dict[str, Any]:
        """Render the in-repo trainer's job spec (``train/cli.py`` schema).

        The TPU-native replacement for the reference's free-form container
        ``command`` — the training program is ours, so the spec is structured
        data, not a shell string.
        """
        args = self.training_arguments.model_dump()
        training = {
            "mode": "lora" if self.framework != TrainingFramework.JAX_FULL else "full",
        }
        preference = self.task in (
            TrainingTask.DPO, TrainingTask.RLHF, TrainingTask.REWARD,
        )
        if preference:
            # select the DPO/rlhf/reward trainer (prefs/, docs/preference.md)
            training["task"] = self.task.value
        # Lift known trainer knobs out of the user arguments.
        for key in (
            "learning_rate", "warmup_steps", "total_steps", "schedule",
            "weight_decay", "clip_norm", "batch_size", "seq_len", "seed",
            "log_every", "checkpoint_every", "profile_steps", "export_merged",
            "eval_every", "eval_steps", "frozen_dtype", "grad_accum_steps",
        ):
            if key in args:
                training[key] = args.pop(key)
        if "beta" in args:
            if preference:
                training["dpo_beta"] = args.pop("beta")
            else:
                args.pop("beta")  # meaningless for SFT; don't fail the run
        rollout: dict[str, Any] = {}
        if self.task is TrainingTask.RLHF:
            # remote actor count is a TRAINER knob (TrainConfig — it selects
            # the disaggregated data plane), not a RolloutConfig field
            if "rollout_workers" in args:
                training["rollout_workers"] = args.pop("rollout_workers")
            # actor/learner loop knobs (prefs/learner.py::RolloutConfig)
            for key in (
                "rollout_pairs_per_round", "rollout_buffer_capacity",
                "rollout_min_fill", "rollout_staleness_checkpoints",
                "rollout_temperature", "rollout_top_k",
                "rollout_max_new_tokens", "rollout_slots",
                "rollout_reward_host", "rollout_reward_port",
            ):
                if key in args:
                    rollout[key[len("rollout_"):]] = args.pop(key)
        model: dict[str, Any] = {"preset": self.model_preset}
        if self.pretrained_weights_dir:
            model["weights_dir"] = self.pretrained_weights_dir
        overrides = dict(self.model_overrides)
        if self.framework == TrainingFramework.JAX_QLORA:
            # int4 base weights (models/quant.py); adapters still train in LoRA
            overrides["quantize_base"] = True
        if overrides:
            model["overrides"] = overrides
        if "lora_rank" in args:
            model["lora"] = {"rank": args.pop("lora_rank")}
        spec: dict[str, Any] = {
            "job_id": job_id,
            "model": model,
            "training": training,
            "artifacts_dir": artifacts_dir,
        }
        if rollout:
            spec["rollout"] = rollout
        if mesh:
            spec["mesh"] = mesh
        if dataset_path:
            spec["dataset"] = {"path": dataset_path}
        elif preference:
            # DPO trains on the seeded synthetic increment pairs; the rlhf
            # actor generates its own data, so the dataset section only
            # drives the held-out eval stream (data/preference.py)
            spec["dataset"] = {"synthetic": {"task": "preference"}}
        else:
            # multimodal smoke jobs get the vision-wiring probe task; text
            # jobs the increment task (data/synthetic.py)
            task_name = (
                "brightness" if self.task is TrainingTask.MULTIMODAL else "increment"
            )
            spec["dataset"] = {"synthetic": {"task": task_name}}
        if args:
            spec["extra_arguments"] = args
        return spec

    def run_cmd(self, spec_path: str = "/data/job.json") -> str:
        """Container command for K8s-style backends (reference:
        ``finetuning.py:98-104``; done.txt convention
        ``PyTorchJobDeployer.py:30-32``)."""
        return (
            f"python -m finetune_controller_tpu.train.cli --spec {shlex.quote(spec_path)}"
            f" && touch {shlex.quote(self.checkpoint_mount)}/done.txt"
        )
