"""Runtime assembly: settings → wired control-plane components.

The reference wires its singletons at import time — kube config, settings
reading a k8s Secret, S3 handler (``SURVEY.md`` §3.5), its biggest
testability wart. Here, assembly is an explicit factory: nothing touches the
filesystem or spawns tasks until :func:`build_runtime` is called, and every
component can be swapped in tests.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any

from ..obs.prom import ObsHub
from ..sched import FairShareScheduler
from ..resilience.heartbeat import LeaseChecker
from ..resilience.policy import RetryPolicy
from ..resilience.supervisor import RetrySupervisor
from .backends.base import TrainingBackend
from .backends.local import LocalProcessBackend
from .config import Settings, get_settings
from .devices import DeviceCatalog, load_catalog
from .monitor import JobMonitor
from .objectstore import ObjectStore, Presigner, build_object_store
from .registry import load_model_modules
from .statestore import StateStore

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class Runtime:
    """Everything a control-plane process needs (API server or monitor daemon)."""

    settings: Settings
    state: StateStore
    store: ObjectStore
    catalog: DeviceCatalog
    backend: TrainingBackend
    monitor: JobMonitor
    presigner: Presigner
    #: inference sessions over promoted checkpoints (serve/service.py);
    #: lazily populated — nothing loads until a generate/load request
    serve: Any = None
    #: the process's observability hub (obs/prom.py): latency histograms +
    #: build info/uptime, rendered by /metrics (docs/observability.md)
    obs: Any = None

    async def start(self, *, with_monitor: bool | None = None) -> None:
        await self.state.connect()
        run_monitor = (
            self.settings.monitor_in_process if with_monitor is None else with_monitor
        )
        if run_monitor:
            self.monitor.start()
        prewarm = getattr(self.backend, "prewarm", None)
        if prewarm is not None:
            # local backend: spawn the warm trainer pool for the default
            # flavor so the FIRST submission already warm-starts
            await prewarm()

    async def close(self) -> None:
        if self.serve is not None:
            await self.serve.close()
        await self.monitor.stop()
        await self.backend.close()
        await self.state.close()
        await self.store.close()


def build_runtime(
    settings: Settings | None = None,
    *,
    plugin_dir: str | None = None,
) -> Runtime:
    """Assemble a runtime from settings (reference startup flow §3.5, made lazy)."""
    settings = settings or get_settings()
    load_model_modules(plugin_dir)
    if settings.state_backend == "remote":
        # the shared state service: N API replicas + the monitor see one
        # consistent store (and rate limits become cluster-scope)
        from .statestore_service import RemoteStateStore

        state: StateStore = RemoteStateStore(  # type: ignore[assignment]
            settings.state_service_url, token=settings.state_service_token
        )
    else:
        state = StateStore(settings.state_path, backend=settings.state_backend)
    store = build_object_store(settings)
    catalog = load_catalog(settings.device_config_file or None)
    backend: TrainingBackend
    if settings.backend == "local":
        sched_queues = None
        if settings.sched_queues:
            import json

            parsed = json.loads(settings.sched_queues)
            if not isinstance(parsed, dict):
                raise ValueError(
                    "FTC_SCHED_QUEUES must be a JSON object of "
                    "queue-name -> weight"
                )
            sched_queues = {str(k): float(v) for k, v in parsed.items()}
        backend = LocalProcessBackend(
            settings.state_path / "sandboxes",
            store,
            catalog,
            sync_interval_s=settings.artifact_sync_interval_s,
            warm_workers=settings.warm_workers,
            sched_policy=settings.sched_policy,
            sched_queues=sched_queues,
            sched_resize=settings.sched_resize,
            sched_grow_delay_s=settings.sched_grow_delay_s,
        )
    elif settings.backend == "k8s":
        from .backends.k8s import K8sJobSetBackend

        backend = K8sJobSetBackend(catalog, settings)
    else:
        raise ValueError(f"unknown backend {settings.backend!r}")
    # resilience attachments (docs/resilience.md): the retry supervisor
    # closes the failure loop the reference leaves to operators, the lease
    # checker catches silently-stuck jobs. Either can be disabled via
    # settings (reference-parity behavior).
    # one observability hub per process (docs/observability.md): the monitor,
    # supervisor and serve batchers observe into it; /metrics renders it
    obs = ObsHub()
    supervisor = None
    if settings.retry_max_attempts > 0:
        supervisor = RetrySupervisor(
            state, backend, catalog,
            policy=RetryPolicy(
                max_attempts=settings.retry_max_attempts,
                base_delay_s=settings.retry_base_delay_s,
                max_delay_s=settings.retry_max_delay_s,
            ),
            obs=obs,
        )
    lease = None
    if settings.liveness_lease_s > 0:
        # floor: heartbeat freshness through the store is bounded by the
        # artifact sync cadence — a lease tighter than that would kill
        # healthy jobs between syncs
        lease = LeaseChecker(
            store,
            lease_s=max(
                settings.liveness_lease_s, 3 * settings.artifact_sync_interval_s
            ),
        )
    monitor = JobMonitor(
        state, store, backend,
        interval_s=settings.job_monitor_interval_s,
        supervisor=supervisor, lease=lease, obs=obs,
    )
    presigner = Presigner(settings.presign_secret, settings.presign_expiry_s)
    from ..serve.service import ServeManager

    return Runtime(
        settings=settings,
        state=state,
        store=store,
        catalog=catalog,
        backend=backend,
        monitor=monitor,
        presigner=presigner,
        # the fair-share scheduler handle (local backend) makes serve an
        # autoscaling preemptible tenant when FTC_SERVE_AUTOSCALE is on
        # (docs/scheduling.md §Serve tenant); FIFO/k8s backends serve
        # statically-sized fleets
        serve=ServeManager(
            state, store, settings, obs=obs,
            scheduler=(
                backend.scheduler
                if isinstance(getattr(backend, "scheduler", None),
                              FairShareScheduler)
                else None
            ),
            # serve_transport=process: worker sandboxes ride the backend's
            # substrate (docs/serving.md §Cross-process transport)
            backend=backend,
        ),
        obs=obs,
    )
