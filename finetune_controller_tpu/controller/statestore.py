"""Async document state store — the control plane's source of truth.

Capability parity with the reference's ``MongoDBManager`` (``app/database/db.py``,
710 LoC — SURVEY.md §2 component 7): jobs / metrics / datasets / archived_jobs
collections, indexed lookups, paginated job queries with server-side computed
fields, metadata merge on status updates, archive-on-delete. The engine is an
embedded document store instead of an external MongoDB server — the reference's
Mongo is an external C++ process (SURVEY.md §2.2), so "external document store"
is the delegation seam we replace with an embedded one. The public API is
transport-agnostic, so a Mongo-backed implementation can be swapped in behind
the same interface.

Two engines behind one interface:

- ``sqlite`` (default when a state dir is given) — one WAL-mode SQLite file,
  a table per collection, every read served from the database and every
  read-modify-write inside a ``BEGIN IMMEDIATE`` transaction.  This is the
  **multi-process-safe** engine: the deployed layout runs the API server and
  the monitor as separate processes against one state dir, exactly like the
  reference's two deployments share one MongoDB (``app/database/db.py:51``,
  ``Dockerfile.monitor:30``), so job state written by the monitor must be
  immediately visible to — and never clobbered by — the API process.
- ``jsonl`` — append-only JSONL log + in-memory indexes.  Single-process
  only (no cross-process locking or reload); kept for inspectability and as
  the in-memory engine's persistence format.

Fixes a reference wart on the way: the monitor's N+1 per-job DB reads
(``app/core/monitor.py:151-158``) are avoided by :meth:`StateStore.get_jobs_by_ids`.
"""

from __future__ import annotations

import asyncio
import collections
import json
import os
import sqlite3
import threading
import time
import uuid
from pathlib import Path
from typing import Any, Callable

from .schemas import (
    DatabaseStatus,
    DatasetRecord,
    JobRecord,
    MetricsDocument,
    PaginatedTableResponse,
    PromotionStatus,
)


def generate_short_uuid() -> str:
    """8-char lowercase job-id suffix (reference: ``app/utils/naming.py:4-6``)."""
    return uuid.uuid4().hex[:8]


class Collection:
    """One named document collection with unique-key index and file persistence.

    Persistence is an append-only JSONL log: each write appends the changed
    document (or a ``{"__tombstone__": key}`` record for deletes); load replays
    the log last-record-wins. The log compacts in place once it grows past
    ~4x the live document count, so a single write is O(doc) amortised rather
    than O(collection) — the monitor's per-tick status updates stay cheap even
    with thousands of accumulated jobs. All file I/O runs off the event loop.
    """

    _COMPACT_MIN_RECORDS = 1024

    def __init__(self, path: Path | None, key: str, index_fields: tuple[str, ...] = ()):
        self._path = path
        self._key = key
        self._docs: dict[str, dict[str, Any]] = {}
        self._lock = asyncio.Lock()
        self._loaded = False
        self._log_records = 0
        # secondary equality indexes: field -> value -> set of primary keys
        # (reference: Mongo ``_ensure_indexes``, ``db.py:77-105``)
        self._index_fields = index_fields
        self._index: dict[str, dict[Any, set[str]]] = {f: {} for f in index_fields}

    def _index_add(self, doc: dict[str, Any]) -> None:
        for f in self._index_fields:
            self._index[f].setdefault(doc.get(f), set()).add(doc[self._key])

    def _index_remove(self, doc: dict[str, Any]) -> None:
        for f in self._index_fields:
            bucket = self._index[f].get(doc.get(f))
            if bucket is not None:
                bucket.discard(doc[self._key])
                if not bucket:
                    del self._index[f][doc.get(f)]

    def _load(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        if self._path is not None and self._path.exists():
            with self._path.open() as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    self._log_records += 1
                    if "__tombstone__" in rec:
                        old = self._docs.pop(rec["__tombstone__"], None)
                        if old is not None:
                            self._index_remove(old)
                    else:
                        old = self._docs.get(rec[self._key])
                        if old is not None:
                            self._index_remove(old)
                        # ftc: ignore[lock-discipline] -- every caller holds the collection's asyncio lock ACROSS its to_thread hop, so the loader thread and loop-side writers are serialized by it
                        self._docs[rec[self._key]] = rec
                        self._index_add(rec)

    def _append(self, record: dict[str, Any]) -> None:
        if self._path is None:
            return
        self._path.parent.mkdir(parents=True, exist_ok=True)
        with self._path.open("a") as f:
            f.write(json.dumps(record) + "\n")
        self._log_records += 1
        if self._log_records >= max(self._COMPACT_MIN_RECORDS, 4 * len(self._docs)):
            self._compact()

    def _compact(self) -> None:
        tmp = self._path.with_suffix(".tmp")
        with tmp.open("w") as f:
            for doc in self._docs.values():
                f.write(json.dumps(doc) + "\n")
        tmp.replace(self._path)
        self._log_records = len(self._docs)

    async def insert(self, doc: dict[str, Any]) -> None:
        async with self._lock:
            await asyncio.to_thread(self._load)
            doc = dict(doc)
            old = self._docs.get(doc[self._key])
            if old is not None:
                self._index_remove(old)
            self._docs[doc[self._key]] = doc
            self._index_add(doc)
            await asyncio.to_thread(self._append, doc)

    async def get(self, key: str) -> dict[str, Any] | None:
        async with self._lock:
            await asyncio.to_thread(self._load)
            doc = self._docs.get(key)
            return dict(doc) if doc else None

    async def update(self, key: str, fields: dict[str, Any]) -> bool:
        """Atomic set of top-level fields (reference: Mongo ``update_one`` with
        ``$set``, ``db.py:217-219``)."""
        async with self._lock:
            await asyncio.to_thread(self._load)
            doc = self._docs.get(key)
            if doc is None:
                return False
            self._index_remove(doc)
            doc.update(fields)
            self._index_add(doc)
            await asyncio.to_thread(self._append, doc)
            return True

    async def update_if(
        self,
        key: str,
        fields: dict[str, Any],
        predicate: Callable[[dict[str, Any]], bool],
    ) -> bool:
        """Compare-and-set: apply ``fields`` only when ``predicate(doc)`` holds,
        read and write under the collection lock (the guard+transition pattern
        concurrent HTTP handlers need — a bare read-then-update has an await
        window where a second request slips through)."""
        async with self._lock:
            await asyncio.to_thread(self._load)
            doc = self._docs.get(key)
            if doc is None or not predicate(doc):
                return False
            self._index_remove(doc)
            doc.update(fields)
            self._index_add(doc)
            await asyncio.to_thread(self._append, doc)
            return True

    async def merge_subdoc(self, key: str, field: str, patch: dict[str, Any]) -> bool:
        """Last-writer-wins merge into a dict field (reference metadata merge,
        ``db.py:206-215``)."""
        async with self._lock:
            await asyncio.to_thread(self._load)
            doc = self._docs.get(key)
            if doc is None:
                return False
            sub = dict(doc.get(field) or {})
            sub.update(patch)
            doc[field] = sub
            await asyncio.to_thread(self._append, doc)
            return True

    async def append_to_list(
        self,
        key: str,
        field: str,
        item: dict[str, Any],
        dedupe_key: str | None = None,
    ) -> bool:
        """Atomic append to a list field.  ``dedupe_key`` makes the append
        idempotent: when an existing element carries the same ``"key"`` the
        append is skipped (False) — the exactly-once handle the job event
        timeline rides (docs/observability.md)."""
        async with self._lock:
            await asyncio.to_thread(self._load)
            doc = self._docs.get(key)
            if doc is None:
                return False
            items = list(doc.get(field) or [])
            if dedupe_key is not None and any(
                isinstance(e, dict) and e.get("key") == dedupe_key
                for e in items
            ):
                return False
            items.append(item)
            doc[field] = items
            await asyncio.to_thread(self._append, doc)
            return True

    async def extend_list(
        self, key: str, field: str, new_items: list[dict[str, Any]]
    ) -> int:
        """Batch append: every item is deduped on its ``"key"`` (against the
        stored list AND within the batch), all survivors land in ONE write —
        the trainer-event ingest's per-event-RMW fix.  Returns the number
        appended (0 when the doc is gone or everything was a duplicate)."""
        async with self._lock:
            await asyncio.to_thread(self._load)
            doc = self._docs.get(key)
            if doc is None:
                return 0
            items = list(doc.get(field) or [])
            seen = {
                e.get("key") for e in items
                if isinstance(e, dict) and e.get("key") is not None
            }
            added = 0
            for item in new_items:
                k = item.get("key")
                if k is not None and k in seen:
                    continue
                if k is not None:
                    seen.add(k)
                items.append(item)
                added += 1
            if added:
                doc[field] = items
                await asyncio.to_thread(self._append, doc)
            return added

    async def delete(self, key: str) -> dict[str, Any] | None:
        async with self._lock:
            await asyncio.to_thread(self._load)
            doc = self._docs.pop(key, None)
            if doc is not None:
                self._index_remove(doc)
                await asyncio.to_thread(self._append, {"__tombstone__": key})
            return doc

    async def find(
        self,
        predicate: Callable[[dict[str, Any]], bool] | None = None,
        *,
        eq: dict[str, Any] | None = None,
    ) -> list[dict[str, Any]]:
        """``eq`` filters on indexed fields WITHOUT scanning the collection
        (the in-memory-index promise); ``predicate`` refines the candidates."""
        async with self._lock:
            await asyncio.to_thread(self._load)
            if eq:
                keys: set[str] | None = None
                for f, v in eq.items():
                    if f not in self._index:
                        raise KeyError(f"field {f!r} is not indexed on this collection")
                    bucket = self._index[f].get(v, set())
                    keys = bucket if keys is None else keys & bucket
                # primary-key order: set iteration is hash-randomized, and
                # paginated callers need a deterministic tie-break
                docs = [dict(self._docs[k]) for k in sorted(keys or ())]
            else:
                docs = [dict(d) for d in self._docs.values()]
        if predicate is not None:
            docs = [d for d in docs if predicate(d)]
        return docs

    async def count(
        self, predicate: Callable[[dict[str, Any]], bool] | None = None
    ) -> int:
        return len(await self.find(predicate))


class _SqliteDB:
    """One shared WAL-mode SQLite database holding every collection's table.

    All statements run on worker threads (via ``asyncio.to_thread``) under a
    process-local mutex — SQLite's cross-PROCESS coordination is the WAL +
    busy-timeout machinery; the mutex only serializes this process's threads
    over the single connection.
    """

    def __init__(self, path: Path):
        self._path = path
        self._lock = threading.Lock()
        self._conn: sqlite3.Connection | None = None
        self._pid: int | None = None

    def run(self, fn: Callable[[sqlite3.Connection], Any]) -> Any:
        with self._lock:
            if self._conn is None or self._pid != os.getpid():
                # (re)connect lazily; a forked child must not reuse the
                # parent's connection (sqlite documents this as corruption)
                self._path.parent.mkdir(parents=True, exist_ok=True)
                # The WAL switch on a brand-new database can report "database
                # is locked" when sibling worker processes race it at boot
                # (observed killing a --workers fork under load); it succeeds
                # on the sibling's heels, so retry the CONNECT PHASE only —
                # a locked error out of fn() itself propagates as before.
                for attempt in range(5):
                    conn = sqlite3.connect(
                        self._path, timeout=30.0, check_same_thread=False,
                        isolation_level=None,  # autocommit; RMW uses BEGIN IMMEDIATE
                    )
                    try:
                        conn.execute("PRAGMA journal_mode=WAL")
                        conn.execute("PRAGMA synchronous=NORMAL")
                        conn.execute("PRAGMA busy_timeout=30000")
                    except sqlite3.OperationalError:
                        conn.close()
                        if attempt == 4:
                            raise
                        time.sleep(0.05 * (2 ** attempt))
                        continue
                    break
                self._conn, self._pid = conn, os.getpid()
            return fn(self._conn)

    def close(self) -> None:
        with self._lock:
            if self._conn is not None and self._pid == os.getpid():
                self._conn.close()
            self._conn = None


class SqliteCollection:
    """``Collection``-compatible engine over a shared :class:`_SqliteDB`.

    Every read goes to the database (no in-memory cache to go stale under a
    concurrent writer process) and every read-modify-write runs inside a
    ``BEGIN IMMEDIATE`` transaction, so two processes interleaving
    ``update``/``update_if``/``merge_subdoc`` cannot lose each other's writes.
    """

    def __init__(self, db: _SqliteDB, name: str, key: str,
                 index_fields: tuple[str, ...] = ()):
        self._db = db
        self._name = name
        self._key = key
        self._index_fields = index_fields
        self._ready = False

    def _ensure(self, conn: sqlite3.Connection) -> None:
        if self._ready:
            return
        conn.execute(
            f'CREATE TABLE IF NOT EXISTS "{self._name}" '
            "(key TEXT PRIMARY KEY, doc TEXT NOT NULL)"
        )
        for f in self._index_fields:
            # expression index = the Mongo secondary index of the jsonl engine
            conn.execute(
                f'CREATE INDEX IF NOT EXISTS "idx_{self._name}_{f}" '
                f"ON \"{self._name}\" (json_extract(doc, '$.{f}'))"
            )
        self._ready = True

    async def insert(self, doc: dict[str, Any]) -> None:
        doc = dict(doc)

        def op(conn: sqlite3.Connection) -> None:
            self._ensure(conn)
            conn.execute(
                f'INSERT INTO "{self._name}" (key, doc) VALUES (?, ?) '
                "ON CONFLICT(key) DO UPDATE SET doc = excluded.doc",
                (doc[self._key], json.dumps(doc)),
            )

        await asyncio.to_thread(self._db.run, op)

    async def get(self, key: str) -> dict[str, Any] | None:
        def op(conn: sqlite3.Connection) -> dict[str, Any] | None:
            self._ensure(conn)
            row = conn.execute(
                f'SELECT doc FROM "{self._name}" WHERE key = ?', (key,)
            ).fetchone()
            return json.loads(row[0]) if row else None

        return await asyncio.to_thread(self._db.run, op)

    def _rmw(
        self,
        key: str,
        mutate: Callable[[dict[str, Any]], dict[str, Any] | None],
    ) -> bool:
        """Transactional read-modify-write; ``mutate`` returns the new doc or
        ``None`` to abort (predicate failed)."""

        def op(conn: sqlite3.Connection) -> bool:
            self._ensure(conn)
            conn.execute("BEGIN IMMEDIATE")
            try:
                row = conn.execute(
                    f'SELECT doc FROM "{self._name}" WHERE key = ?', (key,)
                ).fetchone()
                if row is None:
                    conn.execute("ROLLBACK")
                    return False
                new = mutate(json.loads(row[0]))
                if new is None:
                    conn.execute("ROLLBACK")
                    return False
                conn.execute(
                    f'UPDATE "{self._name}" SET doc = ? WHERE key = ?',
                    (json.dumps(new), key),
                )
                conn.execute("COMMIT")
                return True
            except BaseException:
                conn.execute("ROLLBACK")
                raise

        return self._db.run(op)

    async def update(self, key: str, fields: dict[str, Any]) -> bool:
        return await asyncio.to_thread(
            self._rmw, key, lambda doc: {**doc, **fields}
        )

    async def update_if(
        self,
        key: str,
        fields: dict[str, Any],
        predicate: Callable[[dict[str, Any]], bool],
    ) -> bool:
        return await asyncio.to_thread(
            self._rmw, key,
            lambda doc: {**doc, **fields} if predicate(doc) else None,
        )

    async def merge_subdoc(self, key: str, field: str, patch: dict[str, Any]) -> bool:
        def mutate(doc: dict[str, Any]) -> dict[str, Any]:
            sub = dict(doc.get(field) or {})
            sub.update(patch)
            return {**doc, field: sub}

        return await asyncio.to_thread(self._rmw, key, mutate)

    async def append_to_list(
        self,
        key: str,
        field: str,
        item: dict[str, Any],
        dedupe_key: str | None = None,
    ) -> bool:
        """Jsonl-engine parity: transactional list append with idempotency
        (the read and the deduped write share one ``BEGIN IMMEDIATE``)."""

        def mutate(doc: dict[str, Any]) -> dict[str, Any] | None:
            items = list(doc.get(field) or [])
            if dedupe_key is not None and any(
                isinstance(e, dict) and e.get("key") == dedupe_key
                for e in items
            ):
                return None
            return {**doc, field: items + [item]}

        return await asyncio.to_thread(self._rmw, key, mutate)

    async def extend_list(
        self, key: str, field: str, new_items: list[dict[str, Any]]
    ) -> int:
        """Jsonl-engine parity: batch list append, per-item ``"key"`` dedupe,
        one transaction.  Returns the number appended."""
        added = 0

        def mutate(doc: dict[str, Any]) -> dict[str, Any] | None:
            nonlocal added
            added = 0
            items = list(doc.get(field) or [])
            seen = {
                e.get("key") for e in items
                if isinstance(e, dict) and e.get("key") is not None
            }
            for item in new_items:
                k = item.get("key")
                if k is not None and k in seen:
                    continue
                if k is not None:
                    seen.add(k)
                items.append(item)
                added += 1
            if not added:
                return None
            return {**doc, field: items}

        await asyncio.to_thread(self._rmw, key, mutate)
        return added

    async def delete(self, key: str) -> dict[str, Any] | None:
        def op(conn: sqlite3.Connection) -> dict[str, Any] | None:
            self._ensure(conn)
            conn.execute("BEGIN IMMEDIATE")
            try:
                row = conn.execute(
                    f'SELECT doc FROM "{self._name}" WHERE key = ?', (key,)
                ).fetchone()
                if row is None:
                    conn.execute("ROLLBACK")
                    return None
                conn.execute(
                    f'DELETE FROM "{self._name}" WHERE key = ?', (key,)
                )
                conn.execute("COMMIT")
                return json.loads(row[0])
            except BaseException:
                conn.execute("ROLLBACK")
                raise

        return await asyncio.to_thread(self._db.run, op)

    async def find(
        self,
        predicate: Callable[[dict[str, Any]], bool] | None = None,
        *,
        eq: dict[str, Any] | None = None,
    ) -> list[dict[str, Any]]:
        def op(conn: sqlite3.Connection) -> list[dict[str, Any]]:
            self._ensure(conn)
            if eq:
                clauses, params = [], []
                for f, v in eq.items():
                    if f not in self._index_fields:
                        raise KeyError(
                            f"field {f!r} is not indexed on this collection"
                        )
                    # IS (not =) so eq-on-None matches missing/null fields,
                    # mirroring the jsonl engine's dict.get semantics
                    clauses.append(f"json_extract(doc, '$.{f}') IS ?")
                    params.append(v)
                rows = conn.execute(
                    f'SELECT doc FROM "{self._name}" '
                    f"WHERE {' AND '.join(clauses)} ORDER BY key",
                    params,
                ).fetchall()
            else:
                rows = conn.execute(
                    f'SELECT doc FROM "{self._name}" ORDER BY rowid'
                ).fetchall()
            return [json.loads(r[0]) for r in rows]

        docs = await asyncio.to_thread(self._db.run, op)
        if predicate is not None:
            docs = [d for d in docs if predicate(d)]
        return docs

    async def count(
        self, predicate: Callable[[dict[str, Any]], bool] | None = None
    ) -> int:
        return len(await self.find(predicate))


class StateStore:
    """Domain-level store over four collections (reference: ``MongoDBManager``).

    ``state_dir=None`` keeps everything in memory (the unit-test seam the
    reference never had).  With a state dir, ``backend`` picks the engine:
    ``"sqlite"`` (default; multi-process-safe WAL database) or ``"jsonl"``
    (single-process append-only log).  Existing jsonl state is migrated into
    the database on :meth:`connect`, so a round-2 state dir upgrades in place.
    """

    _COLLECTIONS: tuple[tuple[str, str, tuple[str, ...]], ...] = (
        ("jobs", "job_id", ("user_id", "status")),
        ("archived_jobs", "job_id", ()),
        ("metrics", "job_id", ()),
        ("datasets", "dataset_id", ("user_id",)),
    )

    def __init__(
        self,
        state_dir: Path | str | None = None,
        backend: str | None = None,
    ):
        self._dir = Path(state_dir).expanduser() if state_dir is not None else None
        if backend is None:
            backend = os.environ.get("FTC_STATE_BACKEND", "sqlite")
        if backend not in ("sqlite", "jsonl"):
            # a typo'd value silently running the single-process jsonl engine
            # under the two-process deployment would be exactly the
            # lost-update corruption the sqlite engine exists to prevent
            raise ValueError(
                f"unknown state backend {backend!r}: expected 'sqlite' or 'jsonl'"
            )
        self._backend = backend if self._dir is not None else "memory"
        self._db: _SqliteDB | None = None

        if self._dir is not None and self._backend == "sqlite":
            self._db = _SqliteDB(self._dir / "state.db")

            def make(name: str, key: str, idx: tuple[str, ...]):
                return SqliteCollection(self._db, name, key, idx)
        else:
            def make(name: str, key: str, idx: tuple[str, ...]):
                path = None if self._dir is None else self._dir / f"{name}.jsonl"
                return Collection(path, key, index_fields=idx)

        for name, key, idx in self._COLLECTIONS:
            setattr(self, name, make(name, key, idx))
        self._connected = False
        #: rate-limit windows for the memory/jsonl engines (per-process —
        #: the sqlite engine keeps them in the database, cross-process)
        self._mem_rate: dict[str, collections.deque] = {}

    # -- lifecycle (reference: connect/_ensure_indexes, db.py:33-105) --------

    async def connect(self) -> None:
        if self._dir is not None:
            self._dir.mkdir(parents=True, exist_ok=True)
            if self._backend == "sqlite":
                await self._migrate_jsonl()
        self._connected = True

    async def _migrate_jsonl(self) -> None:
        """One-way import of legacy jsonl logs into the sqlite engine.

        The emptiness check and the import run inside ONE ``BEGIN IMMEDIATE``
        transaction per collection: two processes starting concurrently must
        not both see "empty" and have the late importer resurrect stale
        legacy docs over the early one's fresh writes.  After the import the
        legacy log is renamed to ``*.jsonl.migrated`` — once sqlite owns the
        dir the jsonl is never authoritative again, so a later restart with a
        legitimately-empty table (all jobs archived) must not re-import
        deleted documents from it.
        """
        for name, key, idx in self._COLLECTIONS:
            legacy = self._dir / f"{name}.jsonl"
            coll = getattr(self, name)
            if not legacy.exists():
                continue
            old = Collection(legacy, key, index_fields=idx)
            docs = await old.find()

            def op(conn: sqlite3.Connection, coll=coll, docs=docs) -> None:
                coll._ensure(conn)
                conn.execute("BEGIN IMMEDIATE")
                try:
                    n = conn.execute(
                        f'SELECT COUNT(*) FROM "{coll._name}"'
                    ).fetchone()[0]
                    if n == 0:
                        for doc in docs:
                            conn.execute(
                                f'INSERT INTO "{coll._name}" (key, doc) '
                                "VALUES (?, ?)",
                                (doc[coll._key], json.dumps(doc)),
                            )
                    conn.execute("COMMIT")
                except BaseException:
                    conn.execute("ROLLBACK")
                    raise

            await asyncio.to_thread(self._db.run, op)
            try:
                legacy.rename(legacy.with_suffix(".jsonl.migrated"))
            except OSError:
                pass  # a concurrent starter renamed it first — fine

    async def close(self) -> None:
        if self._db is not None:
            await asyncio.to_thread(self._db.close)
        self._connected = False

    # -- jobs (reference: db.py:107-379) -------------------------------------

    async def create_job(self, job: JobRecord) -> None:
        await self.jobs.insert(job.model_dump(mode="json"))

    async def get_job(self, job_id: str) -> JobRecord | None:
        doc = await self.jobs.get(job_id)
        return JobRecord(**doc) if doc else None

    async def get_jobs_by_ids(self, job_ids: list[str]) -> dict[str, JobRecord]:
        """Batch fetch — kills the reference monitor's N+1 pattern
        (``app/core/monitor.py:151-158``)."""
        wanted = set(job_ids)
        docs = await self.jobs.find(lambda d: d["job_id"] in wanted)
        return {d["job_id"]: JobRecord(**d) for d in docs}

    async def get_active_jobs(self) -> list[JobRecord]:
        """Every job not in a final state — the monitor's lost-job sweep input."""
        final = {s.value for s in DatabaseStatus.final_states()}
        docs = await self.jobs.find(lambda d: d["status"] not in final)
        return [JobRecord(**d) for d in docs]

    async def get_jobs_by_status(self, status: DatabaseStatus) -> list[JobRecord]:
        """Indexed status lookup — the retry supervisor polls for RETRYING
        jobs every monitor tick, which must not scan the whole collection."""
        docs = await self.jobs.find(eq={"status": DatabaseStatus(status).value})
        return [JobRecord(**d) for d in docs]

    async def update_job_status(
        self,
        job_id: str,
        status: DatabaseStatus,
        *,
        metadata: dict[str, Any] | None = None,
        **fields: Any,
    ) -> bool:
        """Status update + metadata merge (reference: ``db.py:195-228``)."""
        ok = await self.jobs.update(
            job_id,
            {"status": DatabaseStatus(status).value, **_jsonify(fields)},
        )
        if ok and metadata:
            await self.jobs.merge_subdoc(job_id, "metadata", _jsonify(metadata))
        return ok

    async def transition_job_status(
        self,
        job_id: str,
        expect: DatabaseStatus,
        status: DatabaseStatus,
        *,
        metadata: dict[str, Any] | None = None,
        **fields: Any,
    ) -> bool:
        """Compare-and-set status transition: applies only while the job is
        still in ``expect``.  The retry supervisor's resubmit path needs this
        — a user cancel landing inside the resubmit's await window must not
        be silently overwritten back to QUEUED."""
        ok = await self.jobs.update_if(
            job_id,
            {"status": DatabaseStatus(status).value, **_jsonify(fields)},
            lambda doc: doc.get("status") == DatabaseStatus(expect).value,
        )
        if ok and metadata:
            await self.jobs.merge_subdoc(job_id, "metadata", _jsonify(metadata))
        return ok

    async def update_job_promotion(
        self,
        job_id: str,
        promotion_status: PromotionStatus,
        promotion_uri: str | None = None,
    ) -> bool:
        """Reference: ``db.py:230-255``."""
        fields: dict[str, Any] = {
            "promotion_status": PromotionStatus(promotion_status).value
        }
        if promotion_uri is not None:
            fields["promotion_uri"] = promotion_uri
        return await self.jobs.update(job_id, fields)

    async def begin_promotion(
        self,
        job_id: str,
        promotion_status: PromotionStatus,
        promotion_uri: str,
        expect_from: list[PromotionStatus | str] | None = None,
    ) -> bool:
        """Atomically claim a promote/unpromote transition: succeeds only if no
        transition is already in flight AND (when ``expect_from`` is given) the
        current state is one of the expected sources. Returns False when
        another request won or the state moved underneath the caller —
        promote-while-DELETING and unpromote-while-IN_PROGRESS lose here, in
        the store's consistency domain, not in handler guards racing on
        awaits."""
        in_flight = {
            PromotionStatus.IN_PROGRESS.value,
            PromotionStatus.DELETING.value,
        }
        expect = (
            None if expect_from is None
            else {PromotionStatus(s).value for s in expect_from}
        )

        def ok(doc: dict) -> bool:
            cur = doc.get("promotion_status")
            if cur in in_flight:
                return False
            return expect is None or cur in expect

        return await self.jobs.update_if(
            job_id,
            {
                "promotion_status": PromotionStatus(promotion_status).value,
                "promotion_uri": promotion_uri,
            },
            ok,
        )

    async def transition_job_promotion(
        self,
        job_id: str,
        expect: list[PromotionStatus | str],
        promotion_status: PromotionStatus,
        promotion_uri: str | None = None,
    ) -> bool:
        """Compare-and-set promotion transition (the job-status CAS shape):
        applies only while the job is still in one of ``expect``.  The
        promotion task's completion writes need this — a crash-recovery sweep
        or a concurrent unpromote landing mid-copy must not be stomped by the
        stale task's final blind write."""
        vals = {PromotionStatus(s).value for s in expect}
        fields: dict[str, Any] = {
            "promotion_status": PromotionStatus(promotion_status).value
        }
        if promotion_uri is not None:
            fields["promotion_uri"] = promotion_uri
        return await self.jobs.update_if(
            job_id, fields, lambda doc: doc.get("promotion_status") in vals
        )

    async def update_job_fields(self, job_id: str, **fields: Any) -> bool:
        return await self.jobs.update(job_id, _jsonify(fields))

    async def append_job_event(self, job_id: str, event: dict[str, Any]) -> bool:
        """Append one lifecycle event to the job's timeline
        (docs/observability.md).  Idempotent on ``event["key"]`` — an
        emitter that retries after a crash converges to exactly one event
        per transition instance.  False when the job is gone or the key was
        already recorded."""
        return await self.jobs.append_to_list(
            job_id, "events", event, dedupe_key=event.get("key")
        )

    async def append_job_events(
        self, job_id: str, events: list[dict[str, Any]]
    ) -> int:
        """Batch timeline append — same idempotency per event ``key``, ONE
        document write for the whole batch (the monitor's trainer-event
        ingest folds every new ``events.jsonl`` row per tick through this,
        instead of a doc-rewriting RMW per event).  Returns the number of
        events actually appended."""
        if not events:
            return 0
        return await self.jobs.extend_list(job_id, "events", events)

    async def merge_job_metadata(self, job_id: str, patch: dict[str, Any]) -> bool:
        """Metadata-only merge WITHOUT touching the status field — for
        bookkeeping writers (the monitor's trainer-event ingest watermark)
        that must never race a concurrent status transition back to a stale
        value."""
        return await self.jobs.merge_subdoc(job_id, "metadata", _jsonify(patch))

    async def find_jobs_with_promotion_in(
        self, states: list[PromotionStatus | str]
    ) -> list[JobRecord]:
        """Jobs whose promotion_status is in ``states`` — the promotion
        manager's crash-recovery sweep (kept a domain method so the remote
        state service can serve it; predicates don't cross the wire)."""
        vals = {PromotionStatus(s).value for s in states}
        docs = await self.jobs.find(
            lambda d: d.get("promotion_status") in vals
        )
        return [JobRecord(**d) for d in docs]

    async def get_user_jobs(
        self,
        user_id: str | None,
        *,
        page: int = 1,
        page_size: int = 20,
        status: DatabaseStatus | None = None,
        search: str | None = None,
        sort_by: str = "submitted_at",
        descending: bool = True,
    ) -> PaginatedTableResponse:
        """Paginated job table with computed fields.

        Mirrors the reference's server-side aggregation pipeline
        (``db.py:282-379`` + ``_job_pipeline_add_fields`` ``db.py:381-517``):
        ``duration``, ``status_merged`` (status + promotion), and a stable
        ``index_`` row number; filtering by status and free-text search.
        ``user_id=None`` lists all users' jobs (the admin view,
        ``app/main.py:1099-1297``).
        """
        eq: dict[str, Any] = {}
        if user_id is not None:
            eq["user_id"] = user_id
        if status is not None:
            eq["status"] = DatabaseStatus(status).value
        docs = await self.jobs.find(eq=eq or None)
        if search:
            needle = search.lower()
            docs = [
                d
                for d in docs
                if needle in d["job_id"].lower() or needle in d["model_name"].lower()
            ]
        docs.sort(key=lambda d: (d.get(sort_by) is None, d.get(sort_by)), reverse=descending)
        total = len(docs)
        lo = max(page - 1, 0) * page_size
        page_docs = docs[lo : lo + page_size]
        now = time.time()
        items = []
        for i, d in enumerate(page_docs):
            start, end = d.get("start_time"), d.get("end_time")
            duration = None
            if start is not None:
                duration = (end if end is not None else now) - start
            status_merged = d["status"]
            if d.get("promotion_status") not in (None, PromotionStatus.NOT_PROMOTED.value):
                status_merged = f"{d['status']}/{d['promotion_status']}"
            items.append(
                {**d, "duration": duration, "status_merged": status_merged,
                 "index_": lo + i}
            )
        return PaginatedTableResponse(
            total=total, page=page, page_size=page_size, items=items
        )

    async def purge_job(self, job_id: str) -> bool:
        """Hard-delete without archiving — submission rollback only."""
        return (await self.jobs.delete(job_id)) is not None

    async def delete_job(self, job_id: str) -> bool:
        """Archive-on-delete (reference: ``db.py:519-526``)."""
        doc = await self.jobs.delete(job_id)
        if doc is None:
            return False
        doc["archived_at"] = time.time()
        await self.archived_jobs.insert(doc)
        await self.metrics.delete(job_id)
        return True

    # -- metrics (reference: db.py:150-193,528) -------------------------------

    async def upsert_metrics(self, metrics: MetricsDocument) -> None:
        await self.metrics.insert(metrics.model_dump(mode="json"))

    async def get_metrics(self, job_id: str) -> MetricsDocument | None:
        doc = await self.metrics.get(job_id)
        return MetricsDocument(**doc) if doc else None

    # -- datasets (reference: db.py:534-706) ----------------------------------

    async def insert_dataset(self, dataset: DatasetRecord) -> None:
        await self.datasets.insert(dataset.model_dump(mode="json"))

    async def get_dataset(self, dataset_id: str) -> DatasetRecord | None:
        doc = await self.datasets.get(dataset_id)
        return DatasetRecord(**doc) if doc else None

    async def get_user_datasets(self, user_id: str) -> list[DatasetRecord]:
        docs = await self.datasets.find(eq={"user_id": user_id})
        docs.sort(key=lambda d: d["created_at"], reverse=True)
        return [DatasetRecord(**d) for d in docs]

    async def add_dataset_job_ref(self, dataset_id: str, job_id: str) -> bool:
        """Append a job reference (reference: ``db.py:681-699``)."""
        doc = await self.datasets.get(dataset_id)
        if doc is None:
            return False
        refs = list(doc.get("job_refs") or [])
        if job_id not in refs:
            refs.append(job_id)
        return await self.datasets.update(dataset_id, {"job_refs": refs})

    async def delete_dataset(self, dataset_id: str) -> bool:
        return (await self.datasets.delete(dataset_id)) is not None

    # -- rate limiting --------------------------------------------------------

    async def rate_limit_acquire(
        self, key: str, limit: int, window_s: float = 60.0
    ) -> bool:
        """Sliding-window rate-limit check-and-record, atomic in this store's
        consistency domain: memory/jsonl → per-process (dev), sqlite → all
        processes sharing the state dir, the remote state service → the whole
        cluster (the reference's per-process slowapi limits multiply by the
        worker count — ``app/main.py:377,525,714``; here the scope follows
        the store)."""
        if limit <= 0:
            return True
        now = time.time()
        if self._db is not None:
            # periodic prune: anonymous users key on client IP, so a scanned
            # deployment accumulates one row per distinct IP — fully-stale
            # rows (last hit older than their own window) are swept every
            # few hundred acquires instead of on every hot-path transaction
            self._rate_ops = getattr(self, "_rate_ops", 0) + 1
            prune = self._rate_ops % 512 == 0

            def op(conn: sqlite3.Connection) -> bool:
                conn.execute(
                    'CREATE TABLE IF NOT EXISTS "rate_limits" '
                    "(key TEXT PRIMARY KEY, hits TEXT NOT NULL, "
                    "last_hit REAL NOT NULL DEFAULT 0, "
                    "window_s REAL NOT NULL DEFAULT 60)"
                )
                conn.execute("BEGIN IMMEDIATE")
                try:
                    if prune:
                        conn.execute(
                            'DELETE FROM "rate_limits" '
                            "WHERE last_hit + window_s < ?", (now,)
                        )
                    row = conn.execute(
                        'SELECT hits FROM "rate_limits" WHERE key = ?', (key,)
                    ).fetchone()
                    hits = [
                        t for t in (json.loads(row[0]) if row else [])
                        if t > now - window_s
                    ]
                    ok = len(hits) < limit
                    if ok:
                        hits.append(now)
                    conn.execute(
                        'INSERT INTO "rate_limits" '
                        "(key, hits, last_hit, window_s) VALUES (?, ?, ?, ?) "
                        "ON CONFLICT(key) DO UPDATE SET hits = excluded.hits, "
                        "last_hit = excluded.last_hit, "
                        "window_s = excluded.window_s",
                        (key, json.dumps(hits), now, window_s),
                    )
                    conn.execute("COMMIT")
                    return ok
                except BaseException:
                    conn.execute("ROLLBACK")
                    raise

            return await asyncio.to_thread(self._db.run, op)

        if len(self._mem_rate) > 10_000:
            # sweep fully-stale keys so distinct clients don't grow forever
            stale = [
                k for k, dq in self._mem_rate.items()
                if not dq or dq[-1] <= now - window_s
            ]
            for k in stale:
                del self._mem_rate[k]
        q = self._mem_rate.setdefault(key, collections.deque())
        while q and q[0] <= now - window_s:
            q.popleft()
        if len(q) >= limit:
            return False
        q.append(now)
        return True


def _jsonify(fields: dict[str, Any]) -> dict[str, Any]:
    return {
        k: (v.value if isinstance(v, (DatabaseStatus, PromotionStatus)) else v)
        for k, v in fields.items()
    }
