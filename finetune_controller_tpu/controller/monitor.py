"""Job monitor — the reconciliation loop of the control plane.

Capability parity with the reference's live monitor (``app/core/monitor.py``
— SURVEY.md §2 component 14, §3.2): every tick it snapshots the backend,
computes queue positions, maps backend state → DB status with metadata merge,
pulls training metrics out of the object store for running/finished jobs,
computes training duration, deletes *succeeded* jobs from the execution
substrate (artifacts already shipped), and leaves failed jobs in place for
forensics.

Reference warts fixed (SURVEY.md §7 step 3): the backend snapshot is async
(the reference makes a blocking SDK call inside the loop,
``app/core/monitor.py:131``) and DB lookups are batched instead of N+1
(``app/core/monitor.py:151-158``).

Beyond parity, the tick is also the attachment point for the resilience
subsystem (``finetune_controller_tpu/resilience/``): FAILED and swept-lost
jobs are handed to the :class:`~..resilience.supervisor.RetrySupervisor`
(classify → backoff → resubmit-with-resume), RUNNING jobs are checked
against their liveness lease (``resilience/heartbeat.py``), and due retries
are resubmitted — closing the failure loop the reference leaves to an
operator runbook.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import time
from typing import Any

from ..obs import events as obs_events
from ..obs.events import (
    EVENTS_FILENAME,
    append_event_safe,
    make_event,
    parse_event_lines,
)
from ..obs.trace import export_trace
from ..resilience.policy import classify_failure
from .backends.base import TrainingBackend
from .objectstore import ObjectStore
from .schemas import (
    BackendJobReport,
    BackendJobState,
    DatabaseStatus,
    JobRecord,
    MetricsDocument,
    map_backend_state,
)
from .statestore import StateStore

logger = logging.getLogger(__name__)

#: DB status transition → timeline event name (docs/observability.md)
_STATUS_EVENTS = {
    DatabaseStatus.QUEUED: obs_events.QUEUED,
    DatabaseStatus.CREATED: obs_events.ADMITTED,
    DatabaseStatus.RUNNING: obs_events.RUNNING,
    DatabaseStatus.RESTARTING: obs_events.RESTARTING,
    DatabaseStatus.SUCCEEDED: obs_events.SUCCEEDED,
    DatabaseStatus.FAILED: obs_events.FAILED,
    DatabaseStatus.UNKNOWN: obs_events.LOST,
}


class JobMonitor:
    """Poll-loop reconciler (reference: ``JobMonitor``, ``core/monitor.py:124-197``)."""

    def __init__(
        self,
        state: StateStore,
        store: ObjectStore,
        backend: TrainingBackend,
        *,
        interval_s: float = 2.0,
        supervisor=None,
        lease=None,
        obs=None,
    ):
        self.state = state
        self.store = store
        self.backend = backend
        self.interval_s = interval_s
        #: resilience attachments (None = reference-parity behavior: FAILED
        #: jobs are logged and left in place, no liveness enforcement)
        self.supervisor = supervisor  # resilience.supervisor.RetrySupervisor
        if supervisor is not None:
            # the supervisor writes terminal FAILED on paths the report loop
            # never revisits (budget spent via lease-kill/sweep, resubmit
            # failures inside its tick) — hook its terminal writes so those
            # jobs still get their trace exported
            supervisor.on_terminal = self._export_trace
        self.lease = lease  # resilience.heartbeat.LeaseChecker
        #: observability hub (obs/prom.py): queue-wait + step-phase
        #: histograms observe into it; None = no histogram observation
        self.obs = obs
        self._task: asyncio.Task | None = None
        self._stop = asyncio.Event()
        self.ticks = 0  # observability: total reconcile passes
        self.lease_kills = 0  # jobs declared stuck by the liveness lease
        #: per-job high-water mark (step) for phase-histogram observation —
        #: the stored record COUNT is not a safe watermark: the resume
        #: replay-truncation shrinks the metrics doc, and a count would
        #: observe the re-logged windows a second time
        self._phase_step_hwm: dict[str, float] = {}
        #: per-job events.jsonl byte size at the last successful ingest — a
        #: cheap stat short-circuit so an unchanged file costs no read/tick
        self._events_size: dict[str, int] = {}

    # -- lifecycle (reference: core/monitor.py:207-224) ----------------------

    def start(self) -> None:
        if self._task is not None and not self._task.done():
            return
        self._stop.clear()
        self._task = asyncio.get_running_loop().create_task(self._loop())
        logger.info("job monitor started (interval=%.1fs)", self.interval_s)

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None
        logger.info("job monitor stopped")

    async def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                await self.tick()
            except Exception:
                logger.exception("monitor tick failed")  # keep reconciling
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._stop.wait(), timeout=self.interval_s)

    # -- one reconcile pass (reference: core/monitor.py:124-197) -------------

    #: a non-final DB job absent from the backend snapshot for longer than
    #: this is declared lost (covers the record-before-submit window)
    lost_job_grace_s: float = 30.0

    async def tick(self) -> None:
        self.ticks += 1
        reports = await self.backend.list_jobs()
        await self._sweep_lost_jobs({r.job_id for r in reports})
        if self.supervisor is not None:
            # resubmit retries whose backoff expired — runs even on an empty
            # snapshot (a RETRYING job has, by design, no backend half)
            await self.supervisor.tick()
        sched_tick = getattr(self.backend, "scheduler_tick", None)
        if sched_tick is not None:
            # tick-driven admission (docs/scheduling.md): re-evaluate
            # admission/preemption even without a submit/release edge, and
            # within the same tick that resubmitted due retries — a
            # preemptor must be admitted within one monitor tick of its
            # victims' chips freeing
            sched_tick()
        if not reports:
            return
        pending = await self.backend.queue_snapshot()  # queue order (kueue_helpers.py:19-46)
        db_jobs = await self.state.get_jobs_by_ids([r.job_id for r in reports])
        for report in reports:
            job = db_jobs.get(report.job_id)
            if job is None:
                # backend knows a job the DB doesn't — externally created or
                # the record was deleted; nothing to reconcile into
                continue
            if job.status.is_final:
                # settled: the per-job observation watermarks have no more
                # rows to gate (bounded memory across a long-lived monitor)
                self._phase_step_hwm.pop(job.job_id, None)
                self._events_size.pop(job.job_id, None)
                # skip already-final jobs (core/monitor.py:150-155); a job the
                # user cancelled still needs its backend half cleaned up —
                # including any resize reservation (it is not coming back)
                if job.status is DatabaseStatus.CANCELLED:
                    await self.backend.delete_job(
                        report.job_id, forget_reservations=True
                    )
                if job.artifacts_uri and not job.metadata.get("trace_exported"):
                    # settled outside the report loop (user cancel, a
                    # terminal write that raced this tick) while its report
                    # lingers: export the trace before the report disappears
                    await self._export_trace(job.job_id)
                continue
            if job.status is DatabaseStatus.RETRYING:
                # waiting out its backoff: the supervisor owns this job and
                # already tore the backend half down — a report that lingers
                # (delete raced/failed) is stale and must not re-enter the
                # failure path (it would burn an attempt per tick)
                continue
            # for a FAILED report the supervisor owns the status transition
            # (RETRYING or terminal FAILED) — persisting FAILED here first
            # would open a crash window in which a retryable job is stuck
            # terminally FAILED with no attempt recorded; persist the timing
            # fields/metadata under the CURRENT status instead
            keep_status = (
                report.state is BackendJobState.FAILED
                and self.supervisor is not None
            )
            await self._update_job_status(
                job, report, pending, keep_status=keep_status
            )
            status = map_backend_state(report.state)
            if status in (DatabaseStatus.RUNNING,) or status.is_final:
                await self._process_job_metrics(job)
                # trainer-side lifecycle events (checkpoint-committed, ...)
                # ride events.jsonl through the artifact channel; fold new
                # rows into the job document's timeline
                await self._ingest_trainer_events(job)
            if report.state is BackendJobState.SUCCEEDED:
                await self._export_trace(job.job_id)
                # artifacts are in the object store; free the substrate
                # (core/monitor.py:182-186), reservations included — a
                # finished job's pending grow/shrink is moot
                await self.backend.delete_job(
                    report.job_id, forget_reservations=True
                )
            elif report.state is BackendJobState.FAILED:
                await self._handle_failed(job, report)
                # terminal failures (retry budget spent / user error) freeze
                # the timeline — export the assembled trace next to the
                # artifacts while the spans are still fresh
                await self._export_trace(job.job_id)
            elif report.state is BackendJobState.RUNNING:
                await self._check_lease(job, report)

    async def _handle_failed(self, job: JobRecord, report: BackendJobReport) -> None:
        """Failure intake: classify + persist forensics, then either hand the
        job to the retry supervisor or (reference behavior) leave it FAILED
        in place for inspection (core/monitor.py:187-191)."""
        exit_code = report.metadata.get("exit_code")
        if self.supervisor is not None:
            await self.supervisor.on_job_failed(
                job, exit_code=exit_code, message=report.message,
                # a scheduler resize rides the failure path (SIGTERM → 143)
                # but resubmits at a DIFFERENT topology (docs/elasticity.md)
                resize_to=report.metadata.get("resize_to_num_slices"),
                # preemption/resize context for the timeline events the
                # supervisor appends (docs/observability.md)
                report_metadata=report.metadata,
            )
            return
        # no supervisor: still persist the failure class so users (and a
        # later-enabled supervisor) can tell an OOM from bad hyperparameters
        failure = classify_failure(exit_code, report.message)
        await self.state.update_job_status(
            job.job_id,
            DatabaseStatus.FAILED,
            metadata={"failure_class": failure.value},
        )
        logger.warning(
            "job %s failed (class=%s): %s",
            report.job_id, failure.value, report.message,
        )

    async def _check_lease(self, job: JobRecord, report: BackendJobReport) -> None:
        """Liveness lease (resilience/heartbeat.py): a RUNNING job whose
        heartbeat went stale is stuck — kill it and route it through the
        failure path like any infra failure."""
        if self.lease is None:
            return
        if not await self.lease.expired(job, report):
            return
        self.lease_kills += 1
        # the last heartbeat names where the job got stuck — log it and put
        # it on the timeline so a post-mortem starts from the right step
        last_hb = getattr(self.lease, "last_heartbeat", None) or {}
        last_step = last_hb.get("last_step", last_hb.get("step"))
        message = (
            f"liveness lease expired: no heartbeat for >{self.lease.lease_s:.0f}s"
            + (f" (last known step {last_step})" if last_step is not None else "")
        )
        logger.warning("job %s declared stuck (%s); killing", job.job_id, message)
        await self._event(
            job, obs_events.LEASE_KILLED, last_step=last_step,
            lease_s=self.lease.lease_s,
        )
        await self.backend.delete_job(job.job_id)
        if self.supervisor is not None:
            await self.supervisor.on_job_failed(job, exit_code=None, message=message)
        else:
            await self.state.update_job_status(
                job.job_id,
                DatabaseStatus.FAILED,
                metadata={
                    "backend_message": message,
                    "failure_class": classify_failure(None, message).value,
                },
                end_time=time.time(),
                queue_position=None,
            )
        # the kill deleted the backend half, so the report loop never sees a
        # FAILED report for this job — export here if the kill was terminal
        # (no-op while a retry is scheduled: the job is not final yet)
        await self._export_trace(job.job_id)

    async def _sweep_lost_jobs(self, backend_ids: set[str]) -> None:
        """Mark non-final DB jobs the backend has forgotten as UNKNOWN (or
        hand them straight to the retry supervisor).

        The reference never needed this — its substrate (the cluster) is
        durable. An in-memory backend forgets everything on process restart,
        so without the sweep a QUEUED/RUNNING record would stay live forever.
        RETRYING jobs are exempt: their backend half was deliberately torn
        down while they wait out a backoff window.
        """
        for job in await self.state.get_active_jobs():
            if job.job_id in backend_ids or job.status in (
                DatabaseStatus.UNKNOWN, DatabaseStatus.RETRYING,
            ):
                continue
            if time.time() - job.submitted_at < self.lost_job_grace_s:
                continue  # may still be inside the submit path
            message = "job no longer tracked by the backend"
            if self.supervisor is not None:
                # a vanished job is an infra failure (substrate restart, node
                # loss): hand it straight to the supervisor, which CAS-es
                # from the CURRENT status to RETRYING/FAILED in one write —
                # an UNKNOWN stopover would open a crash window in which the
                # job parks in UNKNOWN forever (the sweep skips UNKNOWN)
                logger.warning("job %s vanished from backend; supervising",
                               job.job_id)
                await self.supervisor.on_job_failed(
                    job, exit_code=None, message=message
                )
                continue
            logger.warning("job %s vanished from backend; marking unknown", job.job_id)
            await self._event(job, obs_events.LOST, message=message)
            await self.state.update_job_status(
                job.job_id,
                DatabaseStatus.UNKNOWN,
                metadata={"backend_message": message},
                queue_position=None,
            )

    async def _update_job_status(
        self,
        job: JobRecord,
        report: BackendJobReport,
        pending: list[str],
        *,
        keep_status: bool = False,
    ) -> None:
        """Map + persist one job's state (reference: ``core/monitor.py:97-122``).

        ``keep_status`` persists the fields/metadata but leaves the status
        untouched — used when a downstream owner (the retry supervisor) will
        write the real transition atomically."""
        status = job.status if keep_status else map_backend_state(report.state)
        fields: dict[str, Any] = {}
        if report.start_time is not None:
            fields["start_time"] = report.start_time
        if report.completion_time is not None:
            fields["end_time"] = report.completion_time
            if report.start_time is not None:
                # training duration (reference: core/monitor.py:56-69)
                fields["training_duration"] = report.completion_time - report.start_time
        queue_position = (
            pending.index(report.job_id) + 1 if report.job_id in pending else None
        )
        fields["queue_position"] = queue_position
        metadata: dict[str, Any] = {}
        if report.message:
            metadata["backend_message"] = report.message
        if report.metadata:
            metadata.update(report.metadata)
        changed = (
            status != job.status
            or queue_position != job.queue_position
            or "end_time" in fields
            or ("start_time" in fields and job.start_time is None)
        )
        if status != job.status:
            # timeline event BEFORE the status write: a crash in between
            # re-observes the same transition next tick and the idempotency
            # key folds the retry into exactly one event.  The key carries a
            # transition sequence number that only advances WITH the status
            # write below — so a crash-retry reuses the key (exactly-once)
            # while a genuine repeat within one attempt (pod restart →
            # RESTARTING → RUNNING recovery) gets a fresh one instead of
            # being dropped as a duplicate
            seq = int(job.metadata.get("obs_transition_seq") or 0)
            metadata["obs_transition_seq"] = seq + 1
            event = _STATUS_EVENTS.get(status)
            if event is not None:
                attempt = 1 + len(job.metadata.get("attempt_history") or [])
                attrs: dict[str, Any] = {}
                if event == obs_events.RUNNING:
                    attrs["slices"] = report.metadata.get("last_ran_num_slices")
                if report.message and event in (
                    obs_events.FAILED, obs_events.LOST,
                ):
                    attrs["message"] = report.message
                await self._event(
                    job, event, key=f"{event}:a{attempt}:t{seq}", **attrs
                )
            if (
                self.obs is not None
                and status is DatabaseStatus.RUNNING
                and job.status in (DatabaseStatus.QUEUED, DatabaseStatus.CREATED)
            ):
                # queue wait: submit (or requeue — submitted_at resets on
                # resubmission) to execution, per attempt
                started = report.start_time or time.time()
                self.obs.queue_wait_seconds.observe(
                    max(started - job.submitted_at, 0.0)
                )
        if changed:
            await self.state.update_job_status(
                job.job_id, status, metadata=metadata or None, **fields
            )

    async def _process_job_metrics(self, job: JobRecord) -> None:
        """Metrics CSV → DB records (reference: ``core/monitor.py:34-95`` +
        ``S3Handler.py:237-292``): newest ``*metrics*.csv`` under the
        artifacts prefix wins."""
        if not job.artifacts_uri:
            return
        try:
            result = await self.store.get_metrics_records(job.artifacts_uri)
        except Exception:
            logger.exception("metrics fetch failed for %s", job.job_id)
            return
        if result is None:
            return
        records, source_uri = result
        existing = await self.state.get_metrics(job.job_id)
        if existing is not None and existing.records == records:
            return  # unchanged (content compare: rewritten rows with the same
            # count must still propagate)
        if self.obs is not None:
            # step-phase histograms (docs/observability.md): each row's
            # phase_*_ms columns are one observation per phase, exactly once
            # per step — gated on a per-process step high-water mark.  The
            # stored record count is NOT a safe watermark: a crash-resume
            # truncates replayed rows from the CSV (MetricsWriter's
            # replay-drop), the doc shrinks, and a count would observe the
            # re-logged windows a second time, inflating every bucket.
            hwm = self._phase_step_hwm.get(job.job_id)
            if hwm is None:
                # first sight since this monitor started: rows already in
                # the doc belong to a previous process's histograms — only
                # genuinely new rows observe into this one
                hwm = max(
                    (
                        float(r["step"]) for r in
                        (existing.records if existing is not None else [])
                        if isinstance(r.get("step"), (int, float))
                    ),
                    default=float("-inf"),
                )
            for row in records:
                step = row.get("step")
                if not isinstance(step, (int, float)) or float(step) <= hwm:
                    continue
                self.obs.observe_step_phases(row)
                # rows are step-ascending within one CSV; max() keeps a
                # ragged row from rolling the mark backwards
                hwm = max(hwm, float(step))
            self._phase_step_hwm[job.job_id] = hwm
        await self.state.upsert_metrics(
            MetricsDocument(
                job_id=job.job_id,
                records=records,
                source_uri=source_uri,
                updated_at=time.time(),
            )
        )

    # -- observability (docs/observability.md) -------------------------------

    async def _event(self, job: JobRecord, event: str, *,
                     key: str | None = None, **attrs: Any) -> None:
        """Append a timeline event for ``job``, keyed per supervisor attempt
        so re-observed transitions stay exactly-once; best-effort — the
        timeline must never stall reconciliation.  Status transitions pass
        an episode-scoped ``key`` (see ``_update_job_status``) because the
        per-attempt default would fold a second same-attempt episode (pod
        restart → recovery) into the first."""
        attempt = 1 + len(job.metadata.get("attempt_history") or [])
        await append_event_safe(
            self.state, job.job_id, event,
            key=key or f"{event}:a{attempt}", attempt=attempt, **attrs,
        )

    async def _ingest_trainer_events(self, job: JobRecord) -> None:
        """Fold new ``events.jsonl`` rows (trainer-side lifecycle:
        train-started, checkpoint-committed, profile-captured, ...) into the
        job document's timeline.  The watermark in the job metadata is an
        optimization only — the per-line idempotency key (scoped by attempt,
        see below) is what guarantees exactly-once.  All new rows of a tick
        land in ONE batched document write."""
        if not job.artifacts_uri:
            return
        uri = f"{job.artifacts_uri}/{EVENTS_FILENAME}"
        try:
            size = await self.store.size(uri)
            if size is not None and size == self._events_size.get(job.job_id):
                return  # unchanged since the last successful ingest
            if size is None and not await self.store.exists(uri):
                return  # store can't stat cheaply; fall back to exists+read
            rows = parse_event_lines(await self.store.get_bytes(uri))
        except FileNotFoundError:
            return  # no events file yet
        except Exception:
            logger.debug("trainer-event read failed for %s", job.job_id,
                         exc_info=True)
            return
        n0 = int(job.metadata.get("obs_events_ingested") or 0)
        # Restart detection: a fresh sandbox on a backend that does not
        # stage events.jsonl back (e.g. a k8s retry pod) re-begins the file
        # at line 0 and the sidecar overwrites the stored copy — the
        # positional watermark is void.  The first line is the fingerprint
        # (append-only files never change it); a length check alone would
        # miss a restarted file that has already grown past the watermark,
        # silently dropping the new attempt's first n0 rows.  The
        # attempt-scoped keys below keep the re-scan from colliding with
        # (and being dropped as) the old attempt's lines.
        head = (
            json.dumps(rows[0], sort_keys=True) if rows else None
        )
        stored_head = job.metadata.get("obs_events_head")
        if n0 and (
            len(rows) < n0
            or (stored_head is not None and head != stored_head)
        ):
            n0 = 0
        if len(rows) <= n0:
            if size is not None:
                self._events_size[job.job_id] = size
            return
        events = []
        for idx in range(n0, len(rows)):
            row = rows[idx]
            attrs = {
                k: v for k, v in (row.get("attrs") or {}).items()
                # the file is untrusted input: an attr named after one of
                # make_event's own parameters would raise a TypeError
                if isinstance(k, str) and k not in ("event", "ts", "key")
            }
            try:
                # key on (attempt, line index): the line index alone would
                # make a restarted file's row idx collide with a prior
                # attempt's already-ingested key and silently drop the event
                attempt = attrs.get("attempt")
                attempt = (
                    int(attempt) if isinstance(attempt, (int, float)) else 0
                )
                # a garbage ts must not poison the ingest every tick — fall
                # back to make_event's now-stamp
                ts = row.get("ts")
                events.append(
                    make_event(row["event"],
                               ts=ts if isinstance(ts, (int, float)) else None,
                               key=f"trainer:a{attempt}:{idx}", **attrs)
                )
            except Exception:
                # one corrupt row (NaN attempt, ...) must not abort the
                # reconcile pass — skip it, keep the rest of the batch
                logger.debug("skipping corrupt events.jsonl row %d for %s",
                             idx, job.job_id, exc_info=True)
        try:
            await self.state.append_job_events(job.job_id, events)
            await self.state.merge_job_metadata(
                job.job_id,
                {"obs_events_ingested": len(rows), "obs_events_head": head},
            )
        except Exception:
            # best-effort (the module contract: the timeline must never
            # stall reconciliation) — the size cache stays stale so the
            # next tick retries, and the per-event keys keep that idempotent
            logger.debug("trainer-event ingest write failed for %s",
                         job.job_id, exc_info=True)
            return
        if size is not None:
            self._events_size[job.job_id] = size

    async def _export_trace(self, job_id: str) -> None:
        """Persist the assembled span tree next to the artifacts when a job
        settles — traces survive control-plane restarts and substrate
        cleanup, like the archived logs."""
        await export_trace(self.state, self.store, job_id)
