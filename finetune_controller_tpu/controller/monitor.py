"""Job monitor — the reconciliation loop of the control plane.

Capability parity with the reference's live monitor (``app/core/monitor.py``
— SURVEY.md §2 component 14, §3.2): every tick it snapshots the backend,
computes queue positions, maps backend state → DB status with metadata merge,
pulls training metrics out of the object store for running/finished jobs,
computes training duration, deletes *succeeded* jobs from the execution
substrate (artifacts already shipped), and leaves failed jobs in place for
forensics.

Reference warts fixed (SURVEY.md §7 step 3): the backend snapshot is async
(the reference makes a blocking SDK call inside the loop,
``app/core/monitor.py:131``) and DB lookups are batched instead of N+1
(``app/core/monitor.py:151-158``).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time
from typing import Any

from .backends.base import TrainingBackend
from .objectstore import ObjectStore
from .schemas import (
    BackendJobReport,
    BackendJobState,
    DatabaseStatus,
    JobRecord,
    MetricsDocument,
    map_backend_state,
)
from .statestore import StateStore

logger = logging.getLogger(__name__)


class JobMonitor:
    """Poll-loop reconciler (reference: ``JobMonitor``, ``core/monitor.py:124-197``)."""

    def __init__(
        self,
        state: StateStore,
        store: ObjectStore,
        backend: TrainingBackend,
        *,
        interval_s: float = 2.0,
    ):
        self.state = state
        self.store = store
        self.backend = backend
        self.interval_s = interval_s
        self._task: asyncio.Task | None = None
        self._stop = asyncio.Event()
        self.ticks = 0  # observability: total reconcile passes

    # -- lifecycle (reference: core/monitor.py:207-224) ----------------------

    def start(self) -> None:
        if self._task is not None and not self._task.done():
            return
        self._stop.clear()
        self._task = asyncio.get_running_loop().create_task(self._loop())
        logger.info("job monitor started (interval=%.1fs)", self.interval_s)

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None
        logger.info("job monitor stopped")

    async def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                await self.tick()
            except Exception:
                logger.exception("monitor tick failed")  # keep reconciling
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._stop.wait(), timeout=self.interval_s)

    # -- one reconcile pass (reference: core/monitor.py:124-197) -------------

    #: a non-final DB job absent from the backend snapshot for longer than
    #: this is declared lost (covers the record-before-submit window)
    lost_job_grace_s: float = 30.0

    async def tick(self) -> None:
        self.ticks += 1
        reports = await self.backend.list_jobs()
        await self._sweep_lost_jobs({r.job_id for r in reports})
        if not reports:
            return
        pending = await self.backend.queue_snapshot()  # queue order (kueue_helpers.py:19-46)
        db_jobs = await self.state.get_jobs_by_ids([r.job_id for r in reports])
        for report in reports:
            job = db_jobs.get(report.job_id)
            if job is None:
                # backend knows a job the DB doesn't — externally created or
                # the record was deleted; nothing to reconcile into
                continue
            if job.status.is_final:
                # skip already-final jobs (core/monitor.py:150-155); a job the
                # user cancelled still needs its backend half cleaned up
                if job.status is DatabaseStatus.CANCELLED:
                    await self.backend.delete_job(report.job_id)
                continue
            await self._update_job_status(job, report, pending)
            status = map_backend_state(report.state)
            if status in (DatabaseStatus.RUNNING,) or status.is_final:
                await self._process_job_metrics(job)
            if report.state is BackendJobState.SUCCEEDED:
                # artifacts are in the object store; free the substrate
                # (core/monitor.py:182-186)
                await self.backend.delete_job(report.job_id)
            elif report.state is BackendJobState.FAILED:
                # keep for inspection (core/monitor.py:187-191)
                logger.warning("job %s failed: %s", report.job_id, report.message)

    async def _sweep_lost_jobs(self, backend_ids: set[str]) -> None:
        """Mark non-final DB jobs the backend has forgotten as UNKNOWN.

        The reference never needed this — its substrate (the cluster) is
        durable. An in-memory backend forgets everything on process restart,
        so without the sweep a QUEUED/RUNNING record would stay live forever.
        """
        for job in await self.state.get_active_jobs():
            if job.job_id in backend_ids or job.status is DatabaseStatus.UNKNOWN:
                continue
            if time.time() - job.submitted_at < self.lost_job_grace_s:
                continue  # may still be inside the submit path
            logger.warning("job %s vanished from backend; marking unknown", job.job_id)
            await self.state.update_job_status(
                job.job_id,
                DatabaseStatus.UNKNOWN,
                metadata={"backend_message": "job no longer tracked by the backend"},
                queue_position=None,
            )

    async def _update_job_status(
        self,
        job: JobRecord,
        report: BackendJobReport,
        pending: list[str],
    ) -> None:
        """Map + persist one job's state (reference: ``core/monitor.py:97-122``)."""
        status = map_backend_state(report.state)
        fields: dict[str, Any] = {}
        if report.start_time is not None:
            fields["start_time"] = report.start_time
        if report.completion_time is not None:
            fields["end_time"] = report.completion_time
            if report.start_time is not None:
                # training duration (reference: core/monitor.py:56-69)
                fields["training_duration"] = report.completion_time - report.start_time
        queue_position = (
            pending.index(report.job_id) + 1 if report.job_id in pending else None
        )
        fields["queue_position"] = queue_position
        metadata: dict[str, Any] = {}
        if report.message:
            metadata["backend_message"] = report.message
        if report.metadata:
            metadata.update(report.metadata)
        changed = (
            status != job.status
            or queue_position != job.queue_position
            or "end_time" in fields
            or ("start_time" in fields and job.start_time is None)
        )
        if changed:
            await self.state.update_job_status(
                job.job_id, status, metadata=metadata or None, **fields
            )

    async def _process_job_metrics(self, job: JobRecord) -> None:
        """Metrics CSV → DB records (reference: ``core/monitor.py:34-95`` +
        ``S3Handler.py:237-292``): newest ``*metrics*.csv`` under the
        artifacts prefix wins."""
        if not job.artifacts_uri:
            return
        try:
            result = await self.store.get_metrics_records(job.artifacts_uri)
        except Exception:
            logger.exception("metrics fetch failed for %s", job.job_id)
            return
        if result is None:
            return
        records, source_uri = result
        existing = await self.state.get_metrics(job.job_id)
        if existing is not None and existing.records == records:
            return  # unchanged (content compare: rewritten rows with the same
            # count must still propagate)
        await self.state.upsert_metrics(
            MetricsDocument(
                job_id=job.job_id,
                records=records,
                source_uri=source_uri,
                updated_at=time.time(),
            )
        )
