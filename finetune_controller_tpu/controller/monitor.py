"""Job monitor — the reconciliation loop of the control plane.

Capability parity with the reference's live monitor (``app/core/monitor.py``
— SURVEY.md §2 component 14, §3.2): every tick it snapshots the backend,
computes queue positions, maps backend state → DB status with metadata merge,
pulls training metrics out of the object store for running/finished jobs,
computes training duration, deletes *succeeded* jobs from the execution
substrate (artifacts already shipped), and leaves failed jobs in place for
forensics.

Reference warts fixed (SURVEY.md §7 step 3): the backend snapshot is async
(the reference makes a blocking SDK call inside the loop,
``app/core/monitor.py:131``) and DB lookups are batched instead of N+1
(``app/core/monitor.py:151-158``).

Beyond parity, the tick is also the attachment point for the resilience
subsystem (``finetune_controller_tpu/resilience/``): FAILED and swept-lost
jobs are handed to the :class:`~..resilience.supervisor.RetrySupervisor`
(classify → backoff → resubmit-with-resume), RUNNING jobs are checked
against their liveness lease (``resilience/heartbeat.py``), and due retries
are resubmitted — closing the failure loop the reference leaves to an
operator runbook.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time
from typing import Any

from ..resilience.policy import classify_failure
from .backends.base import TrainingBackend
from .objectstore import ObjectStore
from .schemas import (
    BackendJobReport,
    BackendJobState,
    DatabaseStatus,
    JobRecord,
    MetricsDocument,
    map_backend_state,
)
from .statestore import StateStore

logger = logging.getLogger(__name__)


class JobMonitor:
    """Poll-loop reconciler (reference: ``JobMonitor``, ``core/monitor.py:124-197``)."""

    def __init__(
        self,
        state: StateStore,
        store: ObjectStore,
        backend: TrainingBackend,
        *,
        interval_s: float = 2.0,
        supervisor=None,
        lease=None,
    ):
        self.state = state
        self.store = store
        self.backend = backend
        self.interval_s = interval_s
        #: resilience attachments (None = reference-parity behavior: FAILED
        #: jobs are logged and left in place, no liveness enforcement)
        self.supervisor = supervisor  # resilience.supervisor.RetrySupervisor
        self.lease = lease  # resilience.heartbeat.LeaseChecker
        self._task: asyncio.Task | None = None
        self._stop = asyncio.Event()
        self.ticks = 0  # observability: total reconcile passes
        self.lease_kills = 0  # jobs declared stuck by the liveness lease

    # -- lifecycle (reference: core/monitor.py:207-224) ----------------------

    def start(self) -> None:
        if self._task is not None and not self._task.done():
            return
        self._stop.clear()
        self._task = asyncio.get_running_loop().create_task(self._loop())
        logger.info("job monitor started (interval=%.1fs)", self.interval_s)

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
            self._task = None
        logger.info("job monitor stopped")

    async def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                await self.tick()
            except Exception:
                logger.exception("monitor tick failed")  # keep reconciling
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._stop.wait(), timeout=self.interval_s)

    # -- one reconcile pass (reference: core/monitor.py:124-197) -------------

    #: a non-final DB job absent from the backend snapshot for longer than
    #: this is declared lost (covers the record-before-submit window)
    lost_job_grace_s: float = 30.0

    async def tick(self) -> None:
        self.ticks += 1
        reports = await self.backend.list_jobs()
        await self._sweep_lost_jobs({r.job_id for r in reports})
        if self.supervisor is not None:
            # resubmit retries whose backoff expired — runs even on an empty
            # snapshot (a RETRYING job has, by design, no backend half)
            await self.supervisor.tick()
        sched_tick = getattr(self.backend, "scheduler_tick", None)
        if sched_tick is not None:
            # tick-driven admission (docs/scheduling.md): re-evaluate
            # admission/preemption even without a submit/release edge, and
            # within the same tick that resubmitted due retries — a
            # preemptor must be admitted within one monitor tick of its
            # victims' chips freeing
            sched_tick()
        if not reports:
            return
        pending = await self.backend.queue_snapshot()  # queue order (kueue_helpers.py:19-46)
        db_jobs = await self.state.get_jobs_by_ids([r.job_id for r in reports])
        for report in reports:
            job = db_jobs.get(report.job_id)
            if job is None:
                # backend knows a job the DB doesn't — externally created or
                # the record was deleted; nothing to reconcile into
                continue
            if job.status.is_final:
                # skip already-final jobs (core/monitor.py:150-155); a job the
                # user cancelled still needs its backend half cleaned up —
                # including any resize reservation (it is not coming back)
                if job.status is DatabaseStatus.CANCELLED:
                    await self.backend.delete_job(
                        report.job_id, forget_reservations=True
                    )
                continue
            if job.status is DatabaseStatus.RETRYING:
                # waiting out its backoff: the supervisor owns this job and
                # already tore the backend half down — a report that lingers
                # (delete raced/failed) is stale and must not re-enter the
                # failure path (it would burn an attempt per tick)
                continue
            # for a FAILED report the supervisor owns the status transition
            # (RETRYING or terminal FAILED) — persisting FAILED here first
            # would open a crash window in which a retryable job is stuck
            # terminally FAILED with no attempt recorded; persist the timing
            # fields/metadata under the CURRENT status instead
            keep_status = (
                report.state is BackendJobState.FAILED
                and self.supervisor is not None
            )
            await self._update_job_status(
                job, report, pending, keep_status=keep_status
            )
            status = map_backend_state(report.state)
            if status in (DatabaseStatus.RUNNING,) or status.is_final:
                await self._process_job_metrics(job)
            if report.state is BackendJobState.SUCCEEDED:
                # artifacts are in the object store; free the substrate
                # (core/monitor.py:182-186), reservations included — a
                # finished job's pending grow/shrink is moot
                await self.backend.delete_job(
                    report.job_id, forget_reservations=True
                )
            elif report.state is BackendJobState.FAILED:
                await self._handle_failed(job, report)
            elif report.state is BackendJobState.RUNNING:
                await self._check_lease(job, report)

    async def _handle_failed(self, job: JobRecord, report: BackendJobReport) -> None:
        """Failure intake: classify + persist forensics, then either hand the
        job to the retry supervisor or (reference behavior) leave it FAILED
        in place for inspection (core/monitor.py:187-191)."""
        exit_code = report.metadata.get("exit_code")
        if self.supervisor is not None:
            await self.supervisor.on_job_failed(
                job, exit_code=exit_code, message=report.message,
                # a scheduler resize rides the failure path (SIGTERM → 143)
                # but resubmits at a DIFFERENT topology (docs/elasticity.md)
                resize_to=report.metadata.get("resize_to_num_slices"),
            )
            return
        # no supervisor: still persist the failure class so users (and a
        # later-enabled supervisor) can tell an OOM from bad hyperparameters
        failure = classify_failure(exit_code, report.message)
        await self.state.update_job_status(
            job.job_id,
            DatabaseStatus.FAILED,
            metadata={"failure_class": failure.value},
        )
        logger.warning(
            "job %s failed (class=%s): %s",
            report.job_id, failure.value, report.message,
        )

    async def _check_lease(self, job: JobRecord, report: BackendJobReport) -> None:
        """Liveness lease (resilience/heartbeat.py): a RUNNING job whose
        heartbeat went stale is stuck — kill it and route it through the
        failure path like any infra failure."""
        if self.lease is None:
            return
        if not await self.lease.expired(job, report):
            return
        self.lease_kills += 1
        message = (
            f"liveness lease expired: no heartbeat for >{self.lease.lease_s:.0f}s"
        )
        logger.warning("job %s declared stuck (%s); killing", job.job_id, message)
        await self.backend.delete_job(job.job_id)
        if self.supervisor is not None:
            await self.supervisor.on_job_failed(job, exit_code=None, message=message)
        else:
            await self.state.update_job_status(
                job.job_id,
                DatabaseStatus.FAILED,
                metadata={
                    "backend_message": message,
                    "failure_class": classify_failure(None, message).value,
                },
                end_time=time.time(),
                queue_position=None,
            )

    async def _sweep_lost_jobs(self, backend_ids: set[str]) -> None:
        """Mark non-final DB jobs the backend has forgotten as UNKNOWN (or
        hand them straight to the retry supervisor).

        The reference never needed this — its substrate (the cluster) is
        durable. An in-memory backend forgets everything on process restart,
        so without the sweep a QUEUED/RUNNING record would stay live forever.
        RETRYING jobs are exempt: their backend half was deliberately torn
        down while they wait out a backoff window.
        """
        for job in await self.state.get_active_jobs():
            if job.job_id in backend_ids or job.status in (
                DatabaseStatus.UNKNOWN, DatabaseStatus.RETRYING,
            ):
                continue
            if time.time() - job.submitted_at < self.lost_job_grace_s:
                continue  # may still be inside the submit path
            message = "job no longer tracked by the backend"
            if self.supervisor is not None:
                # a vanished job is an infra failure (substrate restart, node
                # loss): hand it straight to the supervisor, which CAS-es
                # from the CURRENT status to RETRYING/FAILED in one write —
                # an UNKNOWN stopover would open a crash window in which the
                # job parks in UNKNOWN forever (the sweep skips UNKNOWN)
                logger.warning("job %s vanished from backend; supervising",
                               job.job_id)
                await self.supervisor.on_job_failed(
                    job, exit_code=None, message=message
                )
                continue
            logger.warning("job %s vanished from backend; marking unknown", job.job_id)
            await self.state.update_job_status(
                job.job_id,
                DatabaseStatus.UNKNOWN,
                metadata={"backend_message": message},
                queue_position=None,
            )

    async def _update_job_status(
        self,
        job: JobRecord,
        report: BackendJobReport,
        pending: list[str],
        *,
        keep_status: bool = False,
    ) -> None:
        """Map + persist one job's state (reference: ``core/monitor.py:97-122``).

        ``keep_status`` persists the fields/metadata but leaves the status
        untouched — used when a downstream owner (the retry supervisor) will
        write the real transition atomically."""
        status = job.status if keep_status else map_backend_state(report.state)
        fields: dict[str, Any] = {}
        if report.start_time is not None:
            fields["start_time"] = report.start_time
        if report.completion_time is not None:
            fields["end_time"] = report.completion_time
            if report.start_time is not None:
                # training duration (reference: core/monitor.py:56-69)
                fields["training_duration"] = report.completion_time - report.start_time
        queue_position = (
            pending.index(report.job_id) + 1 if report.job_id in pending else None
        )
        fields["queue_position"] = queue_position
        metadata: dict[str, Any] = {}
        if report.message:
            metadata["backend_message"] = report.message
        if report.metadata:
            metadata.update(report.metadata)
        changed = (
            status != job.status
            or queue_position != job.queue_position
            or "end_time" in fields
            or ("start_time" in fields and job.start_time is None)
        )
        if changed:
            await self.state.update_job_status(
                job.job_id, status, metadata=metadata or None, **fields
            )

    async def _process_job_metrics(self, job: JobRecord) -> None:
        """Metrics CSV → DB records (reference: ``core/monitor.py:34-95`` +
        ``S3Handler.py:237-292``): newest ``*metrics*.csv`` under the
        artifacts prefix wins."""
        if not job.artifacts_uri:
            return
        try:
            result = await self.store.get_metrics_records(job.artifacts_uri)
        except Exception:
            logger.exception("metrics fetch failed for %s", job.job_id)
            return
        if result is None:
            return
        records, source_uri = result
        existing = await self.state.get_metrics(job.job_id)
        if existing is not None and existing.records == records:
            return  # unchanged (content compare: rewritten rows with the same
            # count must still propagate)
        await self.state.upsert_metrics(
            MetricsDocument(
                job_id=job.job_id,
                records=records,
                source_uri=source_uri,
                updated_at=time.time(),
            )
        )
