"""Model registry: name → job-spec class, with plugin discovery.

Capability parity with the reference's registry pair
(``app/jobs/registered_models.py:15-37`` + ``app/models/model_loader.py:14-45``
— SURVEY.md §2 component 3): a process-wide manifest dict, built-in specs
registered eagerly, and dynamic discovery of user plugin modules from a
directory via importlib. Unlike the reference, registration is re-entrant and
resettable (test seam), and a bad plugin module is reported per-file instead of
aborting the scan.
"""

from __future__ import annotations

import importlib.util
import logging
import sys
from pathlib import Path

from .specs import BaseFineTuneJob

logger = logging.getLogger(__name__)

#: name → spec class (reference: ``JOB_MANIFESTS``, ``registered_models.py:15-17``)
JOB_MANIFESTS: dict[str, type[BaseFineTuneJob]] = {}


def register(cls: type[BaseFineTuneJob]) -> type[BaseFineTuneJob]:
    """Register a job-spec class (usable as a decorator in plugins)."""
    if not (isinstance(cls, type) and issubclass(cls, BaseFineTuneJob)):
        raise TypeError(f"{cls!r} is not a BaseFineTuneJob subclass")
    JOB_MANIFESTS[cls.model_name] = cls
    return cls


def get_spec(model_name: str) -> type[BaseFineTuneJob] | None:
    return JOB_MANIFESTS.get(model_name)


def reset() -> None:
    JOB_MANIFESTS.clear()


def load_builtin_models() -> None:
    """Register the shipped example specs (reference:
    ``registered_models.py:20-27`` registering ``app/models/examples``)."""
    from .examples import BUILTIN_JOB_SPECS

    for cls in BUILTIN_JOB_SPECS:
        register(cls)


def load_models_from_directory(directory: Path | str) -> list[str]:
    """Import every ``*.py`` in ``directory`` and register any
    :class:`BaseFineTuneJob` subclasses found (reference:
    ``model_loader.py:14-45`` — importlib scan of ``app/models/custom/``).

    Returns the model names registered. A module that fails to import is
    logged and skipped — one broken plugin must not take the API down.
    """
    directory = Path(directory).expanduser()
    registered: list[str] = []
    if not directory.is_dir():
        logger.warning("plugin directory %s does not exist; skipping", directory)
        return registered
    for py in sorted(directory.glob("*.py")):
        if py.name.startswith("_"):
            continue
        mod_name = f"ftc_plugin_{py.stem}"
        try:
            spec = importlib.util.spec_from_file_location(mod_name, py)
            assert spec and spec.loader
            module = importlib.util.module_from_spec(spec)
            sys.modules[mod_name] = module
            spec.loader.exec_module(module)
        except Exception:
            logger.exception("failed to load model plugin %s", py)
            continue
        for obj in vars(module).values():
            if (
                isinstance(obj, type)
                and issubclass(obj, BaseFineTuneJob)
                and obj is not BaseFineTuneJob
                and obj.__module__ == mod_name
            ):
                register(obj)
                registered.append(obj.model_name)
    if registered:
        logger.info("registered %d plugin model(s): %s", len(registered), registered)
    return registered


def load_model_modules(plugin_dir: Path | str | None = None) -> None:
    """Full registry bootstrap (reference: ``load_model_modules``,
    ``registered_models.py:20-37``): built-ins first, then the plugin dir."""
    load_builtin_models()
    if plugin_dir:
        load_models_from_directory(plugin_dir)
