"""Job submission orchestration — one validated request → a queued job.

Capability parity with the reference's ``task_builder``
(``app/jobs/task_builder.py:19-81`` — SURVEY.md §2 component 5, §3.1): resolve
the dataset (existing id / URL stream / uploaded file), compute the artifact
URI, hand the job to the execution backend, and write the DB record the
monitor will reconcile against.

Reference warts fixed here (SURVEY.md §7 step 3): the backend call is fully
async (the reference does a blocking kube call inside an async route,
``PyTorchJobDeployer.py:256``), and a backend submit failure rolls the
dataset job-ref back instead of leaving a half-registered job.
"""

from __future__ import annotations

import dataclasses
import logging

from ..obs.events import SUBMITTED, make_event
from ..obs.trace import new_trace_id
from .backends.base import TrainingBackend
from .datasets import stream_dataset_url, upload_dataset_bytes
from .devices import DeviceCatalog
from .objectstore import ObjectStore, artifacts_prefix
from .schemas import DatabaseStatus, JobInput, JobRecord
from .specs import BaseFineTuneJob
from .statestore import StateStore

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class DatasetInput:
    """One of three dataset sources (reference: ``main.py:425-435``)."""

    dataset_id: str | None = None
    url: str | None = None
    file_name: str | None = None
    file_data: bytes | None = None
    content_type: str | None = None

    @property
    def kind(self) -> str:
        if self.dataset_id:
            return "id"
        if self.url:
            return "url"
        if self.file_data is not None:
            return "file"
        return "none"


class TaskBuildError(Exception):
    def __init__(self, message: str, status: int = 400):
        super().__init__(message)
        self.status = status


async def task_builder(
    job: JobInput,
    spec: BaseFineTuneJob,
    dataset_input: DatasetInput,
    *,
    state: StateStore,
    store: ObjectStore,
    backend: TrainingBackend,
    catalog: DeviceCatalog,
    datasets_bucket: str,
    artifacts_bucket: str,
    http_session: object | None = None,
) -> JobRecord:
    """Reference flow ``task_builder.py:19-81``, backend-neutral."""
    # -- dataset resolution (reference: task_builder.py:28-53) ---------------
    dataset_uri: str | None = None
    dataset_id: str | None = None
    kind = dataset_input.kind
    if kind == "none" and spec.dataset.required:
        raise TaskBuildError("this model requires a dataset (id, url, or file)")
    if kind == "id":
        record = await state.get_dataset(dataset_input.dataset_id)
        if record is None or record.user_id != job.user_id:
            raise TaskBuildError(f"dataset {dataset_input.dataset_id!r} not found", 404)
        dataset_uri, dataset_id = record.uri, record.dataset_id
    elif kind == "url":
        record = await stream_dataset_url(
            store, state,
            user_id=job.user_id, url=dataset_input.url,
            bucket=datasets_bucket, session=http_session,
        )
        dataset_uri, dataset_id = record.uri, record.dataset_id
    elif kind == "file":
        record = await upload_dataset_bytes(
            store, state,
            user_id=job.user_id,
            filename=dataset_input.file_name or "dataset.jsonl",
            data=dataset_input.file_data,
            bucket=datasets_bucket,
            content_type=dataset_input.content_type,
        )
        dataset_uri, dataset_id = record.uri, record.dataset_id
    if dataset_id is not None:
        await state.add_dataset_job_ref(dataset_id, job.job_id)

    # -- artifact URI (reference: task_builder.py:55) ------------------------
    artifacts_uri = artifacts_prefix(artifacts_bucket, job.user_id, job.job_id)

    # -- DB record first, then deploy ----------------------------------------
    # The reference deploys before writing the record (task_builder.py:60-79),
    # leaving a window where a record-write failure orphans a running cluster
    # job nothing tracks. Record-first closes it: a submit failure rolls the
    # record back; the monitor's lost-job sweep covers the reverse crash.
    flavor = catalog.get_worker(job.device)
    # trace propagation (docs/observability.md): mint the trace id HERE, the
    # job's birth — it rides the job metadata, the backend env, every
    # supervisor resubmission, and the serve load, naming the job's whole life
    job.trace_id = job.trace_id or new_trace_id()
    record = JobRecord(
        job_id=job.job_id,
        user_id=job.user_id,
        model_name=job.model_name,
        status=DatabaseStatus.QUEUED,
        device=flavor.name,
        num_slices=job.num_slices,
        arguments=job.arguments,
        dataset_id=dataset_id,
        dataset_uri=dataset_uri,
        artifacts_uri=artifacts_uri,
        # queue/priority live in metadata (crash-safe, like retry_next_at):
        # the retry supervisor rebuilds the JobInput from the record, so a
        # resubmitted job must re-enter the SAME tenant queue at the SAME
        # priority (docs/scheduling.md).  The task type rides along so the
        # job table (ftc-ctl jobs) and the ftc_dpo_* gauges can tell a DPO
        # job from an SFT one without a registry lookup per row.
        metadata={
            "queue": job.queue,
            "priority": job.priority,
            "task": spec.task.value,
            "trace_id": job.trace_id,
        },
        # the timeline's first event — every later span/phase hangs off it
        events=[make_event(
            SUBMITTED, key="submitted:1",
            queue=job.queue, priority=str(job.priority),
            model=job.model_name, device=flavor.name,
            num_slices=job.num_slices, trace_id=job.trace_id,
        )],
    )
    try:
        await state.create_job(record)
        await backend.submit(
            job, spec, flavor,
            dataset_uri=dataset_uri, artifacts_uri=artifacts_uri,
        )
    except Exception as exc:
        await state.purge_job(job.job_id)
        if dataset_id is not None:
            # roll back the job-ref so a failed submit doesn't pin the dataset
            ds = await state.get_dataset(dataset_id)
            if ds is not None and job.job_id in ds.job_refs:
                ds.job_refs.remove(job.job_id)
                await state.insert_dataset(ds)
        raise TaskBuildError(f"job submission failed: {exc}", 500) from exc
    logger.info("job %s submitted (device=%s dataset=%s)", job.job_id, flavor.name, kind)
    return record
