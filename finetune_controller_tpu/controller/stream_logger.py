"""WebSocket training-log streaming.

Capability parity with the reference's ``LogStreamManager``
(``app/utils/stream_logger.py:18-514`` — SURVEY.md §2 component 17, §3.3):

- wait-for-start with timeout, polling the DB until the job leaves the queue
  (terminal states pass straight through) — reference ``:53-109``;
- historical logs in chunks, live follow with liveness probing, last-N mode —
  reference ``:204-398`` (the per-line tail + liveness lives in
  ``TrainingBackend.read_logs``, our pod-log seam);
- a **search-string gate** that suppresses output until a marker (e.g.
  ``"Epoch"``) appears — reference ``:404-433``, default from settings
  (``LOG_STREAM_SEARCH_STRING``, ``config.py:26``).

The reference resolves the *master pod* for logs (``:138-169``); in the
multi-controller JAX runtime every worker runs the same program, so the
backend elects rank-0's log stream instead (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any

from .backends.base import BackendError, TrainingBackend
from .schemas import DatabaseStatus
from .statestore import StateStore

logger = logging.getLogger(__name__)


class LogStreamManager:
    """One WS session worth of log streaming."""

    def __init__(
        self,
        ws: Any,  # aiohttp WebSocketResponse (anything with .send_str/.closed)
        job_id: str,
        state: StateStore,
        backend: TrainingBackend,
        *,
        follow: bool = True,
        last_lines: int | None = None,
        search_string: str = "",
        start_timeout_s: float = 300.0,
        start_poll_s: float = 2.0,
        chunk_lines: int = 100,
    ):
        self.ws = ws
        self.job_id = job_id
        self.state = state
        self.backend = backend
        self.follow = follow
        self.last_lines = last_lines
        self.search_string = search_string
        self.start_timeout_s = start_timeout_s
        self.start_poll_s = start_poll_s
        self.chunk_lines = chunk_lines
        self._gate_open = not search_string
        #: per-line attribution prefix (docs/observability.md): the job's
        #: trace id (short form) + attempt number, so a multi-attempt stream
        #: — retries append to the same log file — stays attributable.  The
        #: attempt number moves while a follow stream is attached (the
        #: supervisor resubmits into the same log), so the prefix is
        #: re-resolved from the DB on a poll cadence, not frozen at start
        self._prefix = ""
        self._prefix_at = 0.0  # monotonic time of the last prefix resolve

    # -- helpers -------------------------------------------------------------

    async def _send(self, text: str) -> bool:
        if getattr(self.ws, "closed", False):
            return False
        try:
            await self.ws.send_str(text)
            return True
        except Exception:
            # a dead peer ends the stream; the cause still goes somewhere
            logger.debug("ws send to %s failed; ending stream", self.job_id,
                         exc_info=True)
            return False

    def _filter(self, line: str) -> str | None:
        """Search-string gate (reference: ``stream_logger.py:404-433``):
        swallow everything until the marker appears once, then stream all.
        Passed lines gain the trace/attempt attribution prefix."""
        if self._gate_open:
            return self._prefix + line
        if self.search_string in line:
            self._gate_open = True
            return self._prefix + line
        return None

    def _set_prefix(self, job) -> None:
        self._prefix_at = time.monotonic()
        trace = ((job.metadata or {}).get("trace_id") or "")[:8]
        if trace:
            attempt = 1 + len((job.metadata or {}).get("attempt_history") or [])
            self._prefix = f"[{trace}#a{attempt}] "

    async def _refresh_prefix(self) -> None:
        """Re-resolve the attempt number mid-stream (throttled to the start
        poll cadence): lines appended by a retry attempt must carry ITS
        number — a frozen prefix would label every post-retry line with the
        attempt that was live when the stream attached."""
        if time.monotonic() - self._prefix_at < self.start_poll_s:
            return
        # re-arm the throttle BEFORE the lookup: a gone record (or an
        # erroring store) must not turn every streamed line into a DB query
        self._prefix_at = time.monotonic()
        try:
            job = await self.state.get_job(self.job_id)
        except Exception:
            # attribution must not kill a healthy stream
            logger.debug("prefix refresh failed for %s", self.job_id,
                         exc_info=True)
            return
        if job is not None:
            self._set_prefix(job)

    async def _wait_for_job_start(self) -> DatabaseStatus | None:
        """Poll the DB until the job is running or terminal (reference:
        ``stream_logger.py:53-109``). Returns the status reached, or None on
        timeout / unknown job."""
        deadline = time.monotonic() + self.start_timeout_s
        while time.monotonic() < deadline:
            job = await self.state.get_job(self.job_id)
            if job is None:
                await self._send(f"error: job {self.job_id} not found")
                return None
            if job.status in (
                DatabaseStatus.RUNNING,
                DatabaseStatus.RESTARTING,
                *DatabaseStatus.final_states(),
            ):
                self._set_prefix(job)
                return job.status
            pos = f" (queue position {job.queue_position})" if job.queue_position else ""
            await self._send(f"waiting: job is {job.status.value}{pos}")
            await asyncio.sleep(self.start_poll_s)
        await self._send("error: timed out waiting for job to start")
        return None

    # -- main ----------------------------------------------------------------

    async def run(self) -> None:
        """Reference: ``LogStreamManager.run``, ``stream_logger.py:449-514``."""
        status = await self._wait_for_job_start()
        if status is None:
            return
        follow = self.follow and status not in DatabaseStatus.final_states()
        try:
            lines = await self.backend.read_logs(
                self.job_id, follow=follow, last_lines=self.last_lines
            )
        except BackendError as e:
            # terminal job already cleaned from the substrate: logs are gone
            # (the reference has the same property once pods are deleted)
            await self._send(f"logs unavailable: {e}")
            return
        sent = 0
        buffer: list[str] = []
        # live follow sends per line; historical bulk sends chunked
        # (reference :204-250 vs :286-341)
        chunk = 1 if follow else self.chunk_lines
        try:
            async for line in lines:
                if follow:
                    # a retry lands well after the backoff, so the throttled
                    # refresh settles on the new attempt before its first line
                    await self._refresh_prefix()
                filtered = self._filter(line)
                if filtered is None:
                    continue
                buffer.append(filtered)
                if len(buffer) >= chunk:
                    if not await self._send("\n".join(buffer)):
                        return
                    sent += len(buffer)
                    buffer.clear()
                    await asyncio.sleep(0)
            if buffer and await self._send("\n".join(buffer)):
                sent += len(buffer)
        finally:
            logger.debug("log stream for %s done (%d lines)", self.job_id, sent)
