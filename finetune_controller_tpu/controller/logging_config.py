"""Logging setup (reference: ``app/utils/logging_config.py:5-44``).

Same shape — dictConfig, colored console handler, root INFO with package DEBUG —
but the color formatter is stdlib ANSI (colorlog is not in the image).
"""

from __future__ import annotations

import logging
import logging.config

_COLORS = {
    logging.DEBUG: "\x1b[36m",
    logging.INFO: "\x1b[32m",
    logging.WARNING: "\x1b[33m",
    logging.ERROR: "\x1b[31m",
    logging.CRITICAL: "\x1b[35m",
}
_RESET = "\x1b[0m"


class ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        color = _COLORS.get(record.levelno, "")
        record.levelcolor = f"{color}{record.levelname:8s}{_RESET}"
        return super().format(record)


def setup_logging(level: int = logging.INFO) -> None:
    logging.config.dictConfig(
        {
            "version": 1,
            "disable_existing_loggers": False,
            "formatters": {
                "color": {
                    "()": ColorFormatter,
                    "format": "%(asctime)s %(levelcolor)s %(name)s: %(message)s",
                }
            },
            "handlers": {
                "console": {
                    "class": "logging.StreamHandler",
                    "formatter": "color",
                }
            },
            "root": {"level": level, "handlers": ["console"]},
            "loggers": {
                "finetune_controller_tpu": {"level": logging.DEBUG},
            },
        }
    )
