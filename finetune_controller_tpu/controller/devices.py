"""TPU device catalog — flavors, topologies, quotas, and the submission-form enum.

Capability parity with the reference's worker/device configuration
(``app/core/device_config.py:16-109`` + ``example.config.json`` — SURVEY.md §2
component 12), redesigned for TPU pod-slice granularity:

- the reference's flat GPU count (``accelerators: {"nvidia.com/gpu": n}``,
  ``example.config.json:20-23``) becomes a **slice flavor**: chip generation,
  topology (e.g. ``4x4``), hosts × chips/host — because TPUs are provisioned as
  whole slices, not per-chip (SURVEY.md §7 "hard parts": slice topology ↔
  scheduler quota);
- each flavor carries its scheduler queue name + nominal chip quota (the
  Kueue ClusterQueue / ResourceFlavor data, ``crds/kueue/cluster-queue.yaml:13-22``)
  so the in-repo gang scheduler can enforce admission the way Kueue does;
- JSON config files may contain ``//`` comments, as the reference allows
  (``device_config.py:81-85``);
- a missing config file degrades to the built-in default catalog with a log
  line, mirroring the reference's empty-catalog fallback (``device_config.py:96-101``).
"""

from __future__ import annotations

import enum
import json
import logging
import re
from pathlib import Path

from pydantic import BaseModel, Field

logger = logging.getLogger(__name__)


class DeviceFlavor(BaseModel):
    """One schedulable slice shape (reference: ``Worker``, ``device_config.py:16-44``)."""

    name: str  # e.g. "v5e-16"
    description: str = ""
    generation: str = "v5e"  # v4 | v5e | v5p | v6e | cpu
    topology: str = ""  # e.g. "4x4" (empty for cpu flavors)
    hosts: int = 1
    chips_per_host: int = 4
    #: scheduler LocalQueue this flavor feeds (reference: ``LocalQueue``,
    #: ``example.config.json:18``)
    queue: str = "default-queue"
    #: host-side pod resources (reference: default resources, ``example.config.json:8-14``)
    cpu: str = "8"
    memory: str = "32Gi"
    #: node-selector labels for K8s backends (replaces GPU tolerations,
    #: reference ``example.config.json:24-31``)
    node_selectors: dict[str, str] = Field(default_factory=dict)
    #: "tpu" runs on real chips; "cpu" runs on a virtual CPU mesh (the
    #: CI/smoke runtime the reference never had — SURVEY.md §4)
    runtime: str = "tpu"

    @property
    def total_chips(self) -> int:
        return self.hosts * self.chips_per_host

    def k8s_resource_name(self) -> str:
        """The extended-resource key requested on pods (replaces
        ``nvidia.com/gpu``, reference ``PyTorchJobDeployer.py:45-55``)."""
        return "cpu" if self.runtime == "cpu" else "google.com/tpu"

    def accelerator_selectors(self) -> dict[str, str]:
        """TPU slice node selectors (SURVEY.md §2.2: topology selectors
        replace the reference's free GPU count)."""
        if self.runtime == "cpu":
            return {}
        sel = {
            "cloud.google.com/gke-tpu-accelerator": f"tpu-{self.generation}-slice",
            "cloud.google.com/gke-tpu-topology": self.topology,
        }
        sel.update(self.node_selectors)
        return sel


class FlavorQuota(BaseModel):
    """Nominal chip quota for one flavor in the cluster queue (reference:
    ``nominalQuota``, ``crds/kueue/cluster-queue.yaml:18-22``)."""

    flavor: str
    nominal_chips: int


class DeviceCatalog(BaseModel):
    """The full worker catalog (reference: ``APIConfiguration``,
    ``device_config.py:46-75``)."""

    flavors: list[DeviceFlavor] = Field(default_factory=list)
    quotas: list[FlavorQuota] = Field(default_factory=list)
    default_flavor: str = ""

    def get(self, name: str) -> DeviceFlavor | None:
        for f in self.flavors:
            if f.name == name:
                return f
        return None

    def get_worker(self, name: str) -> DeviceFlavor:
        """Resolve a flavor, falling back to the default (reference:
        ``device_configuration.get_worker`` + default-queue fallback,
        ``device_config.py:59-75``)."""
        f = self.get(name)
        if f is not None:
            return f
        if self.default_flavor:
            fallback = self.get(self.default_flavor)
            if fallback is not None:
                logger.warning("unknown device %r; using default %r", name, fallback.name)
                return fallback
        raise KeyError(f"unknown device flavor {name!r} and no default configured")

    def quota_for(self, flavor: str) -> int:
        for q in self.quotas:
            if q.flavor == flavor:
                return q.nominal_chips
        f = self.get(flavor)
        return f.total_chips if f else 0

    def names(self) -> list[str]:
        return [f.name for f in self.flavors]

    def device_enum(self) -> type[enum.Enum]:
        """Dynamic enum for the submission form (reference: ``DeviceTypes``,
        ``device_config.py:107-109``)."""
        return enum.Enum("DeviceTypes", {f.name: f.name for f in self.flavors})


def default_catalog() -> DeviceCatalog:
    """Built-in catalog covering the BASELINE.md configs plus the CPU smoke flavor."""
    return DeviceCatalog(
        flavors=[
            DeviceFlavor(
                name="cpu-test", description="virtual CPU mesh for CI/smoke",
                generation="cpu", topology="", hosts=1, chips_per_host=1,
                queue="cpu-queue", cpu="2", memory="4Gi", runtime="cpu",
            ),
            DeviceFlavor(
                name="cpu-test-2", description="2-device virtual CPU mesh (ep/tp smoke)",
                generation="cpu", topology="", hosts=1, chips_per_host=2,
                queue="cpu-queue", cpu="4", memory="8Gi", runtime="cpu",
            ),
            DeviceFlavor(
                name="v5e-4", description="single-host v5e slice",
                generation="v5e", topology="2x2", hosts=1, chips_per_host=4,
                queue="tpu-small-queue",
            ),
            DeviceFlavor(
                name="v5e-8", description="two-host v5e slice",
                generation="v5e", topology="2x4", hosts=2, chips_per_host=4,
                queue="tpu-small-queue",
            ),
            DeviceFlavor(
                name="v5e-16", description="four-host v5e slice (8B FSDP north star)",
                generation="v5e", topology="4x4", hosts=4, chips_per_host=4,
                queue="tpu-medium-queue", cpu="96", memory="384Gi",
            ),
            DeviceFlavor(
                name="v5p-64", description="v5p-64 slice (MoE expert-parallel config)",
                generation="v5p", topology="4x4x4", hosts=16, chips_per_host=4,
                queue="tpu-large-queue", cpu="96", memory="448Gi",
            ),
        ],
        quotas=[
            FlavorQuota(flavor="cpu-test", nominal_chips=2),
            FlavorQuota(flavor="cpu-test-2", nominal_chips=4),
            FlavorQuota(flavor="v5e-4", nominal_chips=8),
            FlavorQuota(flavor="v5e-8", nominal_chips=16),
            FlavorQuota(flavor="v5e-16", nominal_chips=32),
            FlavorQuota(flavor="v5p-64", nominal_chips=64),
        ],
        default_flavor="cpu-test",
    )


_COMMENT_RE = re.compile(r"^\s*//.*$", re.MULTILINE)


def load_catalog(path: Path | str | None) -> DeviceCatalog:
    """Load the catalog from a JSON file with ``//`` comment support
    (reference: ``load_config``, ``device_config.py:81-104``); fall back to
    the built-in default catalog when absent."""
    if not path:
        return default_catalog()
    path = Path(path).expanduser()
    if not path.is_file():
        logger.warning("device config %s not found; using built-in catalog", path)
        return default_catalog()
    text = _COMMENT_RE.sub("", path.read_text())
    return DeviceCatalog.model_validate(json.loads(text))


#: axes a mesh policy may declare (trainer MeshSpec axis names)
_POLICY_AXES = ("fsdp", "ep", "pp", "sp", "tp")


def default_mesh_for(
    flavor: DeviceFlavor,
    num_slices: int = 1,
    policy: dict[str, int] | None = None,
) -> dict[str, int]:
    """Map a slice request to trainer MeshSpec axis sizes.

    ``policy`` is the job spec's intra-slice axis declaration (reference
    pattern: per-model resource declaration, ``finetuning.py:51-104`` — here
    it declares *parallelism*, which the reference never could):

    * keys are intra-slice axes (fsdp/ep/pp/sp/tp); at most one value may be
      ``-1``, meaning "all remaining chips";
    * the default policy ``{"fsdp": -1}`` is FSDP over the whole slice (the
      north-star strategy, SURVEY.md §2.3);
    * DP always runs over slices (the DCN axis): ``dp = num_slices``.

    Raises ``ValueError`` when the flavor's chip count cannot satisfy the
    policy — surfaced at submit time as a 400, not at train time on-device.
    """
    from ..parallel.mesh import MeshSpec

    policy = dict(policy) if policy else {"fsdp": -1}
    unknown = set(policy) - set(_POLICY_AXES)
    if unknown:
        raise ValueError(f"mesh policy axes {sorted(unknown)} not in {_POLICY_AXES}")
    for a, v in policy.items():
        if v != -1 and v < 1:
            raise ValueError(f"mesh policy axis {a}={v} must be >= 1 or -1")
    # One source of truth for -1-fill/divisibility/exact-coverage: the
    # trainer's own MeshSpec.resolve. fsdp is pinned to 1 unless the policy
    # says otherwise — MeshSpec's fsdp=-1 default ("absorb everything") must
    # not kick in when a policy chose other axes.
    try:
        sizes = MeshSpec(dp=1, **{"fsdp": 1, **policy}).resolve(flavor.total_chips)
    except ValueError as exc:
        raise ValueError(
            f"device {flavor.name!r} ({flavor.total_chips} chips) cannot "
            f"satisfy the model's mesh policy {policy}: {exc}"
        ) from None
    return {"dp": num_slices, **{a: sizes[a] for a in _POLICY_AXES}}
