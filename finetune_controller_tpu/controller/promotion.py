"""Artifact promotion — publish a finished job's artifacts for inference.

Capability parity with the reference's ``PromotionTask``
(``app/tasks/promotion.py:10-62`` — SURVEY.md §2 component 19, §3.4): a
background copy of the artifacts prefix into the deploy bucket with the state
machine NOT_PROMOTED → IN_PROGRESS → COMPLETED/FAILED, and the reverse
(DELETING → cleanup → NOT_PROMOTED).
"""

from __future__ import annotations

import asyncio
import logging

from ..obs import events as obs_events
from ..obs.events import make_event
from .objectstore import ObjectStore, build_uri
from .schemas import PromotionStatus
from .statestore import StateStore

logger = logging.getLogger(__name__)

#: settled promotion state → timeline event (docs/observability.md)
_SETTLE_EVENTS = {
    PromotionStatus.COMPLETED: obs_events.PROMOTED,
    PromotionStatus.FAILED: obs_events.PROMOTION_FAILED,
    PromotionStatus.NOT_PROMOTED: obs_events.UNPROMOTED,
}


def promotion_destination(deploy_bucket: str, promotion_path: str, job_id: str) -> str:
    """Reference: destination assembly, ``app/main.py:736,769-771``."""
    return build_uri(deploy_bucket, promotion_path, job_id)


class PromotionTask:
    """Background promote/unpromote operations (run via ``asyncio.create_task``,
    the reference used FastAPI ``BackgroundTasks`` — ``app/main.py:776-781``)."""

    def __init__(self, state: StateStore, store: ObjectStore):
        self.state = state
        self.store = store

    async def _settle(
        self, job_id: str, expect: PromotionStatus, to: PromotionStatus,
        uri: str | None = None,
    ) -> None:
        """CAS the task's completion write: applies only while the job is
        still in the state THIS task claimed.  A blind write here could stomp
        a crash-recovery sweep (another process already marked FAILED and the
        user re-promoted) — the stale task must lose, not the fresh one."""
        if not await self.state.transition_job_promotion(
            job_id, [expect], to, uri
        ):
            logger.warning(
                "promotion state for %s moved concurrently (expected %s); "
                "leaving the newer transition in place", job_id, expect.value,
            )
            return
        event = _SETTLE_EVENTS.get(to)
        if event is not None:
            # timeline (docs/observability.md): only the task whose CAS won
            # records the outcome — a stale task's event would lie
            try:
                await self.state.append_job_event(
                    job_id, make_event(event, destination=uri)
                )
            except Exception:
                logger.debug("timeline append (%s) failed for %s", event,
                             job_id, exc_info=True)

    async def promote_job_task(
        self, job_id: str, artifacts_uri: str, destination_uri: str
    ) -> None:
        """Reference: ``promotion.py:11-36``.  The caller already claimed
        IN_PROGRESS via ``begin_promotion``; every write here is a CAS from
        that state so concurrent transitions are never overwritten."""
        try:
            n = await self.store.copy_prefix(artifacts_uri, destination_uri)
            if n == 0:
                raise FileNotFoundError(f"no artifacts under {artifacts_uri}")
            await self._settle(
                job_id, PromotionStatus.IN_PROGRESS, PromotionStatus.COMPLETED,
                destination_uri,
            )
            logger.info("promoted %s: %d objects -> %s", job_id, n, destination_uri)
        except asyncio.CancelledError:
            # shutdown mid-copy: record FAILED so the job isn't stuck
            # IN_PROGRESS forever (the promote guard refuses retries otherwise)
            await self._settle(
                job_id, PromotionStatus.IN_PROGRESS, PromotionStatus.FAILED
            )
            raise
        except Exception:
            logger.exception("promotion failed for %s", job_id)
            await self._settle(
                job_id, PromotionStatus.IN_PROGRESS, PromotionStatus.FAILED
            )

    async def unpromote_job_task(self, job_id: str, destination_uri: str) -> None:
        """Reference: ``unpromote_job_task``, ``promotion.py:38-62``; DELETING
        was claimed by the caller's ``begin_promotion`` CAS."""
        try:
            await self.store.delete_prefix(destination_uri)
            await self._settle(
                job_id, PromotionStatus.DELETING, PromotionStatus.NOT_PROMOTED
            )
            logger.info("unpromoted %s (removed %s)", job_id, destination_uri)
        except asyncio.CancelledError:
            await self._settle(
                job_id, PromotionStatus.DELETING, PromotionStatus.FAILED
            )
            raise
        except Exception:
            logger.exception("unpromotion failed for %s", job_id)
            await self._settle(
                job_id, PromotionStatus.DELETING, PromotionStatus.FAILED
            )

    async def recover_interrupted(self) -> int:
        """Crash recovery at startup: anything still IN_PROGRESS/DELETING has
        no task running (the process died) — mark FAILED so the user can retry."""
        n = 0
        for job in await self.state.find_jobs_with_promotion_in(
            [PromotionStatus.IN_PROGRESS, PromotionStatus.DELETING]
        ):
            # CAS from the observed in-flight state: with a shared remote
            # store, another replica's LIVE task may settle between our read
            # and this write — its fresher transition must win
            if await self.state.transition_job_promotion(
                job.job_id,
                [PromotionStatus.IN_PROGRESS, PromotionStatus.DELETING],
                PromotionStatus.FAILED,
            ):
                n += 1
        if n:
            logger.warning("marked %d interrupted promotion(s) as failed", n)
        return n
