"""Settings for the control plane.

Capability parity with the reference's pydantic-settings singleton
(``app/core/config.py:16-93``) with its two warts fixed:

- **No import-time I/O.** The reference reads a Kubernetes Secret inside computed
  fields at import (``app/core/config.py:59-90``), which makes the package
  unimportable without cluster access. Here nothing happens until
  :func:`get_settings` is called, and tests inject their own instance via
  :func:`set_settings`.
- **No hard dependency on pydantic-settings.** Plain env parsing over a pydantic
  model keeps the dependency surface to what is baked into the image.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from pydantic import BaseModel, Field

#: the well-known dev secret — treated as UNSET by the auth startup guard:
#: a deployment that enables auth outside `local` with only this secret would
#: let anyone who read the source forge admin tokens
DEFAULT_JWT_SECRET = "dev-secret-do-not-use-in-prod"


class Settings(BaseModel):
    """Environment-driven configuration (reference: ``app/core/config.py:16-58``)."""

    environment: str = "local"  # local | development | production
    namespace: str = "default"

    # --- API ---
    cors_origins: list[str] = Field(default_factory=lambda: ["*"])
    api_prefix: str = "/api/v1"

    # --- Auth (reference: OpenBridge OAuth, app/core/config.py:33-42) ---
    auth_enabled: bool = False
    introspection_url: str = ""  # remote token introspection endpoint
    introspection_client_id: str = ""
    introspection_client_secret: str = ""
    jwks_url: str = ""  # JWKS endpoint for RS256 validation
    jwt_secret: str = DEFAULT_JWT_SECRET  # HS256 dev mint/verify
    #: RS256 audience enforcement is opt-in: set it and tokens must carry a
    #: matching `aud` (string or array); empty = no audience check
    jwt_audience: str = ""
    dev_disable_introspection: bool = True

    # --- State store (reference: Mongo URL/creds, app/core/config.py:44-49) ---
    state_dir: str = "~/.finetune_controller_tpu/state"
    #: "sqlite" (WAL database — safe for the deployed API+monitor two-process
    #: layout on one node) | "jsonl" (single-process append-only log) |
    #: "remote" (the shared state service, ``statestore_main`` — API×N
    #: replicas + monitor across nodes, the role MongoDB plays for the
    #: reference, ``app/database/db.py:51``)
    state_backend: str = "sqlite"
    #: remote state service endpoint + bearer token (state_backend=remote)
    state_service_url: str = ""
    state_service_token: str = ""

    # --- Object store (reference: S3 buckets, app/core/config.py:53-58) ---
    #: "local" (filesystem root, hermetic CI) | "gcs" | "s3" (cloud buckets)
    object_store_backend: str = "local"
    object_store_root: str = "~/.finetune_controller_tpu/objects"
    #: GCS: endpoint override (fake server in tests) + real-bucket prefix so
    #: one project hosts the datasets/artifacts/deploy logical buckets
    gcs_endpoint: str = "https://storage.googleapis.com"
    gcs_bucket_prefix: str = ""
    #: S3: endpoint/region (MinIO-style gateways and the test fake override
    #: the endpoint); creds ride AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY —
    #: the env contract the reference's k8s Secret fills (config.py:59-90)
    s3_endpoint: str = "https://s3.amazonaws.com"
    s3_region: str = "us-east-1"
    s3_bucket_prefix: str = ""
    datasets_bucket: str = "datasets"
    artifacts_bucket: str = "artifacts"
    deploy_bucket: str = "deploy"
    presign_secret: str = "dev-presign-secret"
    presign_expiry_s: int = 3600

    # --- Monitor / sync cadence (reference: app/core/config.py:50-52) ---
    job_monitor_interval_s: float = 2.0
    artifact_sync_interval_s: float = 60.0
    #: the standalone monitor daemon's /metrics listener port
    #: (docs/observability.md: ftc_build_info / ftc_uptime_seconds for BOTH
    #: control-plane processes); 0 = no listener (in-process monitors are
    #: already covered by the API server's /metrics)
    monitor_metrics_port: int = 0
    #: pre-warmed trainer processes per platform env on the local backend —
    #: they pay JAX import + backend init before a job arrives, collapsing
    #: the submit -> first-training-step latency (0 = off)
    warm_workers: int = 0

    # --- Log streaming (reference: LOG_STREAM_SEARCH_STRING, app/core/config.py:26) ---
    log_stream_search_string: str = ""
    log_stream_start_timeout_s: float = 300.0

    # --- Scheduler / device catalog (reference: CONFIGURATION_FILE, app/core/config.py:43) ---
    device_config_file: str = ""
    #: admission policy (docs/scheduling.md): "fairshare" (multi-tenant
    #: weighted DRF with checkpoint-aware preemption, the default) | "fifo"
    #: (the legacy best-effort gang scheduler — no tenants, no preemption)
    sched_policy: str = "fairshare"
    #: tenant queue weights as a JSON object, e.g. '{"prod": 4, "batch": 1}'.
    #: Unknown queues named at submit auto-register with weight 1.0.
    sched_queues: str = ""
    #: resize-instead-of-evict (docs/elasticity.md): shrink multi-slice
    #: victims to their fair share (and admit blocked multi-slice jobs
    #: shrunk) instead of full eviction, growing them back when chips free.
    #: false restores the PR-5 evict-only behavior.
    sched_resize: bool = True
    #: how long a flavor must be free of other tenants' demand before the
    #: scheduler grows a shrunk job back (a grow costs a checkpoint
    #: restart, so this debounces thrash); also the per-job floor between
    #: consecutive resizes of the same job
    sched_grow_delay_s: float = 60.0

    # --- Backend selection ---
    backend: str = "local"  # local | k8s
    monitor_in_process: bool = True  # reference: DEV_LOCAL_JOB_MONITOR (config.py:51)

    # --- Rate limits per minute (reference: app/main.py:377,525,714) ---
    rate_limit_submit_per_min: int = 10
    rate_limit_read_per_min: int = 50
    rate_limit_promote_per_min: int = 2
    rate_limit_generate_per_min: int = 120

    # --- Serving (finetune_controller_tpu/serve/, docs/serving.md) ---
    #: decode lanes per served model — the compiled batch; traffic above this
    #: queues (continuous batching refills lanes between steps)
    serve_slots: int = 8
    #: prefill pad targets (ascending); one prefill compile per bucket — the
    #: compile-count dial (docs/serving.md)
    serve_prompt_buckets: list[int] = Field(default_factory=lambda: [32, 128, 512])
    #: hard per-request generation cap; also sizes the KV cache
    #: (max(buckets) + this = cache slots per lane)
    serve_max_new_tokens: int = 128
    #: prefix-reuse KV cache (docs/serving.md): admissions sharing a cached
    #: prompt prefix (the shared-system-prompt case) splice it in and prefill
    #: only the suffix — bit-identical outputs, prefill compute saved
    serve_prefix_cache: bool = True
    #: byte budget (MiB) of device-resident prefix snapshots per served
    #: model; least-recently-used snapshots evict past it.  Size it to hold
    #: AT LEAST one snapshot (2 * cache_len * n_kv_heads * head_dim *
    #: n_layers * dtype bytes — ~84 MB for an 8B config at the default
    #: buckets): a budget below one snapshot makes every insert refuse and
    #: the cache silently inert (the engine logs a warning once)
    serve_prefix_cache_mb: int = 512
    #: default when a request omits max_new_tokens
    serve_default_max_new_tokens: int = 32
    #: admission queue depth — past it requests get 429 (backpressure)
    serve_max_queue: int = 64
    #: idle park interval of the drive loop (1 ms floor).  Submissions wake
    #: the loop IMMEDIATELY via an event, so this never adds first-token
    #: latency — it only bounds the fallback re-check while fully idle
    #: (keep it large: an idle loop wakes 1000/this times per second)
    serve_max_wait_ms: float = 1000.0
    #: default per-request deadline: queued-past-it → dropped, decoding-past-it
    #: → evicted mid-flight (0 = no deadline)
    serve_request_timeout_s: float = 60.0
    #: load a promoted job's checkpoint on its first generate request (off =
    #: only explicit POST /admin/serve/{job}/load serves traffic)
    serve_autoload: bool = True
    #: fold LoRA deltas into the base kernels at load (dense-model matmul
    #: count; int4-quantized bases always serve unmerged).  Ignored when
    #: serve_max_adapters > 0: multi-tenant serving needs the pristine base,
    #: so the loaded job's own adapter becomes tenant #1 instead of merging
    serve_merge_lora: bool = True

    # --- Paged KV cache (docs/serving.md §Paged KV) ---
    #: page the serve KV cache: lanes hold fixed-size pages proportional to
    #: their actual length instead of reserving cache_len slots at admit —
    #: memory stops capping concurrency (vLLM-style; PAPERS.md).  Greedy and
    #: sampled outputs are bit-identical to the unpaged path
    serve_paged_kv: bool = False
    #: sequence positions per KV page; smaller pages pack mixed-length lanes
    #: tighter, larger pages cut page-table overhead.  Divides the cache
    #: length (max bucket + serve_max_new_tokens) for the tightest layout
    serve_kv_page_tokens: int = 16
    #: total pool pages per replica INCLUDING the reserved scratch page;
    #: 0 auto-sizes to the unpaged capacity (slots * pages-per-lane + 1) —
    #: set it LOWER to actually oversubscribe memory, which is the point:
    #: admission reserves worst-case pages, so a full pool backpressures
    #: (429 + Retry-After) instead of OOMing mid-decode
    serve_kv_pool_pages: int = 0
    #: host-RAM KV tier budget (MiB) behind the device page pool (0 = off;
    #: docs/serving.md §KV tiering).  Needs paged KV and the prefix cache:
    #: past the DEVICE prefix budget (serve_prefix_cache_mb), LRU prefix
    #: entries demote page-by-page to pinned host memory and page back in
    #: on their next hit — effective prefix capacity grows past the device
    #: budget with zero change to splice semantics, and idle-session KV
    #: stops competing with hot decode lanes for device pages
    serve_kv_host_pool_mb: int = 0

    # --- Multi-tenant adapters (docs/serving.md §Multi-tenant adapters) ---
    #: tenant adapters multiplexable per served base model (0 = off): LoRA
    #: jobs serve UNMERGED on a shared base fleet, each lane applying its
    #: request's adapter via a gathered batched einsum — N tenants per base
    #: model on the same chips.  When on, the base job loads unmerged and
    #: its own adapter auto-registers as the first tenant
    serve_max_adapters: int = 0
    #: adapter stack rank ceiling; tenants trained at lower rank zero-pad
    #: (bit-neutral), higher-rank adapters are refused at load
    serve_adapter_rank: int = 32
    #: deficit-round-robin admission quantum (token cost credited to every
    #: waiting tenant per round) — fairness knob: one hot tenant cannot
    #: starve the rest of the batch
    serve_drr_quantum_tokens: int = 256

    # --- Serve fleet (docs/serving.md §Fleet, failover, and drain) ---
    #: replicas per served job (each a full engine+batcher stack behind the
    #: router); 1 keeps the single-engine footprint but gains health checks,
    #: drains, and rollover
    serve_replicas: int = 1
    #: fleet health-check cadence (stall/fault detection + due restarts);
    #: also the autoscale tenant's reconcile cadence
    serve_health_interval_s: float = 2.0
    #: a replica with work in flight that completes no decode step for this
    #: long is stuck: torn down (requests fail over) and restarted with
    #: backoff.  Must exceed the worst-case single decode step INCLUDING a
    #: first-use prefill compile (minutes on large configs)
    serve_replica_stall_s: float = 120.0
    #: graceful-drain budget: in-flight lanes get this long to finish before
    #: stragglers fail over (rollover, scale-down, and preemption all drain)
    serve_drain_timeout_s: float = 30.0
    #: failover budget: extra replicas a request may be re-enqueued on after
    #: its replica dies mid-decode (original deadline preserved)
    serve_failover_retries: int = 2
    #: restart budget for crashed/stuck replicas per incident streak (the
    #: backoff schedule rides retry_base_delay_s/retry_max_delay_s)
    serve_replica_restart_attempts: int = 3
    #: serve-as-a-scheduler-tenant autoscale (docs/scheduling.md §Serve
    #: tenant): replica count follows queue-depth pressure, with every
    #: replica a preemptible low-priority workload; needs the local
    #: backend's fair-share scheduler
    serve_autoscale: bool = False
    serve_min_replicas: int = 1
    serve_max_replicas: int = 4
    #: queued requests PER healthy replica that count as pressure
    serve_scale_up_queue_depth: int = 8
    #: consecutive pressured health ticks before a grow is submitted
    serve_scale_sustain_ticks: int = 2
    #: tenant queue serve workloads land in (weight via FTC_SCHED_QUEUES)
    serve_queue: str = "serve"
    #: device flavor for replica workloads ("" = the catalog's default)
    serve_flavor: str = ""

    # --- Serve transport (docs/serving.md §Cross-process transport) ---
    #: where replicas run: "inproc" (engines share the API process's JAX
    #: runtime — tests/dev footprint) or "process" (one worker PROCESS per
    #: replica with its own runtime behind the RPC socket — replicas stop
    #: sharing cores, which is what makes 2 replicas actually ~2x)
    serve_transport: str = "inproc"
    #: first worker port; 0 = ephemeral ports (collision-free; the bound
    #: port is read back from the worker sandbox's transport.json)
    serve_worker_port_base: int = 0
    #: spawn handshake budget per worker (payload build + engine warm-start
    #: compiles happen inside it; raise for big presets on cold caches)
    serve_worker_spawn_timeout_s: float = 300.0
    #: worker heartbeat cadence; the fleet's liveness lease is 3x this
    #: (floored) — a SIGKILLed or wedged worker is declared dead past it
    serve_worker_heartbeat_s: float = 2.0

    # --- Resilience (finetune_controller_tpu/resilience/, docs/resilience.md) ---
    #: total run attempts per job before a retryable failure becomes terminal
    #: (0 disables the retry supervisor entirely — reference-parity behavior:
    #: FAILED jobs stay in place for forensics and nothing is resubmitted)
    retry_max_attempts: int = 3
    #: backoff floor/ceiling for the decorrelated-jitter schedule
    retry_base_delay_s: float = 2.0
    retry_max_delay_s: float = 60.0
    #: liveness lease: a RUNNING job whose newest heartbeat is older than
    #: this is declared stuck, killed, and handed to the supervisor (0 = off).
    #: Must comfortably exceed artifact_sync_interval_s + the trainer's
    #: heartbeat_interval_s — the runtime enforces a floor of 3x the sync
    #: cadence so a slow sync can never masquerade as a dead trainer.
    #: It must ALSO exceed the worst-case single-step time including the
    #: first step's XLA compile (minutes on large configs): heartbeats land
    #: between steps, so a lease tighter than one step phase kills healthy
    #: jobs mid-compile (docs/resilience.md).
    liveness_lease_s: float = 300.0

    @property
    def state_path(self) -> Path:
        return Path(self.state_dir).expanduser()

    @property
    def object_store_path(self) -> Path:
        return Path(self.object_store_root).expanduser()


_ENV_PREFIX = "FTC_"
_settings: Settings | None = None


def _from_env() -> Settings:
    """Build Settings from ``FTC_*`` env vars (upper-snake of the field name)."""
    raw: dict[str, object] = {}
    for name, field in Settings.model_fields.items():
        env_val = os.environ.get(_ENV_PREFIX + name.upper())
        if env_val is None:
            continue
        ann = field.annotation
        if ann is bool:
            raw[name] = env_val.lower() in ("1", "true", "yes", "on")
        elif ann in (int, float):
            raw[name] = env_val
        elif ann == list[str]:
            raw[name] = (
                json.loads(env_val) if env_val.startswith("[") else env_val.split(",")
            )
        elif ann == list[int]:
            parts = (
                json.loads(env_val) if env_val.startswith("[")
                else env_val.split(",")
            )
            raw[name] = [int(p) for p in parts]
        else:
            raw[name] = env_val
    return Settings(**raw)


def get_settings() -> Settings:
    """Lazily build the process-wide settings (first call reads the env)."""
    global _settings
    if _settings is None:
        _settings = _from_env()
    return _settings


def set_settings(settings: Settings | None) -> None:
    """Inject (or reset with ``None``) settings — the test seam."""
    global _settings
    _settings = settings
