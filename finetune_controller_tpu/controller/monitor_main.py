"""Standalone monitor daemon: ``python -m finetune_controller_tpu.controller.monitor_main``.

Capability parity with the reference's monitor entrypoint
(``app/monitor_main.py:19-89`` — SURVEY.md §2 component 15): an asyncio
service with signal handlers and clean shutdown, running the reconciler
forever. Meaningful for cluster-shared backends (k8s); with the in-process
local backend the monitor instead runs inside the API process
(``Settings.monitor_in_process``, reference ``DEV_LOCAL_JOB_MONITOR``
``app/main.py:91-99``).

Observability (docs/observability.md): with ``FTC_MONITOR_METRICS_PORT > 0``
the daemon serves the same ``/metrics`` exposition as the API server —
``ftc_build_info{process="monitor"}`` / ``ftc_uptime_seconds`` plus the
histograms THIS process observes (queue wait, retry latency, step phases) —
so a split deployment scrapes both halves of the control plane.
"""

from __future__ import annotations

import asyncio
import logging
import signal

from aiohttp import web

from .logging_config import setup_logging
from .runtime import build_runtime

logger = logging.getLogger(__name__)


async def _start_metrics_listener(runtime, port: int):
    """Mount the server module's /metrics handler on a bare app — one
    exposition implementation for both processes, labelled by PROCESS_KEY."""
    from .server import PROCESS_KEY, RUNTIME_KEY, prometheus_metrics

    app = web.Application()
    app[RUNTIME_KEY] = runtime
    app[PROCESS_KEY] = "monitor"
    app.router.add_get("/metrics", prometheus_metrics)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "0.0.0.0", port)
    await site.start()
    logger.info("monitor /metrics listening on :%d", port)
    return runner


async def amain() -> None:
    # ftc: ignore[blocking-io-in-async-transitive] -- startup path: the device-catalog read runs once, before the loop serves anything
    runtime = build_runtime()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        # reference: shutdown handlers, monitor_main.py:19-32
        loop.add_signal_handler(sig, stop.set)
    await runtime.start(with_monitor=True)
    metrics_runner = None
    if runtime.settings.monitor_metrics_port > 0:
        metrics_runner = await _start_metrics_listener(
            runtime, runtime.settings.monitor_metrics_port
        )
    logger.info("monitor daemon up (backend=%s)", runtime.settings.backend)
    try:
        await stop.wait()
    finally:
        if metrics_runner is not None:
            await metrics_runner.cleanup()
        await runtime.close()
        logger.info("monitor daemon shut down")


def main() -> int:
    setup_logging()
    asyncio.run(amain())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
