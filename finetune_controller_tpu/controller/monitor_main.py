"""Standalone monitor daemon: ``python -m finetune_controller_tpu.controller.monitor_main``.

Capability parity with the reference's monitor entrypoint
(``app/monitor_main.py:19-89`` — SURVEY.md §2 component 15): an asyncio
service with signal handlers and clean shutdown, running the reconciler
forever. Meaningful for cluster-shared backends (k8s); with the in-process
local backend the monitor instead runs inside the API process
(``Settings.monitor_in_process``, reference ``DEV_LOCAL_JOB_MONITOR``
``app/main.py:91-99``).
"""

from __future__ import annotations

import asyncio
import logging
import signal

from .logging_config import setup_logging
from .runtime import build_runtime

logger = logging.getLogger(__name__)


async def amain() -> None:
    runtime = build_runtime()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        # reference: shutdown handlers, monitor_main.py:19-32
        loop.add_signal_handler(sig, stop.set)
    await runtime.start(with_monitor=True)
    logger.info("monitor daemon up (backend=%s)", runtime.settings.backend)
    try:
        await stop.wait()
    finally:
        await runtime.close()
        logger.info("monitor daemon shut down")


def main() -> int:
    setup_logging()
    asyncio.run(amain())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
