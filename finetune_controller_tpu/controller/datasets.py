"""Dataset ingestion helpers: file upload, URL streaming, record keeping.

Capability parity with the reference's ``app/utils/dataset_helpers.py``
(SURVEY.md §2 component 18): save-upload-cleanup for file uploads (:20-57),
zero-copy URL → object-store streaming (:113-145), filename extraction from
``Content-Disposition`` (:60-70) — plus the dataset-record bookkeeping the
reference's API layer does inline (``app/main.py:953-1060``).
"""

from __future__ import annotations

import logging
import re
from typing import Any, AsyncIterator
from urllib.parse import unquote, urlparse

from .objectstore import ObjectStore, build_uri
from .schemas import DatasetRecord
from .statestore import StateStore, generate_short_uuid

logger = logging.getLogger(__name__)

_DISPOSITION_RE = re.compile(r"filename\*?=(?:UTF-8''|\"?)([^\";]+)", re.IGNORECASE)


def filename_from_content_disposition(header: str | None) -> str | None:
    """Reference: ``dataset_helpers.py:60-70``."""
    if not header:
        return None
    m = _DISPOSITION_RE.search(header)
    return unquote(m.group(1).strip()) if m else None


def dataset_uri_for(bucket: str, user_id: str, dataset_id: str, filename: str) -> str:
    return build_uri(bucket, "datasets", user_id, dataset_id, filename)


async def upload_dataset_bytes(
    store: ObjectStore,
    state: StateStore,
    *,
    user_id: str,
    filename: str,
    data: bytes,
    bucket: str,
    content_type: str | None = None,
    name: str | None = None,
) -> DatasetRecord:
    """File-upload path (reference: ``upload_dataset_file``,
    ``dataset_helpers.py:20-57`` — minus the tmp-file hop, since the object
    store accepts bytes directly)."""
    dataset_id = generate_short_uuid()
    uri = dataset_uri_for(bucket, user_id, dataset_id, filename)
    await store.put_bytes(uri, data)
    record = DatasetRecord(
        dataset_id=dataset_id,
        user_id=user_id,
        name=name or filename,
        uri=uri,
        size_bytes=len(data),
        content_type=content_type,
    )
    await state.insert_dataset(record)
    return record


async def upload_dataset_stream(
    store: ObjectStore,
    state: StateStore,
    *,
    user_id: str,
    filename: str,
    chunks: AsyncIterator[bytes],
    bucket: str,
    content_type: str | None = None,
    name: str | None = None,
) -> DatasetRecord:
    """Streaming upload — no full-file buffering (the zero-copy property of
    the reference's URL path, ``dataset_helpers.py:113-145``)."""
    dataset_id = generate_short_uuid()
    uri = dataset_uri_for(bucket, user_id, dataset_id, filename)
    size = await store.put_stream(uri, chunks)
    record = DatasetRecord(
        dataset_id=dataset_id,
        user_id=user_id,
        name=name or filename,
        uri=uri,
        size_bytes=size,
        content_type=content_type,
    )
    await state.insert_dataset(record)
    return record


async def stream_dataset_url(
    store: ObjectStore,
    state: StateStore,
    *,
    user_id: str,
    url: str,
    bucket: str,
    session: Any | None = None,
    chunk_size: int = 1 << 20,
) -> DatasetRecord:
    """Download a dataset URL straight into the object store (reference:
    ``stream_dataset_url``, ``dataset_helpers.py:113-145``): the HTTP body is
    piped chunk-by-chunk, never buffered whole.

    ``session`` is an injected aiohttp-compatible client session (test seam);
    a real one is created per call when omitted.
    """
    import aiohttp

    own_session = session is None
    if own_session:
        session = aiohttp.ClientSession()
    try:
        async with session.get(url) as resp:
            if resp.status != 200:
                raise ValueError(f"dataset URL returned HTTP {resp.status}")
            filename = (
                filename_from_content_disposition(resp.headers.get("Content-Disposition"))
                or unquote(urlparse(url).path.rsplit("/", 1)[-1])
                or "dataset.bin"
            )
            content_type = resp.headers.get("Content-Type")

            async def chunks() -> AsyncIterator[bytes]:
                async for chunk in resp.content.iter_chunked(chunk_size):
                    yield chunk

            return await upload_dataset_stream(
                store, state,
                user_id=user_id, filename=filename, chunks=chunks(),
                bucket=bucket, content_type=content_type, name=url,
            )
    finally:
        if own_session:
            await session.close()
