"""The control-plane HTTP/WS API (aiohttp).

Capability parity with the reference's FastAPI app (``app/main.py`` 1,355 LoC
— SURVEY.md §2 component 1) plus its middleware wiring (component 20) and
OpenAPI customization (component 21). Route-by-route mapping to the reference
is cited on each handler. Differences by design:

- aiohttp instead of FastAPI (dependency surface: aiohttp is in the image);
- the execution substrate is the backend seam, not raw Kubernetes clients;
- nothing global: the app is built from an injected :class:`Runtime`
  (reference wires singletons at import, SURVEY.md §3.5 wart).
"""

from __future__ import annotations

import asyncio
import json
import logging
import tempfile
import time
from pathlib import Path
from typing import Any
from urllib.parse import urlencode

from aiohttp import web
from pydantic import ValidationError

from ..obs import events as obs_events
from ..obs.events import append_event_safe, make_event
from ..obs.prom import ObsHub, escape_label
from ..obs.trace import (
    TRACE_DIRNAME,
    TRAINER_SPANS_FILENAME,
    build_trace,
    export_trace,
    parse_span_lines,
)
from ..sched.queues import parse_priority
from . import registry
from .config import Settings
from .promotion import PromotionTask, promotion_destination
from .runtime import Runtime, build_runtime
from .schemas import DatabaseStatus, JobInput, PromotionStatus
from .config import DEFAULT_JWT_SECRET
from .security import (
    TokenValidator,
    build_auth_middleware,
    build_cors_middleware,
    dev_generate_token,
)
from .statestore import generate_short_uuid
from .stream_logger import LogStreamManager
from .task_builder import DatasetInput, TaskBuildError, task_builder

logger = logging.getLogger(__name__)

RUNTIME_KEY = web.AppKey("runtime", Runtime)
PROMOTION_KEY = web.AppKey("promotion", PromotionTask)
LIMITER_KEY = web.AppKey("limiter", object)
BG_TASKS_KEY = web.AppKey("bg_tasks", set)
#: which process is serving /metrics — "server" here, "monitor" when the
#: standalone monitor daemon mounts the same handler (monitor_main.py)
PROCESS_KEY = web.AppKey("process_name", str)


# ---------------------------------------------------------------------------
# Rate limiting (reference: slowapi limiter, app/api/middleware.py:18,
# limits at app/main.py:377,525,714)
# ---------------------------------------------------------------------------


class RateLimiter:
    """Sliding-window per-user, per-class limiter, enforced in the STATE
    STORE's consistency domain (``StateStore.rate_limit_acquire``): memory
    store → per-process (dev), sqlite → every worker sharing the state dir,
    remote state service → the whole cluster. The reference's slowapi limits
    are per-process, so ``--workers N`` silently multiplies them
    (``app/main.py:377,525,714``); here the scope follows the store."""

    def __init__(self, state, limits_per_min: dict[str, int]):
        self.state = state
        self.limits = limits_per_min

    async def check(self, user_id: str, bucket: str) -> bool:
        limit = self.limits.get(bucket)
        if not limit:
            return True
        return await self.state.rate_limit_acquire(
            f"rl/{bucket}/{user_id}", limit, 60.0
        )


def _limited(bucket: str):
    """Decorator enforcing a rate-limit class on a handler."""

    def deco(handler):
        async def wrapped(request: web.Request):
            limiter: RateLimiter = request.app[LIMITER_KEY]
            user = request.get("user")
            uid = user.user_id if user else request.remote or "anon"
            if not await limiter.check(uid, bucket):
                raise web.HTTPTooManyRequests(
                    text=json.dumps({"detail": f"rate limit exceeded ({bucket})"}),
                    content_type="application/json",
                )
            return await handler(request)

        wrapped.__name__ = handler.__name__
        wrapped.__doc__ = handler.__doc__
        return wrapped

    return deco


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------


def _json_error(status: int, detail: Any) -> web.Response:
    return web.json_response({"detail": detail}, status=status)


def _bad_request(detail: str) -> web.HTTPBadRequest:
    return web.HTTPBadRequest(
        text=json.dumps({"detail": detail}), content_type="application/json"
    )


def _int_param(q, name: str, default: int) -> int:
    raw = q.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        raise _bad_request(f"query parameter {name!r} must be an integer")


def _status_param(q) -> DatabaseStatus | None:
    raw = q.get("status")
    if not raw:
        return None
    try:
        return DatabaseStatus(raw)
    except ValueError:
        raise _bad_request(
            f"unknown status {raw!r}; one of {[s.value for s in DatabaseStatus]}"
        )


async def _json_body(request: web.Request) -> dict[str, Any]:
    try:
        body = await request.json()
    except Exception:
        raise _bad_request("request body must be valid JSON")
    if not isinstance(body, dict):
        raise _bad_request("request body must be a JSON object")
    return body


def _signed_download_url(rt: Runtime, uri: str) -> str:
    """Presigned, URL-encoded download link (unencoded URIs with spaces/&
    would self-invalidate the signature)."""
    query = urlencode({"uri": uri, "sig": rt.presigner.sign(uri)})
    return f"{rt.settings.api_prefix}/download?{query}"


@web.middleware
async def error_middleware(request: web.Request, handler):
    """Uniform JSON error shapes (reference: FastAPI exception handlers)."""
    try:
        return await handler(request)
    except web.HTTPException:
        raise
    except TaskBuildError as e:
        return _json_error(e.status, str(e))
    except ValidationError as e:
        # reference renders a per-field list on submit validation
        # (app/main.py:437-471)
        errors = [
            {"field": ".".join(str(p) for p in err["loc"]), "message": err["msg"]}
            for err in e.errors()
        ]
        return _json_error(400, errors)
    except Exception:
        logger.exception("unhandled error on %s %s", request.method, request.path)
        return _json_error(500, "internal server error")


def _user(request: web.Request):
    user = request.get("user")
    if user is None:
        raise web.HTTPUnauthorized(
            text=json.dumps({"detail": "not authenticated"}),
            content_type="application/json",
        )
    return user


async def _owned_job(request: web.Request, job_id: str):
    """Fetch a job and enforce ownership (reference: ``app/main.py:725-726``;
    admins see everything, as in the reference's admin routes)."""
    rt = request.app[RUNTIME_KEY]
    user = _user(request)
    job = await rt.state.get_job(job_id)
    if job is None or (job.user_id != user.user_id and not user.is_admin):
        raise web.HTTPNotFound(
            text=json.dumps({"detail": f"job {job_id!r} not found"}),
            content_type="application/json",
        )
    return job


def _spawn_bg(app: web.Application, coro) -> None:
    """Track background tasks so shutdown can await them (reference used
    FastAPI BackgroundTasks, ``app/main.py:776-781``)."""
    task = asyncio.get_running_loop().create_task(coro)
    app[BG_TASKS_KEY].add(task)
    task.add_done_callback(app[BG_TASKS_KEY].discard)


# ---------------------------------------------------------------------------
# Handlers — models & form schema
# ---------------------------------------------------------------------------


async def health(request: web.Request) -> web.Response:
    return web.json_response({"status": "ok"})


async def list_models(request: web.Request) -> web.Response:
    """Entitled models (reference: ``user_available_models``,
    ``app/main.py:1323-1341``)."""
    rt = request.app[RUNTIME_KEY]
    user = _user(request)
    names = user.entitled_models(sorted(registry.JOB_MANIFESTS))
    out = []
    for name in names:
        cls = registry.JOB_MANIFESTS[name]
        out.append(
            {
                "name": name,
                "description": cls.description,
                "task": cls.task.value,
                "framework": cls.framework.value,
                "default_device": cls.default_device,
                "devices": rt.catalog.names(),
                "dataset": cls.dataset.model_dump(),
            }
        )
    return web.json_response({"models": out})


async def model_schema(request: web.Request) -> web.Response:
    """Submission-form JSON schema (reference: ``app/main.py:244-281`` —
    the pydantic Field metadata IS the form)."""
    user = _user(request)
    name = request.match_info["model_name"]
    cls = registry.get_spec(name)
    if cls is None or name not in user.entitled_models(list(registry.JOB_MANIFESTS)):
        return _json_error(404, f"model {name!r} not found")
    rt = request.app[RUNTIME_KEY]
    return web.json_response(
        {
            "model": name,
            "arguments_schema": cls.arguments_schema(),
            "devices": rt.catalog.names(),
            "default_device": cls.default_device,
            "default_num_slices": cls.default_num_slices,
        }
    )


# ---------------------------------------------------------------------------
# Handlers — job submission (reference: start_job, app/main.py:376-502, §3.1)
# ---------------------------------------------------------------------------


def _parse_arguments(raw: Any) -> dict[str, Any]:
    """Reference: ``_parse_arguments_input``, ``app/main.py:505-511``."""
    if raw is None or raw == "":
        return {}
    if isinstance(raw, dict):
        return raw
    try:
        parsed = json.loads(raw)
    except json.JSONDecodeError as e:
        raise TaskBuildError(f"arguments is not valid JSON: {e}") from e
    if not isinstance(parsed, dict):
        raise TaskBuildError("arguments must be a JSON object")
    return parsed


async def _stream_part_to_dataset(request: web.Request, part) -> str:
    """Stream a multipart file part straight into the object store as a
    dataset record (no whole-file buffering); returns the dataset id."""
    from .datasets import upload_dataset_stream

    rt = request.app[RUNTIME_KEY]
    user = _user(request)

    async def chunks():
        while chunk := await part.read_chunk(1 << 20):
            yield chunk

    record = await upload_dataset_stream(
        rt.store, rt.state,
        user_id=user.user_id,
        filename=part.filename or "dataset.jsonl",
        chunks=chunks(),
        bucket=rt.settings.datasets_bucket,
        content_type=part.headers.get("Content-Type"),
    )
    return record.dataset_id


async def _read_submission(request: web.Request) -> tuple[dict[str, Any], DatasetInput]:
    """Accept JSON or multipart (file upload) submissions."""
    ds = DatasetInput()
    if request.content_type == "multipart/form-data":
        fields: dict[str, Any] = {}
        async for part in await request.multipart():
            if part.name == "dataset_file":
                # uploaded file becomes a first-class dataset record; the job
                # then references it by id (streams, never buffers)
                ds.dataset_id = await _stream_part_to_dataset(request, part)
            else:
                fields[part.name] = (await part.read(decode=True)).decode()
    else:
        fields = await _json_body(request)
    ds.dataset_id = fields.pop("dataset_id", None) or ds.dataset_id
    ds.url = fields.pop("dataset_url", None) or None
    return fields, ds


@_limited("submit")
async def start_job(request: web.Request) -> web.Response:
    rt = request.app[RUNTIME_KEY]
    user = _user(request)
    fields, ds = await _read_submission(request)

    # unknown fields are rejected, not ignored: a typo'd "training_arguments"
    # silently training 100 default steps is far costlier than a 400
    known = {"model_name", "model", "arguments", "task", "device",
             "num_slices", "queue", "priority"}
    unknown = sorted(set(fields) - known)
    if unknown:
        return _json_error(
            400, f"unknown submission fields {unknown}; accepted: {sorted(known)}"
        )

    model_name = fields.get("model_name") or fields.get("model")
    if not model_name:
        return _json_error(400, "model_name is required")
    cls = registry.get_spec(model_name)
    if cls is None:
        return _json_error(404, f"model {model_name!r} not found")
    # entitlement check (reference: app/main.py:408-416)
    if model_name not in user.entitled_models(list(registry.JOB_MANIFESTS)):
        return _json_error(403, f"not entitled to model {model_name!r}")

    arguments = _parse_arguments(fields.get("arguments"))
    # pydantic-validates the typed hyperparameters; ValidationError → 400 list
    spec = cls(training_arguments=arguments)

    # task validation (reference: app/main.py:455-459, hardened): an unknown
    # task value is a 400 NAMING the known tasks — previously any string
    # passed as long as it didn't collide with the model's task
    task = fields.get("task")
    if task:
        from .specs import known_tasks

        known_task_values = known_tasks()
        if task not in known_task_values:
            return _json_error(
                400,
                f"unknown task {task!r}; known tasks: {known_task_values}",
            )
        if task != cls.task.value:
            return _json_error(
                400,
                f"model {model_name!r} is a {cls.task.value} model, "
                f"not {task!r}",
            )

    device = fields.get("device") or cls.default_device
    flavor = rt.catalog.get(device)
    if flavor is None:
        return _json_error(
            400,
            f"unknown device {device!r}; available: {rt.catalog.names()}",
        )
    try:
        num_slices = int(fields.get("num_slices") or cls.default_num_slices)
    except (TypeError, ValueError):
        return _json_error(400, "num_slices must be an integer")
    need = flavor.total_chips * max(1, num_slices)
    quota = rt.catalog.quota_for(device)
    if need > quota:
        # the fair-share scheduler refuses never-fitting workloads (they
        # would wedge their flavor's reservation); surface that as a 400
        # with the quota named instead of a 500 from the backend
        return _json_error(
            400,
            f"request needs {need} chips of {device!r} but the quota is "
            f"{quota}; reduce num_slices or pick a larger flavor",
        )

    # tenant queue + priority class (docs/scheduling.md): validated here so
    # a bad priority is a 400 at submit, never a failure inside admission
    queue = str(fields.get("queue") or "default").strip()
    if not queue or len(queue) > 64:
        return _json_error(400, "queue must be a non-empty name (<= 64 chars)")
    priority = fields.get("priority", "normal")
    try:
        parse_priority(priority)
    except ValueError as exc:
        return _json_error(400, str(exc))

    job_id = f"{model_name}-{generate_short_uuid()}"  # reference: app/main.py:422
    job = JobInput(
        job_id=job_id,
        user_id=user.user_id,
        model_name=model_name,
        device=device,
        num_slices=num_slices,
        arguments=arguments,
        queue=queue,
        priority=priority,
    )
    await task_builder(
        job, spec, ds,
        state=rt.state, store=rt.store, backend=rt.backend, catalog=rt.catalog,
        datasets_bucket=rt.settings.datasets_bucket,
        artifacts_bucket=rt.settings.artifacts_bucket,
    )
    # reference response shape: app/main.py:488
    return web.json_response({"message": "Job started successfully", "job_id": job_id})


# ---------------------------------------------------------------------------
# Handlers — job reads
# ---------------------------------------------------------------------------


@_limited("read")
async def get_jobs_page(request: web.Request) -> web.Response:
    """Paginated job table (reference: ``get_user_jobs_page``,
    ``app/main.py:524-613``)."""
    rt = request.app[RUNTIME_KEY]
    user = _user(request)
    q = request.query
    page = await rt.state.get_user_jobs(
        user.user_id,
        page=_int_param(q, "page", 1),
        page_size=min(_int_param(q, "page_size", 20), 100),
        status=_status_param(q),
        search=q.get("search"),
        sort_by=q.get("sort_by", "submitted_at"),
        descending=q.get("descending", "true").lower() != "false",
    )
    return web.json_response(page.model_dump(mode="json"))


async def get_job(request: web.Request) -> web.Response:
    job = await _owned_job(request, request.match_info["job_id"])
    return web.json_response(job.model_dump(mode="json"))


async def get_job_metrics(request: web.Request) -> web.Response:
    """Last 100 metric rows reversed + presigned CSV link (reference:
    ``app/main.py:660-709``)."""
    rt = request.app[RUNTIME_KEY]
    job = await _owned_job(request, request.match_info["job_id"])
    doc = await rt.state.get_metrics(job.job_id)
    records = (doc.records if doc else [])[-100:][::-1]
    csv_url = _signed_download_url(rt, doc.source_uri) if doc and doc.source_uri else None
    return web.json_response(
        {"job_id": job.job_id, "records": records, "csv_url": csv_url}
    )


async def get_job_artifacts(request: web.Request) -> web.Response:
    """Artifact zip download (reference: ``S3Handler.py:294-373`` streamed
    through the API)."""
    rt = request.app[RUNTIME_KEY]
    job = await _owned_job(request, request.match_info["job_id"])
    if not job.artifacts_uri:
        return _json_error(404, "job has no artifacts")
    objs = await rt.store.list_prefix(job.artifacts_uri)
    if not objs:
        return _json_error(404, "no artifacts found")
    if request.query.get("list"):
        # JSON inventory instead of the zip — how clients discover e.g. the
        # profiler trace under profile/ without downloading everything
        prefix_len = len(job.artifacts_uri.rstrip("/")) + 1
        return web.json_response(
            {
                "job_id": job.job_id,
                "artifacts": [
                    {"path": o["uri"][prefix_len:], "size": o["size"]}
                    for o in objs
                ],
            }
        )
    # spool the zip to disk and stream it out — multi-GB checkpoint prefixes
    # must not be materialised in RAM per download
    with tempfile.NamedTemporaryFile(suffix=".zip", delete=False) as tmp:
        tmp_path = Path(tmp.name)
    try:
        await rt.store.zip_prefix_to_path(job.artifacts_uri, tmp_path)
        resp = web.StreamResponse(
            headers={
                "Content-Type": "application/zip",
                "Content-Disposition": (
                    f'attachment; filename="{job.job_id}_artifacts.zip"'
                ),
                "Content-Length": str(tmp_path.stat().st_size),
            }
        )
        await resp.prepare(request)
        # ftc: ignore[blocking-io-in-async] -- open() of a local tmp file is metadata-only; the reads below go through to_thread
        with open(tmp_path, "rb") as f:
            while chunk := await asyncio.to_thread(f.read, 1 << 20):
                await resp.write(chunk)
        await resp.write_eof()
        return resp
    finally:
        tmp_path.unlink(missing_ok=True)


async def download(request: web.Request) -> web.Response:
    """Presigned-URL fulfillment (LocalObjectStore's stand-in for S3
    presigned GETs, reference ``S3Handler.py:168``)."""
    rt = request.app[RUNTIME_KEY]
    uri, sig = request.query.get("uri", ""), request.query.get("sig", "")
    if not uri or not rt.presigner.verify(uri, sig):
        return _json_error(403, "invalid or expired signature")
    if not await rt.store.exists(uri):
        return _json_error(404, "object not found")
    data = await rt.store.get_bytes(uri)
    return web.Response(
        body=data,
        content_type="application/octet-stream",
        headers={
            "Content-Disposition": f'attachment; filename="{uri.rsplit("/", 1)[-1]}"'
        },
    )


# ---------------------------------------------------------------------------
# Handlers — observability (docs/observability.md)
# ---------------------------------------------------------------------------


async def _append_event(rt: Runtime, job_id: str, event: str,
                        key: str | None = None, **attrs: Any) -> None:
    """Best-effort timeline append from a request handler."""
    await append_event_safe(rt.state, job_id, event, key=key, **attrs)


async def get_job_timeline(request: web.Request) -> web.Response:
    """The job's lifecycle event timeline, oldest first — the data behind
    ``ftc-ctl timeline`` (docs/observability.md §Timeline)."""
    job = await _owned_job(request, request.match_info["job_id"])
    events = sorted(job.events, key=lambda e: e.get("ts") or 0)
    return web.json_response(
        {
            "job_id": job.job_id,
            "trace_id": (job.metadata or {}).get("trace_id"),
            "status": job.status.value,
            "events": events,
        }
    )


async def get_job_trace(request: web.Request) -> web.Response:
    """The assembled span tree (controller phases derived from the timeline
    + trainer spans from the artifact channel), OTel-compatible dicts."""
    rt = request.app[RUNTIME_KEY]
    job = await _owned_job(request, request.match_info["job_id"])
    trainer_spans: list[dict[str, Any]] = []
    if job.artifacts_uri:
        uri = f"{job.artifacts_uri}/{TRACE_DIRNAME}/{TRAINER_SPANS_FILENAME}"
        try:
            if await rt.store.exists(uri):
                trainer_spans = parse_span_lines(await rt.store.get_bytes(uri))
        except Exception:
            logger.debug("trainer span read failed for %s", job.job_id,
                         exc_info=True)
    return web.json_response(
        build_trace(job.model_dump(mode="json"), trainer_spans)
    )


async def request_job_profile(request: web.Request) -> web.Response:
    """Arm an on-demand ``jax.profiler`` trace window on a LIVE job — no
    restart: the request rides the artifact channel in reverse
    (``backend.deliver_file`` → ``profile_request.json`` → the trainer's
    fit loop polls for it at the preemption-sync cadence and captures N
    steps into ``profile/``, shipped with the artifacts).  The poll is
    independent of the tracing kill switch (a ``FTC_TRACE=0`` job still
    profiles); only ``FTC_PROFILE=0`` in the trainer env opts out, in which
    case the delivered request is never consumed."""
    rt = request.app[RUNTIME_KEY]
    job = await _owned_job(request, request.match_info["job_id"])
    if job.status is not DatabaseStatus.RUNNING:
        return _json_error(
            409, f"job is {job.status.value}; profiling needs a running job"
        )
    body = await _json_body(request) if request.can_read_body else {}
    steps = body.get("steps", 5)
    if not isinstance(steps, int) or not 1 <= steps <= 1000:
        return _json_error(400, "steps must be an integer in [1, 1000]")
    payload = json.dumps(
        {"steps": steps, "requested_at": time.time()}
    ).encode()
    delivered = await rt.backend.deliver_file(
        job.job_id, "profile_request.json", payload
    )
    if not delivered:
        return _json_error(
            501, "this backend cannot deliver control files to running jobs"
        )
    await _append_event(
        rt, job.job_id, obs_events.PROFILE_REQUESTED, steps=steps,
    )
    return web.json_response(
        {
            "message": f"profiler window armed for {steps} steps",
            "artifact": "profile/ (fetch via GET /jobs/{id}/artifacts?list=1)",
        },
        status=202,
    )


# ---------------------------------------------------------------------------
# Handlers — lifecycle mutations
# ---------------------------------------------------------------------------


@_limited("promote")
async def promote_job(request: web.Request) -> web.Response:
    """Reference: ``promote_job``, ``app/main.py:713-794`` (§3.4), with the
    same guards."""
    rt = request.app[RUNTIME_KEY]
    job = await _owned_job(request, request.match_info["job_id"])
    if job.promotion_status is PromotionStatus.IN_PROGRESS:
        return web.json_response(
            {"detail": "promotion already in progress"}, status=202
        )
    if not job.status.is_final:
        return _json_error(400, "cannot promote a running job")
    if job.status is not DatabaseStatus.SUCCEEDED:
        return _json_error(400, f"cannot promote a {job.status.value} job")
    if not job.artifacts_uri or not await rt.store.list_prefix(job.artifacts_uri):
        return _json_error(404, "job has no artifacts to promote")
    cls = registry.get_spec(job.model_name)
    promotion_path = cls.promotion_path if cls else "models"
    destination = promotion_destination(
        rt.settings.deploy_bucket, promotion_path, job.job_id
    )
    promo = request.app[PROMOTION_KEY]
    # Compare-and-set claim: concurrent promote requests race on the awaits
    # between the guard above and here, so the IN_PROGRESS transition itself
    # must be atomic — only the request that wins the CAS spawns the copy.
    # expect_from pins the legal sources: a promote landing while an
    # unpromote is DELETING (or any state the guards above didn't see) loses
    # in the store, not in these stale-read guards.
    if not await rt.state.begin_promotion(
        job.job_id, PromotionStatus.IN_PROGRESS, destination,
        expect_from=[
            PromotionStatus.NOT_PROMOTED,
            PromotionStatus.FAILED,
            PromotionStatus.COMPLETED,  # re-promote refreshes the deploy copy
        ],
    ):
        return web.json_response(
            {"detail": "promotion already in progress"}, status=202
        )
    await _append_event(
        rt, job.job_id, obs_events.PROMOTION_STARTED, destination=destination
    )
    _spawn_bg(
        request.app,
        promo.promote_job_task(job.job_id, job.artifacts_uri, destination),
    )
    return web.json_response(
        {"message": "promotion started", "destination": destination}, status=202
    )


@_limited("promote")
async def unpromote_job(request: web.Request) -> web.Response:
    """Reference: ``unpromote_job``, ``app/main.py:798-835``."""
    rt = request.app[RUNTIME_KEY]
    job = await _owned_job(request, request.match_info["job_id"])
    if job.promotion_status not in (PromotionStatus.COMPLETED, PromotionStatus.FAILED):
        return _json_error(400, "job is not promoted")
    if not job.promotion_uri:
        return _json_error(404, "no promotion destination recorded")
    promo = request.app[PROMOTION_KEY]
    # Same CAS claim as promote: only the winning request spawns the cleanup,
    # and only from a settled promoted/failed state (never mid-promote).
    if not await rt.state.begin_promotion(
        job.job_id, PromotionStatus.DELETING, job.promotion_uri,
        expect_from=[PromotionStatus.COMPLETED, PromotionStatus.FAILED],
    ):
        return web.json_response(
            {"detail": "unpromotion already in progress"}, status=202
        )
    _spawn_bg(request.app, promo.unpromote_job_task(job.job_id, job.promotion_uri))
    return web.json_response({"message": "unpromotion started"}, status=202)


async def cancel_job(request: web.Request) -> web.Response:
    """Reference: ``cancel_job``, ``app/main.py:839-903``: stop the backend
    half, mark CANCELLED."""
    rt = request.app[RUNTIME_KEY]
    job = await _owned_job(request, request.match_info["job_id"])
    if job.status.is_final:
        return _json_error(400, f"job already {job.status.value}")
    await rt.backend.delete_job(job.job_id)
    # fixed key: two racing cancel requests must fold into ONE timeline
    # event, or the second lands outside every span and poisons the
    # exported trace's gap-free verdict
    await _append_event(rt, job.job_id, obs_events.CANCELLED, key="cancelled")
    await rt.state.update_job_status(
        job.job_id, DatabaseStatus.CANCELLED, end_time=time.time(), queue_position=None
    )
    # the backend half is gone, so the monitor's report loop may never see
    # this job again — export the trace here (docs/observability.md promises
    # an export for EVERY terminal state, cancels included)
    _spawn_bg(request.app, export_trace(rt.state, rt.store, job.job_id))
    return web.json_response({"message": "job cancelled", "job_id": job.job_id})


async def delete_job(request: web.Request) -> web.Response:
    """Reference: ``delete_job``, ``app/main.py:907-946``: archive-on-delete;
    running jobs must be cancelled first."""
    rt = request.app[RUNTIME_KEY]
    job = await _owned_job(request, request.match_info["job_id"])
    if not job.status.is_final and job.status is not DatabaseStatus.UNKNOWN:
        return _json_error(400, "cancel the job before deleting it")
    await rt.backend.delete_job(job.job_id)
    await rt.state.delete_job(job.job_id)
    return web.json_response({"message": "job deleted", "job_id": job.job_id})


# ---------------------------------------------------------------------------
# Handlers — datasets (reference: app/main.py:953-1060)
# ---------------------------------------------------------------------------


async def upload_dataset(request: web.Request) -> web.Response:
    from .datasets import stream_dataset_url

    rt = request.app[RUNTIME_KEY]
    user = _user(request)
    if request.content_type == "multipart/form-data":
        async for part in await request.multipart():
            if part.name in ("file", "dataset_file"):
                dataset_id = await _stream_part_to_dataset(request, part)
                record = await rt.state.get_dataset(dataset_id)
                return web.json_response(record.model_dump(mode="json"), status=201)
        return _json_error(400, "multipart field 'file' is required")
    body = await _json_body(request)
    url = body.get("url")
    if not url:
        return _json_error(400, "provide a multipart file or a JSON body with 'url'")
    record = await stream_dataset_url(
        rt.store, rt.state,
        user_id=user.user_id, url=url, bucket=rt.settings.datasets_bucket,
    )
    return web.json_response(record.model_dump(mode="json"), status=201)


async def list_datasets(request: web.Request) -> web.Response:
    rt = request.app[RUNTIME_KEY]
    user = _user(request)
    records = await rt.state.get_user_datasets(user.user_id)
    return web.json_response(
        {"datasets": [r.model_dump(mode="json") for r in records]}
    )


async def get_dataset(request: web.Request) -> web.Response:
    rt = request.app[RUNTIME_KEY]
    user = _user(request)
    record = await rt.state.get_dataset(request.match_info["dataset_id"])
    if record is None or (record.user_id != user.user_id and not user.is_admin):
        return _json_error(404, "dataset not found")
    out = record.model_dump(mode="json")
    out["download_url"] = _signed_download_url(rt, record.uri)
    return web.json_response(out)


async def delete_dataset(request: web.Request) -> web.Response:
    rt = request.app[RUNTIME_KEY]
    user = _user(request)
    record = await rt.state.get_dataset(request.match_info["dataset_id"])
    if record is None or (record.user_id != user.user_id and not user.is_admin):
        return _json_error(404, "dataset not found")
    await rt.store.delete_prefix(record.uri.rsplit("/", 1)[0])
    await rt.state.delete_dataset(record.dataset_id)
    return web.json_response({"message": "dataset deleted"})


# ---------------------------------------------------------------------------
# Handlers — WebSocket log streaming (reference: app/main.py:340-366, §3.3)
# ---------------------------------------------------------------------------


async def stream_logs_ws(request: web.Request) -> web.WebSocketResponse:
    rt = request.app[RUNTIME_KEY]
    job_id = request.match_info["job_id"]
    # ownership check before accepting (the reference checks inside the
    # manager via DB reads; checking here fails fast)
    await _owned_job(request, job_id)
    q = request.query
    # validate query params BEFORE hijacking the connection — a 400 must go
    # out as HTTP, not onto a prepared WebSocket
    follow = q.get("follow", "true").lower() != "false"
    last_lines = _int_param(q, "last_lines", 0) or None
    search_string = q.get("search_string", rt.settings.log_stream_search_string)
    ws = web.WebSocketResponse(heartbeat=30)
    await ws.prepare(request)
    manager = LogStreamManager(
        ws, job_id, rt.state, rt.backend,
        follow=follow,
        last_lines=last_lines,
        search_string=search_string,
        start_timeout_s=rt.settings.log_stream_start_timeout_s,
    )
    try:
        await manager.run()
    finally:
        await ws.close()
    return ws


async def get_job_logs(request: web.Request) -> web.Response:
    """REST log read (reference admin pod-log route ``app/main.py:1214-1252``)."""
    rt = request.app[RUNTIME_KEY]
    job = await _owned_job(request, request.match_info["job_id"])
    last = _int_param(request.query, "last_lines", 0) or None
    try:
        lines_iter = await rt.backend.read_logs(
            job.job_id, follow=False, last_lines=last
        )
        lines = [line async for line in lines_iter]
    except Exception:
        # substrate cleaned up: serve the archived copy from the artifacts
        # (capability the reference lacks — pod logs die with the pods)
        logger.debug("live log read failed for %s; trying archived copy",
                     job.job_id, exc_info=True)
        archived = f"{job.artifacts_uri}/logs.txt" if job.artifacts_uri else None
        if not archived or not await rt.store.exists(archived):
            return _json_error(404, "logs unavailable")
        text = (await rt.store.get_bytes(archived)).decode(errors="replace")
        lines = text.splitlines()
        if last:
            lines = lines[-last:]
    return web.json_response({"job_id": job.job_id, "lines": lines})


# ---------------------------------------------------------------------------
# Handlers — admin (reference: app/main.py:1099-1297)
# ---------------------------------------------------------------------------


def _admin(request: web.Request):
    user = _user(request)
    if not user.is_admin:
        raise web.HTTPForbidden(
            text=json.dumps({"detail": "admin only"}), content_type="application/json"
        )
    return user


async def admin_jobs(request: web.Request) -> web.Response:
    """All users' jobs (reference: admin job table, ``app/main.py:1099-1150``)."""
    rt = request.app[RUNTIME_KEY]
    _admin(request)
    q = request.query
    page = await rt.state.get_user_jobs(
        None,
        page=_int_param(q, "page", 1),
        page_size=min(_int_param(q, "page_size", 20), 100),
        status=_status_param(q),
        search=q.get("search"),
    )
    return web.json_response(page.model_dump(mode="json"))


async def admin_queue(request: web.Request) -> web.Response:
    """Queue order + quota usage (reference: Kueue introspection,
    ``app/utils/kueue_helpers.py``)."""
    rt = request.app[RUNTIME_KEY]
    _admin(request)
    pending = await rt.backend.queue_snapshot()
    usage = None
    scheduler = getattr(rt.backend, "scheduler", None)
    if scheduler is not None:
        usage = scheduler.usage()
    return web.json_response({"pending": pending, "usage": usage})


async def admin_job_events(request: web.Request) -> web.Response:
    """Pod-events debug digest (reference: ``app/main.py:1214-1252``,
    ``kube_helpers.py:26-95``)."""
    rt = request.app[RUNTIME_KEY]
    _admin(request)
    events = await rt.backend.job_events(request.match_info["job_id"])
    return web.json_response({"events": events})


async def admin_scheduler(request: web.Request) -> web.Response:
    """Fair-share scheduler introspection (docs/scheduling.md): per-queue
    usage, weighted shares, borrowed chips, pending positions, preemption
    counters — the tenant view ``ftc-ctl queue`` renders."""
    rt = request.app[RUNTIME_KEY]
    _admin(request)
    scheduler = getattr(rt.backend, "scheduler", None)
    if scheduler is None:
        return web.json_response({"policy": None, "queues": {}, "flavors": {}})
    snapshot = getattr(scheduler, "snapshot", None)
    if snapshot is None:
        # the FIFO escape hatch has no tenant view; serve what it knows
        return web.json_response({
            "policy": "fifo", "queues": {}, "flavors": scheduler.usage(),
            "pending": scheduler.pending(),
        })
    return web.json_response(snapshot())


async def admin_backend_jobs(request: web.Request) -> web.Response:
    rt = request.app[RUNTIME_KEY]
    _admin(request)
    reports = await rt.backend.list_jobs()
    return web.json_response(
        {"jobs": [r.model_dump(mode="json") for r in reports]}
    )


async def admin_resilience(request: web.Request) -> web.Response:
    """Retry-supervisor + liveness-lease state (docs/resilience.md): the
    active policy, jobs waiting out a backoff, and lease-kill counters."""
    rt = request.app[RUNTIME_KEY]
    _admin(request)
    supervisor = rt.monitor.supervisor
    lease = rt.monitor.lease
    body: dict[str, Any] = {
        "enabled": supervisor is not None,
        "lease_enabled": lease is not None,
        "lease_kills": rt.monitor.lease_kills,
    }
    if supervisor is not None:
        body["policy"] = {
            "max_attempts": supervisor.policy.max_attempts,
            "base_delay_s": supervisor.policy.base_delay_s,
            "max_delay_s": supervisor.policy.max_delay_s,
        }
        body["counters"] = {
            "retries_scheduled": supervisor.retries_scheduled,
            "resubmits": supervisor.resubmits,
            "terminal_failures": supervisor.terminal_failures,
            # elasticity (docs/elasticity.md)
            "resizes": supervisor.resizes,
            "elastic_restores": supervisor.elastic_restores,
            "topology_downgrades": supervisor.topology_downgrades,
        }
        body["pending_retries"] = await supervisor.pending_retries()
    if lease is not None:
        body["lease_s"] = lease.lease_s
    # per-job progress (docs/observability.md): each RUNNING job's newest
    # heartbeat now carries last_step/last_step_ms — rate, not just liveness
    from ..resilience.heartbeat import HEARTBEAT_FILENAME, parse_heartbeat

    async def _job_progress(job) -> dict[str, Any] | None:
        uri = f"{job.artifacts_uri}/{HEARTBEAT_FILENAME}"
        try:
            if not await rt.store.exists(uri):
                return None
            hb = parse_heartbeat(await rt.store.get_bytes(uri))
        except Exception:
            logger.debug("heartbeat read failed for %s", job.job_id,
                         exc_info=True)
            return None
        if hb is None:
            return None
        step_ms = hb.get("last_step_ms")
        return {
            "job_id": job.job_id,
            "last_step": hb.get("last_step", hb.get("step")),
            "last_step_ms": step_ms,
            "steps_per_min": (
                round(60000.0 / step_ms, 2) if step_ms else None
            ),
            "heartbeat_age_s": round(max(time.time() - hb["ts"], 0.0), 1),
        }

    # the per-job reads are independent remote round-trips — run them
    # concurrently so the endpoint costs the slowest read, not the sum
    running = [
        job for job in await rt.state.get_jobs_by_status(DatabaseStatus.RUNNING)
        if job.artifacts_uri
    ]
    body["progress"] = [
        p for p in await asyncio.gather(*(_job_progress(j) for j in running))
        if p is not None
    ]
    return web.json_response(body)


# ---------------------------------------------------------------------------
# Handlers — auth + observability
# ---------------------------------------------------------------------------


async def mint_dev_token(request: web.Request) -> web.Response:
    """Dev-mode token mint (reference: ``dev_generate_token``,
    ``app/core/security.py:347-389``); disabled in production."""
    rt = request.app[RUNTIME_KEY]
    # the mint route is reachable unauthenticated, so it must only exist in
    # the local env — in any deployed environment an open mint + the HS256
    # verify fallback would hand out admin tokens to anyone
    if rt.settings.environment != "local":
        return _json_error(403, "dev tokens are only available in the local environment")
    body = await _json_body(request)
    token = dev_generate_token(
        body.get("user_id", "dev-user"),
        rt.settings.jwt_secret,
        scopes=body.get("scopes"),
        is_admin=bool(body.get("is_admin", False)),
        email=body.get("email", ""),
    )
    return web.json_response({"access_token": token, "token_type": "bearer"})


#: the Prometheus text exposition content type (version 0.0.4) — scrapers
#: key parsing off it; a bare text/plain is accepted but ambiguous
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# one escaping implementation for the whole /metrics payload: a rule added
# to one copy but not another would render the same label value differently
# between the gauge and histogram sections, forking series identity
prom_escape = escape_label


async def prometheus_metrics(request: web.Request) -> web.Response:
    """Controller self-metrics in Prometheus text format — a gap in the
    reference (SURVEY.md §5.5: 'No Prometheus/metrics endpoint')."""
    rt = request.app[RUNTIME_KEY]
    lines = [
        "# TYPE ftc_monitor_ticks_total counter",
        f"ftc_monitor_ticks_total {rt.monitor.ticks}",
    ]
    counts: dict[str, int] = {}
    active_jobs = await rt.state.get_active_jobs()
    for job in active_jobs:
        counts[job.status.value] = counts.get(job.status.value, 0) + 1
    lines.append("# TYPE ftc_jobs_active gauge")
    for status, n in sorted(counts.items()):
        lines.append(f'ftc_jobs_active{{status="{prom_escape(status)}"}} {n}')
    scheduler = getattr(rt.backend, "scheduler", None)
    if scheduler is not None:
        lines.append("# TYPE ftc_quota_chips gauge")
        for flavor, u in scheduler.usage().items():
            f = prom_escape(flavor)
            lines.append(
                f'ftc_quota_chips{{flavor="{f}",kind="used"}} {u["used_chips"]}'
            )
            lines.append(
                f'ftc_quota_chips{{flavor="{f}",kind="nominal"}} {u["nominal_chips"]}'
            )
    if scheduler is not None and hasattr(scheduler, "snapshot"):
        # fair-share tenant gauges (docs/scheduling.md)
        snap = scheduler.snapshot()
        sched_gauges = (
            ("ftc_sched_queue_depth", "gauge", "depth"),
            ("ftc_sched_queue_running", "gauge", "running"),
            ("ftc_sched_queue_used_chips", "gauge", "used_chips_total"),
            ("ftc_sched_queue_dominant_share", "gauge", "dominant_share"),
            ("ftc_sched_queue_borrowed_chips", "gauge", "borrowed_chips"),
            ("ftc_sched_queue_preemptions_total", "counter", "preemptions"),
            ("ftc_sched_queue_resizes_total", "counter", "resizes"),
        )
        for metric, kind, stat_key in sched_gauges:
            lines.append(f"# TYPE {metric} {kind}")
            for qname, q in sorted(snap["queues"].items()):
                lines.append(
                    f'{metric}{{queue="{prom_escape(qname)}"}} '
                    f"{q.get(stat_key, 0)}"
                )
        lines.append("# TYPE ftc_sched_preemptions_total counter")
        lines.append(f"ftc_sched_preemptions_total {snap['preemptions_total']}")
        # resize-instead-of-evict (docs/elasticity.md)
        lines.append("# TYPE ftc_sched_resizes_total counter")
        lines.append(f"ftc_sched_resizes_total {snap.get('resizes_total', 0)}")
        lines.append("# TYPE ftc_sched_shrunk_workloads gauge")
        lines.append(
            f"ftc_sched_shrunk_workloads {len(snap.get('shrunk_workloads') or {})}"
        )
    supervisor = rt.monitor.supervisor
    if supervisor is not None:
        # cross-topology restores executed by the retry loop
        lines.append("# TYPE ftc_elastic_restores_total counter")
        lines.append(
            f"ftc_elastic_restores_total {supervisor.elastic_restores}"
        )
        lines.append("# TYPE ftc_topology_downgrades_total counter")
        lines.append(
            f"ftc_topology_downgrades_total {supervisor.topology_downgrades}"
        )
    # runtime shard audit (analysis/shard_audit.py): process-wide counters
    # from the rule-table sharding trap at checkpoint/restore/serve-load
    # boundaries — violations > 0 means some state tree lost its sharding
    from ..analysis.shard_audit import metrics_snapshot as shard_audit_snapshot

    ssnap = shard_audit_snapshot()
    for metric, key in (
        ("ftc_shard_audit_checks_total", "checks_total"),
        ("ftc_shard_audit_violations_total", "violations_total"),
    ):
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric} {ssnap.get(key, 0)}")
    if rt.serve is not None:
        sessions = rt.serve.stats()
        serve_gauges = (
            ("ftc_serve_queue_depth", "gauge", "queue_depth"),
            ("ftc_serve_slots_busy", "gauge", "slots_busy"),
            ("ftc_serve_slots_total", "gauge", "slots_total"),
            ("ftc_serve_tokens_generated_total", "counter",
             "tokens_generated_total"),
            ("ftc_serve_requests_completed_total", "counter",
             "requests_completed_total"),
            ("ftc_serve_requests_rejected_total", "counter",
             "requests_rejected_total"),
            ("ftc_serve_decode_steps_total", "counter", "steps_total"),
            ("ftc_serve_compilations", "gauge", "compilations"),
            # prefix-reuse KV cache (docs/serving.md)
            ("ftc_serve_prefix_hits_total", "counter", "prefix_hits_total"),
            ("ftc_serve_prefix_misses_total", "counter",
             "prefix_misses_total"),
            ("ftc_serve_prefill_tokens_saved_total", "counter",
             "prefill_tokens_saved_total"),
            ("ftc_serve_prefix_cache_bytes", "gauge", "prefix_cache_bytes"),
            # replica fleet + router (docs/serving.md §Fleet)
            ("ftc_serve_replica_total", "gauge", "replicas_total"),
            ("ftc_serve_replica_healthy", "gauge", "replicas_healthy"),
            ("ftc_serve_replica_draining", "gauge", "replicas_draining"),
            ("ftc_serve_replica_generation", "gauge", "generation"),
            ("ftc_serve_replica_restarts_total", "counter",
             "replica_restarts_total"),
            ("ftc_serve_replica_failed_total", "counter",
             "replicas_failed_total"),
            ("ftc_serve_drains_total", "counter", "drains_total"),
            ("ftc_serve_rollovers_total", "counter", "rollovers_total"),
            ("ftc_serve_failovers_total", "counter", "failovers_total"),
            ("ftc_serve_duplicates_suppressed_total", "counter",
             "duplicates_suppressed_total"),
            ("ftc_serve_shed_total", "counter", "shed_total"),
            ("ftc_serve_step_errors_total", "counter", "step_errors_total"),
            # paged KV pool (docs/serving.md §Paged KV) — zeros when unpaged
            ("ftc_serve_kv_pages_total", "gauge", "kv_pages_total"),
            ("ftc_serve_kv_pages_free", "gauge", "kv_pages_free"),
            ("ftc_serve_kv_pages_used", "gauge", "kv_pages_used"),
            ("ftc_serve_kv_pages_shared", "gauge", "kv_pages_shared"),
            ("ftc_serve_kv_cow_copies_total", "counter",
             "kv_cow_copies_total"),
            ("ftc_serve_kv_pool_exhaustions_total", "counter",
             "kv_pool_exhaustions_total"),
            # host KV tier (docs/serving.md §KV tiering) — zeros when off
            ("ftc_serve_kv_tier_host_pages_total", "gauge",
             "kv_tier_host_pages_total"),
            ("ftc_serve_kv_tier_host_pages_used", "gauge",
             "kv_tier_host_pages_used"),
            ("ftc_serve_kv_tier_host_bytes", "gauge", "kv_tier_host_bytes"),
            ("ftc_serve_kv_demotions_total", "counter", "kv_demotions_total"),
            ("ftc_serve_kv_restores_total", "counter", "kv_restores_total"),
            # multi-tenant adapters (docs/serving.md §Multi-tenant adapters)
            ("ftc_serve_adapters_loaded", "gauge", "adapters_loaded"),
        )
        lines.append("# TYPE ftc_serve_models_loaded gauge")
        lines.append(f"ftc_serve_models_loaded {len(sessions)}")
        for metric, kind, stat_key in serve_gauges:
            lines.append(f"# TYPE {metric} {kind}")
            for job_id, stats in sorted(sessions.items()):
                lines.append(
                    f'{metric}{{job_id="{prom_escape(job_id)}"}} '
                    f"{stats.get(stat_key, 0)}"
                )
        # per-tenant series — bounded cardinality: loaded adapters only
        # ("" = the base model, labeled "base")
        tenant_gauges = (
            ("ftc_serve_tenant_tokens_total", "counter", "tokens_by_tenant"),
            ("ftc_serve_tenant_lanes", "gauge", "lanes_by_tenant"),
            ("ftc_serve_tenant_queue_depth", "gauge",
             "queue_depth_by_tenant"),
        )
        for metric, kind, stat_key in tenant_gauges:
            series = [
                (job_id, tenant, value)
                for job_id, stats in sorted(sessions.items())
                for tenant, value in sorted(
                    (stats.get(stat_key) or {}).items())
            ]
            if not series:
                continue
            lines.append(f"# TYPE {metric} {kind}")
            for job_id, tenant, value in series:
                lines.append(
                    f'{metric}{{job_id="{prom_escape(job_id)}",'
                    f'adapter="{prom_escape(tenant or "base")}"}} {value}'
                )
        # cross-process transport (docs/serving.md §Cross-process
        # transport): process-wide RPC/byte/respawn counters shared by
        # every process-mode fleet in this control plane
        from ..transport import metrics_snapshot as transport_snapshot

        tsnap = transport_snapshot()
        for metric, key in (
            ("ftc_serve_transport_rpcs_total", "rpcs_total"),
            ("ftc_serve_transport_rpc_errors_total", "rpc_errors_total"),
            ("ftc_serve_transport_worker_respawns_total",
             "worker_respawns_total"),
            ("ftc_serve_transport_bytes_total", "bytes_total"),
        ):
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {tsnap.get(key, 0)}")
    # preference-optimization gauges (docs/preference.md): surfaced from the
    # newest synced metrics row of every ACTIVE dpo/rlhf job — reward margin
    # is the number a healthy DPO run drives up, and the rollout triple
    # (buffer depth, staleness, actor tok/s) is the actor/learner loop's
    # health check.  Bounded cardinality: active preference jobs only.
    dpo_jobs = [
        j for j in active_jobs
        if (j.metadata or {}).get("task") in ("dpo", "rlhf", "reward")
    ]
    if dpo_jobs:
        dpo_gauges = (
            ("ftc_dpo_reward_margin", "reward_margin"),
            ("ftc_dpo_accuracy", "dpo_accuracy"),
            ("ftc_dpo_rollout_buffer_depth", "rollout_buffer_depth"),
            ("ftc_dpo_rollout_staleness", "rollout_staleness"),
            ("ftc_dpo_actor_tokens_per_sec", "actor_tokens_per_sec"),
            # disaggregated data plane (docs/preference.md §Disaggregated
            # rollouts): remote-actor fleet health, absent on in-process
            # rlhf rows and skipped by the column guard below
            ("ftc_rollout_workers_alive", "rollout_workers_alive"),
            ("ftc_rollout_respawns_total", "rollout_respawns_total"),
            ("ftc_rollout_dup_pairs_total", "rollout_dup_pairs_total"),
            ("ftc_rollout_actor_version", "actor_version"),
        )
        rows: dict[str, dict] = {}
        for job in dpo_jobs:
            doc = await rt.state.get_metrics(job.job_id)
            if doc is not None and doc.records:
                rows[job.job_id] = doc.records[-1]
        for metric, column in dpo_gauges:
            samples = []
            for job_id, row in sorted(rows.items()):
                try:
                    value = float(row.get(column, ""))
                except (TypeError, ValueError):
                    continue  # column absent (e.g. rollout_* on a plain DPO job)
                samples.append(
                    f'{metric}{{job_id="{prom_escape(job_id)}"}} {value:g}'
                )
            if samples:
                lines.append(f"# TYPE {metric} gauge")
                lines.extend(samples)
    # observability layer (docs/observability.md): latency histograms (step
    # phases, queue wait, retry latency, serve TTFT) + process identity
    obs = getattr(rt, "obs", None)
    if obs is not None:
        from .. import __version__

        lines.extend(obs.render())
        lines.extend(obs.render_process_info(
            process=request.app.get(PROCESS_KEY) or "server",
            version=__version__,
            backend=rt.settings.backend,
        ))
    return web.Response(
        body=("\n".join(lines) + "\n").encode("utf-8"),
        headers={"Content-Type": PROMETHEUS_CONTENT_TYPE},
    )


def _openapi_schema(app: web.Application, settings: Settings) -> dict[str, Any]:
    """Minimal OpenAPI doc with BearerAuth on every API path (reference:
    ``custom_openapi_jwt_auth``, ``app/api/custom_openapi.py:6-31``)."""
    paths: dict[str, Any] = {}
    for route in app.router.routes():
        info = route.resource.get_info() if route.resource else {}
        path = info.get("path") or info.get("formatter")
        if not path or not path.startswith(settings.api_prefix):
            continue
        method = route.method.lower()
        if method in ("head", "options", "*"):
            continue
        entry = paths.setdefault(path, {})
        entry[method] = {
            "summary": (route.handler.__doc__ or "").strip().split("\n")[0],
            "security": [{"BearerAuth": []}],
            "responses": {"200": {"description": "OK"}},
        }
    return {
        "openapi": "3.1.0",
        "info": {"title": "finetune-controller-tpu", "version": "0.1.0"},
        "paths": paths,
        "components": {
            "securitySchemes": {
                "BearerAuth": {"type": "http", "scheme": "bearer", "bearerFormat": "JWT"}
            }
        },
    }


async def openapi_json(request: web.Request) -> web.Response:
    rt = request.app[RUNTIME_KEY]
    return web.json_response(_openapi_schema(request.app, rt.settings))


# ---------------------------------------------------------------------------
# App assembly (reference: setup_middleware app/api/middleware.py:59-66 +
# lifespan app/main.py:78-105)
# ---------------------------------------------------------------------------


def build_app(runtime: Runtime, *, with_monitor: bool | None = None) -> web.Application:
    settings = runtime.settings
    # The default jwt_secret is a PUBLIC string: with auth enabled and no
    # real validation source configured, anyone could forge admin tokens.
    # Refuse to start outside the local environment; warn inside it.
    # (reference warns when prod auth is unconfigured, middleware.py:28-30)
    secret_unset = settings.jwt_secret in ("", DEFAULT_JWT_SECRET)
    real_source = bool(settings.introspection_url or settings.jwks_url)
    if settings.auth_enabled and secret_unset and not real_source:
        msg = (
            "auth_enabled=True but no introspection URL, no JWKS URL, and the "
            "JWT secret is the well-known default — tokens would be forgeable"
        )
        if settings.environment != "local":
            raise RuntimeError(msg)
        logger.warning("%s (allowed only because environment=local)", msg)
    # With a real validation source configured, the well-known default secret
    # must not remain a valid HS256 fallback — neutralise it so only the real
    # source can authenticate tokens.
    effective_secret = "" if (secret_unset and real_source) else settings.jwt_secret
    validator = TokenValidator(
        jwt_secret=effective_secret,
        introspection_url=settings.introspection_url,
        introspection_client_id=settings.introspection_client_id,
        introspection_client_secret=settings.introspection_client_secret,
        jwks_url=settings.jwks_url,
        audience=settings.jwt_audience,
    )
    app = web.Application(
        middlewares=[
            build_cors_middleware(settings.cors_origins),
            error_middleware,
            build_auth_middleware(
                validator,
                enabled=settings.auth_enabled,
                api_prefix=settings.api_prefix,
            ),
        ],
        client_max_size=1 << 30,  # dataset uploads
    )
    app[RUNTIME_KEY] = runtime
    app[PROMOTION_KEY] = PromotionTask(runtime.state, runtime.store)
    app[LIMITER_KEY] = RateLimiter(
        runtime.state,
        {
            "submit": settings.rate_limit_submit_per_min,
            "read": settings.rate_limit_read_per_min,
            "promote": settings.rate_limit_promote_per_min,
            "generate": settings.rate_limit_generate_per_min,
        },
    )
    app[BG_TASKS_KEY] = set()
    app[PROCESS_KEY] = "server"
    # observability hub (docs/observability.md): runtimes assembled outside
    # build_runtime (tests) get one here, and components constructed without
    # one adopt it so their observations reach /metrics
    if getattr(runtime, "obs", None) is None:
        runtime.obs = ObsHub()
    if runtime.monitor is not None and getattr(runtime.monitor, "obs", None) is None:
        runtime.monitor.obs = runtime.obs
    supervisor = getattr(runtime.monitor, "supervisor", None)
    if supervisor is not None and getattr(supervisor, "obs", None) is None:
        supervisor.obs = runtime.obs
    # inference over promoted checkpoints (serve/service.py); runtimes built
    # outside build_runtime (tests) get a manager here so the routes work
    from ..serve.service import SERVE_KEY, ServeManager, add_serve_routes

    if runtime.serve is None:
        runtime.serve = ServeManager(
            runtime.state, runtime.store, settings, obs=runtime.obs,
            backend=runtime.backend,
        )
    elif getattr(runtime.serve, "obs", None) is None:
        runtime.serve.obs = runtime.obs
    app[SERVE_KEY] = runtime.serve

    p = settings.api_prefix
    app.router.add_get(f"{p}/health", health)
    app.router.add_get(f"{p}/models", list_models)
    app.router.add_get(f"{p}/models/{{model_name}}/schema", model_schema)
    app.router.add_post(f"{p}/jobs", start_job)
    app.router.add_get(f"{p}/jobs", get_jobs_page)
    app.router.add_get(f"{p}/jobs/{{job_id}}", get_job)
    app.router.add_get(f"{p}/jobs/{{job_id}}/metrics", get_job_metrics)
    app.router.add_get(f"{p}/jobs/{{job_id}}/timeline", get_job_timeline)
    app.router.add_get(f"{p}/jobs/{{job_id}}/trace", get_job_trace)
    app.router.add_post(f"{p}/jobs/{{job_id}}/profile", request_job_profile)
    app.router.add_get(f"{p}/jobs/{{job_id}}/artifacts", get_job_artifacts)
    app.router.add_get(f"{p}/jobs/{{job_id}}/logs", get_job_logs)
    app.router.add_post(f"{p}/jobs/{{job_id}}/promote", promote_job)
    app.router.add_post(f"{p}/jobs/{{job_id}}/unpromote", unpromote_job)
    app.router.add_post(f"{p}/jobs/{{job_id}}/cancel", cancel_job)
    app.router.add_delete(f"{p}/jobs/{{job_id}}", delete_job)
    app.router.add_get(f"{p}/logs/{{job_id}}", stream_logs_ws)  # WS
    app.router.add_post(f"{p}/datasets", upload_dataset)
    app.router.add_get(f"{p}/datasets", list_datasets)
    app.router.add_get(f"{p}/datasets/{{dataset_id}}", get_dataset)
    app.router.add_delete(f"{p}/datasets/{{dataset_id}}", delete_dataset)
    app.router.add_get(f"{p}/download", download)
    app.router.add_get(f"{p}/admin/jobs", admin_jobs)
    app.router.add_get(f"{p}/admin/queue", admin_queue)
    app.router.add_get(f"{p}/admin/scheduler", admin_scheduler)
    app.router.add_get(f"{p}/admin/jobs/{{job_id}}/events", admin_job_events)
    app.router.add_get(f"{p}/admin/backend/jobs", admin_backend_jobs)
    app.router.add_get(f"{p}/admin/resilience", admin_resilience)
    app.router.add_post(f"{p}/auth/dev-token", mint_dev_token)
    app.router.add_get(f"{p}/openapi.json", openapi_json)
    app.router.add_get("/metrics", prometheus_metrics)
    add_serve_routes(app, p)

    async def on_startup(app: web.Application) -> None:
        await runtime.start(with_monitor=with_monitor)
        # crash recovery: promotions interrupted by a previous shutdown
        await app[PROMOTION_KEY].recover_interrupted()
        logger.info(
            "control plane up: backend=%s monitor_in_process=%s",
            settings.backend,
            settings.monitor_in_process if with_monitor is None else with_monitor,
        )

    async def on_cleanup(app: web.Application) -> None:
        for task in list(app[BG_TASKS_KEY]):
            task.cancel()
        await runtime.close()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    return app


def main(argv: list[str] | None = None) -> int:
    """``python -m finetune_controller_tpu.controller.server --port 8787``
    (reference: ``uvicorn app.main:app``, ``Dockerfile:28``).

    ``--workers N`` serves from N processes sharing the port via
    ``SO_REUSEPORT`` — the reference's ``uvicorn --workers 4``.  Requires the
    k8s backend (stateless against the apiserver; job/dataset state shared
    through the sqlite WAL store, which is multi-process-safe on one host).
    The local fake-cluster backend holds per-process job handles, so it
    refuses to fan out.  The monitor runs in worker 0 only.
    """
    import argparse
    import os
    import signal

    from .config import get_settings
    from .logging_config import setup_logging

    parser = argparse.ArgumentParser(prog="ftc-serve")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8787)
    parser.add_argument("--plugin-dir", default=None, help="model plugin directory")
    parser.add_argument("--workers", type=int, default=1,
                        help="server processes sharing the port (k8s backend only)")
    args = parser.parse_args(argv)
    setup_logging()

    workers = max(1, args.workers)
    settings = get_settings()
    if workers > 1 and settings.backend == "local":
        parser.error(
            "--workers > 1 requires FTC_BACKEND=k8s: the local backend's "
            "job handles live in one process"
        )
    if workers > 1 and settings.state_backend != "sqlite":
        parser.error("--workers > 1 requires FTC_STATE_BACKEND=sqlite")

    worker_idx, children = 0, []
    for i in range(1, workers):
        pid = os.fork()
        if pid == 0:
            worker_idx, children = i, []
            break
        children.append(pid)

    if children:
        # reap + log dead workers so an OOM-killed child is neither a silent
        # capacity loss nor a zombie for the parent's lifetime
        def _reap(signum, frame):
            while True:
                try:
                    pid, status = os.waitpid(-1, os.WNOHANG)
                except ChildProcessError:
                    return
                if pid == 0:
                    return
                if pid in children:
                    children.remove(pid)
                    logger.error(
                        "worker %d died (status %d): serving capacity reduced",
                        pid, status,
                    )

        signal.signal(signal.SIGCHLD, _reap)

    try:
        # each worker builds its own runtime AFTER the fork (no shared
        # fds/locks); the try covers the build too — a parent-side build
        # failure must not orphan already-forked children on the port
        runtime = build_runtime(plugin_dir=args.plugin_dir)
        # monitor in worker 0 only — and only if the operator wants an
        # in-process monitor at all (a separate monitor deployment sets it
        # false)
        with_monitor = (
            None if workers == 1
            else (worker_idx == 0 and settings.monitor_in_process)
        )
        app = build_app(runtime, with_monitor=with_monitor)
        web.run_app(
            app, host=args.host, port=args.port, reuse_port=workers > 1
        )
    finally:
        if children:
            signal.signal(signal.SIGCHLD, signal.SIG_DFL)
        for pid in list(children):
            try:
                os.kill(pid, signal.SIGTERM)
            except ProcessLookupError:
                continue
        deadline = time.monotonic() + 10
        for pid in list(children):
            try:
                while time.monotonic() < deadline:
                    done, _ = os.waitpid(pid, os.WNOHANG)
                    if done:
                        break
                    time.sleep(0.1)
                else:
                    os.kill(pid, signal.SIGKILL)
                    os.waitpid(pid, 0)
            except (ChildProcessError, ProcessLookupError):
                continue  # already reaped by the SIGCHLD handler
    return 0


if __name__ == "__main__":
    # `python -m ...controller.server` loads this file as `__main__`, a
    # SECOND module instance with its own AppKey objects. Handlers that
    # import the module by its canonical name (serve/service.py) would then
    # look up different keys than build_app stored and 500. Delegate to the
    # canonical instance so there is exactly one set of keys.
    from finetune_controller_tpu.controller.server import main as _main

    raise SystemExit(_main())
