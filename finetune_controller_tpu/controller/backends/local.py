"""Local process backend — the in-repo fake cluster.

Runs each job as a ``python -m finetune_controller_tpu.train.cli`` subprocess
in a sandbox directory, reproducing the full pod lifecycle the reference gets
from Kubernetes (SURVEY.md §3.1 post-admission flow):

- **init container** (``aws s3 cp`` dataset download,
  ``PyTorchJobDeployer.py:70-91``) → async dataset staging from the object
  store into the sandbox before launch;
- **suspend-until-admitted** (Kueue, ``PyTorchJobDeployer.py:179-185``) → the
  in-repo :class:`~.scheduler.GangScheduler`;
- **artifact sidecar** (``aws s3 sync`` loop every 60 s, exit on ``done.txt``,
  ``PyTorchJobDeployer.py:121-168``) → an asyncio sync task copying
  ``store_asset_patterns`` matches to the object store;
- **restartPolicy OnFailure + backoffLimit 2** (``PyTorchJobDeployer.py:183,189``)
  → bounded restart loop with a ``Restarting`` state;
- **pod logs** (``stream_logger.py:204-284``) → a log file per job, tailed by
  :meth:`read_logs`;
- **pod events** (``kube_helpers.py:26-95``) → per-job event list.

It also carries what the reference lacks: deterministic fault injection for
elastic-recovery tests (SURVEY.md §5.3 gap).
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import json
import logging
import os
import shlex
import sys
import time
from pathlib import Path
from typing import Any, AsyncIterator

from ...sched import FairShareScheduler
from ..devices import DeviceCatalog, DeviceFlavor, default_mesh_for
from ..objectstore import ObjectStore
from ..schemas import BackendJobReport, BackendJobState, JobInput
from ..specs import BaseFineTuneJob
from ..syncer import sync_dir_to_store
from .base import BackendError, TrainingBackend
from .scheduler import GangScheduler

logger = logging.getLogger(__name__)

#: per-job trace identity (docs/observability.md) — scrubbed from warm-pool
#: spawn env and (re)injected per claim via the request line, so a pooled
#: worker never carries another job's trace
_OBS_ENV_KEYS = ("FTC_TRACE_ID", "FTC_ATTEMPT")


class _JobHandle:
    """Mutable per-job state (the backend's 'pod')."""

    def __init__(self, job_id: str, sandbox: Path, artifacts_uri: str, patterns: list[str]):
        self.job_id = job_id
        self.sandbox = sandbox
        self.artifacts_dir = sandbox / "artifacts"
        self.logs_path = sandbox / "logs.txt"
        self.spec_path = sandbox / "job.json"
        self.artifacts_uri = artifacts_uri
        self.patterns = patterns
        self.state = BackendJobState.PENDING
        self.message = ""
        self.proc: asyncio.subprocess.Process | None = None
        self.run_task: asyncio.Task | None = None
        self.sync_task: asyncio.Task | None = None
        self.restarts = 0
        #: tenant queue + priority (sched/), echoed into reports/metadata
        self.queue = "default"
        self.priority: object = "normal"
        #: scheduler evicted this job: the run loop must NOT burn local
        #: restarts — it reports FAILED (exit 143) so the resilience
        #: supervisor requeues it with resume (docs/scheduling.md)
        self.preempted = False
        self.preempted_by = ""
        #: scheduler resize (docs/elasticity.md): the supervisor resubmits
        #: the job at this slice count instead of its current topology
        self.resize_to: int | None = None
        self.resize_kind = ""  # "shrink" | "grow" ("" = plain eviction)
        #: topology bookkeeping for elastic admission / resize re-renders
        self.requested_slices = 1
        self.granted_slices = 1
        #: trace propagation (docs/observability.md): threaded into the
        #: trainer env as FTC_TRACE_ID / FTC_ATTEMPT on every (re)render
        self.trace_id = ""
        self.attempt = 1
        self.spec_obj: BaseFineTuneJob | None = None
        self.flavor_obj: DeviceFlavor | None = None
        self.dataset_path: str | None = None
        self.exit_code: int | None = None  # last attempt's exit code
        self.restored_checkpoints = 0  # files staged back from the store
        self.start_time: float | None = None
        self.completion_time: float | None = None
        self.events: list[dict[str, Any]] = []
        self.env: dict[str, str] = {}
        self.fault_kill_at_step: int | None = None
        self.cancelled = False
        #: path -> (mtime, size) at last successful upload (sync change detection)
        self.synced: dict[str, tuple[float, int]] = {}

    def event(self, reason: str, message: str = "") -> None:
        self.events.append({"ts": time.time(), "reason": reason, "message": message})

    def set_state(self, state: BackendJobState, message: str = "") -> None:
        if state is not self.state:
            self.event("StateChange", f"{self.state.value} -> {state.value}")
        self.state = state
        if message:
            self.message = message


class LocalProcessBackend(TrainingBackend):
    """Fake cluster: gang-scheduled subprocesses + artifact sync sidecars."""

    #: SIGTERM → SIGKILL escalation grace in :meth:`delete_job`
    term_grace_s: float = 5.0

    def __init__(
        self,
        root_dir: Path | str,
        object_store: ObjectStore,
        catalog: DeviceCatalog,
        *,
        sync_interval_s: float = 60.0,
        backoff_limit: int = 2,
        python: str | None = None,
        extra_env: dict[str, str] | None = None,
        warm_workers: int = 0,
        sched_policy: str = "fairshare",
        sched_queues: dict[str, float] | None = None,
        sched_resize: bool = True,
        sched_grow_delay_s: float = 60.0,
    ):
        self.root = Path(root_dir).expanduser()
        self.root.mkdir(parents=True, exist_ok=True)
        self.store = object_store
        self.catalog = catalog
        #: admission control (docs/scheduling.md): the multi-tenant
        #: fair-share scheduler by default; "fifo" is the legacy best-effort
        #: gang scheduler (no tenants, no preemption) kept as an escape hatch
        if sched_policy == "fifo":
            self.scheduler = GangScheduler(catalog)
        elif sched_policy == "fairshare":
            self.scheduler = FairShareScheduler(
                catalog, sched_queues,
                resize=sched_resize, grow_delay_s=sched_grow_delay_s,
            )
        else:
            raise ValueError(f"unknown sched_policy {sched_policy!r}")
        self.sync_interval_s = sync_interval_s
        self.backoff_limit = backoff_limit
        self.python = python or sys.executable
        self.extra_env = dict(extra_env or {})
        self._handles: dict[str, _JobHandle] = {}
        #: tombstone reports for jobs the backend lost before launch (the
        #: admitted-without-a-handle race): surfaced as FAILED so the retry
        #: supervisor classifies + resubmits instead of the DB job sitting
        #: QUEUED forever (ISSUE 5 satellite)
        self._lost: dict[str, BackendJobReport] = {}
        self._closing = False
        #: pre-warmed trainer processes (train/warm_worker.py) keyed by their
        #: platform env — they have already paid JAX import + backend init,
        #: collapsing the submit -> first-step span (BASELINE.md north-star
        #: #2). 0 disables the pool (tests keep deterministic process counts).
        self.warm_workers = warm_workers
        self._warm: dict[tuple, list[asyncio.subprocess.Process]] = {}

    # ------------------------------------------------------------------ submit

    async def submit(
        self,
        job: JobInput,
        spec: BaseFineTuneJob,
        flavor: DeviceFlavor,
        *,
        dataset_uri: str | None,
        artifacts_uri: str,
    ) -> None:
        if job.job_id in self._handles:
            raise BackendError(f"job {job.job_id!r} already exists")
        sandbox = self.root / job.job_id
        handle = _JobHandle(job.job_id, sandbox, artifacts_uri, list(spec.store_asset_patterns))
        self._handles[job.job_id] = handle
        try:
            handle.artifacts_dir.mkdir(parents=True, exist_ok=True)

            # resume staging (resilience/supervisor.py resubmit contract):
            # if a previous attempt committed checkpoints to the object store
            # and this sandbox has none, pull them back down so the trainer's
            # resume path continues the run instead of restarting it
            await self._stage_resume_state(handle)

            # init-container equivalent: stage the dataset into the sandbox
            # (reference: aws s3 cp init container, PyTorchJobDeployer.py:70-91)
            dataset_path: str | None = None
            if dataset_uri:
                local = sandbox / "dataset" / Path(dataset_uri).name
                await self.store.get_file(dataset_uri, local)  # streamed, not buffered
                dataset_path = str(local)
                handle.event("DatasetStaged", dataset_uri)

            mesh = default_mesh_for(flavor, job.num_slices, policy=spec.mesh_policy)
            trainer_spec = spec.build_trainer_spec(
                job.job_id,
                str(handle.artifacts_dir),
                dataset_path=dataset_path,
                mesh=mesh,
            )
            await asyncio.to_thread(
                handle.spec_path.write_text, json.dumps(trainer_spec, indent=2)
            )

            handle.trace_id = job.trace_id
            handle.attempt = max(1, job.attempt)
            handle.env = self._runtime_env(flavor, job.num_slices)
            handle.env.update(self._obs_env(handle))

            handle.queue = job.queue
            handle.priority = job.priority
            # elastic-admission context (docs/elasticity.md): the scheduler
            # may grant FEWER slices than asked — the spec/env must then be
            # re-rendered at the granted topology before spawn
            handle.spec_obj = spec
            handle.flavor_obj = flavor
            handle.dataset_path = dataset_path
            handle.requested_slices = job.requested_num_slices or job.num_slices
            handle.granted_slices = job.num_slices
            self.scheduler.submit(
                job.job_id, flavor.name, job.num_slices,
                queue=job.queue, priority=job.priority,
                requested_slices=handle.requested_slices,
                # an atomic gang (RLHF actor+learner) must never run
                # partially: floor every shrink at the full gang size
                min_slices=(
                    job.num_slices if getattr(spec, "atomic_gang", False)
                    else 1
                ),
            )
            self._lost.pop(job.job_id, None)  # resubmit clears any tombstone
            handle.set_state(BackendJobState.SUSPENDED)
            handle.event(
                "Queued",
                f"flavor={flavor.name} slices={job.num_slices} "
                f"queue={job.queue} priority={job.priority}",
            )
        except BackendError:
            raise
        except Exception as exc:
            self.scheduler.release(job.job_id)
            self._handles.pop(job.job_id, None)
            raise BackendError(f"submit failed: {exc}") from exc
        # ftc: ignore[blocking-io-in-async-transitive] -- elastic re-render writes one small local spec on the rare granted<requested admission; the sync scheduler_tick hook shares this path so it cannot await
        self._admit_pending()

    async def _stage_resume_state(self, handle: _JobHandle) -> None:
        """Pull committed checkpoints (and the metrics history) back from the
        object store into a fresh sandbox — the controller half of elastic
        recovery (SURVEY.md §5.4): a resubmitted job must resume from the
        latest committed step even when its original sandbox is gone.

        Deliberately skips ``heartbeat.json`` (a stale heartbeat restored
        into a new attempt could trip the liveness lease) and ``done.txt``
        (only a SUCCEEDED attempt writes it).  No-op when the sandbox already
        has checkpoints (local restart — the fast path) or when the store has
        none (first attempt).
        """
        ckpt_dir = handle.artifacts_dir / "checkpoints"
        if ckpt_dir.is_dir() and any(ckpt_dir.iterdir()):
            return  # the sandbox survived; the trainer resumes from it as-is
        try:
            objs = await self.store.list_prefix(handle.artifacts_uri)
        except Exception:
            logger.exception(
                "resume staging: listing %s failed; job %s starts cold",
                handle.artifacts_uri, handle.job_id,
            )
            return
        prefix = handle.artifacts_uri.rstrip("/") + "/"
        n = 0
        for obj in objs:
            uri = obj["uri"]
            if not uri.startswith(prefix):
                continue
            rel = uri[len(prefix):]
            if not (
                rel.startswith("checkpoints/")
                or rel == "metrics.csv"
                # observability continuity (docs/observability.md): the
                # trainer APPENDS to events.jsonl / trace/trainer.jsonl, and
                # the monitor's ingest watermark is the line index — a fresh
                # sandbox must carry the prior attempts' lines or the synced
                # file would shrink under the watermark
                or rel == "events.jsonl"
                or rel.startswith("trace/")
            ):
                continue
            dest = handle.artifacts_dir / rel
            try:
                await self.store.get_file(uri, dest)
            except Exception:
                logger.exception("resume staging: fetch of %s failed", uri)
                continue
            # seed the sync sidecar's change detection so the files we just
            # pulled down are not immediately re-uploaded unchanged
            st = dest.stat()
            handle.synced[rel] = (st.st_mtime, st.st_size)
            n += 1
        if n:
            handle.restored_checkpoints = n
            handle.event("CheckpointsRestored",
                         f"{n} files <- {handle.artifacts_uri}")

    def _runtime_env(self, flavor: DeviceFlavor, num_slices: int) -> dict[str, str]:
        """Runtime env for a job (or warm worker) on a flavor: CPU flavors get
        a virtual device mesh the size of the slice (the TPU-less test story,
        SURVEY.md §4)."""
        env = dict(os.environ)
        env.update(self.extra_env)
        # the subprocess runs with the sandbox as cwd — make our package
        # importable regardless of install state
        pkg_root = str(Path(__file__).resolve().parents[3])
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else pkg_root
        )
        if flavor.runtime == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            n = flavor.total_chips * max(1, num_slices)
            flags = env.get("XLA_FLAGS", "")
            flags = " ".join(
                p for p in flags.split() if "host_platform_device_count" not in p
            )
            env["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_count={n}").strip()
        return env

    @staticmethod
    def _obs_env(handle: _JobHandle) -> dict[str, str]:
        """Trace-propagation env (docs/observability.md): the trainer stamps
        every span/event/log line with the job's trace id and this dispatch's
        attempt number."""
        if not handle.trace_id:
            return {}
        return {
            "FTC_TRACE_ID": handle.trace_id,
            "FTC_ATTEMPT": str(handle.attempt),
        }

    # ------------------------------------------------------- warm worker pool

    def _env_key(self, env: dict[str, str]) -> tuple:
        """Workers are only interchangeable within one runtime environment.

        Keyed on the platform vars + PYTHONPATH + a digest of the
        controller's ``extra_env`` overlay: a worker prewarmed before
        ``extra_env`` changed must not be claimed by a job that expects the
        new values — it inherited its env at spawn time and cannot be
        re-pointed.  Deliberately NOT a digest of the full ``os.environ``
        snapshot: unrelated env mutations (libraries setdefault-ing vars)
        would orphan every pooled worker under a key nothing ever claims.
        """
        extra = hashlib.sha256(
            "\x00".join(f"{k}={v}" for k, v in sorted(self.extra_env.items()))
            .encode()
        ).hexdigest()
        return (
            env.get("JAX_PLATFORMS", ""),
            env.get("XLA_FLAGS", ""),
            env.get("PYTHONPATH", ""),
            extra,
        )

    async def _spawn_warm(self, env: dict[str, str]) -> None:
        if self._closing or self.warm_workers <= 0:
            return
        key = self._env_key(env)
        pool = self._warm.setdefault(key, [])
        pool[:] = [p for p in pool if p.returncode is None]
        if len(pool) >= self.warm_workers:
            return
        # pre-claim output (JAX import warnings) goes to a pool log, not any
        # job's log; after the claim the worker re-points itself at the job
        pool_log = await asyncio.to_thread(open, self.root / "warm_workers.log", "ab")
        # the pool is replenished with the finished job's env — that job's
        # trace identity must not ride into whatever job claims this worker
        # next (each claim injects its own via the request line)
        env = {k: v for k, v in env.items() if k not in _OBS_ENV_KEYS}
        ready_path = self.root / f".warm_ready_{time.time_ns()}"
        env["FTC_WARM_READY_FILE"] = str(ready_path)
        try:
            proc = await asyncio.create_subprocess_exec(
                self.python, "-m", "finetune_controller_tpu.train.warm_worker",
                stdin=asyncio.subprocess.PIPE,
                stdout=pool_log, stderr=asyncio.subprocess.STDOUT,
                env=env, cwd=str(self.root),
            )
        finally:
            pool_log.close()
        proc.ftc_ready_path = ready_path  # type: ignore[attr-defined]
        pool.append(proc)

    def _claim_warm(self, env: dict[str, str]) -> asyncio.subprocess.Process | None:
        pool = self._warm.get(self._env_key(env), [])
        alive = [p for p in pool if p.returncode is None]
        pool[:] = alive
        # prefer a worker that has finished its import/init (ready file)
        alive.sort(key=lambda p: Path(getattr(p, "ftc_ready_path", "/nonexistent")).exists())
        if not alive:
            return None
        proc = alive[-1]
        pool.remove(proc)
        ready = getattr(proc, "ftc_ready_path", None)
        if ready is not None:
            Path(ready).unlink(missing_ok=True)
        return proc

    async def prewarm(
        self,
        flavor: DeviceFlavor | None = None,
        num_slices: int = 1,
        wait_s: float = 0.0,
    ) -> None:
        """Spawn the warm pool for a flavor (default: the catalog default) —
        call at service startup so the first submission already warm-starts.
        ``wait_s > 0`` blocks until the workers report ready (or the deadline
        passes) — mainly for benchmarks that need a steady-state pool."""
        if self.warm_workers <= 0:
            return
        if flavor is None:
            try:
                flavor = self.catalog.get_worker(self.catalog.default_flavor)
            except KeyError:
                # a latency optimization must not turn a config gap (no
                # default flavor in the catalog) into a startup outage
                logger.warning(
                    "warm_workers=%d but the device catalog has no default "
                    "flavor; skipping prewarm", self.warm_workers,
                )
                return
        env = self._runtime_env(flavor, num_slices)
        for _ in range(self.warm_workers):
            await self._spawn_warm(env)
        deadline = time.time() + wait_s
        pool = self._warm.get(self._env_key(env), [])
        while time.time() < deadline:
            alive = [p for p in pool if p.returncode is None]
            if not alive:
                # every spawned worker died (broken env, import failure) —
                # an empty pool must not report "ready": claims will cold-
                # spawn, and a latency bench would otherwise publish a bogus
                # warm number
                logger.warning(
                    "warm-worker pool is empty: all spawned workers exited "
                    "(see %s)", self.root / "warm_workers.log",
                )
                return
            if all(
                Path(getattr(p, "ftc_ready_path", "/nonexistent")).exists()
                for p in alive
            ):
                return
            await asyncio.sleep(0.2)

    def _admit_pending(self) -> None:
        if self._closing:
            return
        for w in self.scheduler.try_admit():
            if getattr(w, "owner", "train") != "train":
                # a serve-tenant replica workload: admission grants it chips,
                # but its lifecycle (spawn/drain) belongs to the serve plane
                # (sched/serve_tenant.py polls is_admitted) — there is no
                # trainer process to start and no handle to miss
                continue
            handle = self._handles.get(w.job_id)
            if handle is None:
                # the workload outlived its handle (a submit-path crash
                # dropped the handle after the scheduler registration): a
                # silent release here left the DB job QUEUED forever.  Leave
                # a FAILED tombstone report instead — the monitor hands it
                # to the retry supervisor, which classifies the message as
                # an infra failure and resubmits (ISSUE 5 satellite).
                self.scheduler.release(w.job_id)
                logger.error(
                    "job %s admitted without a live handle; reporting it "
                    "as failed so the supervisor can retry", w.job_id,
                )
                self._lost[w.job_id] = BackendJobReport(
                    job_id=w.job_id,
                    state=BackendJobState.FAILED,
                    completion_time=time.time(),
                    message=(
                        "backend error: workload admitted without a live "
                        "handle (submit-path crash); the job never started"
                    ),
                    metadata={"exit_code": None, "restarts": 0},
                )
                continue
            granted = getattr(w, "num_slices", handle.granted_slices)
            if granted != handle.granted_slices:
                # elastic admission: the scheduler granted a smaller
                # topology than the spec was rendered for — re-render the
                # mesh/env at the granted size (topology-portable
                # checkpoints make the resumed state land on it cleanly)
                try:
                    self._rerender_topology(handle, granted)
                except Exception as exc:
                    logger.exception(
                        "re-rendering %s at %d slices failed", w.job_id, granted
                    )
                    handle.set_state(
                        BackendJobState.FAILED, f"elastic re-render failed: {exc}"
                    )
                    self.scheduler.release(w.job_id)
                    continue
            handle.set_state(BackendJobState.CREATED)
            handle.event(
                "Admitted",
                f"queue={w.queue} priority={handle.priority} "
                f"slices={granted}/{handle.requested_slices}",
            )
            handle.run_task = asyncio.get_running_loop().create_task(self._run(handle))
        self._execute_preemptions()

    def _rerender_topology(self, handle: _JobHandle, num_slices: int) -> None:
        """Rewrite the trainer spec + runtime env for a new slice count
        (elastic admission granted less than asked).  The global batch stays
        in the spec untouched — ``train/elastic.py`` recomputes the
        microstructure at resume/start time."""
        spec, flavor = handle.spec_obj, handle.flavor_obj
        if spec is None or flavor is None:
            raise RuntimeError("no render context on the handle")
        mesh = default_mesh_for(flavor, num_slices, policy=spec.mesh_policy)
        trainer_spec = spec.build_trainer_spec(
            handle.job_id,
            str(handle.artifacts_dir),
            dataset_path=handle.dataset_path,
            mesh=mesh,
        )
        handle.spec_path.write_text(json.dumps(trainer_spec, indent=2))
        handle.env = self._runtime_env(flavor, num_slices)
        handle.env.update(self._obs_env(handle))
        handle.granted_slices = num_slices
        handle.event(
            "ElasticAdmission",
            f"granted {num_slices}/{handle.requested_slices} slices",
        )

    def _execute_preemptions(self) -> None:
        """Deliver the scheduler's eviction/resize decisions: SIGTERM each
        victim so the trainer checkpoints and exits 143; the run loop then
        reports FAILED without burning local restarts, and the resilience
        supervisor requeues the victim with resume — at ``to_slices`` when
        the decision is a resize (docs/elasticity.md).  The victim's chips
        stay reserved (for the preemptor, and for the victim's own shrunk
        resubmit) inside the scheduler until they actually free."""
        take = getattr(self.scheduler, "take_preemptions", None)
        if take is None:
            return
        # train-owned decisions only: a serve replica's preemption routes to
        # the serve tenant (sched/serve_tenant.py), which DRAINS the replica
        # instead of SIGTERMing a process that does not exist
        for decision in take(owner="train"):
            victim_id = decision.job_id
            preemptor_id = decision.preemptor_id or ""
            handle = self._handles.get(victim_id)
            if handle is None:
                # no backend half to resize: drop the workload AND any
                # reservation the decision just created — nothing will
                # resubmit to consume it
                getattr(self.scheduler, "forget", self.scheduler.release)(
                    victim_id
                )
                continue
            handle.preempted = True
            handle.preempted_by = preemptor_id
            if decision.kind == "evict":
                handle.event("Preempted", f"evicted for {preemptor_id}")
                logger.info("preempting job %s for %s", victim_id, preemptor_id)
            else:
                handle.resize_to = decision.to_slices
                handle.resize_kind = decision.kind
                handle.event(
                    "Resizing",
                    f"{decision.kind} {decision.from_slices}->"
                    f"{decision.to_slices} slices"
                    + (f" for {preemptor_id}" if preemptor_id else ""),
                )
                logger.info(
                    "resizing job %s: %s %d->%d slices%s",
                    victim_id, decision.kind, decision.from_slices,
                    decision.to_slices,
                    f" for {preemptor_id}" if preemptor_id else "",
                )
            if handle.proc is not None:
                with contextlib.suppress(ProcessLookupError):
                    handle.proc.terminate()
            # a proc-less victim (admitted, subprocess not yet spawned) is
            # caught by the post-spawn check in _run_once

    def scheduler_tick(self) -> None:
        """Monitor-tick admission hook: re-evaluate admission/preemption even
        without a submit/release edge (e.g. shares drifted, or a reservation
        became satisfiable) — the Kueue reconcile loop equivalent."""
        self._admit_pending()

    # --------------------------------------------------------------- run loop

    async def _run(self, handle: _JobHandle) -> None:
        """Pod main loop: launch, restart on failure up to backoffLimit."""
        try:
            attempt = 0
            outcome = BackendJobState.FAILED
            message = ""
            while True:
                rc = await self._run_once(handle, attempt)
                handle.exit_code = rc
                if handle.cancelled:
                    return
                if rc == 0:
                    # a preemption that lands as the process exits 0 is moot:
                    # the job trained to completion and must be SUCCEEDED,
                    # not spuriously failed-and-requeued
                    handle.preempted = False
                    handle.resize_to = None
                    handle.resize_kind = ""
                    outcome = BackendJobState.SUCCEEDED
                    break
                if handle.preempted:
                    # scheduler eviction/resize: do NOT restart locally — the
                    # chips are reserved (for the preemptor and, on a resize,
                    # for this job's own resubmit).  Report FAILED with the
                    # SIGTERM exit code so the supervisor classifies it as a
                    # preemption and requeues it with resume — at the resize
                    # topology when one is set.
                    outcome = BackendJobState.FAILED
                    if handle.resize_to is not None:
                        message = (
                            f"resized by scheduler ({handle.resize_kind} to "
                            f"{handle.resize_to} slices"
                            + (f" for {handle.preempted_by}"
                               if handle.preempted_by else "")
                            + f"; exit code {rc})"
                        )
                    else:
                        message = (
                            f"preempted by scheduler for {handle.preempted_by} "
                            f"(exit code {rc})"
                        )
                    break
                attempt += 1
                handle.restarts = attempt
                if attempt > self.backoff_limit:
                    outcome = BackendJobState.FAILED
                    message = f"exit code {rc} after {attempt} attempts"
                    break
                handle.set_state(BackendJobState.RESTARTING, f"exit code {rc}; retrying")
                handle.event("Restarting", f"attempt {attempt}/{self.backoff_limit}")
            handle.completion_time = time.time()
            # the terminal state must only become visible AFTER the final
            # artifact sync: the monitor deletes succeeded jobs from the
            # substrate as soon as it sees SUCCEEDED, which would cancel an
            # in-flight upload and lose the artifacts
            await self._final_sync(handle)
            handle.set_state(outcome, message)
            handle.event(outcome.value, message)
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # backend bug — surface as job failure
            logger.exception("job %s runner crashed", handle.job_id)
            handle.completion_time = handle.completion_time or time.time()
            handle.set_state(BackendJobState.FAILED, f"backend error: {exc}")
        finally:
            self.scheduler.release(handle.job_id)
            # ftc: ignore[blocking-io-in-async-transitive] -- same rare small-spec re-render write as the submit path; shared with the sync scheduler_tick hook
            self._admit_pending()
            # replenish the warm pool AFTER the job: a replacement spawning
            # at claim time would contend (imports vs the job's first-step
            # compile) and erase the warm start's saving
            with contextlib.suppress(Exception):
                await self._spawn_warm(handle.env)

    async def _run_once(self, handle: _JobHandle, attempt: int) -> int:
        proc = self._claim_warm(handle.env)
        if proc is not None:
            # warm start: the worker already paid JAX import + backend init;
            # hand it the spec and let it re-point its output at the job log.
            # The obs env rides the request — a pooled process was spawned
            # before this job existed and cannot inherit its trace identity
            request = json.dumps({
                "spec": str(handle.spec_path),
                "log": str(handle.logs_path),
                "cwd": str(handle.sandbox),
                "env": self._obs_env(handle),
            })
            try:
                proc.stdin.write(request.encode() + b"\n")
                await proc.stdin.drain()
                proc.stdin.close()
                handle.event(
                    "Started", f"attempt {attempt}: warm worker pid={proc.pid}"
                )
            except (BrokenPipeError, ConnectionResetError, OSError) as exc:
                # the worker died between the liveness check and the handoff —
                # a dead pool member must not fail the job; cold-spawn instead
                logger.warning(
                    "warm worker pid=%s unusable (%s); falling back to cold spawn",
                    proc.pid, exc,
                )
                handle.event("WarmWorkerLost", str(exc))
                proc = None
        if proc is None:
            cmd = [
                self.python, "-m", "finetune_controller_tpu.train.cli",
                "--spec", str(handle.spec_path),
            ]
            handle.event("Started", f"attempt {attempt}: {shlex.join(cmd)}")
            log_f = await asyncio.to_thread(open, handle.logs_path, "ab")
            try:
                # the child inherits the fd; the parent's copy closes either way
                proc = await asyncio.create_subprocess_exec(
                    *cmd,
                    stdout=log_f,
                    stderr=asyncio.subprocess.STDOUT,
                    env=handle.env,
                    cwd=str(handle.sandbox),
                )
            finally:
                log_f.close()
        handle.proc = proc
        if handle.preempted:
            # preemption landed between admission and spawn: the victim's
            # process must still die now, not run to completion on chips the
            # scheduler already promised away
            with contextlib.suppress(ProcessLookupError):
                proc.terminate()
        if handle.start_time is None:
            handle.start_time = time.time()
        handle.set_state(BackendJobState.RUNNING)
        if handle.sync_task is None or handle.sync_task.done():
            handle.sync_task = asyncio.get_running_loop().create_task(
                self._sync_loop(handle)
            )
        try:
            rc = await proc.wait()
        finally:
            handle.proc = None
        return rc

    # ------------------------------------------------------- artifact sidecar

    async def _sync_dir(self, handle: _JobHandle) -> int:
        """Upload changed matching files only (shared ``syncer`` core — the
        behavior ``aws s3 sync`` gave the reference for free)."""
        return await sync_dir_to_store(
            self.store, handle.artifacts_dir, handle.artifacts_uri,
            patterns=handle.patterns, synced=handle.synced,
        )

    async def _sync_loop(self, handle: _JobHandle) -> None:
        """Sidecar: sync every interval until done.txt appears
        (``PyTorchJobDeployer.py:134-138``); the final sync runs in
        :meth:`_final_sync`."""
        try:
            while not (handle.artifacts_dir / "done.txt").exists():
                await asyncio.sleep(self.sync_interval_s)
                if handle.state in BackendJobState.stopped_states():
                    return
                with contextlib.suppress(Exception):
                    await self._sync_dir(handle)
        except asyncio.CancelledError:
            pass

    async def _final_sync(self, handle: _JobHandle) -> None:
        if handle.sync_task is not None:
            handle.sync_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await handle.sync_task
            handle.sync_task = None
        try:
            n = await self._sync_dir(handle)
            if handle.logs_path.exists():
                # archive the training log with the artifacts so logs survive
                # substrate cleanup (the reference loses pod logs once the
                # succeeded job is deleted — core/monitor.py:182-186)
                await self.store.put_file(
                    f"{handle.artifacts_uri}/logs.txt", handle.logs_path
                )
            handle.event("ArtifactsSynced", f"{n} files -> {handle.artifacts_uri}")
        except Exception as exc:
            # losing the final sync silently would let the monitor delete the
            # sandbox believing artifacts are safe — record it loudly instead
            logger.exception("job %s final artifact sync failed", handle.job_id)
            handle.event("ArtifactSyncFailed", str(exc))
            handle.message = (handle.message + f"; artifact sync failed: {exc}").lstrip("; ")

    # ----------------------------------------------------------- introspection

    def _report(self, handle: _JobHandle) -> BackendJobReport:
        # exit_code rides the report metadata so the monitor persists it and
        # the retry supervisor can classify the failure (resilience/policy.py)
        metadata: dict[str, Any] = {
            "restarts": handle.restarts,
            "exit_code": handle.exit_code,
            "queue": handle.queue,
            "priority": handle.priority,
        }
        if handle.restored_checkpoints:
            metadata["restored_checkpoints"] = handle.restored_checkpoints
        # the topology this attempt actually runs at: the supervisor's
        # elastic-restore accounting compares successive attempts against
        # it, and an elastic ADMISSION (granted < asked on the very first
        # attempt) would otherwise be invisible to it
        metadata["last_ran_num_slices"] = handle.granted_slices
        if handle.granted_slices != handle.requested_slices:
            # running elastically below its requested topology
            metadata["current_num_slices"] = handle.granted_slices
            metadata["requested_num_slices"] = handle.requested_slices
        if handle.preempted:
            # persisted by the monitor's metadata merge -> the preemption
            # event survives in the job document (crash-safe, like
            # retry_next_at)
            metadata["preempted"] = True
            if handle.preempted_by:
                metadata["preempted_by"] = handle.preempted_by
        if handle.resize_to is not None:
            # the supervisor resubmits at this topology (crash-safe: the
            # monitor merges it into the job document before the RETRYING
            # transition)
            metadata["resize_to_num_slices"] = handle.resize_to
            metadata["resize_kind"] = handle.resize_kind
        return BackendJobReport(
            job_id=handle.job_id,
            state=handle.state,
            start_time=handle.start_time,
            completion_time=handle.completion_time,
            message=handle.message,
            metadata=metadata,
        )

    async def list_jobs(self) -> list[BackendJobReport]:
        return [self._report(h) for h in self._handles.values()] + list(
            self._lost.values()
        )

    async def get_job(self, job_id: str) -> BackendJobReport | None:
        h = self._handles.get(job_id)
        if h is not None:
            return self._report(h)
        return self._lost.get(job_id)

    async def queue_snapshot(self) -> list[str]:
        return self.scheduler.pending()

    async def job_events(self, job_id: str) -> list[dict[str, Any]]:
        h = self._handles.get(job_id)
        return list(h.events) if h else []

    # ---------------------------------------------------------------- control

    async def delete_job(self, job_id: str, *,
                         forget_reservations: bool = False) -> bool:
        """Kill + forget (cluster-delete equivalent; DB record survives).

        Escalates SIGTERM → SIGKILL: a trainer hung hard enough to trip the
        liveness lease may ignore SIGTERM, and the supervisor resubmits into
        the SAME sandbox — two writers on one artifacts dir would corrupt
        the checkpoints the resumed attempt depends on, so the old process
        must be dead before this returns.

        ``forget_reservations`` (terminal deletions only) also drops the
        job's scheduler resize reservation — see the base-class contract."""
        release = self.scheduler.release
        if forget_reservations:
            release = getattr(self.scheduler, "forget", release)
        if self._lost.pop(job_id, None) is not None:
            # tombstone of a job that never started: nothing to kill
            release(job_id)
            return True
        handle = self._handles.pop(job_id, None)
        if handle is None:
            return False
        handle.cancelled = True
        proc = handle.proc
        if proc is not None:
            with contextlib.suppress(ProcessLookupError):
                proc.terminate()
        for task in (handle.run_task, handle.sync_task):
            if task is not None and not task.done():
                task.cancel()
                with contextlib.suppress(asyncio.CancelledError, Exception):
                    await task
        if proc is not None and proc.returncode is None:
            try:
                await asyncio.wait_for(proc.wait(), timeout=self.term_grace_s)
            except asyncio.TimeoutError:
                logger.warning(
                    "job %s ignored SIGTERM for %.1fs; escalating to SIGKILL",
                    job_id, self.term_grace_s,
                )
                with contextlib.suppress(ProcessLookupError):
                    proc.kill()
                with contextlib.suppress(Exception):
                    await proc.wait()
        release(job_id)
        # ftc: ignore[blocking-io-in-async-transitive] -- same rare small-spec re-render write as the submit path; shared with the sync scheduler_tick hook
        self._admit_pending()
        return True

    def serve_worker_root(self, job_id: str) -> Path:
        """Serve-worker sandboxes live NEXT to the trainer sandboxes
        (docs/serving.md §Cross-process transport): a worker process gets
        the same debugging surface a failed trainer attempt does — spec,
        log, heartbeat and socket file under one per-replica dir — and the
        spawn/kill lifecycle rides this backend's substrate."""
        root = self.root / "serve_workers" / job_id
        root.mkdir(parents=True, exist_ok=True)
        return root

    async def inject_fault(self, job_id: str, *, signum: int = 15) -> bool:
        """Fault injection (SURVEY.md §5.3 gap): kill the running process;
        the restart loop then exercises the elastic/backoff path."""
        handle = self._handles.get(job_id)
        if handle is None or handle.proc is None:
            return False
        handle.event("FaultInjected", f"signal {signum}")
        with contextlib.suppress(ProcessLookupError):
            handle.proc.send_signal(signum)
        return True

    async def deliver_file(self, job_id: str, rel_path: str,
                           data: bytes) -> bool:
        """Artifact channel, reverse direction (docs/observability.md): drop
        a control file into the job's artifacts dir — atomically, so the
        trainer polling for it never reads a torn payload."""
        handle = self._handles.get(job_id)
        if handle is None:
            return False
        dest = (handle.artifacts_dir / rel_path).resolve()
        if handle.artifacts_dir.resolve() not in dest.parents:
            raise BackendError(f"refusing delivery outside the sandbox: {rel_path!r}")

        def write() -> None:
            dest.parent.mkdir(parents=True, exist_ok=True)
            tmp = dest.with_name(dest.name + ".tmp")
            tmp.write_bytes(data)
            os.replace(tmp, dest)

        await asyncio.to_thread(write)
        handle.event("FileDelivered", rel_path)
        return True

    # ------------------------------------------------------------------- logs

    async def read_logs(
        self,
        job_id: str,
        *,
        follow: bool = False,
        last_lines: int | None = None,
    ) -> AsyncIterator[str]:
        handle = self._handles.get(job_id)
        if handle is None:
            raise BackendError(f"unknown job {job_id!r}")

        path = handle.logs_path

        async def aiter() -> AsyncIterator[str]:
            # wait for the log file to exist (pod may still be pending);
            # historical reads return empty immediately rather than blocking
            # on a job that has not started
            while not path.exists():
                h = self._handles.get(job_id)
                if not follow:
                    return
                if h is None or h.state in BackendJobState.stopped_states():
                    return
                await asyncio.sleep(0.1)
            f = await asyncio.to_thread(open, path, "r", errors="replace")
            try:
                if last_lines is not None:
                    lines = await asyncio.to_thread(f.readlines)
                    for line in lines[-last_lines:]:
                        yield line.rstrip("\n")
                    if not follow:
                        return
                else:
                    while True:
                        line = await asyncio.to_thread(f.readline)
                        if not line:
                            break
                        yield line.rstrip("\n")
                if not follow:
                    return
                # live tail with pod-liveness probe on empty reads
                # (reference: stream_logger.py:286-341)
                while True:
                    line = await asyncio.to_thread(f.readline)
                    if line:
                        yield line.rstrip("\n")
                        continue
                    h = self._handles.get(job_id)
                    if h is None or (
                        h.state in BackendJobState.stopped_states() and h.proc is None
                    ):
                        # drain anything written between readline and the check
                        tail = await asyncio.to_thread(f.read)
                        for extra in tail.splitlines():
                            yield extra
                        return
                    await asyncio.sleep(0.2)
            finally:
                await asyncio.to_thread(f.close)

        return aiter()

    async def close(self) -> None:
        self._closing = True
        self._lost.clear()
        for job_id in list(self._handles):
            await self.delete_job(job_id, forget_reservations=True)
        for pool in self._warm.values():
            for proc in pool:
                if proc.returncode is None:
                    # closing stdin without a request is the graceful exit
                    with contextlib.suppress(Exception):
                        proc.stdin.close()
                    with contextlib.suppress(ProcessLookupError):
                        proc.terminate()
                    with contextlib.suppress(Exception):
                        await proc.wait()
                ready = getattr(proc, "ftc_ready_path", None)
                if ready is not None:
                    Path(ready).unlink(missing_ok=True)
        self._warm.clear()
