"""Gang scheduler — the in-repo Kueue.

The reference delegates admission control to the external Kueue operator
(SURVEY.md §2.2): jobs are created **suspended** with a queue label
(``PyTorchJobDeployer.py:66-68,179-185``) and Kueue flips ``suspend`` off when
the ClusterQueue has quota; queue order is derived by listing workloads with
``QuotaReserved=False`` sorted by creation time (``kueue_helpers.py:19-46``).

This module is that state machine, in-process and synchronous (trivially
testable): flavors carry nominal chip quotas (``crds/kueue/cluster-queue.yaml:13-22``),
a workload reserves ``flavor.total_chips * num_slices``, admission is
best-effort FIFO (a small job may pass a blocked large one — Kueue's
``BestEffortFIFO`` default), and gang semantics hold because a workload's chips
are reserved atomically or not at all.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging

from ..devices import DeviceCatalog

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class Workload:
    """One queued/admitted job (Kueue ``Workload`` CR equivalent)."""

    job_id: str
    flavor: str
    chips: int
    queue: str
    seq: int = 0
    admitted: bool = False


class GangScheduler:
    """Quota-based all-or-nothing admission over the device catalog."""

    def __init__(self, catalog: DeviceCatalog):
        self._catalog = catalog
        self._workloads: dict[str, Workload] = {}
        # per-scheduler sequence: the previous module-global counter leaked
        # submission ordering across instances, making queue positions
        # depend on which tests (or sibling backends) ran first
        self._seq = itertools.count()

    # -- bookkeeping ---------------------------------------------------------

    def _used_chips(self, flavor: str) -> int:
        return sum(
            w.chips for w in self._workloads.values() if w.admitted and w.flavor == flavor
        )

    def submit(
        self,
        job_id: str,
        flavor_name: str,
        num_slices: int = 1,
        *,
        queue: str | None = None,
        priority: object | None = None,
        requested_slices: int | None = None,
        min_slices: int = 1,
    ) -> Workload:
        """Register a suspended workload (``runPolicy.suspend: true`` until
        admitted — ``PyTorchJobDeployer.py:179-185``).

        ``queue``/``priority``/``requested_slices``/``min_slices`` are accepted for
        signature parity with the fair-share scheduler
        (``finetune_controller_tpu/sched/``) and deliberately ignored: this
        is the documented FIFO escape hatch (``FTC_SCHED_POLICY=fifo``),
        which has no tenant semantics and never resizes.
        """
        if job_id in self._workloads:
            raise ValueError(f"workload {job_id!r} already queued")
        flavor = self._catalog.get_worker(flavor_name)
        w = Workload(
            job_id=job_id,
            flavor=flavor.name,
            chips=flavor.total_chips * max(1, num_slices),
            queue=flavor.queue,
            seq=next(self._seq),
        )
        self._workloads[job_id] = w
        return w

    def try_admit(self) -> list[Workload]:
        """Admit every pending workload that fits, FIFO by submission order.

        Returns the newly admitted workloads; the backend starts them.
        """
        admitted: list[Workload] = []
        for w in sorted(self._workloads.values(), key=lambda w: w.seq):
            if w.admitted:
                continue
            quota = self._catalog.quota_for(w.flavor)
            if self._used_chips(w.flavor) + w.chips <= quota:
                w.admitted = True
                admitted.append(w)
                logger.info(
                    "admitted %s (%d chips of %s, %d/%d used)",
                    w.job_id, w.chips, w.flavor, self._used_chips(w.flavor), quota,
                )
        return admitted

    def release(self, job_id: str) -> None:
        """Free a workload's quota (job finished or deleted)."""
        self._workloads.pop(job_id, None)

    # -- queue introspection (reference: kueue_helpers.py) -------------------

    def pending(self) -> list[str]:
        """Pending job ids in queue order (``get_kueue_queue``,
        ``kueue_helpers.py:19-46``: QuotaReserved=False sorted by creation)."""
        return [
            w.job_id
            for w in sorted(self._workloads.values(), key=lambda w: w.seq)
            if not w.admitted
        ]

    def position(self, job_id: str) -> int | None:
        """1-based queue position (``get_kueue_position``,
        ``kueue_helpers.py:49-81``); None when not pending."""
        pend = self.pending()
        return pend.index(job_id) + 1 if job_id in pend else None

    def is_admitted(self, job_id: str) -> bool:
        w = self._workloads.get(job_id)
        return bool(w and w.admitted)

    def usage(self) -> dict[str, dict[str, int]]:
        """Per-flavor quota usage (admin/debug surface)."""
        out: dict[str, dict[str, int]] = {}
        for f in self._catalog.flavors:
            out[f.name] = {
                "used_chips": self._used_chips(f.name),
                "nominal_chips": self._catalog.quota_for(f.name),
                "pending": sum(
                    1 for w in self._workloads.values()
                    if not w.admitted and w.flavor == f.name
                ),
            }
        return out
