"""The backend seam: what the control plane needs from an execution substrate.

The reference's equivalent surface is scattered across
``PyTorchJobDeployer.create_pytorch_job/get_job_status/delete_job``
(``app/jobs/kubeflow/PyTorchJobDeployer.py:20,264,274``), the monitor's
``kubeflow_api.list_jobs`` (``app/core/monitor.py:131``), and the log
streamer's pod-log reads (``app/utils/stream_logger.py:204-284``). Collapsing
it into one interface makes every consumer (task builder, monitor, WS log
streamer, admin debug routes) backend-neutral and fake-able in tests.
"""

from __future__ import annotations

import abc
from typing import Any, AsyncIterator

from ..devices import DeviceFlavor
from ..schemas import BackendJobReport, JobInput
from ..specs import BaseFineTuneJob


class BackendError(Exception):
    """Raised when the backend cannot perform an operation."""


class TrainingBackend(abc.ABC):
    """Execution substrate for fine-tune jobs."""

    @abc.abstractmethod
    async def submit(
        self,
        job: JobInput,
        spec: BaseFineTuneJob,
        flavor: DeviceFlavor,
        *,
        dataset_uri: str | None,
        artifacts_uri: str,
    ) -> None:
        """Accept a job for (gang-scheduled) execution.

        Replaces ``PyTorchJobDeployer.create_pytorch_job``
        (``PyTorchJobDeployer.py:20-262``): the deployer renders whatever the
        substrate runs (subprocess spec / JobSet manifest) and enqueues it
        suspended until the scheduler admits it.

        Resubmit contract (``resilience/supervisor.py``): a job may be
        submitted again under the SAME ``job_id``/``artifacts_uri`` after its
        backend half was deleted.  A backend that can should stage committed
        checkpoints from ``{artifacts_uri}/checkpoints`` back into the fresh
        substrate so the trainer's resume path continues the run rather than
        restarting it (the local backend does; see
        ``LocalProcessBackend._stage_resume_state``)."""

    @abc.abstractmethod
    async def list_jobs(self) -> list[BackendJobReport]:
        """Snapshot every job the backend knows (monitor input — replaces
        ``kubeflow_api.list_jobs``, ``app/core/monitor.py:131``)."""

    @abc.abstractmethod
    async def get_job(self, job_id: str) -> BackendJobReport | None:
        """One job's report, or None if the backend no longer tracks it."""

    @abc.abstractmethod
    async def delete_job(self, job_id: str, *,
                         forget_reservations: bool = False) -> bool:
        """Stop (if needed) and forget a job — used both for post-success
        cluster cleanup (``app/core/monitor.py:182-186``) and user cancel
        (``app/main.py:839-903``). Artifacts already live in the object
        store, so deletion loses nothing.

        ``forget_reservations=True`` (terminal deletions: success cleanup,
        user cancel) additionally drops any scheduler resize reservation the
        job holds — it is not coming back at a new size.  The default keeps
        reservations alive: the retry supervisor's teardown of a mid-resize
        victim must NOT release the chips fenced for its own resubmit
        (docs/elasticity.md)."""

    @abc.abstractmethod
    async def read_logs(
        self,
        job_id: str,
        *,
        follow: bool = False,
        last_lines: int | None = None,
    ) -> AsyncIterator[str]:
        """Yield log lines (historical, then live when ``follow``) — the
        pod-log seam the WS streamer consumes
        (``stream_logger.py:204-284``)."""

    @abc.abstractmethod
    async def queue_snapshot(self) -> list[str]:
        """Ordered pending job ids (Kueue queue order —
        ``kueue_helpers.py:19-46``)."""

    async def job_events(self, job_id: str) -> list[dict[str, Any]]:
        """Debug event log for one job (reference: pod events digest,
        ``kube_helpers.py:26-95``). Optional; default empty."""
        return []

    async def inject_fault(self, job_id: str, *, signum: int = 15) -> bool:
        """Chaos seam (``resilience/faults.py``): deliver a signal to a
        running job's process, exercising the preemption/recovery paths.
        Optional; backends without process access report False (not
        injected)."""
        return False

    async def deliver_file(self, job_id: str, rel_path: str,
                           data: bytes) -> bool:
        """Deliver a small control file into a RUNNING job's artifacts dir —
        the artifact channel in reverse (docs/observability.md: the
        on-demand ``jax.profiler`` window rides this as
        ``profile_request.json``).  Optional; backends without sandbox
        access report False (not delivered)."""
        return False

    def serve_worker_root(self, job_id: str) -> Any | None:
        """Root directory for cross-process serve-worker sandboxes of one
        served job (docs/serving.md §Cross-process transport).  The local
        backend hosts worker sandboxes next to its trainer sandboxes so the
        spawn/kill lifecycle and debugging surface ride the same substrate;
        backends without local process access return None and the serve
        manager falls back to its own state dir (or, on k8s, to rendering
        one worker POD per replica — ``k8s.render_serve_worker_pod``)."""
        return None

    async def close(self) -> None:
        """Release resources (subprocesses, watch tasks)."""
        return None
