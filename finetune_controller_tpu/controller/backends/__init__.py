"""Training backends: where jobs actually run.

The reference delegates execution to a Kubernetes cluster (Kubeflow
``PyTorchJob`` + Kueue — SURVEY.md §2 components 6/10/11/24). Here the seam is
an explicit interface (:class:`~finetune_controller_tpu.controller.backends.base.TrainingBackend`)
with two implementations:

- :mod:`.local` — in-process fake cluster running the in-repo JAX trainer as
  subprocesses, with gang-scheduled admission. Carries the CI/integration
  story the reference never had (SURVEY.md §4).
- :mod:`.k8s` — renders TPU JobSet manifests for a real cluster (SURVEY.md §7
  step 4).
"""

from .base import BackendError, TrainingBackend
from .scheduler import GangScheduler, Workload

__all__ = ["BackendError", "TrainingBackend", "GangScheduler", "Workload"]
