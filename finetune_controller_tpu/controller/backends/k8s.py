"""Kubernetes TPU backend: JobSet manifests, Kueue TPU quota, jax.distributed bootstrap.

The TPU-native replacement for the reference's PyTorchJob deployer
(``app/jobs/kubeflow/PyTorchJobDeployer.py`` — SURVEY.md §2 component 6) and
its Kueue CRDs (component 24), redesigned per SURVEY.md §2.2/§7 step 4:

- **JobSet instead of PyTorchJob.** TPU workers are symmetric peers (every
  host runs the same SPMD program), so the reference's Master + (N−1) Workers
  split (``PyTorchJobDeployer.py:186-249``) becomes one indexed Job per slice
  with ``hosts`` completions; rank 0 is elected, not special-cased.
- **Slice topology instead of a GPU count.** Resources request
  ``google.com/tpu: chips_per_host`` with GKE topology node selectors
  (replaces ``nvidia.com/gpu`` requests, ``PyTorchJobDeployer.py:45-55``).
- **jax.distributed bootstrap instead of Training-Operator rendezvous.**
  The pod env carries coordinator address / process count / process id
  (``parallel/distributed.py``); collectives ride ICI within a slice and DCN
  across slices — no NCCL, no MASTER_ADDR.
- **Same Kueue integration**: jobs are created suspended with a queue label
  (``PyTorchJobDeployer.py:66-68,179-185``); :func:`render_kueue_crds` emits
  TPU ResourceFlavors/ClusterQueues replacing ``crds/kueue/*.yaml``.
- **Same sidecar/init pattern**: a dataset-fetch init container and an
  artifact-sync sidecar that exits on ``done.txt``
  (``PyTorchJobDeployer.py:70-168``), but running our storage CLI instead of
  ``amazon/aws-cli`` images.

No kubernetes SDK is required: :class:`AiohttpKubeClient` talks to the API
server directly (in-cluster service-account auth), and
:class:`InMemoryKubeClient` is the hermetic test double.
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
import time
from pathlib import Path
from typing import Any, AsyncIterator

from ..devices import DeviceCatalog, DeviceFlavor, default_mesh_for
from ..schemas import BackendJobReport, BackendJobState, JobInput
from ..specs import BaseFineTuneJob
from .base import BackendError, TrainingBackend

logger = logging.getLogger(__name__)

JOBSET_GROUP = "jobset.x-k8s.io"
JOBSET_VERSION = "v1alpha2"
JOBSET_PLURAL = "jobsets"
KUEUE_QUEUE_LABEL = "kueue.x-k8s.io/queue-name"  # reference: PyTorchJobDeployer.py:66-68
APP_LABEL = "finetune-controller-tpu"
COORDINATOR_PORT = 8476


def _sanitize_label(value: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_.-]", "-", value)[:63]


def _parse_k8s_time(value: Any) -> float | None:
    """Accept epoch floats (fakes) or RFC3339 strings (real API server)."""
    if isinstance(value, (int, float)):
        return float(value)
    if isinstance(value, str) and value:
        from datetime import datetime

        try:
            return datetime.fromisoformat(value.replace("Z", "+00:00")).timestamp()
        except ValueError:
            return None
    return None


# ---------------------------------------------------------------------------
# Manifest rendering (pure functions — the testable core)
# ---------------------------------------------------------------------------


def render_trainer_spec(
    job: JobInput,
    spec: BaseFineTuneJob,
    flavor: DeviceFlavor,
    *,
    dataset_uri: str | None,
    artifacts_dir: str = "/data/artifacts",
) -> dict[str, Any]:
    dataset_path = None
    if dataset_uri:
        dataset_path = f"/data/dataset/{dataset_uri.rsplit('/', 1)[-1]}"
    return spec.build_trainer_spec(
        job.job_id,
        artifacts_dir,
        dataset_path=dataset_path,
        mesh=default_mesh_for(flavor, job.num_slices, policy=spec.mesh_policy),
    )


def render_jobset(
    job: JobInput,
    spec: BaseFineTuneJob,
    flavor: DeviceFlavor,
    *,
    namespace: str,
    image: str,
    dataset_uri: str | None,
    artifacts_uri: str,
    sync_interval_s: float = 60.0,
    max_restarts: int = 2,
    object_store_env: dict[str, str] | None = None,
) -> dict[str, Any]:
    """Render the JobSet CR (replaces ``create_pytorch_job``'s manifest dict,
    ``PyTorchJobDeployer.py:170-252``)."""
    hosts = flavor.hosts
    total_processes = hosts * max(1, job.num_slices)
    # JobSet creates a headless service named after the jobset; pod 0 of the
    # first slice-job is the jax.distributed coordinator (rank-0 election —
    # no Master/Worker asymmetry, SURVEY.md §7 hard parts)
    coordinator = (
        f"{job.job_id}-slice-0-0.{job.job_id}:{COORDINATOR_PORT}"
    )
    store_env = [
        {"name": k, "value": v} for k, v in (object_store_env or {}).items()
    ]

    # process id = slice_index * hosts + host_index, both from downward API
    bootstrap = (
        f"export FTC_PROCESS_ID=$((FTC_SLICE_INDEX * {hosts} + JOB_COMPLETION_INDEX)) && "
    )
    trainer_cmd = bootstrap + spec.run_cmd("/etc/ftc/job.json")

    # the JobSet replicated-job index IS the slice index — one fieldRef
    # shared by the jax.distributed seam (FTC_SLICE_INDEX) and libtpu's
    # MEGASCALE contract so the two can never drift
    slice_index_ref = {
        "fieldRef": {
            "fieldPath": "metadata.annotations['jobset.sigs.k8s.io/job-index']"
        }
    }

    # Multi-slice: libtpu's DCN transport needs the MEGASCALE_* contract in
    # addition to the jax.distributed FTC_* seam — the coordinator is slice
    # 0's host 0, the slice id is the JobSet replicated-job index. Harmless
    # (and omitted) on single-slice jobs.
    # trace propagation (docs/observability.md): every pod of every attempt
    # stamps its spans/events/logs with the job's trace id
    obs_env: list[dict[str, Any]] = []
    if job.trace_id:
        obs_env = [
            {"name": "FTC_TRACE_ID", "value": job.trace_id},
            {"name": "FTC_ATTEMPT", "value": str(max(1, job.attempt))},
        ]

    megascale_env: list[dict[str, Any]] = []
    if max(1, job.num_slices) > 1:
        megascale_env = [
            {
                "name": "MEGASCALE_COORDINATOR_ADDRESS",
                "value": f"{job.job_id}-slice-0-0.{job.job_id}",
            },
            {"name": "MEGASCALE_NUM_SLICES", "value": str(job.num_slices)},
            {"name": "MEGASCALE_SLICE_ID", "valueFrom": slice_index_ref},
        ]

    trainer_container = {
        "name": "trainer",
        "image": image,
        "command": ["/bin/sh", "-c", trainer_cmd],
        "env": [
            {"name": "FTC_COORDINATOR_ADDRESS", "value": coordinator},
            {"name": "FTC_NUM_PROCESSES", "value": str(total_processes)},
            *obs_env,
            *megascale_env,
            {"name": "FTC_SLICE_INDEX", "valueFrom": slice_index_ref},
            {
                "name": "JOB_COMPLETION_INDEX",
                "valueFrom": {
                    "fieldRef": {
                        "fieldPath": (
                            "metadata.annotations"
                            "['batch.kubernetes.io/job-completion-index']"
                        )
                    }
                },
            },
            *store_env,
        ],
        "ports": [{"containerPort": COORDINATOR_PORT}],
        "resources": {
            "requests": {
                "cpu": flavor.cpu,
                "memory": flavor.memory,
                flavor.k8s_resource_name(): str(flavor.chips_per_host),
            },
            "limits": {
                flavor.k8s_resource_name(): str(flavor.chips_per_host),
            },
        },
        "volumeMounts": [
            {"name": "data", "mountPath": "/data"},
            {"name": "job-spec", "mountPath": "/etc/ftc"},
        ],
    }

    # artifact-sync sidecar (reference: aws s3 sync loop + done.txt exit,
    # PyTorchJobDeployer.py:121-168) — ours runs the storage CLI with the
    # spec's store_asset_patterns. Rendered as a NATIVE sidecar (init
    # container with restartPolicy Always, K8s >=1.28): the kubelet kills it
    # when the trainer container terminates, so a crashed trainer that never
    # touches done.txt cannot wedge the pod in Running forever.
    sync_cmd = [
        "python", "-m", "finetune_controller_tpu.controller.storage_cli",
        "sync", "/data/artifacts", artifacts_uri,
        "--interval", str(sync_interval_s),
        "--until-done-file", "/data/artifacts/done.txt",
    ]
    for pattern in spec.store_asset_patterns:
        sync_cmd += ["--pattern", pattern]
    sync_container = {
        "name": "artifact-sync",
        "image": image,
        "restartPolicy": "Always",  # marks it a native sidecar
        "command": sync_cmd,
        "env": store_env,
        "volumeMounts": [{"name": "data", "mountPath": "/data"}],
    }

    # ordering: dataset fetch completes first, then the sync sidecar starts
    # and keeps running alongside the trainer
    init_containers = []
    if dataset_uri:
        # dataset-fetch init container (reference: aws s3 cp init container,
        # PyTorchJobDeployer.py:70-91)
        init_containers.append(
            {
                "name": "dataset-fetch",
                "image": image,
                "command": [
                    "python", "-m",
                    "finetune_controller_tpu.controller.storage_cli",
                    "get", dataset_uri,
                    f"/data/dataset/{dataset_uri.rsplit('/', 1)[-1]}",
                ],
                "env": store_env,
                "volumeMounts": [{"name": "data", "mountPath": "/data"}],
            }
        )

    init_containers.append(sync_container)

    pod_spec: dict[str, Any] = {
        "restartPolicy": "Never",  # restarts are JobSet-level (gang semantics)
        "initContainers": init_containers,
        "containers": [trainer_container],
        "volumes": [
            {"name": "data", "emptyDir": {}},
            {"name": "job-spec", "configMap": {"name": f"{job.job_id}-spec"}},
        ],
    }
    selectors = flavor.accelerator_selectors()
    if selectors:
        pod_spec["nodeSelector"] = selectors

    return {
        "apiVersion": f"{JOBSET_GROUP}/{JOBSET_VERSION}",
        "kind": "JobSet",
        "metadata": {
            "name": job.job_id,
            "namespace": namespace,
            "labels": {
                "app": APP_LABEL,
                KUEUE_QUEUE_LABEL: flavor.queue,
                "ftc/user": _sanitize_label(job.user_id),
                "ftc/model": _sanitize_label(job.model_name),
                # total chips, as the reference records
                # (PyTorchJobDeployer.py:57-63)
                "ftc/chips": str(flavor.total_chips * max(1, job.num_slices)),
            },
            "annotations": {
                # keep every slice on one nodepool so ICI stays intra-slice
                "alpha.jobset.sigs.k8s.io/exclusive-topology": (
                    "cloud.google.com/gke-nodepool"
                ),
            },
        },
        "spec": {
            "suspend": True,  # Kueue admits (PyTorchJobDeployer.py:179-185)
            "failurePolicy": {"maxRestarts": max_restarts},
            "replicatedJobs": [
                {
                    "name": "slice",
                    "replicas": max(1, job.num_slices),
                    "template": {
                        "spec": {
                            "parallelism": hosts,
                            "completions": hosts,
                            "completionMode": "Indexed",
                            "backoffLimit": 0,
                            "template": {
                                "metadata": {"labels": {"app": APP_LABEL}},
                                "spec": pod_spec,
                            },
                        }
                    },
                }
            ],
        },
    }


def render_spec_configmap(
    job: JobInput, trainer_spec: dict[str, Any], namespace: str
) -> dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": f"{job.job_id}-spec", "namespace": namespace},
        "data": {"job.json": json.dumps(trainer_spec, indent=2)},
    }


def render_serve_worker_pod(
    job_id: str,
    replica_id: str,
    *,
    namespace: str,
    image: str,
    worker_spec: dict[str, Any],
    flavor: DeviceFlavor | None = None,
    port: int = 7077,
    extra_env: dict[str, str] | None = None,
) -> dict[str, Any]:
    """One POD per serve replica (docs/serving.md §Cross-process transport):
    the k8s rendering of the worker sandbox the local backend spawns as a
    subprocess.  The worker spec rides an inline env var (it is a small JSON
    document — the payload itself is staged from the object store by the
    builder inside the pod), the heartbeat/sandbox dir is an ``emptyDir``,
    and the RPC port is fixed per pod because every pod has its own IP —
    ``serve_worker_port_base`` only matters when replicas share a host.
    ``FTC_FAULT_SERVE_*`` rides ``extra_env`` so the chaos hand crosses the
    pod boundary exactly as it crosses the local process boundary."""
    spec_doc = dict(worker_spec)
    spec_doc.setdefault("sandbox", "/var/run/ftc-serve")
    spec_doc.setdefault("host", "0.0.0.0")
    spec_doc["port"] = port
    env = [
        {"name": "FTC_SERVE_WORKER_SPEC", "value": json.dumps(spec_doc)},
        *({"name": k, "value": v} for k, v in (extra_env or {}).items()),
    ]
    resources: dict[str, Any] = {}
    if flavor is not None and flavor.runtime != "cpu":
        resources = {
            "limits": {flavor.k8s_resource_name(): flavor.chips_per_host}
        }
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"{job_id}-serve-{replica_id}",
            "namespace": namespace,
            "labels": {
                "app": "ftc-serve-worker",
                "ftc/job": _sanitize_label(job_id),
                "ftc/replica": _sanitize_label(replica_id),
            },
        },
        "spec": {
            "restartPolicy": "Never",  # the FLEET respawns with backoff
            "containers": [{
                "name": "serve-worker",
                "image": image,
                "command": [
                    "/bin/sh", "-c",
                    "mkdir -p /var/run/ftc-serve && "
                    "printf '%s' \"$FTC_SERVE_WORKER_SPEC\" "
                    "> /var/run/ftc-serve/worker_spec.json && "
                    "python -m finetune_controller_tpu.transport.worker "
                    "--spec /var/run/ftc-serve/worker_spec.json",
                ],
                "env": env,
                "ports": [{"containerPort": port, "name": "ftc-rpc"}],
                "volumeMounts": [{
                    "name": "serve-sandbox",
                    "mountPath": "/var/run/ftc-serve",
                }],
                **({"resources": resources} if resources else {}),
            }],
            "volumes": [{"name": "serve-sandbox", "emptyDir": {}}],
        },
    }
    if flavor is not None:
        selectors = flavor.accelerator_selectors()
        if selectors:
            pod["spec"]["nodeSelector"] = selectors
    return pod


def render_kueue_crds(
    catalog: DeviceCatalog, *, namespace: str = "default",
    cluster_queue: str = "ftc-cluster-queue",
) -> list[dict[str, Any]]:
    """TPU ResourceFlavors + ClusterQueue + LocalQueues from the device
    catalog (replaces ``crds/kueue/*.yaml`` + ``examples/Kueue/crds`` —
    SURVEY.md §2 component 24, with ``google.com/tpu`` quotas per §2.2)."""
    out: list[dict[str, Any]] = []
    # Kueue requires each resource name to appear in exactly ONE resourceGroup
    # per ClusterQueue, so flavors are grouped by the resource they cover
    # (all TPU flavors share "google.com/tpu")
    by_resource: dict[str, list] = {}
    for f in catalog.flavors:
        flavor_obj: dict[str, Any] = {
            "apiVersion": "kueue.x-k8s.io/v1beta1",
            "kind": "ResourceFlavor",
            "metadata": {"name": f.name},
        }
        if f.accelerator_selectors():
            flavor_obj["spec"] = {"nodeLabels": f.accelerator_selectors()}
        out.append(flavor_obj)
        by_resource.setdefault(f.k8s_resource_name(), []).append(
            {
                "name": f.name,
                "resources": [
                    {
                        "name": f.k8s_resource_name(),
                        "nominalQuota": catalog.quota_for(f.name),
                    }
                ],
            }
        )
    resource_groups = [
        {"coveredResources": [resource], "flavors": flavors}
        for resource, flavors in by_resource.items()
    ]
    out.append(
        {
            "apiVersion": "kueue.x-k8s.io/v1beta1",
            "kind": "ClusterQueue",
            "metadata": {"name": cluster_queue},
            "spec": {
                "namespaceSelector": {},
                "resourceGroups": resource_groups,
            },
        }
    )
    for queue in sorted({f.queue for f in catalog.flavors}):
        out.append(
            {
                "apiVersion": "kueue.x-k8s.io/v1beta1",
                "kind": "LocalQueue",
                "metadata": {"name": queue, "namespace": namespace},
                "spec": {"clusterQueue": cluster_queue},
            }
        )
    return out


# ---------------------------------------------------------------------------
# Kube API clients
# ---------------------------------------------------------------------------


class KubeClient:
    """Minimal async surface over the Kubernetes API (the seam the reference
    covers with the kubernetes/kubeflow SDKs — SURVEY.md §2 component 10)."""

    async def create(self, api_path: str, body: dict[str, Any]) -> dict[str, Any]:
        raise NotImplementedError

    async def get(self, api_path: str, name: str) -> dict[str, Any] | None:
        raise NotImplementedError

    async def list(self, api_path: str, label_selector: str = "") -> list[dict[str, Any]]:
        raise NotImplementedError

    async def delete(self, api_path: str, name: str) -> bool:
        raise NotImplementedError

    async def pod_log_lines(
        self, namespace: str, pod: str, *, container: str, follow: bool,
        tail_lines: int | None,
    ) -> AsyncIterator[str]:
        raise NotImplementedError

    async def close(self) -> None:
        return None


class InMemoryKubeClient(KubeClient):
    """Hermetic fake API server for tests.

    Two modes: test code may mutate ``objects`` directly to script status
    transitions, or use the built-in **Kueue/pod simulation** —
    :meth:`kueue_tick` admits suspended JobSets FIFO within a chip quota
    (unsuspend + pod creation + active status, what the real Kueue and JobSet
    operators do), and :meth:`finish_jobset` drives terminal conditions — so
    backend tests exercise the real SUSPENDED → RUNNING → terminal mapping
    and the rank-0 pod-resolution path instead of hand-written fixtures.

    On create, JobSet manifests are schema-checked the way the operators
    would reject them: coordinator DNS convention, downward-API annotation
    paths, and Indexed completion mode.
    """

    def __init__(self, *, quota_chips: int | None = None):
        self.objects: dict[tuple[str, str], dict[str, Any]] = {}
        self.pod_logs: dict[str, list[str]] = {}
        self.quota_chips = quota_chips

    # -- JobSet manifest validation (what a real API server/operator rejects) --

    @staticmethod
    def _validate_jobset(body: dict[str, Any]) -> None:
        name = body["metadata"]["name"]
        spec = body["spec"]
        if "suspend" not in spec:
            raise BackendError(f"JobSet {name}: missing spec.suspend (Kueue contract)")
        rjs = spec.get("replicatedJobs") or []
        if not rjs:
            raise BackendError(f"JobSet {name}: no replicatedJobs")
        rj = rjs[0]
        job_spec = rj["template"]["spec"]
        if job_spec.get("completionMode") != "Indexed":
            raise BackendError(
                f"JobSet {name}: completionMode must be Indexed for the "
                "downward-API completion index to exist"
            )
        pod_spec = job_spec["template"]["spec"]
        containers = {c["name"]: c for c in pod_spec.get("containers", [])}
        trainer = containers.get("trainer")
        if trainer is None:
            raise BackendError(f"JobSet {name}: no trainer container")
        env = {e["name"]: e for e in trainer.get("env", [])}
        coord = env.get("FTC_COORDINATOR_ADDRESS", {}).get("value", "")
        # the headless service JobSet creates is named after the jobset; pod 0
        # of replicated job 0 must be the coordinator
        want_prefix = f"{name}-{rj['name']}-0-0.{name}:"
        if not coord.startswith(want_prefix):
            raise BackendError(
                f"JobSet {name}: coordinator {coord!r} does not match the "
                f"JobSet DNS convention {want_prefix}<port>"
            )
        for var, field in (
            ("FTC_SLICE_INDEX", "jobset.sigs.k8s.io/job-index"),
            ("JOB_COMPLETION_INDEX", "batch.kubernetes.io/job-completion-index"),
        ):
            got = (
                env.get(var, {})
                .get("valueFrom", {})
                .get("fieldRef", {})
                .get("fieldPath", "")
            )
            if f"['{field}']" not in got:
                raise BackendError(
                    f"JobSet {name}: env {var} must come from the downward-API "
                    f"annotation {field!r}, got {got!r}"
                )

    # -- Kueue + JobSet operator simulation ------------------------------------

    def _jobsets(self) -> list[dict[str, Any]]:
        return [
            obj for (path, _), obj in self.objects.items()
            if path.endswith(f"/{JOBSET_PLURAL}")
        ]

    @staticmethod
    def _is_terminal(obj: dict[str, Any]) -> bool:
        return any(
            c.get("status") == "True" and c.get("type") in ("Completed", "Failed")
            for c in obj.get("status", {}).get("conditions", [])
        )

    @staticmethod
    def _chips(obj: dict[str, Any]) -> int:
        return int(obj["metadata"].get("labels", {}).get("ftc/chips", 0) or 0)

    def _pods_path(self, namespace: str) -> str:
        return f"/api/v1/namespaces/{namespace}/pods"

    def kueue_tick(self) -> None:
        """One reconcile pass of the fake Kueue + JobSet operators: admit
        suspended JobSets FIFO within the chip quota, then materialise pods
        and active status for every admitted, non-terminal JobSet."""
        jobsets = sorted(
            self._jobsets(), key=lambda o: o["metadata"].get("creationTimestamp", 0)
        )
        used = sum(
            self._chips(o) for o in jobsets
            if not o["spec"].get("suspend") and not self._is_terminal(o)
        )
        for obj in jobsets:
            if not obj["spec"].get("suspend") or self._is_terminal(obj):
                continue
            chips = self._chips(obj)
            if self.quota_chips is not None and used + chips > self.quota_chips:
                continue  # FIFO with borrowing disabled: later jobs may still fit
            obj["spec"]["suspend"] = False
            used += chips
        for obj in jobsets:
            if obj["spec"].get("suspend") or self._is_terminal(obj):
                continue
            self._materialise_pods(obj)

    def _materialise_pods(self, obj: dict[str, Any]) -> None:
        name = obj["metadata"]["name"]
        namespace = obj["metadata"].get("namespace", "default")
        status = obj.setdefault("status", {})
        rj_status = []
        for rj in obj["spec"]["replicatedJobs"]:
            hosts = rj["template"]["spec"].get("parallelism", 1)
            replicas = rj.get("replicas", 1)
            for slice_idx in range(replicas):
                for host_idx in range(hosts):
                    pod_name = f"{name}-{rj['name']}-{slice_idx}-{host_idx}"
                    key = (self._pods_path(namespace), pod_name)
                    if key in self.objects:
                        continue
                    self.objects[key] = {
                        "metadata": {
                            "name": pod_name,
                            "namespace": namespace,
                            "creationTimestamp": time.time(),
                            "labels": {
                                "jobset.sigs.k8s.io/jobset-name": name,
                                "jobset.sigs.k8s.io/job-index": str(slice_idx),
                                "batch.kubernetes.io/job-completion-index": str(host_idx),
                            },
                        },
                        "status": {"phase": "Running"},
                    }
                    self.pod_logs.setdefault(pod_name, []).append(
                        f"{pod_name}: training started"
                    )
            rj_status.append({"name": rj["name"], "active": replicas * hosts})
        status["replicatedJobsStatus"] = rj_status

    def finish_jobset(
        self, name: str, *, failed: bool = False, message: str = ""
    ) -> None:
        """Drive a JobSet to a terminal condition; succeeded pods are removed
        (the kubelet reaps them), failed pods stay for forensics."""
        for obj in self._jobsets():
            if obj["metadata"]["name"] != name:
                continue
            status = obj.setdefault("status", {})
            status["replicatedJobsStatus"] = []
            status.setdefault("conditions", []).append(
                {
                    "type": "Failed" if failed else "Completed",
                    "status": "True",
                    "message": message,
                }
            )
            if not failed:
                namespace = obj["metadata"].get("namespace", "default")
                for key in [
                    k for k in self.objects
                    if k[0] == self._pods_path(namespace)
                    and self.objects[k]["metadata"]["labels"].get(
                        "jobset.sigs.k8s.io/jobset-name"
                    ) == name
                ]:
                    del self.objects[key]
            return
        raise BackendError(f"unknown JobSet {name!r}")

    @staticmethod
    def _name(body: dict[str, Any]) -> str:
        return body["metadata"]["name"]

    async def create(self, api_path: str, body: dict[str, Any]) -> dict[str, Any]:
        key = (api_path, self._name(body))
        if key in self.objects:
            raise BackendError(f"{key} already exists")
        if body.get("kind") == "JobSet":
            self._validate_jobset(body)
        body.setdefault("metadata", {})["creationTimestamp"] = time.time()
        self.objects[key] = body
        return body

    async def get(self, api_path: str, name: str) -> dict[str, Any] | None:
        return self.objects.get((api_path, name))

    async def list(self, api_path: str, label_selector: str = "") -> list[dict[str, Any]]:
        out = []
        for (path, _), obj in self.objects.items():
            if path != api_path:
                continue
            if label_selector:
                want = dict(
                    part.split("=", 1) for part in label_selector.split(",")
                )
                labels = obj["metadata"].get("labels", {})
                if not all(labels.get(k) == v for k, v in want.items()):
                    continue
            out.append(obj)
        return out

    async def delete(self, api_path: str, name: str) -> bool:
        return self.objects.pop((api_path, name), None) is not None

    async def pod_log_lines(
        self, namespace: str, pod: str, *, container: str, follow: bool,
        tail_lines: int | None,
    ) -> AsyncIterator[str]:
        lines = self.pod_logs.get(pod, [])
        if tail_lines is not None:
            lines = lines[-tail_lines:]

        async def aiter() -> AsyncIterator[str]:
            for line in lines:
                yield line

        return aiter()


class AiohttpKubeClient(KubeClient):
    """Direct Kubernetes API access over aiohttp with in-cluster
    service-account auth (token + CA from the standard mount) — no SDK.

    Replaces the reference's import-time kubeconfig load
    (``app/utils/kube_config.py:9-19``) with lazy, injected construction.
    """

    SA_DIR = Path("/var/run/secrets/kubernetes.io/serviceaccount")

    #: re-read the projected SA token at this cadence — bound tokens expire
    #: (~1h) and the kubelet rotates them on disk
    TOKEN_TTL_S = 300.0

    def __init__(self, base_url: str | None = None, token: str | None = None):
        import os

        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise BackendError("not running in-cluster and no base_url given")
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        self._static_token = token
        self._token = token
        self._token_read_at = 0.0
        self._session = None

    async def _headers(self) -> dict[str, str]:
        now = time.monotonic()
        if self._static_token is None and (
            self._token is None or now - self._token_read_at > self.TOKEN_TTL_S
        ):
            token_file = self.SA_DIR / "token"

            def read_token() -> str | None:
                # projected SA token, rotated on disk by the kubelet: a
                # small file, but kubelet IO stalls have been observed in
                # the seconds range — never pay them on the event loop
                if token_file.exists():
                    return token_file.read_text().strip()
                return None

            token = await asyncio.to_thread(read_token)
            if token is not None:
                self._token = token
                self._token_read_at = now
        return {"Authorization": f"Bearer {self._token}"} if self._token else {}

    def _get_session(self):
        import ssl

        import aiohttp

        if self._session is None:
            ca = self.SA_DIR / "ca.crt"
            ctx = ssl.create_default_context(
                cafile=str(ca) if ca.exists() else None
            )
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(ssl=ctx),
            )
        return self._session

    #: transient apiserver statuses worth retrying (rate limit + 5xx); 401
    #: additionally forces a token re-read (a rotated SA token mid-flight)
    RETRY_STATUSES = frozenset({429, 500, 502, 503, 504})
    MAX_TRIES = 4
    BASE_DELAY_S = 0.25

    async def _request(
        self,
        method: str,
        url: str,
        *,
        params: dict[str, Any] | None = None,
        json_body: dict[str, Any] | None = None,
    ) -> tuple[int, Any]:
        """One apiserver call with bounded retry/backoff.

        Retries 429 (honoring ``Retry-After``), 5xx, and transport errors
        with exponential backoff; a 401 re-reads the projected SA token once
        per attempt (kubelet rotates it on disk).  Terminal statuses (2xx,
        404, 409, 403...) return ``(status, parsed-body)`` for the caller to
        interpret.  The reference leaned on the official SDKs for this
        (``app/utils/kube_config.py:22-23``); the hand-rolled client must
        carry its own retry discipline.
        """
        import aiohttp

        s = self._get_session()
        delay = self.BASE_DELAY_S
        last_err: Exception | None = None
        for attempt in range(self.MAX_TRIES):
            try:
                async with s.request(
                    method, url, params=params, json=json_body,
                    headers=await self._headers(),
                ) as resp:
                    retriable = resp.status in self.RETRY_STATUSES or (
                        resp.status == 401 and self._static_token is None
                    )
                    if not retriable or attempt == self.MAX_TRIES - 1:
                        ctype = resp.content_type or ""
                        body = (
                            await resp.json() if "json" in ctype
                            else await resp.text()
                        )
                        return resp.status, body
                    if resp.status == 401:
                        self._token_read_at = 0.0  # force token re-read
                    retry_after = resp.headers.get("Retry-After")
                    if retry_after:
                        try:
                            delay = max(delay, float(retry_after))
                        except ValueError:
                            pass
                    last_err = BackendError(
                        f"{method} {url} -> {resp.status} (attempt {attempt + 1})"
                    )
            except aiohttp.ClientError as e:
                if attempt == self.MAX_TRIES - 1:
                    raise BackendError(f"{method} {url} failed: {e}") from e
                last_err = e
            await asyncio.sleep(delay)
            delay *= 2
        raise BackendError(f"{method} {url} failed after retries: {last_err}")

    async def create(self, api_path: str, body: dict[str, Any]) -> dict[str, Any]:
        url = f"{self.base_url}{api_path}"
        status, payload = await self._request("POST", url, json_body=body)
        if status == 409:
            # AlreadyExists — idempotent create: a resubmit after a crashed
            # ack must not fail the job; adopt the live object instead
            name = body.get("metadata", {}).get("name", "")
            existing = await self.get(api_path, name) if name else None
            if existing is not None:
                return existing
        if status >= 300:
            raise BackendError(f"create failed ({status}): {payload}")
        return payload

    async def get(self, api_path: str, name: str) -> dict[str, Any] | None:
        status, payload = await self._request(
            "GET", f"{self.base_url}{api_path}/{name}"
        )
        if status == 404:
            return None
        if status >= 300:
            raise BackendError(f"get failed ({status}): {payload}")
        return payload

    async def list(self, api_path: str, label_selector: str = "") -> list[dict[str, Any]]:
        params = {"labelSelector": label_selector} if label_selector else None
        status, payload = await self._request(
            "GET", f"{self.base_url}{api_path}", params=params
        )
        if status >= 300:
            raise BackendError(f"list failed ({status}): {payload}")
        return payload.get("items", [])

    async def delete(self, api_path: str, name: str) -> bool:
        status, _ = await self._request(
            "DELETE", f"{self.base_url}{api_path}/{name}"
        )
        return status < 300

    async def pod_log_lines(
        self, namespace: str, pod: str, *, container: str, follow: bool,
        tail_lines: int | None,
    ) -> AsyncIterator[str]:
        s = self._get_session()
        params: dict[str, Any] = {"container": container}
        if follow:
            params["follow"] = "true"
        if tail_lines is not None:
            params["tailLines"] = str(tail_lines)
        url = f"{self.base_url}/api/v1/namespaces/{namespace}/pods/{pod}/log"

        async def aiter() -> AsyncIterator[str]:
            async with s.get(url, params=params, timeout=None, headers=await self._headers()) as resp:
                if resp.status >= 300:
                    raise BackendError(f"pod logs failed ({resp.status})")
                async for raw in resp.content:
                    yield raw.decode(errors="replace").rstrip("\n")

        return aiter()

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


# ---------------------------------------------------------------------------
# The backend
# ---------------------------------------------------------------------------


def map_jobset_state(obj: dict[str, Any]) -> tuple[BackendJobState, str]:
    """JobSet status → backend state (replaces the Kubeflow condition mapping,
    ``app/schemas/kubeflow_schemas.py:61-85``)."""
    spec = obj.get("spec", {})
    status = obj.get("status", {})
    conditions = status.get("conditions", [])
    for cond in conditions:
        if cond.get("status") != "True":
            continue
        if cond.get("type") == "Completed":
            return BackendJobState.SUCCEEDED, cond.get("message", "")
        if cond.get("type") == "Failed":
            return BackendJobState.FAILED, cond.get("message", "")
    restarts = int(status.get("restarts", 0) or 0)
    if restarts > 0:
        return BackendJobState.RESTARTING, f"restarts={restarts}"
    if spec.get("suspend"):
        return BackendJobState.SUSPENDED, "awaiting quota"
    if any(rj.get("active") for rj in status.get("replicatedJobsStatus", [])):
        return BackendJobState.RUNNING, ""
    return BackendJobState.CREATED, ""


class K8sJobSetBackend(TrainingBackend):
    """Cluster execution via JobSet CRs, Kueue-scheduled."""

    def __init__(
        self,
        catalog: DeviceCatalog,
        settings: Any,
        *,
        client: KubeClient | None = None,
        image: str = "finetune-controller-tpu:latest",
        object_store_env: dict[str, str] | None = None,
    ):
        self.catalog = catalog
        self.settings = settings
        self.namespace = settings.namespace
        self.client = client or AiohttpKubeClient()
        self.image = image
        self.object_store_env = object_store_env or {}

    # API paths
    @property
    def _jobsets_path(self) -> str:
        return (
            f"/apis/{JOBSET_GROUP}/{JOBSET_VERSION}"
            f"/namespaces/{self.namespace}/{JOBSET_PLURAL}"
        )

    @property
    def _configmaps_path(self) -> str:
        return f"/api/v1/namespaces/{self.namespace}/configmaps"

    async def submit(
        self,
        job: JobInput,
        spec: BaseFineTuneJob,
        flavor: DeviceFlavor,
        *,
        dataset_uri: str | None,
        artifacts_uri: str,
    ) -> None:
        from ...sched.queues import DEFAULT_QUEUE, PRIORITY_CLASSES, parse_priority

        try:
            non_default_priority = (
                parse_priority(job.priority) != PRIORITY_CLASSES["normal"]
            )
        except ValueError:
            non_default_priority = True  # unparseable: certainly not default
        if job.queue != DEFAULT_QUEUE or non_default_priority:
            # tenant queue/priority are the in-repo fair-share scheduler's
            # vocabulary (docs/scheduling.md); on k8s, admission belongs to
            # Kueue (LocalQueue label from the flavor + WorkloadPriorityClass
            # CRs).  Say so loudly rather than silently dropping the intent.
            logger.warning(
                "job %s: queue=%r priority=%r are ignored on the k8s "
                "backend — admission is Kueue's (flavor LocalQueue %r); "
                "configure Kueue WorkloadPriorityClass for priorities",
                job.job_id, job.queue, job.priority, flavor.queue,
            )
        trainer_spec = render_trainer_spec(
            job, spec, flavor, dataset_uri=dataset_uri
        )
        cm = render_spec_configmap(job, trainer_spec, self.namespace)
        jobset = render_jobset(
            job, spec, flavor,
            namespace=self.namespace,
            image=self.image,
            dataset_uri=dataset_uri,
            artifacts_uri=artifacts_uri,
            sync_interval_s=self.settings.artifact_sync_interval_s,
            object_store_env=self.object_store_env,
        )
        await self.client.create(self._configmaps_path, cm)
        try:
            await self.client.create(self._jobsets_path, jobset)
        except Exception:
            await self.client.delete(self._configmaps_path, cm["metadata"]["name"])
            raise

    def _report(self, obj: dict[str, Any]) -> BackendJobReport:
        state, message = map_jobset_state(obj)
        status = obj.get("status", {})
        start = _parse_k8s_time(status.get("startTime"))
        completion = _parse_k8s_time(status.get("completionTime"))
        if completion is None and state in BackendJobState.stopped_states():
            # JobSet's own status carries no completionTime; the terminal
            # condition's transition time is the ground truth
            for cond in status.get("conditions", []):
                if cond.get("type") in ("Completed", "Failed") and cond.get(
                    "status"
                ) == "True":
                    completion = _parse_k8s_time(cond.get("lastTransitionTime"))
        return BackendJobReport(
            job_id=obj["metadata"]["name"],
            state=state,
            start_time=start,
            completion_time=completion,
            message=message,
            metadata={"restarts": int(status.get("restarts", 0) or 0)},
        )

    async def list_jobs(self) -> list[BackendJobReport]:
        objs = await self.client.list(self._jobsets_path, f"app={APP_LABEL}")
        return [self._report(o) for o in objs]

    async def get_job(self, job_id: str) -> BackendJobReport | None:
        obj = await self.client.get(self._jobsets_path, job_id)
        return self._report(obj) if obj else None

    async def delete_job(self, job_id: str, *,
                         forget_reservations: bool = False) -> bool:
        # forget_reservations is part of the backend contract (base.py) but
        # moot here: Kueue owns admission, this backend holds no in-process
        # scheduler reservations
        await self.client.delete(self._configmaps_path, f"{job_id}-spec")
        return await self.client.delete(self._jobsets_path, job_id)

    async def queue_snapshot(self) -> list[str]:
        """Suspended jobsets in creation order — the reference's Kubeflow
        fallback queue (``kueue_helpers.py:84-122``; the Kueue Workload API
        would be the richer source, same as the reference's primary path)."""
        objs = await self.client.list(self._jobsets_path, f"app={APP_LABEL}")
        suspended = [
            o for o in objs
            if map_jobset_state(o)[0] is BackendJobState.SUSPENDED
        ]
        suspended.sort(key=lambda o: o["metadata"].get("creationTimestamp", 0))
        return [o["metadata"]["name"] for o in suspended]

    async def _rank0_pod_name(self, job_id: str) -> str:
        """Resolve the rank-0 pod by labels — indexed-Job pods carry a random
        name suffix, so the deterministic ``{job}-0`` string is only the pod
        *hostname*, never its name. Peer-aware replacement for the
        reference's master-pod lookup (``stream_logger.py:142-144``)."""
        selector = (
            f"jobset.sigs.k8s.io/jobset-name={job_id},"
            "batch.kubernetes.io/job-completion-index=0,"
            "jobset.sigs.k8s.io/job-index=0"
        )
        pods = await self.client.list(
            f"/api/v1/namespaces/{self.namespace}/pods", selector
        )
        if not pods:
            raise BackendError(f"no rank-0 pod found for {job_id!r}")
        # newest pod wins (restarts leave terminated predecessors around)
        pods.sort(
            key=lambda p: str(p["metadata"].get("creationTimestamp", "")),
            reverse=True,
        )
        return pods[0]["metadata"]["name"]

    async def read_logs(
        self,
        job_id: str,
        *,
        follow: bool = False,
        last_lines: int | None = None,
    ) -> AsyncIterator[str]:
        pod = await self._rank0_pod_name(job_id)
        return await self.client.pod_log_lines(
            self.namespace, pod,
            container="trainer", follow=follow, tail_lines=last_lines,
        )

    async def close(self) -> None:
        await self.client.close()
