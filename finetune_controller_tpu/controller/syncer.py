"""Shared artifact-sync core used by the local backend's sidecar task and the
pod-side storage CLI — one implementation of the ``aws s3 sync`` semantics the
reference delegated to its sidecar container
(``app/jobs/kubeflow/PyTorchJobDeployer.py:121-168``): glob-pattern selection
(``store_asset_patterns``, ``finetuning.py:94-97``) + (mtime, size) change
detection so unchanged bytes are never re-uploaded.
"""

from __future__ import annotations

from pathlib import Path

from .objectstore import ObjectStore


def matched_files(src_dir: Path, patterns: list[str] | None) -> list[Path]:
    if not src_dir.is_dir():
        return []
    if not patterns:
        return sorted(p for p in src_dir.rglob("*") if p.is_file())
    out: set[Path] = set()
    for pattern in patterns:
        out.update(p for p in src_dir.glob(pattern) if p.is_file())
    return sorted(out)


async def sync_dir_to_store(
    store: ObjectStore,
    src_dir: Path,
    dest_uri: str,
    *,
    patterns: list[str] | None = None,
    synced: dict[str, tuple[float, int]] | None = None,
) -> int:
    """Upload changed files matching ``patterns`` under ``src_dir`` to
    ``dest_uri``; mutates ``synced`` (path → (mtime, size)) for change
    detection across calls. Returns files uploaded."""
    synced = synced if synced is not None else {}
    n = 0
    for path in matched_files(src_dir, patterns):
        rel = path.relative_to(src_dir).as_posix()
        st = path.stat()
        stamp = (st.st_mtime, st.st_size)
        if synced.get(rel) == stamp:
            continue
        await store.put_file(f"{dest_uri}/{rel}", path)
        synced[rel] = stamp
        n += 1
    return n
