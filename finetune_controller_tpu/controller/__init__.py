"""Control plane for the TPU fine-tuning framework.

This package provides the capability surface of the reference control plane
(``acceleratedscience/finetune-controller`` — FastAPI app + Mongo + S3 + Kubeflow/
Kueue, see SURVEY.md §1) re-designed for a TPU-native stack:

- jobs are **our in-repo JAX trainer** (``finetune_controller_tpu.train``) on TPU
  slice topologies, not arbitrary user CUDA containers;
- state lives in an async in-repo document store (reference: MongoDB via motor,
  ``app/database/db.py``);
- artifacts/datasets move through a pluggable object store (reference: S3 via
  aioboto3, ``app/utils/S3Handler.py``);
- scheduling/quota is an in-repo gang scheduler speaking TPU slice flavors
  (reference: external Kueue CRDs, ``crds/kueue/*``);
- everything is lazy and injectable — no import-time cluster I/O (the
  reference's biggest testability wart, ``app/core/config.py:59-90``).
"""
