"""Built-in example job specs (reference: ``app/models/examples/mnist.py`` —
SURVEY.md §2 component 4's example half).

The reference ships one CPU-runnable example (MNIST with ``no_cuda``,
``mnist.py:28-30``) as its designed smoke workload; ours is a TinyLlama LoRA
SFT spec runnable on a CPU mesh (BASELINE config #1) plus the larger model
family specs from BASELINE.md.

Each module is also executable as a self-test by convention (reference:
``mnist.py:102-107``, ``docs/setup_models.md:419-430``):
``python -m finetune_controller_tpu.controller.examples``.
"""

from __future__ import annotations

from pydantic import Field

from .specs import (
    BaseFineTuneJob,
    TrainingArguments,
    TrainingDataset,
    TrainingFramework,
    TrainingTask,
)


class LoRASFTArguments(TrainingArguments):
    """Hyperparameters surfaced on the submission form — the Field metadata IS
    the UI (reference pattern: ``mnist.py:17-38``)."""

    learning_rate: float = Field(
        2e-4, gt=0, le=1.0, description="Peak AdamW learning rate"
    )
    total_steps: int = Field(100, ge=1, le=1_000_000, description="Optimizer steps")
    warmup_steps: int = Field(10, ge=0, description="Linear warmup steps")
    batch_size: int = Field(8, ge=1, le=4096, description="Global batch size (rows)")
    seq_len: int = Field(512, ge=16, le=1_048_576, description="Sequence length")
    lora_rank: int = Field(16, ge=1, le=256, description="LoRA adapter rank")
    weight_decay: float = Field(0.0, ge=0, description="AdamW weight decay")
    seed: int = Field(0, description="PRNG seed")
    profile_steps: int = Field(
        0, ge=0, le=100,
        description="Capture a jax.profiler trace for N steps (0 = off); the "
                    "trace ships with the job artifacts under profile/",
    )
    eval_every: int = Field(
        0, ge=0,
        description="Evaluate a held-out split every N steps (0 = off); adds "
                    "eval_loss/eval_accuracy columns to the metrics",
    )
    eval_steps: int = Field(
        8, ge=1, le=1024, description="Batches averaged per evaluation pass"
    )
    grad_accum_steps: int = Field(
        1, ge=1, le=1024,
        description="Microbatches accumulated per optimizer step (batch_size "
                    "must divide by it) — for batches whose activations "
                    "exceed HBM",
    )
    log_every: int = Field(
        10, ge=1, description="Metrics-row cadence (optimizer steps)"
    )
    checkpoint_every: int = Field(
        100, ge=1,
        description="Checkpoint cadence (optimizer steps) — also the resume "
                    "granularity after preemption or a supervised retry",
    )


class DPOArguments(LoRASFTArguments):
    """Hyperparameters of a DPO job (docs/preference.md): the SFT knobs plus
    the preference-objective β."""

    beta: float = Field(
        0.1, gt=0, le=100,
        description="DPO inverse-temperature β — how strongly the implicit "
                    "KL pins the policy to the frozen reference (the "
                    "adapter-disabled base)",
    )


class RLHFArguments(DPOArguments):
    """DPO knobs plus the actor/learner rollout loop's
    (``prefs/learner.py::RolloutConfig``; ``FTC_RLHF_*`` env vars override
    per pod)."""

    rollout_pairs_per_round: int = Field(
        16, ge=1, le=4096,
        description="Prompts the actor decodes (2 candidates each) per "
                    "generation round",
    )
    rollout_buffer_capacity: int = Field(
        256, ge=1, le=1_000_000,
        description="Rollout buffer size (bounded; oldest pairs drop first)",
    )
    rollout_min_fill: int = Field(
        16, ge=1, le=1_000_000,
        description="Pairs the buffer must hold before the learner samples "
                    "a batch",
    )
    rollout_staleness_checkpoints: int = Field(
        2, ge=1, le=1000,
        description="Staleness cap: drop pairs generated more than this "
                    "many checkpoints behind the newest commit",
    )
    rollout_temperature: float = Field(
        0.8, ge=0, le=10,
        description="Actor sampling temperature (two candidates per prompt)",
    )
    rollout_top_k: int = Field(
        0, ge=0, le=100_000,
        description="Actor top-k sampling cutoff (0 = full distribution)",
    )
    rollout_max_new_tokens: int = Field(
        16, ge=1, le=4096, description="Completion length per rollout"
    )
    rollout_slots: int = Field(
        4, ge=1, le=256,
        description="Decode lanes of the actor's serve engine",
    )
    rollout_workers: int = Field(
        0, ge=0, le=64,
        description="Remote rollout actor processes (0 = the in-process "
                    "actor/learner gang; > 0 selects the disaggregated "
                    "data plane — docs/preference.md §Disaggregated "
                    "rollouts)",
    )
    rollout_reward_host: str = Field(
        "", description="Served reward model host the remote actors score "
                        "against (empty = programmatic increment reward)",
    )
    rollout_reward_port: int = Field(
        0, ge=0, le=65535,
        description="Served reward model port (0 = programmatic reward)",
    )


class RewardModelArguments(DPOArguments):
    """Hyperparameters of a ``task: reward`` job: the DPO data-path knobs
    train a Bradley–Terry scalar head on the policy trunk
    (``prefs/reward_trainer.py``); β is ignored by the objective."""


class TinyLlamaLoRA(BaseFineTuneJob):
    """BASELINE config #1 — the CPU-runnable smoke workload and CI workhorse."""

    model_name = "tinyllama-1.1b-lora"
    description = "TinyLlama-1.1B LoRA SFT (single host; CPU-runnable smoke config)"
    task = TrainingTask.CAUSAL_LM
    framework = TrainingFramework.JAX_LORA
    model_preset = "tinyllama-1.1b"
    default_device = "cpu-test"
    promotion_path = "models/tinyllama"

    training_arguments: LoRASFTArguments


class Llama3_8B_LoRA(BaseFineTuneJob):
    """BASELINE config #2 — the v5e-16 FSDP north star."""

    model_name = "llama3-8b-lora"
    description = "Llama-3 8B LoRA SFT, FSDP over a v5e-16 slice"
    task = TrainingTask.CAUSAL_LM
    framework = TrainingFramework.JAX_LORA
    model_preset = "llama3-8b"
    default_device = "v5e-16"
    promotion_path = "models/llama3-8b"

    training_arguments: LoRASFTArguments


class Llama32_3B_LoRA(BaseFineTuneJob):
    """Llama-3.2 small family (tied embeddings + llama3 RoPE scaling to
    128k positions) — rope-scaling numerics verified against transformers
    (tests/test_hf_import.py). Measured MFU 0.76 bf16 LoRA on one v5e chip
    (BASELINE.md), the best single-chip shapes in the catalog."""

    model_name = "llama3.2-3b-lora"
    description = "Llama-3.2 3B LoRA SFT (llama3 RoPE scaling, 128k positions)"
    task = TrainingTask.CAUSAL_LM
    framework = TrainingFramework.JAX_LORA
    model_preset = "llama3.2-3b"
    default_device = "v5e-4"
    promotion_path = "models/llama3.2-3b"

    training_arguments: LoRASFTArguments


class Gemma7B_LoRA(BaseFineTuneJob):
    """Gemma family (GeGLU, tied head, head_dim 256) — numerics verified
    against transformers' GemmaForCausalLM (tests/test_hf_import.py)."""

    model_name = "gemma-7b-lora"
    description = "Gemma-7B LoRA SFT on TPU"
    task = TrainingTask.CAUSAL_LM
    framework = TrainingFramework.JAX_LORA
    model_preset = "gemma-7b"
    default_device = "v5e-8"
    promotion_path = "models/gemma-7b"

    training_arguments: LoRASFTArguments


class Qwen2_7B_LoRA(BaseFineTuneJob):
    """Qwen-2 family (q/k/v projection biases) — numerics verified against
    transformers' Qwen2ForCausalLM (tests/test_hf_import.py)."""

    model_name = "qwen2-7b-lora"
    description = "Qwen2-7B LoRA SFT on TPU"
    task = TrainingTask.CAUSAL_LM
    framework = TrainingFramework.JAX_LORA
    model_preset = "qwen2-7b"
    default_device = "v5e-8"
    promotion_path = "models/qwen2-7b"

    training_arguments: LoRASFTArguments


class Mistral7B_LongContext_LoRA(BaseFineTuneJob):
    """Long-context SFT: the sequence dimension sharded over an ``sp`` ring
    (``parallel/ring.py``); 32k tokens land as 8k per chip with sp=4 on a
    v5e-8. The 32k preset raises the RoPE base to 1e6 (the Mistral v0.2+
    recipe) so positions past 8k stay in the trained frequency range.
    Ulysses head-sharding (``attention_impl="ulysses"``) is the alternative
    when sp divides the model's KV heads — see docs/performance.md."""

    model_name = "mistral-7b-longctx-lora"
    description = "Mistral-7B 32k-context LoRA SFT (ring attention over sp)"
    task = TrainingTask.CAUSAL_LM
    framework = TrainingFramework.JAX_LORA
    model_preset = "mistral-7b-32k"
    default_device = "v5e-8"
    promotion_path = "models/mistral-7b"
    mesh_policy = {"sp": 4, "fsdp": -1}

    training_arguments: LoRASFTArguments


class Mistral7B_QLoRA(BaseFineTuneJob):
    """BASELINE config #3 — int4-quantized base weights, LoRA deltas."""

    model_name = "mistral-7b-qlora"
    description = "Mistral-7B QLoRA (int4 base weights) on TPU"
    task = TrainingTask.CAUSAL_LM
    framework = TrainingFramework.JAX_QLORA
    model_preset = "mistral-7b"
    default_device = "v5e-8"
    promotion_path = "models/mistral-7b"

    training_arguments: LoRASFTArguments


class Mixtral8x7B_MoE_LoRA(BaseFineTuneJob):
    """BASELINE config #4 — MoE LoRA with expert parallelism on v5p-64.

    The mesh policy puts the 8 experts on the ``ep`` axis (expert matmuls stay
    chip-local, token exchange is an all-to-all over ICI) and FSDP-shards the
    rest of the slice.
    """

    model_name = "mixtral-8x7b-moe-lora"
    description = "Mixtral 8x7B MoE LoRA, expert-parallel over a v5p-64 slice"
    task = TrainingTask.CAUSAL_LM
    framework = TrainingFramework.JAX_LORA
    model_preset = "mixtral-8x7b"
    default_device = "v5p-64"
    promotion_path = "models/mixtral-8x7b"
    mesh_policy = {"ep": 8, "fsdp": -1}

    training_arguments: LoRASFTArguments


class TinyMoETestLoRA(BaseFineTuneJob):
    """Milliseconds-scale MoE spec — proves a submitted job trains with
    ``ep > 1`` on the virtual CPU mesh (the Mixtral path's e2e smoke)."""

    model_name = "tiny-moe-test-lora"
    description = "2-layer 4-expert test model; expert-parallel e2e smoke spec"
    model_preset = "tiny-moe-test"
    default_device = "cpu-test-2"  # ep=2 needs 2 chips even for the smoke run
    promotion_path = "models/tiny-moe-test"
    mesh_policy = {"ep": 2, "fsdp": -1}
    dataset = TrainingDataset(required=False, description="optional jsonl")

    training_arguments: LoRASFTArguments


class Llava15LoRA(BaseFineTuneJob):
    """BASELINE config #5 — LLaVA-1.5 multimodal SFT (ViT → projector →
    Llama decoder; the projector trains alongside the LoRA adapters)."""

    model_name = "llava-1.5-lora"
    description = "LLaVA-1.5 7B multimodal SFT (LoRA + projector) on TPU"
    task = TrainingTask.MULTIMODAL
    framework = TrainingFramework.JAX_LORA
    model_preset = "llava-1.5-7b"
    default_device = "v5e-16"
    promotion_path = "models/llava-1.5"

    training_arguments: LoRASFTArguments


class TinyMMTestLoRA(BaseFineTuneJob):
    """Milliseconds-scale multimodal spec for the e2e lifecycle tests."""

    model_name = "tiny-mm-test-lora"
    description = "2-layer ViT + 2-layer decoder; multimodal e2e smoke spec"
    task = TrainingTask.MULTIMODAL
    model_preset = "tiny-mm-test"
    default_device = "cpu-test"
    promotion_path = "models/tiny-mm-test"
    dataset = TrainingDataset(required=False, description="optional jsonl")

    training_arguments: LoRASFTArguments


class TinyLlamaDPO(BaseFineTuneJob):
    """TinyLlama preference tuning — the CPU-runnable DPO config
    (docs/preference.md)."""

    model_name = "tinyllama-1.1b-dpo"
    description = "TinyLlama-1.1B DPO over preference pairs (LoRA policy, " \
                  "adapter-disabled reference)"
    task = TrainingTask.DPO
    framework = TrainingFramework.JAX_LORA
    model_preset = "tinyllama-1.1b"
    default_device = "cpu-test"
    promotion_path = "models/tinyllama"
    dataset = TrainingDataset(
        required=False,
        description="preference jsonl: {prompt, chosen, rejected} rows "
                    "(or *_tokens variants); omitted = seeded synthetic pairs",
    )

    training_arguments: DPOArguments


class Llama3_8B_DPO(BaseFineTuneJob):
    """Llama-3 8B DPO on the v5e-16 FSDP slice — the production-shaped
    preference-tuning config."""

    model_name = "llama3-8b-dpo"
    description = "Llama-3 8B DPO, FSDP over a v5e-16 slice"
    task = TrainingTask.DPO
    framework = TrainingFramework.JAX_LORA
    model_preset = "llama3-8b"
    default_device = "v5e-16"
    promotion_path = "models/llama3-8b"
    dataset = TrainingDataset(
        required=False,
        description="preference jsonl: {prompt, chosen, rejected} rows",
    )

    training_arguments: DPOArguments


class TinyDPOTest(BaseFineTuneJob):
    """Milliseconds-scale DPO spec for the e2e lifecycle tests."""

    model_name = "tiny-dpo-test"
    description = "2-layer test model; DPO e2e smoke spec"
    task = TrainingTask.DPO
    model_preset = "tiny-test"
    default_device = "cpu-test"
    promotion_path = "models/tiny-test"
    dataset = TrainingDataset(required=False, description="optional jsonl")

    training_arguments: DPOArguments


class TinyRLHFTest(BaseFineTuneJob):
    """RLHF-lite smoke spec: the actor (serve engine over the latest
    committed checkpoint) and the DPO learner run as an inseparable gang —
    ``atomic_gang`` makes the scheduler admit the 2 slices all-or-nothing
    and never shrink them (a partial gang cannot run)."""

    model_name = "tiny-rlhf-test"
    description = "2-layer test model; actor/learner RLHF-lite gang smoke spec"
    task = TrainingTask.RLHF
    model_preset = "tiny-test"
    default_device = "cpu-test"
    default_num_slices = 2  # learner slice + actor slice, admitted as a gang
    atomic_gang = True
    promotion_path = "models/tiny-test"
    dataset = TrainingDataset(required=False, description="optional jsonl")

    training_arguments: RLHFArguments


class TinyRewardTest(BaseFineTuneJob):
    """Reward-model smoke spec: Bradley–Terry head + LoRA trunk trained on
    the synthetic preference pairs; promotable and servable as the rlhf
    actors' scoring endpoint (``reward_score`` RPC)."""

    model_name = "tiny-reward-test"
    description = "2-layer test model; Bradley–Terry reward-model smoke spec"
    task = TrainingTask.REWARD
    model_preset = "tiny-test"
    default_device = "cpu-test"
    promotion_path = "models/tiny-test"
    dataset = TrainingDataset(
        required=False,
        description="preference jsonl: {prompt, chosen, rejected} rows "
                    "(omitted = seeded synthetic pairs)",
    )

    training_arguments: RewardModelArguments


class TinyTestLoRA(BaseFineTuneJob):
    """Milliseconds-scale spec used by the e2e lifecycle tests."""

    model_name = "tiny-test-lora"
    description = "2-layer test model; e2e lifecycle smoke spec"
    model_preset = "tiny-test"
    default_device = "cpu-test"
    promotion_path = "models/tiny-test"
    # smoke spec trains on synthetic data when no dataset is provided
    dataset = TrainingDataset(required=False, description="optional jsonl")

    training_arguments: LoRASFTArguments


BUILTIN_JOB_SPECS: list[type[BaseFineTuneJob]] = [
    TinyLlamaLoRA,
    Llama32_3B_LoRA,
    Llama3_8B_LoRA,
    Gemma7B_LoRA,
    Qwen2_7B_LoRA,
    Mistral7B_LongContext_LoRA,
    Mistral7B_QLoRA,
    Mixtral8x7B_MoE_LoRA,
    Llava15LoRA,
    TinyLlamaDPO,
    Llama3_8B_DPO,
    TinyTestLoRA,
    TinyMoETestLoRA,
    TinyMMTestLoRA,
    TinyDPOTest,
    TinyRLHFTest,
    TinyRewardTest,
]


if __name__ == "__main__":
    # executable smoke-validation, the model-author convention
    import typing as _typing

    for cls in BUILTIN_JOB_SPECS:
        args_cls = _typing.get_type_hints(cls)["training_arguments"]
        job = cls(training_arguments=args_cls())
        spec = job.build_trainer_spec("smoke-1", "/tmp/artifacts")
        assert spec["model"]["preset"] == cls.model_preset
        print(f"{cls.model_name}: ok ({spec['training']})")
