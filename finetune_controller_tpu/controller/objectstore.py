"""Pluggable object store for datasets and training artifacts.

Capability parity with the reference's ``S3Handler`` (``app/utils/S3Handler.py``,
443 LoC — SURVEY.md §2 component 9): dataset upload (bytes / file / async
stream), the ``finetune_jobs/{user}/{job}/{dataset|artifacts}`` URI convention
(``S3Handler.py:46-71``), presigned download URLs (``:168``), newest-metrics-CSV
fetch via pandas (``:237-292``), artifact zip streaming (``:294-373``), recursive
copy for promotion (``:375-439``) and prefix cleanup (``:216-235``).

The default backend is a local-filesystem store (``obj://bucket/key`` URIs) so
the whole control plane runs hermetically in CI; a GCS/S3 backend slots in
behind the same :class:`ObjectStore` interface (cloud creds/IO being exactly the
delegation seam the reference leaves to aioboto3 + aws-cli sidecars).
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import io
import shutil
import time
import zipfile
from pathlib import Path
from typing import Any, AsyncIterator

import pandas as pd

URI_SCHEME = "obj://"


def build_uri(bucket: str, *parts: str) -> str:
    key = "/".join(p.strip("/") for p in parts if p)
    return f"{URI_SCHEME}{bucket}/{key}"


def parse_uri(uri: str) -> tuple[str, str]:
    if not uri.startswith(URI_SCHEME):
        raise ValueError(f"not an object-store uri: {uri!r}")
    bucket, _, key = uri[len(URI_SCHEME) :].partition("/")
    return bucket, key


def dataset_prefix(bucket: str, user_id: str, job_id: str) -> str:
    """Reference convention ``S3Handler.py:46-62``."""
    return build_uri(bucket, "finetune_jobs", user_id, job_id, "dataset")


def artifacts_prefix(bucket: str, user_id: str, job_id: str) -> str:
    """Reference convention ``S3Handler.py:63-71``."""
    return build_uri(bucket, "finetune_jobs", user_id, job_id, "artifacts")


class ObjectStore:
    """Abstract async object store."""

    async def close(self) -> None:
        """Release network resources (no-op for local stores)."""

    async def put_bytes(self, uri: str, data: bytes) -> None:
        raise NotImplementedError

    async def put_stream(self, uri: str, chunks: AsyncIterator[bytes]) -> int:
        raise NotImplementedError

    async def put_file(self, uri: str, path: Path | str) -> None:
        raise NotImplementedError

    async def get_bytes(self, uri: str) -> bytes:
        raise NotImplementedError

    async def get_file(self, uri: str, dest: Path | str) -> int:
        """Stream an object to a local file without buffering it whole;
        returns bytes written."""
        raise NotImplementedError

    async def exists(self, uri: str) -> bool:
        raise NotImplementedError

    async def size(self, uri: str) -> int | None:
        """Byte size of an object via a cheap stat (os.stat / HEAD), or
        ``None`` when the backend has no such operation — callers must then
        fall back to reading.  Raises ``FileNotFoundError`` for a missing
        object (the ``get_bytes`` convention), so pollers can distinguish
        "not there yet" from "can't stat"."""
        return None

    async def list_prefix(self, prefix_uri: str) -> list[dict[str, Any]]:
        """Return [{"uri", "size", "mtime"}] under a prefix."""
        raise NotImplementedError

    async def delete_prefix(self, prefix_uri: str) -> int:
        raise NotImplementedError

    async def copy_prefix(self, src_uri: str, dst_uri: str) -> int:
        raise NotImplementedError

    async def get_chunks(self, uri: str, chunk_size: int = 1 << 20) -> AsyncIterator[bytes]:
        """Stream an object's bytes in chunks. Default materializes the whole
        object (backends override with true streaming)."""
        data = await self.get_bytes(uri)
        for i in range(0, len(data), chunk_size):
            yield data[i : i + chunk_size]

    # -- shared higher-level helpers -----------------------------------------

    async def get_metrics_records(self, artifacts_uri: str) -> tuple[list[dict[str, Any]], str] | None:
        """Pick the newest ``*metrics*.csv`` under the artifacts prefix and
        parse it to records (reference: ``S3Handler.py:237-292``)."""
        objs = await self.list_prefix(artifacts_uri)
        csvs = [o for o in objs if "metrics" in Path(o["uri"]).name and o["uri"].endswith(".csv")]
        if not csvs:
            return None
        newest = max(csvs, key=lambda o: o["mtime"])
        raw = await self.get_bytes(newest["uri"])
        if not raw.strip():
            # the artifact sync can ship metrics.csv between creation and the
            # first row landing — "no metrics yet", not an error
            return None
        df = await asyncio.to_thread(pd.read_csv, io.BytesIO(raw))
        # Ragged rows (e.g. eval columns written on their own cadence) parse
        # as NaN — which is RFC-invalid in the JSON API and breaks the
        # monitor's records-unchanged compare (NaN != NaN). Null them.
        df = df.astype(object).where(pd.notna(df), None)
        records = df.to_dict(orient="records")
        return records, newest["uri"]

    async def zip_prefix(self, prefix_uri: str) -> bytes:
        """Zip every object under a prefix, in memory — small prefixes only
        (reference: ``S3Handler.py:294-373``)."""
        objs = await self.list_prefix(prefix_uri)
        _, prefix_key = parse_uri(prefix_uri)
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for o in objs:
                _, key = parse_uri(o["uri"])
                arcname = key[len(prefix_key) :].lstrip("/") if key.startswith(prefix_key) else key
                zf.writestr(arcname, await self.get_bytes(o["uri"]))
        return buf.getvalue()

    async def zip_prefix_to_path(self, prefix_uri: str, dest: Path | str) -> int:
        """Zip a prefix to a file on disk, streaming each object in chunks —
        bounded memory even when a single object (e.g. a checkpoint shard) is
        multi-GB. Returns object count."""
        objs = await self.list_prefix(prefix_uri)
        _, prefix_key = parse_uri(prefix_uri)
        with zipfile.ZipFile(dest, "w", zipfile.ZIP_DEFLATED) as zf:
            for o in objs:
                _, key = parse_uri(o["uri"])
                arcname = (
                    key[len(prefix_key) :].lstrip("/")
                    if key.startswith(prefix_key) else key
                )
                zi = zipfile.ZipInfo(arcname)
                zi.compress_type = zipfile.ZIP_DEFLATED
                with zf.open(zi, "w") as entry:
                    async for chunk in self.get_chunks(o["uri"]):
                        await asyncio.to_thread(entry.write, chunk)
        return len(objs)


class HttpObjectStore(ObjectStore):
    """Shared aiohttp plumbing for the cloud backends (GCS and S3 both
    inherit this): lazy session with one timeout policy, retry/backoff on
    transient failures, chunked download-to-file with atomic rename, ISO-8601
    mtime parsing, bounded-concurrency fan-out.  One copy so a fix lands in
    every cloud engine (the reference gets all of this from aioboto3 —
    ``S3Handler.py:12,25``)."""

    chunk_size: int = 1 << 20
    #: transient-failure policy: one transfer survives `retry_attempts - 1`
    #: 5xx/429/connection hiccups (the in-repo kube client's pattern —
    #: ``backends/k8s.py``); tests zero `retry_base_delay` for speed
    retry_attempts: int = 4
    retry_base_delay: float = 0.25
    retry_statuses: frozenset = frozenset({429, 500, 502, 503, 504})
    #: concurrent requests for prefix-wide operations (delete/copy fan-out —
    #: the reference batches with asyncio.gather, ``S3Handler.py:330,422``)
    prefix_concurrency: int = 16

    def __init__(self):
        self._session = None

    async def session(self):
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=None, sock_connect=30)
            )
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    def _retry_delay(self, done_attempts: int) -> float:
        return self.retry_base_delay * (2 ** done_attempts)

    async def request_bytes(self, build) -> tuple[int, bytes, dict[str, str]]:
        """Send one logical request with retries; returns
        ``(status, body, headers)`` for the first conclusive outcome.

        ``build()`` must return a FRESH aiohttp response context manager per
        call — it is re-invoked on every attempt so signed engines re-stamp
        dates/signatures.  Retries connection errors/timeouts and
        ``retry_statuses`` with exponential backoff; the final attempt's
        outcome (status or exception) is returned/raised as-is so call sites
        keep their own error mapping.
        """
        import aiohttp

        last = self.retry_attempts - 1
        for attempt in range(self.retry_attempts):
            if attempt:
                await asyncio.sleep(self._retry_delay(attempt - 1))
            try:
                async with await build() as resp:
                    body = await resp.read()
                    if resp.status in self.retry_statuses and attempt < last:
                        continue
                    return resp.status, body, dict(resp.headers)
            except (aiohttp.ClientError, asyncio.TimeoutError):
                if attempt >= last:
                    raise
        raise AssertionError("unreachable")

    async def get_file(self, uri: str, dest: Path | str) -> int:
        """Stream to a local file with atomic rename; transient mid-transfer
        failures restart the WHOLE transfer (objects are immutable here, and
        a restart is simpler and safer than byte-range resumption)."""
        import aiohttp

        dest_p = Path(dest)
        dest_p.parent.mkdir(parents=True, exist_ok=True)
        tmp = dest_p.with_name(dest_p.name + ".tmp")
        last = self.retry_attempts - 1
        try:
            for attempt in range(self.retry_attempts):
                if attempt:
                    await asyncio.sleep(self._retry_delay(attempt - 1))
                total = 0
                try:
                    with tmp.open("wb") as f:
                        async for chunk in self.get_chunks(uri, self.chunk_size):
                            total += len(chunk)
                            await asyncio.to_thread(f.write, chunk)
                    tmp.replace(dest_p)
                    return total
                except FileNotFoundError:
                    raise  # a 404 is conclusive, not transient
                except (IOError, aiohttp.ClientError, asyncio.TimeoutError):
                    if attempt >= last:
                        raise
        except BaseException:
            tmp.unlink(missing_ok=True)
            raise
        raise AssertionError("unreachable")

    async def map_concurrently(self, fn, items: list) -> list:
        """Run ``fn(item)`` over items with bounded concurrency. Waits for
        EVERY task before returning or raising (no orphaned requests keep
        mutating the bucket after the caller has observed a failure), then
        re-raises the first failure."""
        if not items:
            return []
        sem = asyncio.Semaphore(self.prefix_concurrency)

        async def guarded(item):
            async with sem:
                return await fn(item)

        results = await asyncio.gather(
            *(guarded(i) for i in items), return_exceptions=True
        )
        for r in results:
            if isinstance(r, BaseException):
                raise r
        return list(results)

    @staticmethod
    def parse_iso_mtime(text: str) -> float:
        try:
            return __import__("datetime").datetime.fromisoformat(
                text.replace("Z", "+00:00")
            ).timestamp()
        except ValueError:
            return 0.0


class LocalObjectStore(ObjectStore):
    """Filesystem-backed store rooted at ``root/<bucket>/<key>``."""

    def __init__(self, root: Path | str):
        self.root = Path(root).expanduser()

    def path_for(self, uri: str) -> Path:
        bucket, key = parse_uri(uri)
        base = (self.root / bucket).resolve()
        p = (self.root / bucket / key).resolve()
        if p != base and not p.is_relative_to(base):
            raise ValueError(f"path escape in uri {uri!r}")
        return p

    async def put_bytes(self, uri: str, data: bytes) -> None:
        def write() -> None:
            p = self.path_for(uri)
            p.parent.mkdir(parents=True, exist_ok=True)
            tmp = p.with_name(p.name + ".tmp")
            tmp.write_bytes(data)
            tmp.replace(p)

        await asyncio.to_thread(write)

    async def put_stream(self, uri: str, chunks: AsyncIterator[bytes]) -> int:
        """Zero-copy-ish streaming upload (reference: URL→S3 streaming,
        ``dataset_helpers.py:113-145``)."""
        p = self.path_for(uri)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_name(p.name + ".tmp")
        total = 0
        with tmp.open("wb") as f:
            async for chunk in chunks:
                total += len(chunk)
                await asyncio.to_thread(f.write, chunk)
        tmp.replace(p)
        return total

    async def put_file(self, uri: str, path: Path | str) -> None:
        def copy() -> None:
            p = self.path_for(uri)
            p.parent.mkdir(parents=True, exist_ok=True)
            shutil.copyfile(path, p)

        await asyncio.to_thread(copy)

    async def get_bytes(self, uri: str) -> bytes:
        return await asyncio.to_thread(self.path_for(uri).read_bytes)

    async def get_file(self, uri: str, dest: Path | str) -> int:
        src = self.path_for(uri)

        def copy() -> int:
            dest_p = Path(dest)
            dest_p.parent.mkdir(parents=True, exist_ok=True)
            shutil.copyfile(src, dest_p)
            return dest_p.stat().st_size

        return await asyncio.to_thread(copy)

    async def get_chunks(self, uri: str, chunk_size: int = 1 << 20) -> AsyncIterator[bytes]:
        p = self.path_for(uri)
        with p.open("rb") as f:
            while True:
                chunk = await asyncio.to_thread(f.read, chunk_size)
                if not chunk:
                    return
                yield chunk

    async def exists(self, uri: str) -> bool:
        return await asyncio.to_thread(self.path_for(uri).exists)

    async def size(self, uri: str) -> int | None:
        return (await asyncio.to_thread(self.path_for(uri).stat)).st_size

    async def list_prefix(self, prefix_uri: str) -> list[dict[str, Any]]:
        bucket, key = parse_uri(prefix_uri)

        def scan() -> list[dict[str, Any]]:
            base = self.root / bucket / key
            if not base.exists():
                return []
            out = []
            for p in sorted(base.rglob("*")):
                if p.is_file() and not p.name.endswith(".tmp"):
                    rel = p.relative_to(self.root / bucket)
                    st = p.stat()
                    out.append(
                        {
                            "uri": build_uri(bucket, str(rel)),
                            "size": st.st_size,
                            "mtime": st.st_mtime,
                        }
                    )
            return out

        return await asyncio.to_thread(scan)

    async def delete_prefix(self, prefix_uri: str) -> int:
        """Reference: ``S3Handler.py:216-235``."""
        objs = await self.list_prefix(prefix_uri)

        def rm() -> None:
            bucket, key = parse_uri(prefix_uri)
            base = self.root / bucket / key
            if base.is_dir():
                shutil.rmtree(base)
            elif base.exists():
                base.unlink()

        await asyncio.to_thread(rm)
        return len(objs)

    async def copy_prefix(self, src_uri: str, dst_uri: str) -> int:
        """Recursive copy for promotion (reference: ``S3Handler.py:375-439`` —
        head the key; on miss treat as prefix and copy each object)."""
        src_path = self.path_for(src_uri)
        if src_path.is_file():
            await self.put_bytes(dst_uri, await self.get_bytes(src_uri))
            return 1
        objs = await self.list_prefix(src_uri)
        _, src_key = parse_uri(src_uri)
        dst_bucket, dst_key = parse_uri(dst_uri)
        n = 0
        for o in objs:
            _, key = parse_uri(o["uri"])
            rel = key[len(src_key) :].lstrip("/")
            await self.put_bytes(
                build_uri(dst_bucket, dst_key, rel), await self.get_bytes(o["uri"])
            )
            n += 1
        return n


def build_object_store(settings) -> ObjectStore:
    """Object-store factory from settings: ``local`` (hermetic CI), ``gcs``
    (``controller.gcs``), or ``s3`` (``controller.s3`` — SigV4 over aiohttp,
    the layout-compatible migration path off the reference). The seam the
    reference hardwires to aioboto3 (``S3Handler.py:12,25``)."""
    backend = getattr(settings, "object_store_backend", "local")
    if backend == "local":
        return LocalObjectStore(settings.object_store_path)
    if backend == "gcs":
        from .gcs import GCSObjectStore

        return GCSObjectStore(
            endpoint=settings.gcs_endpoint,
            bucket_prefix=settings.gcs_bucket_prefix,
        )
    if backend == "s3":
        from .s3 import S3ObjectStore

        return S3ObjectStore(
            endpoint=settings.s3_endpoint,
            region=settings.s3_region,
            bucket_prefix=settings.s3_bucket_prefix,
        )
    raise ValueError(f"unknown object_store_backend {backend!r}")


class Presigner:
    """HMAC presigned-download tokens (reference: S3 presigned URLs,
    ``S3Handler.py:168-214``; ours are served by the API's ``/download`` route
    since the local store has no external endpoint)."""

    def __init__(self, secret: str, expiry_s: int = 3600):
        self._secret = secret.encode()
        self._expiry_s = expiry_s

    def sign(self, uri: str, now: float | None = None) -> str:
        expires = int((now if now is not None else time.time()) + self._expiry_s)
        mac = hmac.new(self._secret, f"{uri}:{expires}".encode(), hashlib.sha256)
        return f"{expires}.{mac.hexdigest()}"

    def verify(self, uri: str, token: str, now: float | None = None) -> bool:
        try:
            expires_s, digest = token.split(".", 1)
            expires = int(expires_s)
        except ValueError:
            return False
        if (now if now is not None else time.time()) > expires:
            return False
        mac = hmac.new(self._secret, f"{uri}:{expires}".encode(), hashlib.sha256)
        return hmac.compare_digest(mac.hexdigest(), digest)
