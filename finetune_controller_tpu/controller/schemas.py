"""Control-plane schemas: DB documents, API DTOs, and the training-state machine.

Capability parity with the reference's three schema files
(``app/schemas/db_schemas.py``, ``app/schemas/jobs_schemas.py``,
``app/schemas/kubeflow_schemas.py`` — SURVEY.md §2 component 8), with the
Kubeflow-specific state machine generalised to *any* training backend
(local subprocess, K8s TPU JobSet).
"""

from __future__ import annotations

import enum
import time
from typing import Any

from pydantic import BaseModel, Field


# ---------------------------------------------------------------------------
# Status enums
# ---------------------------------------------------------------------------


class DatabaseStatus(str, enum.Enum):
    """Job lifecycle as stored/served (reference: ``db_schemas.py:46-66``)."""

    QUEUED = "queued"
    CREATED = "created"
    RUNNING = "running"
    RESTARTING = "restarting"
    #: failed, classified retryable, waiting out its backoff before the
    #: supervisor resubmits it (``resilience/supervisor.py``); deliberately
    #: NON-final — the job is still the control plane's responsibility
    RETRYING = "retrying"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    CANCELLED = "cancelled"
    UNKNOWN = "unknown"

    @classmethod
    def final_states(cls) -> set["DatabaseStatus"]:
        return {cls.SUCCEEDED, cls.FAILED, cls.CANCELLED}

    @property
    def is_final(self) -> bool:
        return self in self.final_states()


class PromotionStatus(str, enum.Enum):
    """Artifact promotion state machine (reference: ``db_schemas.py:69-74``)."""

    NOT_PROMOTED = "not_promoted"
    IN_PROGRESS = "in_progress"
    COMPLETED = "completed"
    FAILED = "failed"
    DELETING = "deleting"


class BackendJobState(str, enum.Enum):
    """States a training backend reports for a job.

    Generalisation of the reference's Kubeflow condition types
    (``kubeflow_schemas.py:10-35``): Created/Running/Restarting/Succeeded/
    Failed/Suspended map 1:1; ``PENDING`` covers "accepted but no state yet".
    """

    PENDING = "Pending"
    SUSPENDED = "Suspended"  # admitted to queue, not yet running (Kueue suspend)
    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"

    @classmethod
    def running_states(cls) -> set["BackendJobState"]:
        # reference: kubeflow_schemas.py:42-50
        return {cls.PENDING, cls.SUSPENDED, cls.CREATED, cls.RUNNING, cls.RESTARTING}

    @classmethod
    def stopped_states(cls) -> set["BackendJobState"]:
        # reference: kubeflow_schemas.py:52-59
        return {cls.SUCCEEDED, cls.FAILED, cls.UNKNOWN}


#: Backend state → DB status (reference: ``TrainingJobStatus.map_status``,
#: ``kubeflow_schemas.py:61-85``).
_STATE_TO_DB: dict[BackendJobState, DatabaseStatus] = {
    BackendJobState.PENDING: DatabaseStatus.QUEUED,
    BackendJobState.SUSPENDED: DatabaseStatus.QUEUED,
    BackendJobState.CREATED: DatabaseStatus.CREATED,
    BackendJobState.RUNNING: DatabaseStatus.RUNNING,
    BackendJobState.RESTARTING: DatabaseStatus.RESTARTING,
    BackendJobState.SUCCEEDED: DatabaseStatus.SUCCEEDED,
    BackendJobState.FAILED: DatabaseStatus.FAILED,
    BackendJobState.UNKNOWN: DatabaseStatus.UNKNOWN,
}


def map_backend_state(state: BackendJobState | str) -> DatabaseStatus:
    try:
        state = BackendJobState(state)
    except ValueError:
        return DatabaseStatus.UNKNOWN
    return _STATE_TO_DB[state]


# ---------------------------------------------------------------------------
# Backend report (what the monitor consumes each reconcile tick)
# ---------------------------------------------------------------------------


class BackendJobReport(BaseModel):
    """Snapshot of one job as seen by a training backend.

    Replaces the reference's raw ``KubeflowOrgV1PyTorchJob`` objects iterated by
    the monitor (``app/core/monitor.py:134-197``) with a typed, backend-neutral
    report.
    """

    job_id: str
    state: BackendJobState = BackendJobState.UNKNOWN
    start_time: float | None = None  # epoch seconds
    completion_time: float | None = None
    message: str = ""
    metadata: dict[str, Any] = Field(default_factory=dict)


# ---------------------------------------------------------------------------
# DB documents
# ---------------------------------------------------------------------------


class JobRecord(BaseModel):
    """The job document (reference: ``JobStatus``, ``db_schemas.py:85-129``)."""

    job_id: str
    user_id: str
    model_name: str
    status: DatabaseStatus = DatabaseStatus.QUEUED
    device: str = ""  # TPU flavor name from the device catalog (e.g. "v5e-16")
    num_slices: int = 1
    arguments: dict[str, Any] = Field(default_factory=dict)
    dataset_id: str | None = None
    dataset_uri: str | None = None
    artifacts_uri: str | None = None
    promotion_status: PromotionStatus = PromotionStatus.NOT_PROMOTED
    promotion_uri: str | None = None
    queue_position: int | None = None
    submitted_at: float = Field(default_factory=time.time)
    start_time: float | None = None
    end_time: float | None = None
    training_duration: float | None = None
    metadata: dict[str, Any] = Field(default_factory=dict)
    #: the lifecycle event timeline (docs/observability.md): appended by
    #: every plane via ``StateStore.append_job_event`` (exactly-once via
    #: idempotency keys), served by ``GET /jobs/{id}/timeline`` and the
    #: trace assembly (``obs/trace.py``)
    events: list[dict[str, Any]] = Field(default_factory=list)


class DatasetRecord(BaseModel):
    """Dataset document (reference: ``DatasetModel``, ``db_schemas.py:28-44``)."""

    dataset_id: str
    user_id: str
    name: str
    uri: str
    size_bytes: int | None = None
    content_type: str | None = None
    created_at: float = Field(default_factory=time.time)
    job_refs: list[str] = Field(default_factory=list)
    metadata: dict[str, Any] = Field(default_factory=dict)


class MetricsDocument(BaseModel):
    """Training metrics for one job (reference: ``MetricsDocument``,
    ``db_schemas.py:132-150``)."""

    job_id: str
    records: list[dict[str, Any]] = Field(default_factory=list)
    source_uri: str | None = None
    updated_at: float = Field(default_factory=time.time)


# ---------------------------------------------------------------------------
# API DTOs
# ---------------------------------------------------------------------------


class JobInput(BaseModel):
    """Validated submission payload (reference: ``JobInput``,
    ``jobs_schemas.py:18-36``; device validation happens in the API layer
    against the live device catalog)."""

    job_id: str
    user_id: str
    model_name: str
    device: str
    num_slices: int = 1
    arguments: dict[str, Any] = Field(default_factory=dict)
    #: tenant queue + priority class for the fair-share scheduler
    #: (``finetune_controller_tpu/sched/``, docs/scheduling.md); validated
    #: against sched.queues.parse_priority in the API layer
    queue: str = "default"
    priority: str | int = "normal"
    #: the topology the job ORIGINALLY asked for, when ``num_slices`` is a
    #: resized (shrunk) resubmission — the scheduler's grow pass restores
    #: the job toward this when chips free (docs/elasticity.md).  None on a
    #: fresh submission (= num_slices).
    requested_num_slices: int | None = None
    #: observability (docs/observability.md): the job's trace id — minted at
    #: first submit by ``task_builder``, carried by the job metadata, and
    #: re-supplied on supervisor resubmissions so every attempt shares one
    #: trace; backends thread it into the trainer env as ``FTC_TRACE_ID``
    trace_id: str = ""
    #: 1-based attempt number of THIS dispatch (``FTC_ATTEMPT`` in the
    #: trainer env; log streams and trainer events are attributed by it)
    attempt: int = 1


class PaginatedTableResponse(BaseModel):
    """Paginated job table (reference: ``PaginatedTableResponse``,
    ``jobs_schemas.py:81-132``)."""

    total: int
    page: int
    page_size: int
    items: list[dict[str, Any]]
