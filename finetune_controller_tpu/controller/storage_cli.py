"""Storage CLI for job pods: dataset fetch + artifact sync.

The in-repo replacement for the ``amazon/aws-cli`` init/sidecar containers the
reference injects into every training pod
(``app/jobs/kubeflow/PyTorchJobDeployer.py:70-91`` dataset ``s3 cp``;
``:121-168`` artifact ``s3 sync`` loop with ``done.txt`` termination):

    python -m finetune_controller_tpu.controller.storage_cli get obj://... /data/x
    python -m finetune_controller_tpu.controller.storage_cli sync /data/artifacts \
        obj://artifacts/... --interval 60 --until-done-file /data/artifacts/done.txt

The object store root comes from ``FTC_OBJECT_STORE_ROOT`` (a shared volume /
NFS mount in-cluster; cloud-bucket stores plug in behind the same
:class:`~finetune_controller_tpu.controller.objectstore.ObjectStore` seam).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import time
from pathlib import Path

from .config import get_settings
from .objectstore import ObjectStore, build_object_store
from .syncer import sync_dir_to_store

logger = logging.getLogger(__name__)


def _store() -> ObjectStore:
    """Backend comes from env (``FTC_OBJECT_STORE_BACKEND=local|gcs``) — the
    pod-side selection the round-1 build lacked (it assumed a shared
    filesystem mount, which does not survive a real GKE cluster)."""
    return build_object_store(get_settings())


async def cmd_get(uri: str, dest: str) -> int:
    store = _store()
    try:
        n = await store.get_file(uri, dest)
    finally:
        await store.close()
    logger.info("fetched %s -> %s (%d bytes)", uri, dest, n)
    return 0


async def cmd_sync(
    src: str, dest_uri: str, *, interval: float, until_done_file: str | None,
    patterns: list[str] | None,
) -> int:
    store = _store()
    src_path = Path(src)
    synced: dict[str, tuple[float, int]] = {}
    done = Path(until_done_file) if until_done_file else None
    try:
        while True:
            try:
                n = await sync_dir_to_store(
                    store, src_path, dest_uri, patterns=patterns, synced=synced
                )
                if n:
                    logger.info("synced %d file(s) -> %s", n, dest_uri)
            except Exception:
                if done is None:
                    # one-shot mode has no retry: a swallowed failure would
                    # exit 0 and the caller would treat a failed upload as
                    # success
                    logger.exception("one-shot sync failed")
                    return 1
                logger.exception("sync pass failed; retrying")
            if done is not None and done.exists():
                await sync_dir_to_store(  # final pass
                    store, src_path, dest_uri, patterns=patterns, synced=synced
                )
                logger.info("done-file present; exiting after final sync")
                return 0
            if done is None:
                return 0  # one-shot mode
            await asyncio.sleep(interval)
    finally:
        await store.close()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="ftc-storage")
    sub = parser.add_subparsers(dest="cmd", required=True)
    g = sub.add_parser("get", help="fetch one object to a local path")
    g.add_argument("uri")
    g.add_argument("dest")
    s = sub.add_parser("sync", help="sync a directory to an object prefix")
    s.add_argument("src")
    s.add_argument("dest_uri")
    s.add_argument("--interval", type=float, default=60.0)
    s.add_argument("--until-done-file", default=None)
    s.add_argument(
        "--pattern", action="append", default=None,
        help="glob pattern to include (repeatable); default: everything",
    )
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO, force=True)
    if args.cmd == "get":
        return asyncio.run(cmd_get(args.uri, args.dest))
    return asyncio.run(
        cmd_sync(
            args.src, args.dest_uri,
            interval=args.interval, until_done_file=args.until_done_file,
            patterns=args.pattern,
        )
    )


if __name__ == "__main__":
    raise SystemExit(main())
