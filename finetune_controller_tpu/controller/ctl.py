"""``ftc-ctl`` — terminal client for the control-plane API.

The reference pairs its API with a browser frontend; this is the equivalent
surface for terminals and scripts: submit, watch, stream logs, fetch metrics,
promote — against any running controller (local `scripts/serve_local.sh` or
an on-cluster deployment).

    python -m finetune_controller_tpu.controller.ctl [--api URL] [--token T] CMD ...

Commands:
    models                              list submittable models
    submit MODEL [--arg k=v ...] [--device D] [--task T] [--queue Q] [--priority P] [--dataset-file F | --dataset-url U | --dataset-id I] [--watch]
    jobs [--page N]                     paginated job table (incl. task type)
    queue                               tenant queues: usage/share/borrowed + pending
    serve                               serving sessions: slots/queue/tokens + prefix-cache hits
    status JOB_ID [--watch]             one job (``--watch`` polls to final)
    logs JOB_ID [--follow]              job logs (REST; --follow re-polls)
    metrics JOB_ID                      metrics rows (latest last)
    timeline JOB_ID                     lifecycle waterfall: where time went
    profile JOB_ID [--steps N]          arm a jax.profiler window on a live job
    artifacts JOB_ID [-o out.zip]       artifact inventory (or zip download)
    promote JOB_ID / unpromote JOB_ID
    cancel JOB_ID
    generate JOB_ID --tokens 1,2,3      decode from a promoted job's checkpoint
    dev-token [USER_ID]                 mint a dev token (local envs only)

Auth: ``--token`` or the FTC_CTL_TOKEN env var; the API URL defaults to
``FTC_CTL_API`` or http://localhost:8787.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from typing import Any

FINAL_STATES = {"succeeded", "failed", "cancelled", "unknown"}


class ApiError(RuntimeError):
    """HTTP-level failure; carries the status and any Retry-After hint so
    callers (``generate``'s bounded retry) can react without re-parsing."""

    def __init__(self, message: str, status: int = 0,
                 retry_after_s: float | None = None):
        super().__init__(message)
        self.status = status
        self.retry_after_s = retry_after_s


class Client:
    def __init__(self, base: str, token: str | None):
        self.base = base.rstrip("/")
        self.token = token
        self._session = None

    async def __aenter__(self):
        import aiohttp

        headers = {}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        self._session = aiohttp.ClientSession(headers=headers)
        return self

    async def __aexit__(self, *exc):
        await self._session.close()

    async def request(self, method: str, path: str, **kw) -> Any:
        url = f"{self.base}/api/v1{path}"
        async with self._session.request(method, url, **kw) as r:
            if r.status >= 400:
                retry_after = None
                raw = r.headers.get("Retry-After")
                if raw:
                    try:
                        retry_after = float(raw)
                    except ValueError:
                        pass  # HTTP-date form: ignore, callers fall back
                raise ApiError(
                    f"{method} {path} -> {r.status}: {await r.text()}",
                    status=r.status, retry_after_s=retry_after,
                )
            if "json" in r.headers.get("Content-Type", ""):
                return await r.json()
            return await r.text()

    async def get(self, path: str, **kw) -> Any:
        return await self.request("GET", path, **kw)

    async def post(self, path: str, **kw) -> Any:
        return await self.request("POST", path, **kw)

    async def download(self, path: str, dest: str) -> None:
        """Stream a GET response body to ``dest`` (same URL/auth/error
        semantics as :meth:`request`)."""
        url = f"{self.base}/api/v1{path}"
        async with self._session.get(url) as r:
            if r.status >= 400:
                raise ApiError(f"GET {path} -> {r.status}: {await r.text()}")
            f = await asyncio.to_thread(open, dest, "wb")
            try:
                # batch small chunks into ~1 MiB flushes: one thread-pool
                # round-trip per block, not per 64 KiB network read
                buf: list[bytes] = []
                buffered = 0
                async for chunk in r.content.iter_chunked(1 << 16):
                    buf.append(chunk)
                    buffered += len(chunk)
                    if buffered >= (1 << 20):
                        await asyncio.to_thread(f.writelines, buf)
                        buf, buffered = [], 0
                if buf:
                    await asyncio.to_thread(f.writelines, buf)
            finally:
                await asyncio.to_thread(f.close)


def _parse_args_kv(pairs: list[str]) -> dict[str, Any]:
    """k=v pairs with JSON-typed values (`lr=0.001 steps=50 name=run1`)."""
    out: dict[str, Any] = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"--arg expects k=v, got {pair!r}")
        k, _, v = pair.partition("=")
        try:
            out[k] = json.loads(v)
        except json.JSONDecodeError:
            out[k] = v
    return out


def _print_json(obj: Any) -> None:
    print(json.dumps(obj, indent=2, default=str))


async def _watch_job(client: Client, job_id: str, interval_s: float = 2.0) -> dict:
    last = None
    while True:
        job = await client.get(f"/jobs/{job_id}")
        line = f"{job['status']}"
        if job.get("queue_position"):
            line += f" (queue #{job['queue_position']})"
        if line != last:
            print(f"[{time.strftime('%H:%M:%S')}] {line}", file=sys.stderr)
            last = line
        if job["status"] in FINAL_STATES:
            return job
        await asyncio.sleep(interval_s)


async def cmd_submit(client: Client, ns: argparse.Namespace) -> int:
    import aiohttp

    arguments = _parse_args_kv(ns.arg or [])
    if ns.dataset_file:
        form = aiohttp.FormData()
        form.add_field("model_name", ns.model)
        if ns.device:
            form.add_field("device", ns.device)
        if ns.task:
            form.add_field("task", ns.task)
        if ns.queue:
            form.add_field("queue", ns.queue)
        if ns.priority:
            form.add_field("priority", ns.priority)
        form.add_field("arguments", json.dumps(arguments))
        def _read_dataset() -> bytes:
            with open(ns.dataset_file, "rb") as f:
                return f.read()

        form.add_field("dataset_file", await asyncio.to_thread(_read_dataset),
                       filename=os.path.basename(ns.dataset_file))
        result = await client.post("/jobs", data=form)
    else:
        body: dict[str, Any] = {"model_name": ns.model, "arguments": arguments}
        if ns.device:
            body["device"] = ns.device
        if ns.task:
            body["task"] = ns.task
        if ns.queue:
            body["queue"] = ns.queue
        if ns.priority:
            body["priority"] = ns.priority
        if ns.dataset_url:
            body["dataset_url"] = ns.dataset_url
        if ns.dataset_id:
            body["dataset_id"] = ns.dataset_id
        result = await client.post("/jobs", json=body)
    _print_json(result)
    if ns.watch:
        job = await _watch_job(client, result["job_id"])
        _print_json(job)
        return 0 if job["status"] == "succeeded" else 1
    return 0


async def cmd_jobs(client: Client, ns: argparse.Namespace) -> int:
    page = await client.get("/jobs", params={"page": str(ns.page)})
    rows = page.get("items", [])
    if not rows:
        print("no jobs")
        return 0
    width = max(len(r["job_id"]) for r in rows)
    for r in rows:
        dur = r.get("duration") or ""
        # task type rides the job metadata (task_builder): sft jobs predate
        # the column and show as causal_lm/multimodal; blanks are pre-task
        # records
        task = (r.get("metadata") or {}).get("task") or ""
        print(f"{r['job_id']:<{width}}  {task:<12}  {r['status']:<10}  {dur}")
    print(f"(page {ns.page}, total {page.get('total')})")
    return 0


async def cmd_status(client: Client, ns: argparse.Namespace) -> int:
    if ns.watch:
        job = await _watch_job(client, ns.job_id)
        _print_json(job)
        return 0 if job["status"] == "succeeded" else 1
    _print_json(await client.get(f"/jobs/{ns.job_id}"))
    return 0


async def cmd_logs(client: Client, ns: argparse.Namespace) -> int:
    async def fetch_new(seen: int) -> int:
        body = await client.get(f"/jobs/{ns.job_id}/logs")
        lines = body.get("lines", []) if isinstance(body, dict) else body.splitlines()
        for line in lines[seen:]:
            print(line)
        return len(lines)

    seen = await fetch_new(0)
    if not ns.follow:
        return 0
    while True:
        job = await client.get(f"/jobs/{ns.job_id}")
        if job["status"] in FINAL_STATES:
            # the job reached a final state after our last fetch: drain the
            # tail once more so lines written in between aren't dropped
            await fetch_new(seen)
            return 0
        await asyncio.sleep(2.0)
        seen = await fetch_new(seen)


async def cmd_queue(client: Client, ns: argparse.Namespace) -> int:
    """Tenant-queue table from ``GET /admin/scheduler``: usage, weighted
    dominant share, borrowed chips, preemptions, and pending positions."""
    snap = await client.get("/admin/scheduler")
    queues = snap.get("queues") or {}
    if not queues:
        print(f"no tenant queues (policy={snap.get('policy')})")
        return 0
    header = (f"{'QUEUE':<16} {'WEIGHT':>6} {'RUN':>4} {'PEND':>5} "
              f"{'CHIPS':>6} {'SHARE':>7} {'BORROW':>7} {'PREEMPT':>8} "
              f"{'RESIZE':>7}")
    print(header)
    for name, q in sorted(queues.items()):
        print(
            f"{name:<16} {q['weight']:>6.1f} {q['running']:>4} "
            f"{q['depth']:>5} {q['used_chips_total']:>6} "
            f"{q['dominant_share']:>7.3f} {q['borrowed_chips']:>7.1f} "
            f"{q['preemptions']:>8} {q.get('resizes', 0):>7}"
        )
    pending = [
        (p["position"], p["job_id"], name)
        for name, q in queues.items()
        for p in q.get("pending", [])
    ]
    for pos, job_id, qname in sorted(pending):
        print(f"  #{pos}  {job_id}  ({qname})")
    # workloads currently running below their requested topology
    for job_id, s in sorted((snap.get("shrunk_workloads") or {}).items()):
        print(
            f"  ~{job_id}  {s['num_slices']}/{s['requested_slices']} slices "
            f"({s['queue']}, shrunk)"
        )
    if snap.get("preemptions_total") is not None:
        print(f"(preemptions total: {snap['preemptions_total']}, "
              f"resizes total: {snap.get('resizes_total', 0)})")
    # the recent resize decisions (docs/elasticity.md)
    history = snap.get("resize_history") or []
    for h in history[-5:]:
        who = f" for {h['preemptor']}" if h.get("preemptor") else ""
        print(f"  [{h['kind']}] {h['job_id']} "
              f"{h['from_slices']}->{h['to_slices']} slices{who}")
    return 0


async def cmd_serve(client: Client, ns: argparse.Namespace) -> int:
    """Serving-fleet table from ``GET /admin/serve``: per-job aggregates
    (slot/queue occupancy, token throughput, prefix-cache hit economics)
    plus one indented row per replica — state, generation, load, restarts
    and failovers (docs/serving.md §Fleet)."""
    body = await client.get("/admin/serve")
    sessions = body.get("sessions") or {}
    # process-wide shard-audit counters (analysis/shard_audit.py): printed
    # even with no sessions — a nonzero violation count is the operator's
    # cue that a load landed mis-sharded weights
    audit = body.get("shard_audit") or {}
    audit_line = (
        f"(shard audit: {audit.get('checks_total', 0)} leaf checks, "
        f"{audit.get('violations_total', 0)} violations)"
        if audit else ""
    )
    if not sessions:
        print("no serving sessions loaded")
        if audit_line:
            print(audit_line)
        return 0
    header = (f"{'JOB':<24} {'MODE':>7} {'REPL':>5} {'SLOTS':>7} {'QUEUE':>5} "
              f"{'TOKENS':>8} {'HITS':>5} {'MISS':>5} {'SAVED':>8} "
              f"{'CACHE_MB':>8} {'PAGES':>9} {'TIER':>9} {'ADPT':>4}")
    print(header)
    for job_id, s in sorted(sessions.items()):
        slots = f"{s['slots_busy']}/{s['slots_total']}"
        repl = f"{s.get('replicas_healthy', 1)}/{s.get('replicas_total', 1)}"
        cache_mb = s.get("prefix_cache_bytes", 0) / (1 << 20)
        # paged KV occupancy (used/total across replicas; '-' = unpaged)
        pages_total = s.get("kv_pages_total", 0)
        pages = (f"{s.get('kv_pages_used', 0)}/{pages_total}"
                 if pages_total else "-")
        # host KV tier occupancy: device-resident vs host-demoted pages
        # (docs/serving.md §KV tiering; '-' = tiering off)
        tier_total = s.get("kv_tier_host_pages_total", 0)
        tier = (f"{s.get('kv_pages_used', 0)}d/"
                f"{s.get('kv_tier_host_pages_used', 0)}h"
                if tier_total else "-")
        mode = s.get("transport", "inproc")
        print(
            f"{job_id:<24} {mode:>7} {repl:>5} {slots:>7} "
            f"{s['queue_depth']:>5} "
            f"{s['tokens_generated_total']:>8} "
            f"{s.get('prefix_hits_total', 0):>5} "
            f"{s.get('prefix_misses_total', 0):>5} "
            f"{s.get('prefill_tokens_saved_total', 0):>8} {cache_mb:>8.1f} "
            f"{pages:>9} {tier:>9} {s.get('adapters_loaded', 0):>4}"
        )
        for rid, r in sorted((s.get("replicas") or {}).items()):
            rpages = (f" pages {r.get('kv_pages_used', 0)}/"
                      f"{r.get('kv_pages_total', 0)}"
                      if r.get("kv_pages_total") else "")
            # a process-mode replica names its worker pid — the operator's
            # hook into the sandbox (docs/serving.md §Cross-process
            # transport); in-process replicas render '-'
            pid = f"pid {r['pid']} " if r.get("pid") else ""
            print(
                f"  {rid:<10} gen{r.get('generation', 0):<3} "
                f"{r.get('state', '?'):<9} {pid}"
                f"slots {r.get('slots_busy', 0)}/{r.get('slots_total', 0)} "
                f"queue {r.get('queue_depth', 0)} "
                f"tokens {r.get('tokens_generated_total', 0)}{rpages}"
            )
        # one row per multiplexed tenant: slot, live lanes, queue, tokens
        adapters = s.get("adapters") or {}
        tokens_by = s.get("tokens_by_tenant") or {}
        lanes_by = s.get("lanes_by_tenant") or {}
        queue_by = s.get("queue_depth_by_tenant") or {}
        for aid, a in sorted(adapters.items()):
            print(
                f"  @{aid:<22} slot{a.get('slot', '?'):<3} "
                f"r{a.get('rank', '?'):<3} "
                f"lanes {lanes_by.get(aid, 0)} "
                f"queue {queue_by.get(aid, 0)} "
                f"tokens {tokens_by.get(aid, 0)}"
            )
        extras = []
        for label, key in (("failovers", "failovers_total"),
                           ("restarts", "replica_restarts_total"),
                           ("rollovers", "rollovers_total"),
                           ("shed", "shed_total")):
            if s.get(key):
                extras.append(f"{label} {s[key]}")
        if extras:
            print(f"  ({', '.join(extras)})")
    if audit_line:
        print(audit_line)
    return 0


async def cmd_metrics(client: Client, ns: argparse.Namespace) -> int:
    body = await client.get(f"/jobs/{ns.job_id}/metrics")
    records = body.get("records", body)
    _print_json(records)
    # rlhf rollout-plane health one-liner from the newest row: actor tok/s +
    # buffer depth/staleness, plus the remote-fleet triple when the job runs
    # disaggregated actors (docs/preference.md §Disaggregated rollouts)
    last = records[-1] if isinstance(records, list) and records else None
    if isinstance(last, dict) and last.get("actor_tokens_per_sec") \
            not in (None, ""):
        def num(key: str) -> float | None:
            try:
                return float(last[key])
            except (KeyError, TypeError, ValueError):
                return None

        parts = [
            f"actor {num('actor_tokens_per_sec') or 0:.1f} tok/s "
            f"@v{int(num('actor_version') or 0)}",
            f"buffer depth {int(num('rollout_buffer_depth') or 0)} "
            f"staleness {num('rollout_staleness') or 0:.1f} ckpt",
        ]
        workers = num("rollout_workers_alive")
        if workers is not None:
            parts.append(
                f"workers {int(workers)} alive "
                f"(respawns {int(num('rollout_respawns_total') or 0)}, "
                f"dup pairs {int(num('rollout_dup_pairs_total') or 0)})"
            )
        print(f"rollout: {'  '.join(parts)}")
    return 0


def _fmt_attrs(attrs: dict) -> str:
    return " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))


async def cmd_timeline(client: Client, ns: argparse.Namespace) -> int:
    """Waterfall of where the job's time went (docs/observability.md):
    each lifecycle event with its offset from submit and the gap to the
    NEXT event — the gap column is the phase duration."""
    body = await client.get(f"/jobs/{ns.job_id}/timeline")
    events = body.get("events") or []
    if not events:
        print(f"no timeline events for {ns.job_id} "
              f"(pre-observability job?)")
        return 0
    trace = (body.get("trace_id") or "")[:8]
    print(f"{ns.job_id}  trace={trace or '-'}  status={body.get('status')}")
    t0 = events[0]["ts"]
    for i, e in enumerate(events):
        offset = e["ts"] - t0
        gap = (events[i + 1]["ts"] - e["ts"]) if i + 1 < len(events) else None
        gap_s = f"{gap:>9.2f}s" if gap is not None else " " * 10
        print(f"{offset:>9.2f}s  {gap_s}  {e['event']:<22} "
              f"{_fmt_attrs(e.get('attrs') or {})}")
    return 0


async def cmd_generate(client: Client, ns: argparse.Namespace) -> int:
    """Hit the serving endpoint of a promoted job: token ids in, tokens out
    (docs/serving.md; the server refuses non-COMPLETED promotions)."""
    try:
        tokens = [int(t) for t in ns.tokens.replace(" ", "").split(",") if t]
    except ValueError:
        raise SystemExit(f"--tokens expects comma-separated ids, got {ns.tokens!r}")
    if not tokens:
        raise SystemExit("--tokens must name at least one token id")
    body: dict[str, Any] = {"tokens": tokens}
    if ns.max_new_tokens is not None:
        body["max_new_tokens"] = ns.max_new_tokens
    if ns.temperature is not None:
        body["temperature"] = ns.temperature
    if ns.top_k is not None:
        body["top_k"] = ns.top_k
    if ns.eos_id is not None:
        body["eos_id"] = ns.eos_id
    if ns.seed is not None:
        body["seed"] = ns.seed
    if getattr(ns, "adapter", None):
        body["adapter"] = ns.adapter
    try:
        result = await client.post(f"/jobs/{ns.job_id}/generate", json=body)
    except ApiError as exc:
        # the server's 429 carries a Retry-After derived from queue depth
        # and decode rate (docs/serving.md §Fleet): honor it with ONE
        # bounded client-side retry — a busy fleet usually drains within
        # the hint, and more than one retry belongs to the caller's loop
        if exc.status != 429 or exc.retry_after_s is None:
            raise
        wait = min(30.0, max(0.0, exc.retry_after_s))
        print(f"server busy; retrying once in {wait:.0f}s (Retry-After)",
              file=sys.stderr)
        await asyncio.sleep(wait)
        result = await client.post(f"/jobs/{ns.job_id}/generate", json=body)
    _print_json(result)
    return 0


async def cmd_artifacts(client: Client, ns: argparse.Namespace) -> int:
    if ns.output:
        await client.download(f"/jobs/{ns.job_id}/artifacts", ns.output)
        print(f"wrote {ns.output}", file=sys.stderr)
        return 0
    body = await client.get(f"/jobs/{ns.job_id}/artifacts", params={"list": "1"})
    for a in body.get("artifacts", []):
        print(f"{a['size']:>12}  {a['path']}")
    return 0


async def amain(ns: argparse.Namespace) -> int:
    async with Client(ns.api, ns.token) as client:
        if ns.cmd == "models":
            _print_json(await client.get("/models"))
            return 0
        if ns.cmd == "submit":
            return await cmd_submit(client, ns)
        if ns.cmd == "jobs":
            return await cmd_jobs(client, ns)
        if ns.cmd == "queue":
            return await cmd_queue(client, ns)
        if ns.cmd == "serve":
            return await cmd_serve(client, ns)
        if ns.cmd == "status":
            return await cmd_status(client, ns)
        if ns.cmd == "logs":
            return await cmd_logs(client, ns)
        if ns.cmd == "metrics":
            return await cmd_metrics(client, ns)
        if ns.cmd == "timeline":
            return await cmd_timeline(client, ns)
        if ns.cmd == "artifacts":
            return await cmd_artifacts(client, ns)
        if ns.cmd in ("promote", "unpromote", "cancel"):
            _print_json(await client.post(f"/jobs/{ns.job_id}/{ns.cmd}"))
            return 0
        if ns.cmd == "profile":
            # arm an on-demand jax.profiler window on a LIVE job
            # (docs/observability.md §On-demand profiler window)
            _print_json(await client.post(
                f"/jobs/{ns.job_id}/profile", json={"steps": ns.steps}
            ))
            return 0
        if ns.cmd == "generate":
            return await cmd_generate(client, ns)
        if ns.cmd == "dev-token":
            body = await client.post("/auth/dev-token",
                                     json={"user_id": ns.user_id})
            print(body["access_token"])
            return 0
        raise SystemExit(f"unknown command {ns.cmd!r}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ftc-ctl", description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--api", default=os.environ.get("FTC_CTL_API", "http://localhost:8787"))
    p.add_argument("--token", default=os.environ.get("FTC_CTL_TOKEN"))
    sub = p.add_subparsers(dest="cmd", required=True)

    sub.add_parser("models")
    s = sub.add_parser("submit")
    s.add_argument("model")
    s.add_argument("--arg", action="append", metavar="K=V")
    s.add_argument("--device")
    s.add_argument("--task",
                   help="expected task type (causal_lm | multimodal | dpo | "
                        "rlhf ...); the server 400s on unknown values or a "
                        "model/task mismatch")
    s.add_argument("--queue", help="tenant queue (docs/scheduling.md)")
    s.add_argument("--priority", help="low | normal | high | integer")
    s.add_argument("--dataset-file")
    s.add_argument("--dataset-url")
    s.add_argument("--dataset-id")
    s.add_argument("--watch", action="store_true")
    s = sub.add_parser("jobs")
    s.add_argument("--page", type=int, default=1)
    sub.add_parser("queue")
    sub.add_parser("serve")
    for name in ("status", "logs", "metrics", "timeline", "artifacts",
                 "promote", "unpromote", "cancel"):
        s = sub.add_parser(name)
        s.add_argument("job_id")
        if name == "status":
            s.add_argument("--watch", action="store_true")
        if name == "logs":
            s.add_argument("--follow", action="store_true")
        if name == "artifacts":
            s.add_argument("--output", "-o",
                           help="download the artifact zip to this path "
                                "(default: list the inventory)")
    s = sub.add_parser("profile")
    s.add_argument("job_id")
    s.add_argument("--steps", type=int, default=5,
                   help="jax.profiler window length in steps "
                        "(docs/observability.md; trace lands in profile/)")
    s = sub.add_parser("generate")
    s.add_argument("job_id")
    s.add_argument("--tokens", required=True,
                   help="comma-separated prompt token ids (e.g. 1,2,3)")
    s.add_argument("--max-new-tokens", type=int, default=None)
    s.add_argument("--temperature", type=float, default=None)
    s.add_argument("--top-k", type=int, default=None)
    s.add_argument("--eos-id", type=int, default=None)
    s.add_argument("--seed", type=int, default=None)
    s.add_argument("--adapter", default=None,
                   help="decode with this multiplexed tenant adapter (a "
                        "LoRA job id loaded via /admin/serve/.../adapters; "
                        "docs/serving.md §Multi-tenant adapters)")
    s = sub.add_parser("dev-token")
    s.add_argument("user_id", nargs="?", default="dev")
    return p


def main(argv: list[str] | None = None) -> int:
    ns = build_parser().parse_args(argv)
    try:
        import aiohttp  # noqa: F401 — the whole client needs it
    except ImportError:
        print(
            "ftc-ctl needs the control-plane extras: "
            "pip install 'finetune-controller-tpu[control]'",
            file=sys.stderr,
        )
        return 1
    try:
        return asyncio.run(amain(ns))
    except ApiError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # downstream pipe closed early (| head ...) — the unix-polite exit
        try:
            sys.stdout.close()
        except OSError:
            pass  # the close flushing into the same dead pipe — expected
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
