"""Auth: JWT mint/verify, remote token introspection, aiohttp middleware.

Capability parity with the reference's ``app/core/security.py`` (472 LoC —
SURVEY.md §2 component 2): bearer-or-cookie extraction, OAuth token
introspection against a remote endpoint, local JWT validation, a dev-mode mint/
verify path so the whole stack runs without an identity provider
(``security.py:347-421``), and per-user model entitlements carried in the JWT
``scp`` claim (``security.py:17,354``). JWTs are HS256 via stdlib ``hmac``
(PyJWT is not in the image); the introspection client is injectable for tests
(the seam the reference's test implicitly lacked — SURVEY.md §4).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import logging
import time
from typing import Any, Awaitable, Callable

from pydantic import BaseModel, Field

logger = logging.getLogger(__name__)


class AuthError(Exception):
    def __init__(self, message: str, status: int = 401):
        super().__init__(message)
        self.status = status


class UserJWT(BaseModel):
    """Validated identity attached to each request (reference: ``UserJWT``,
    ``security.py:33-38``)."""

    user_id: str
    email: str = ""
    scopes: list[str] = Field(default_factory=list)  # `scp` claim: entitled models
    is_admin: bool = False
    expires_at: float | None = None

    def entitled_models(self, all_models: list[str]) -> list[str]:
        """Models this user may submit (reference: entitlement check,
        ``app/main.py:412,1323-1341``): empty scp ⇒ everything, else filter."""
        if not self.scopes or self.is_admin:
            return list(all_models)
        return [m for m in all_models if m in self.scopes]


# ---------------------------------------------------------------------------
# Stdlib HS256 JWT
# ---------------------------------------------------------------------------


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def encode_jwt(claims: dict[str, Any], secret: str) -> str:
    """Mint an HS256 JWT (dev path; reference: ``dev_generate_token``,
    ``security.py:347-389``)."""
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url(sig)}"


def decode_jwt(token: str, secret: str | None = None, verify_exp: bool = True) -> dict[str, Any]:
    """Decode (and optionally verify) a JWT (reference: ``decode_jwt``,
    ``security.py:46-63``)."""
    try:
        header_s, payload_s, sig_s = token.split(".")
    except ValueError as e:
        raise AuthError("malformed token") from e
    if secret is not None:
        expected = hmac.new(
            secret.encode(), f"{header_s}.{payload_s}".encode(), hashlib.sha256
        ).digest()
        if not hmac.compare_digest(expected, _b64url_decode(sig_s)):
            raise AuthError("invalid token signature")
    try:
        claims = json.loads(_b64url_decode(payload_s))
    except (ValueError, json.JSONDecodeError) as e:
        raise AuthError("malformed token payload") from e
    if verify_exp and "exp" in claims and time.time() > float(claims["exp"]):
        raise AuthError("token expired")
    return claims


def dev_generate_token(
    user_id: str,
    secret: str,
    *,
    scopes: list[str] | None = None,
    is_admin: bool = False,
    email: str = "",
    ttl_s: float = 24 * 3600,
) -> str:
    claims = {
        "sub": user_id,
        "email": email,
        "scp": scopes or [],
        "admin": is_admin,
        "iat": time.time(),
        "exp": time.time() + ttl_s,
    }
    return encode_jwt(claims, secret)


def user_from_claims(claims: dict[str, Any]) -> UserJWT:
    return UserJWT(
        user_id=str(claims.get("sub") or claims.get("user_id") or ""),
        email=str(claims.get("email") or ""),
        scopes=list(claims.get("scp") or []),
        is_admin=bool(claims.get("admin", False)),
        expires_at=claims.get("exp"),
    )


# ---------------------------------------------------------------------------
# RS256 / JWKS validation (reference: security.py:66-189 — python-jose JWKS;
# here via `cryptography`, with the same fetched-key cache)
# ---------------------------------------------------------------------------


def jwt_header(token: str) -> dict[str, Any]:
    try:
        header_s = token.split(".")[0]
        return json.loads(_b64url_decode(header_s))
    except (ValueError, IndexError, json.JSONDecodeError) as e:
        raise AuthError("malformed token") from e


class JWKSClient:
    """Fetches and caches a JWKS document (reference caches fetched keys,
    ``security.py:108-116``). The fetch is injectable for tests."""

    def __init__(
        self,
        url: str,
        *,
        fetch_fn: Callable[[str], Awaitable[dict[str, Any]]] | None = None,
        cache_ttl_s: float = 3600.0,
    ):
        self.url = url
        self._fetch_fn = fetch_fn
        self._cache_ttl_s = cache_ttl_s
        self._keys: dict[str, dict[str, Any]] = {}
        self._fetched_at = 0.0

    async def _fetch(self) -> dict[str, Any]:
        if self._fetch_fn is not None:
            return await self._fetch_fn(self.url)
        import aiohttp

        async with aiohttp.ClientSession() as session:
            async with session.get(self.url) as resp:
                if resp.status != 200:
                    raise AuthError(f"JWKS fetch failed ({resp.status})", 503)
                return await resp.json()

    #: minimum spacing between JWKS fetches — an unknown ``kid`` must not
    #: turn into request-for-request amplification against the IdP
    MIN_REFETCH_S = 30.0

    async def get_key(self, kid: str | None) -> dict[str, Any]:
        now = time.time()
        stale = now - self._fetched_at > self._cache_ttl_s
        missing = kid is not None and kid not in self._keys
        throttled = now - self._fetched_at < self.MIN_REFETCH_S
        if (stale or missing) and not (missing and not stale and throttled):
            doc = await self._fetch()
            self._keys = {k.get("kid", ""): k for k in doc.get("keys", [])}
            self._fetched_at = now
        if kid is None:
            if len(self._keys) == 1:
                return next(iter(self._keys.values()))
            raise AuthError("token has no kid and JWKS has multiple keys")
        key = self._keys.get(kid)
        if key is None:
            raise AuthError(f"unknown signing key {kid!r}")
        return key


def rsa_public_key_from_jwk(jwk: dict[str, Any]):
    """Build an RSA public key from a JWK dict (kty=RSA, base64url n/e)."""
    from cryptography.hazmat.primitives.asymmetric import rsa

    if jwk.get("kty") != "RSA":
        raise AuthError(f"unsupported key type {jwk.get('kty')!r}")
    n = int.from_bytes(_b64url_decode(jwk["n"]), "big")
    e = int.from_bytes(_b64url_decode(jwk["e"]), "big")
    return rsa.RSAPublicNumbers(e, n).public_key()


async def decode_jwt_rs256(
    token: str,
    jwks: JWKSClient,
    *,
    verify_exp: bool = True,
    audience: str | None = None,
) -> dict[str, Any]:
    """Verify an RS256 JWT against a JWKS endpoint and return its claims."""
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding

    header = jwt_header(token)
    if header.get("alg") != "RS256":
        raise AuthError(f"unsupported algorithm {header.get('alg')!r}")
    try:
        header_s, payload_s, sig_s = token.split(".")
    except ValueError as e:
        raise AuthError("malformed token") from e
    key = rsa_public_key_from_jwk(await jwks.get_key(header.get("kid")))
    try:
        key.verify(
            _b64url_decode(sig_s),
            f"{header_s}.{payload_s}".encode(),
            padding.PKCS1v15(),
            hashes.SHA256(),
        )
    except InvalidSignature as e:
        raise AuthError("invalid token signature") from e
    try:
        claims = json.loads(_b64url_decode(payload_s))
    except (ValueError, json.JSONDecodeError) as e:
        raise AuthError("malformed token payload") from e
    if verify_exp and "exp" in claims and time.time() > float(claims["exp"]):
        raise AuthError("token expired")
    if audience:
        # enforced only when the deployment configures an audience; RFC 7519
        # allows both string and array `aud`
        aud = claims.get("aud")
        auds = aud if isinstance(aud, list) else [aud] if aud else []
        if audience not in auds:
            raise AuthError("token audience mismatch")
    return claims


# ---------------------------------------------------------------------------
# Token validation (introspection, JWKS/RS256, or local HS256)
# ---------------------------------------------------------------------------

IntrospectFn = Callable[[str], Awaitable[dict[str, Any]]]


async def dev_mock_token_introspection(token: str) -> dict[str, Any]:
    """Canned introspection for dev/tests (reference:
    ``dev_mock_token_introspection``, ``security.py:412-421``)."""
    if token == "valid_token":
        return {"active": True, "sub": "dev-user", "scp": []}
    return {"active": False}


class TokenValidator:
    """Validates bearer tokens, with a small TTL cache (reference:
    ``TokenValidator``, ``security.py:66-189``).

    Strategies, tried in order:
    1. injected/remote **introspection** (OAuth RFC 7662-style endpoint);
    2. **JWKS/RS256** verification when a JWKS URL is configured and the
       token's header says RS256 (reference: ``security.py:66-189``);
    3. local **HS256 verification** against the configured secret.
    """

    def __init__(
        self,
        *,
        jwt_secret: str,
        introspection_url: str = "",
        introspection_client_id: str = "",
        introspection_client_secret: str = "",
        introspect_fn: IntrospectFn | None = None,
        jwks_url: str = "",
        jwks_client: JWKSClient | None = None,
        audience: str = "",
        cache_ttl_s: float = 60.0,
    ):
        self._jwt_secret = jwt_secret
        self._introspection_url = introspection_url
        self._client_id = introspection_client_id
        self._client_secret = introspection_client_secret
        self._introspect_fn = introspect_fn
        self._jwks = jwks_client or (JWKSClient(jwks_url) if jwks_url else None)
        self._audience = audience
        self._cache: dict[str, tuple[float, UserJWT]] = {}
        self._cache_ttl_s = cache_ttl_s

    async def _remote_introspect(self, token: str) -> dict[str, Any]:
        import aiohttp

        # RFC 7662 endpoints typically require client auth (the reference
        # sends OpenBridge client creds, app/core/security.py:118-130)
        auth = (
            aiohttp.BasicAuth(self._client_id, self._client_secret)
            if self._client_id
            else None
        )
        async with aiohttp.ClientSession(auth=auth) as session:
            async with session.post(
                self._introspection_url, data={"token": token}
            ) as resp:
                if resp.status != 200:
                    raise AuthError(f"introspection failed ({resp.status})", 401)
                return await resp.json()

    async def validate(self, token: str) -> UserJWT:
        now = time.time()
        cached = self._cache.get(token)
        if cached and cached[0] > now:
            return cached[1]

        user: UserJWT | None = None
        if self._introspect_fn is not None or self._introspection_url:
            fn = self._introspect_fn or self._remote_introspect
            data = await fn(token)
            if not data.get("active", False):
                raise AuthError("token not active")
            user = user_from_claims(data)
        elif self._jwks is not None and jwt_header(token).get("alg") == "RS256":
            claims = await decode_jwt_rs256(
                token, self._jwks, audience=self._audience or None
            )
            user = user_from_claims(claims)
        else:
            if not self._jwt_secret:
                # no HS256 secret configured (e.g. JWKS-only deployment with
                # the default secret neutralised): a non-RS256 token has no
                # valid verification path — never fall back to a known secret
                raise AuthError("no local token verification configured")
            claims = decode_jwt(token, self._jwt_secret)
            user = user_from_claims(claims)
        if not user.user_id:
            raise AuthError("token has no subject")
        ttl = self._cache_ttl_s
        if user.expires_at is not None:
            ttl = min(ttl, max(user.expires_at - now, 0.0))
        self._cache[token] = (now + ttl, user)
        if len(self._cache) > 10_000:  # bound the cache
            self._cache = {k: v for k, v in self._cache.items() if v[0] > now}
        return user


# ---------------------------------------------------------------------------
# aiohttp middleware
# ---------------------------------------------------------------------------


def extract_bearer(request: Any) -> str | None:
    """Authorization header or auth cookie (reference cookie-or-bearer
    extraction, ``security.py:211-240``)."""
    auth = request.headers.get("Authorization", "")
    if auth.lower().startswith("bearer "):
        return auth[7:].strip()
    cookie = request.cookies.get("ftc_token")
    return cookie or None


def build_cors_middleware(origins: list[str]):
    """CORS for browser frontends (reference: CORSMiddleware from
    ``settings.cors_origins``, ``app/api/middleware.py:59-66``). Handles the
    OPTIONS preflight and stamps Access-Control headers on every response
    whose Origin is allowed ("*" allows any)."""
    from aiohttp import web

    allow_any = "*" in origins
    allowed = set(origins)

    def _origin_ok(origin: str) -> bool:
        return bool(origin) and (allow_any or origin in allowed)

    def _stamp(resp, origin: str):
        resp.headers["Access-Control-Allow-Origin"] = "*" if allow_any else origin
        resp.headers["Vary"] = "Origin"
        return resp

    @web.middleware
    async def cors_middleware(request, handler):
        origin = request.headers.get("Origin", "")
        if request.method == "OPTIONS" and "Access-Control-Request-Method" in request.headers:
            if not _origin_ok(origin):
                return web.Response(status=403)
            resp = web.Response(status=204)
            resp.headers["Access-Control-Allow-Methods"] = (
                "GET, POST, PUT, DELETE, OPTIONS"
            )
            resp.headers["Access-Control-Allow-Headers"] = (
                request.headers.get("Access-Control-Request-Headers")
                or "Authorization, Content-Type"
            )
            resp.headers["Access-Control-Max-Age"] = "600"
            return _stamp(resp, origin)
        resp = await handler(request)
        if _origin_ok(origin):
            _stamp(resp, origin)
        return resp

    return cors_middleware


def build_auth_middleware(
    validator: TokenValidator,
    *,
    enabled: bool,
    api_prefix: str = "/api/v1",
    dev_user: str = "dev-user",
):
    """aiohttp middleware guarding ``/api/v1/*`` (reference:
    ``OpenBridgeBasicMiddleware``, ``security.py:201-268``). With auth disabled
    (local env) every request is attributed to ``dev_user`` — the reference's
    local-env fallback (``security.py:242-248``)."""
    from aiohttp import web

    @web.middleware
    async def auth_middleware(request, handler):
        if (
            not request.path.startswith(api_prefix)
            or request.path.endswith("/health")
            # token mint must be reachable without a token; the handler
            # itself refuses in production
            or request.path.endswith("/auth/dev-token")
        ):
            return await handler(request)
        if not enabled:
            request["user"] = UserJWT(user_id=dev_user, is_admin=True)
            return await handler(request)
        token = extract_bearer(request)
        if not token:
            return web.json_response({"detail": "missing bearer token"}, status=401)
        try:
            request["user"] = await validator.validate(token)
        except AuthError as e:
            return web.json_response({"detail": str(e)}, status=e.status)
        return await handler(request)

    return auth_middleware
